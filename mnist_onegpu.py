"""Single-device big-image MNIST training — TPU-native rebuild of the
reference ``mnist_onegpu.py`` (same flags, same log lines, same experiment).

Reference behavior (mnist_onegpu.py:34-96): seed 0, ConvNet with a lazily
materialized ~180M-param head at 3000x3000, batch size 5 (bs=10 OOMs a 24GB
A5000 — the README's whole point), CE + SGD(1e-4), loss print every 100
steps, wall-clock total. Data is MNIST resized 28->3000 per image on the
host by PIL.

TPU-native shape: one jit'd train step does resize (on device), forward,
loss, backward, and SGD apply; there is no .cuda() staging, no dummy
forward (Flax init-by-tracing sizes the head), and the host feeds raw
28x28 bytes. Without local MNIST IDX files a deterministic synthetic
MNIST stands in (zero egress — see tpu_sandbox/data/mnist.py).
"""

import argparse

IMAGE_SHAPE = [3000, 3000]


def train(device_index, args):
    import jax

    if args.accum_steps < 1:
        raise SystemExit(f"--accum-steps must be >= 1, got {args.accum_steps}")
    if args.batch_size % args.accum_steps:
        raise SystemExit(
            f"--batch-size {args.batch_size} must be divisible by "
            f"--accum-steps {args.accum_steps}"
        )
    if args.force_cpu:
        from tpu_sandbox.utils.cli import ensure_devices

        ensure_devices(1, force_cpu=True)
    import jax.numpy as jnp
    import optax

    from tpu_sandbox.data import BatchLoader, load_mnist, synthetic_mnist
    from tpu_sandbox.data.mnist import normalize
    from tpu_sandbox.models import pick_convnet
    from tpu_sandbox.train import Trainer, TrainState, make_train_step

    rng = jax.random.key(0)  # parity: torch.manual_seed(0), reference :35
    image_shape = [args.image_size, args.image_size]
    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    model = pick_convnet(args.image_size, plan=args.plan,
                         num_classes=10, dtype=dtype)
    tx = optax.sgd(learning_rate=1e-4)  # reference :49, no momentum

    try:
        images, labels = load_mnist("train", args.data_dir)
    except FileNotFoundError:
        print("MNIST IDX files not found; using deterministic synthetic MNIST")
        images, labels = synthetic_mnist(n=args.synthetic_n, seed=0)
    if args.limit_steps:
        images = images[: args.limit_steps * args.batch_size]
        labels = labels[: args.limit_steps * args.batch_size]

    # reference :55-59: shuffle=True, num_workers=0. --native-loader swaps in
    # the C++ worker-pool loader (gather+normalize off the Python thread).
    # accumulation needs every batch divisible into microbatches: drop the
    # ragged tail instead of crashing on it at the end of an epoch
    drop_last = args.accum_steps > 1
    if args.native_loader:
        from tpu_sandbox.data.native_loader import NativeBatchLoader

        loader = NativeBatchLoader(
            images, labels, args.batch_size, shuffle=True, seed=0, threads=2,
            drop_last=drop_last,
        )
    else:
        loader = BatchLoader(
            normalize(images), labels.astype("int32"), args.batch_size,
            shuffle=True, seed=0, drop_last=drop_last,
        )

    state = TrainState.create(
        model, rng, jnp.zeros([1, *image_shape, 1], dtype), tx
    )
    if args.ckpt_dir and args.resume:
        from tpu_sandbox.train import checkpoint as ckpt

        if ckpt.latest_step(args.ckpt_dir) is not None:
            state = ckpt.restore(args.ckpt_dir, state)
            print(f"resumed from step {int(state.step)}")
    step = make_train_step(model, tx, image_size=tuple(image_shape),
                           accum_steps=args.accum_steps)
    trainer = Trainer(step, log_every=args.log_every,
                      ckpt_dir=args.ckpt_dir, ckpt_every=args.ckpt_every)
    import contextlib

    if args.profile:
        from tpu_sandbox.utils.profiling import trace

        profile_ctx = trace(args.profile)
    else:
        profile_ctx = contextlib.nullcontext()
    with profile_ctx:
        state = trainer.fit(state, loader, args.epochs)
    if args.profile:
        print(f"profiler trace written to {args.profile}")
    if args.eval:
        from tpu_sandbox.train.trainer import make_eval_step

        try:
            eval_images, eval_labels = load_mnist("test", args.data_dir)
        except FileNotFoundError:
            eval_images, eval_labels = synthetic_mnist(n=2000, seed=1)
        eval_images = normalize(eval_images)
        eval_labels = eval_labels.astype("int32")
        eval_step = make_eval_step(model, image_size=tuple(image_shape))
        ebs = min(args.batch_size, len(eval_images))
        correct = total = batches = 0
        loss_sum = 0.0
        for i in range(0, len(eval_images) - ebs + 1, ebs):
            c, l = eval_step(state, eval_images[i:i + ebs],
                             eval_labels[i:i + ebs])
            correct += int(c)
            loss_sum += float(l)
            total += ebs
            batches += 1
        if total:
            print(f"Eval: accuracy {correct}/{total} = {correct / total:.4f}, "
                  f"mean loss {loss_sum / batches:.4f}")
        else:
            print("Eval: no test data available, skipped")
    if args.ckpt_dir:
        from tpu_sandbox.train import checkpoint as ckpt

        print(f"saved checkpoint at step {ckpt.save(args.ckpt_dir, state)}")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2, help="number of epochs")
    parser.add_argument("--batch-size", type=int, default=5,
                        help="reference :45 — bs=10 OOMs one 24GB GPU")
    parser.add_argument("--image-size", type=int, default=IMAGE_SHAPE[0])
    parser.add_argument("--data-dir", type=str, default=None,
                        help="directory with MNIST IDX files; synthetic fallback otherwise")
    parser.add_argument("--synthetic-n", type=int, default=60000)
    parser.add_argument("--limit-steps", type=int, default=None,
                        help="cap steps per epoch (quick runs)")
    parser.add_argument("--log-every", type=int, default=100)
    parser.add_argument("--accum-steps", type=int, default=1,
                        help="gradient accumulation: split each batch into k "
                             "sequential microbatches (OOM workaround on ONE "
                             "device — the counterpart of the reference's "
                             "DDP batch split, README OOM experiment)")
    parser.add_argument("--plan",
                        choices=["auto", "s2dt", "s2d", "plain"],
                        default="auto",
                        help="ConvNet execution plan: s2dt = transposed "
                             "space-to-depth (models/convnet_s2d_t.py), "
                             "s2d = NHWC space-to-depth "
                             "(models/convnet_s2d.py) - same function as "
                             "the plain net either way, tested; auto "
                             "picks s2dt on TPU when the image "
                             "size allows")
    parser.add_argument("--dtype", choices=["bf16", "fp32"], default="bf16",
                        help="compute dtype; params and loss stay fp32")
    parser.add_argument("--native-loader", action="store_true",
                        help="use the C++ prefetching data loader")
    parser.add_argument("--ckpt-every", type=int, default=0, metavar="N",
                        help="with --ckpt-dir: also save every N steps "
                             "(crash recovery), not just at the end")
    parser.add_argument("--ckpt-dir", type=str, default=None,
                        help="save a checkpoint here after training")
    parser.add_argument("--profile", type=str, default=None, metavar="DIR",
                        help="capture a jax.profiler trace of training into "
                             "DIR (view in TensorBoard/Perfetto)")
    parser.add_argument("--eval", action="store_true",
                        help="report test-set accuracy after training")
    parser.add_argument("--resume", action="store_true",
                        help="restore the latest checkpoint from --ckpt-dir first")
    parser.add_argument("--force-cpu", action="store_true",
                        help="run on the CPU backend even if an accelerator is present")
    args = parser.parse_args()
    train(0, args)


if __name__ == "__main__":
    main()
