"""Distributed-init smoke test — TPU-native rebuild of the reference
``test_init.py`` (same flow, same log lines, same exit-0-on-success contract).

Reference behavior (test_init.py:112-117): spawn 4 processes, each sets
MASTER_ADDR/MASTER_PORT, picks gloo or nccl, calls
``dist.init_process_group``, prints progress, exits; the parent prints
``successful test_setup!``. Rank -1 is a "serial code, skip init" sentinel
(test_init.py:73).

TPU-native shape: there is nothing to spawn — JAX runs one process per host
and the 4 "ranks" are devices. ``setup_rank`` reports the same per-rank
progress lines; the rendezvous itself is ``tpu_sandbox.runtime.bootstrap``
(jax.distributed under the hood for real multi-host jobs). Unlike the
reference — which defines ``cleanup()`` but never calls it — the group is
actually torn down at the end.
"""

import jax


def setup_rank(rank: int, world_size: int, port: str, backend: str) -> None:
    """Per-rank progress report, line-for-line with reference :74-94."""
    if rank != -1:  # -1 rank indicates serial code
        print(f"setting up rank={rank} (with world_size={world_size})")
        MASTER_ADDR = "127.0.0.1"
        print(f"{MASTER_ADDR=}")
        print(f"{port=}")
        print(f"{backend=}")
        print(f"--> done setting up rank={rank}")


def test_setup():
    print("test_setup")
    from tpu_sandbox.runtime import bootstrap
    from tpu_sandbox.runtime.mesh import make_mesh
    from tpu_sandbox.utils.cli import ensure_devices

    world_size = 4
    port = bootstrap.find_free_port()
    devices = ensure_devices(world_size)

    bootstrap.init()
    backend = bootstrap.backend_name()
    mesh = make_mesh({"data": world_size}, devices=devices)
    assert mesh.shape["data"] == world_size
    for rank in range(world_size):
        setup_rank(rank, world_size, port, backend)
    print(bootstrap.topology_summary())
    bootstrap.cleanup()
    print("successful test_setup!")


if __name__ == "__main__":
    test_setup()
