"""Distributed-init smoke test — TPU-native rebuild of the reference
``test_init.py`` (same flow, same log lines, same exit-0-on-success contract).

Reference behavior (test_init.py:112-117): spawn 4 processes, each sets
MASTER_ADDR/MASTER_PORT, picks gloo or nccl, calls
``dist.init_process_group``, prints progress, exits; the parent prints
``successful test_setup!``. Rank -1 is a "serial code, skip init" sentinel
(test_init.py:73).

Two modes:
- default: ranks are devices of one process (the TPU-native shape — one
  process per HOST, so there is nothing to spawn on a single host).
- ``--multiprocess``: spawns world_size real OS processes that rendezvous
  through ``jax.distributed`` on the CPU backend (collectives over Gloo —
  the same fabric as the reference's CPU fallback) and run a psum sanity
  check. This is the reference's actual process topology, for parity.

Unlike the reference — which defines ``cleanup()`` but never calls it —
the group is actually torn down at the end.
"""

import argparse
import os
import subprocess
import sys


def setup_rank(rank: int, world_size: int, port: str, backend: str) -> None:
    """Per-rank progress report, line-for-line with reference :74-94."""
    if rank != -1:  # -1 rank indicates serial code
        print(f"setting up rank={rank} (with world_size={world_size})")
        MASTER_ADDR = "127.0.0.1"
        print(f"{MASTER_ADDR=}")
        print(f"{port=}")
        print(f"{backend=}")
        print(f"--> done setting up rank={rank}", flush=True)


def worker(rank: int, world_size: int, port: str) -> None:
    """One spawned process: rendezvous, collective sanity check, teardown."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from tpu_sandbox.runtime import bootstrap

    bootstrap.init(
        coordinator=f"127.0.0.1:{port}",
        num_processes=world_size,
        process_id=rank,
    )
    setup_rank(rank, world_size, port, bootstrap.backend_name())

    # the reference's smoke test stops at rendezvous; we also prove the
    # group works: a cross-process psum must see every rank
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("data",))
    x = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")),
        np.full((jax.local_device_count(), 1), float(rank + 1), np.float32),
        (jax.device_count(), 1),
    )
    total = float(jax.jit(lambda a: a.sum(), out_shardings=NamedSharding(mesh, P()))(x))
    expected = sum(
        (r + 1) * (jax.device_count() // world_size) for r in range(world_size)
    )
    assert total == expected, (total, expected)
    print(f"rank {rank}: psum check {total} == {expected}", flush=True)
    bootstrap.cleanup()


def test_setup(world_size: int, multiprocess: bool,
               force_cpu: bool = False) -> None:
    print("test_setup")
    from tpu_sandbox.runtime import bootstrap

    port = bootstrap.find_free_port()
    if multiprocess:
        procs = [
            subprocess.Popen(
                [sys.executable, __file__, "--worker", "--rank", str(r),
                 "--world-size", str(world_size), "--port", port],
                env={**os.environ},
            )
            for r in range(world_size)
        ]
        codes = [p.wait(timeout=180) for p in procs]
        if any(codes):
            raise SystemExit(f"worker exit codes: {codes}")
    else:
        from tpu_sandbox.runtime.mesh import make_mesh
        from tpu_sandbox.utils.cli import ensure_devices

        devices = ensure_devices(world_size, force_cpu=force_cpu)
        bootstrap.init()
        backend = bootstrap.backend_name()
        mesh = make_mesh({"data": world_size}, devices=devices)
        assert mesh.shape["data"] == world_size
        for rank in range(world_size):
            setup_rank(rank, world_size, port, backend)
        print(bootstrap.topology_summary())
        bootstrap.cleanup()
    print("successful test_setup!")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--world-size", type=int, default=4)
    parser.add_argument("--multiprocess", action="store_true",
                        help="spawn real OS processes (reference topology)")
    parser.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument("--rank", type=int, default=0, help=argparse.SUPPRESS)
    parser.add_argument("--port", type=str, default="", help=argparse.SUPPRESS)
    parser.add_argument("--force-cpu", action="store_true",
                        help="virtual CPU ranks only; skip the accelerator "
                             "(same flag as the training entry scripts)")
    args = parser.parse_args()
    if args.worker:
        worker(args.rank, args.world_size, args.port)
    else:
        test_setup(args.world_size, args.multiprocess, args.force_cpu)


if __name__ == "__main__":
    main()
