"""Headline benchmark: images/sec training the 3000x3000-MNIST ConvNet.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Timing is *fetch-synced differential* (utils/profiling.py::measure_per_step):
r01 reported a physically impossible 9,881 img/s (~10x a v5e's bf16 peak)
because on the experimental 'axon' TPU platform ``block_until_ready``
returns when the dispatch is acknowledged (~0.02 ms for a 100 ms matmul),
not when the device finishes. The only true sync is a device->host fetch of
a value that data-depends on the computation, and its ~80 ms tunnel
round-trip is cancelled by timing n and 2n steps and differencing. Every
number here is cross-checked against an analytic FLOP model and the chip's
published bf16 peak (utils/flops.py); an implausible MFU marks the run
``degraded`` instead of being published as a win.

Baseline accounting (BASELINE.md): the reference publishes no throughput —
only that 2x RTX A5000 under DDP train effective batch 10 at 3000x3000.
``--baseline`` therefore defaults to an *estimated upper bound* for that rig:
~195 GFLOP/image of training compute at an optimistic 50% fp32 utilization
of 2x27.8 TF/s => ~142 img/s; we use 75 img/s from the older conservative
estimate's midpoint, ignoring the reference's real bottleneck (its
single-threaded host-side PIL 28->3000 resize, num_workers=0, caps it far
lower). Comparing against a generous estimate means vs_baseline understates,
never overstates, the win.

Run config mirrors the reference experiment: bs=5 per device, 3000x3000,
bf16 compute (fp32 params), synthetic MNIST (zero-egress), data-parallel
over all available devices (1 chip = plain jit path of the same step).
"""

import argparse
import functools
import json
import os
import subprocess
import sys
import time


def accelerator_usable(timeout: float = 240.0) -> bool:
    """Probe the accelerator in a THROWAWAY subprocess with a hard timeout.

    Backend init happens inside native code a signal can't interrupt, so a
    wedged TPU tunnel would hang this process forever; probing in a child
    and killing it on timeout keeps the bench guaranteed to print its JSON
    line (a CPU fallback number beats a silent hang).
    """
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices()[0].platform != 'cpu'"],
            timeout=timeout, capture_output=True,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def annotate_loss(result: dict, final_loss: float) -> None:
    """Loss-plausibility gate (VERDICT r03 next-3, same spirit as the MFU
    gate): init CE for 10 classes is ln(10) ~= 2.3 nats; a post-warmup
    loss past 2x that — or NaN/inf — is flagged. It is NOT zeroed,
    because the explanation is known and measured: the reference's own
    recipe (SGD 1e-4 on the ~18M-feature fc head at 3000^2) is divergent
    — one update shifts logits by lr*g*||f||^2 = O(100-1000), and the
    torch reference model itself measures loss 2.26 -> 110 -> 421 in two
    steps on this exact config (tools/reference_dynamics_probe.py;
    BASELINE.md "Loss dynamics at 3000^2"). The throughput number is
    sound; the chaotic loss is the architecture's, shared with the
    reference, not a kernel defect (pinned by tests/test_convnet_s2d_t
    ::test_equality_at_production_row_width_bf16)."""
    import math

    if (not (final_loss <= 2 * math.log(10))
            or not math.isfinite(final_loss)):  # NaN/±inf also flagged
        result["loss_flag"] = (
            f"post-warmup loss {final_loss:.2f} > 2x ln(10) init floor: "
            "the reference recipe's own divergence at this scale (torch "
            "reference: 2.26 -> 421 nats in 2 steps at 3000^2, "
            "tools/reference_dynamics_probe.py), not a numerics defect"
        )
    if not math.isfinite(final_loss):
        result["final_loss"] = repr(final_loss)  # keep the JSON standard


def run_plan_ladder(run, image_size: int = 3000,
                    plan: str = "auto") -> dict:
    """Execution-plan fallback ladder around ``run(model_overrides)``: the
    production plan runs three Pallas kernel families (conv, bn-tail)
    that can in principle fail to compile on the runtime at hand. A
    kernel-compile failure must degrade the line (transposed plan off,
    then fused conv off, then all kernels off, then an explicit degraded
    record), never crash the bench and leave the round without an
    artifact. Fallback lines carry the triggering error.

    Rungs that resolve to the SAME concrete plan as an earlier rung are
    skipped: with --plan s2d the transposed rung is byte-identical to
    the first, and with --plan plain every s2d rung would silently
    upgrade past the user's explicit plan choice."""
    from tpu_sandbox.models import resolve_plan

    ladder = [
        ({}, None),
        # the r05 fused conv1/tail backward is the only kernel never yet
        # compiled on real hardware — degrade IT alone before abandoning
        # the whole transposed plan (and with it every r04/r05 win)
        (dict(fused_conv1_bwd=False), "fused conv1 backward disabled"),
        (dict(plan="s2d"), "transposed plan disabled"),
        (dict(plan="s2d", fused_conv=False), "pallas conv kernels disabled"),
        (dict(plan="s2d", fused_conv=False, fused_tail=False),
         "all pallas kernels disabled"),
    ]
    requested = resolve_plan(image_size, plan)
    tried = set()
    last_err = None
    for overrides, note in ladder:
        rp = resolve_plan(image_size, overrides.get("plan", plan))
        # fused_conv1_bwd only exists on the transposed plan; on any
        # other resolved plan the rung is byte-identical to the plain
        # first rung and must dedup away, not re-run
        fcb = overrides.get("fused_conv1_bwd") if rp == "s2dt" else None
        rung = (rp, overrides.get("fused_conv"),
                overrides.get("fused_tail"), fcb)
        if rung[0] != requested and requested in ("plain",):
            continue  # never escalate an explicit plain request
        if rung in tried:
            continue
        tried.add(rung)
        try:
            result = run(overrides)
        except Exception as e:  # noqa: BLE001 — artifact > purity
            last_err = e
            continue
        if note and last_err is not None:
            result["plan_fallback"] = (
                f"{note} after: {type(last_err).__name__}: "
                f"{str(last_err)[:300]}"
            )
        return result
    return {
        "metric": "train_images_per_sec_3000x3000_mnist",
        "value": 0.0, "unit": "images/sec", "vs_baseline": 0.0,
        "degraded": ("every execution plan failed; last error: "
                     f"{type(last_err).__name__}: {str(last_err)[:500]}"),
    }


def bench(image_size: int, batch_per_device: int, steps: int, warmup: int,
          dtype_name: str, force_cpu: bool, baseline: float,
          plan: str = "auto", model_overrides: dict | None = None) -> dict:
    from tpu_sandbox.utils.cli import ensure_devices

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    if force_cpu:
        ensure_devices(1, force_cpu=True)
    n_dev = jax.device_count()
    devices = jax.devices()

    from tpu_sandbox.data import synthetic_mnist
    from tpu_sandbox.data.mnist import normalize
    from tpu_sandbox.models import pick_convnet
    from tpu_sandbox.parallel import DataParallel
    from tpu_sandbox.runtime.mesh import make_mesh
    from tpu_sandbox.train import TrainState
    from tpu_sandbox.utils.flops import convnet_flops, mfu as mfu_check
    from tpu_sandbox.utils.profiling import host_sync, measure_per_step

    dtype = jnp.bfloat16 if dtype_name == "bf16" else jnp.float32
    model_overrides = dict(model_overrides or {})
    # the plan ladder / sweep race express plan switches through the same
    # overrides dict as the kernel toggles
    plan = model_overrides.pop("plan", plan)
    model = pick_convnet(image_size, plan=plan, dtype=dtype,
                         **model_overrides)
    tx = optax.sgd(1e-4)
    global_batch = batch_per_device * n_dev

    images, labels = synthetic_mnist(n=global_batch * 64, seed=0)
    images, labels = normalize(images), labels.astype("int32")
    # The blob task is linearly separable and saturates to loss 0.0 within
    # the warmup (VERDICT r01/r02: a dead loss demonstrates nothing about
    # the timed window). 25% uniform label flips (effective corruption
    # 22.5%) put a ~1.0-nat CE floor under any non-memorizing fit. The
    # first on-chip r03 run still printed 0.0: with only 8 staged batches
    # the 180M-param head saw each fixed flipped label ~24 times and
    # memorized it. 64 staged batches (raw 28x28, ~4 KB each — resize is
    # on-device) cap reuse at ~3 epochs over a bench run, keeping the
    # floor practical. Shapes/FLOPs/traffic are untouched.
    noise_rng = np.random.default_rng(1)
    flip = noise_rng.random(len(labels)) < 0.25
    labels = np.where(
        flip, noise_rng.integers(0, 10, size=len(labels)), labels
    ).astype("int32")

    state = TrainState.create(
        model, jax.random.key(0), jnp.zeros((1, image_size, image_size, 1), dtype), tx
    )
    mesh = make_mesh({"data": n_dev}, devices=devices)
    dp = DataParallel(model, tx, mesh, image_size=(image_size, image_size))
    state = dp.shard_state(state)

    # Pre-stage batches on device so no host->device transfer sits inside the
    # timed region (raw 28x28 batches are ~4 KB; the 3000x3000 resize happens
    # on device inside the step).
    rng = np.random.default_rng(0)
    staged = []
    for _ in range(64):
        sel = rng.integers(0, len(images), size=global_batch)
        staged.append(dp.shard_batch(images[sel], labels[sel]))

    cursor = 0

    def run_steps(k: int):
        # persistent cursor: the staged pool must cycle ACROSS calls, or
        # measure_per_step's repeated run_steps(n) would retrain the same
        # leading batches every call and final_loss would be evaluated on
        # the most-memorized batch — the failure the 64-batch pool fixes
        nonlocal state, cursor
        loss = None
        for _ in range(k):
            im, lb = staged[cursor % len(staged)]
            cursor += 1
            state, loss = dp.train_step(state, im, lb)
        return loss

    for _ in range(max(warmup - 1, 0)):
        run_steps(1)

    timing = measure_per_step(run_steps, steps)
    sec_per_step = timing["sec_per_step"]

    # The legacy (r01) timing, for the record: on async-dispatch platforms
    # this reads near zero — the delta vs the honest number documents why
    # block_until_ready must not be trusted here.
    host_sync(run_steps(1))  # drain the queue
    t0 = time.perf_counter()
    jax.block_until_ready(run_steps(steps))
    bur_per_step = (time.perf_counter() - t0) / steps
    final_loss = host_sync(run_steps(1))

    per_image = convnet_flops(image_size)
    flops_per_step = per_image.train * global_batch
    # guard BEFORE dividing: an exactly-zero differential must still print
    timing_ok = sec_per_step > 0
    util = mfu_check(flops_per_step, sec_per_step if timing_ok else 1.0,
                     str(devices[0].device_kind), n_devices=n_dev)

    # XLA's own FLOP count for the compiled step, when the backend exposes
    # it — an independent cross-check on the analytic model. Under the
    # Pallas plans XLA cannot see into the custom calls (VERDICT r03
    # weak-7: 26.5 GF reported vs thousands executed), so the custom
    # calls' analytic EXECUTED flops are counted from the optimized HLO
    # and composed; `flops_xla_partial` marks lines where that applies.
    flops_xla = flops_xla_composed = custom_flops = None
    try:
        im, lb = staged[0]
        compiled = dp._jitted.lower(state, im, lb).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        if cost and "flops" in cost:
            flops_xla = float(cost["flops"])
        from tpu_sandbox.utils.flops import (
            model_runs_sparse_conv1,
            s2d_custom_call_flops,
        )
        custom = s2d_custom_call_flops(compiled.as_text(), global_batch,
                                       image_size,
                                       plan=type(model).__name__,
                                       sparse_conv1=model_runs_sparse_conv1(
                                           model))
        if custom["custom_calls_counted"] and flops_xla is not None:
            custom_flops = custom
            if custom.get("unmatched_pallas_calls"):
                # a kernel the analytic table doesn't know: the composed
                # number would silently undercount — don't publish it
                flops_xla_composed = None
            else:
                flops_xla_composed = flops_xla + custom["total"]
    except Exception:
        pass

    ips = global_batch / sec_per_step if timing_ok else 0.0
    result = {
        "metric": "train_images_per_sec_3000x3000_mnist",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / baseline, 3),
        "baseline_images_per_sec": baseline,
        "baseline_kind": "estimated 2xA5000 DDP upper bound (see bench.py docstring)",
        "devices": n_dev,
        "device_kind": str(devices[0].device_kind),
        "global_batch": global_batch,
        "image_size": image_size,
        "dtype": dtype_name,
        "execution_plan": type(model).__name__,
        "steps_timed": timing["n"] * 3,
        "sec_per_step": sec_per_step,
        "timing_method": timing["timing_method"],
        "t_n_sec": timing["t_n_sec"],
        "t_2n_sec": timing["t_2n_sec"],
        "sec_per_step_block_until_ready": bur_per_step,
        "flops_per_step_model": flops_per_step,
        "flops_per_step_xla": flops_xla,
        "flops_xla_partial": custom_flops is not None,
        "flops_per_step_xla_composed": flops_xla_composed,
        "flops_custom_calls_analytic": custom_flops,
        "achieved_tflops": round(util["achieved_tflops"], 2),
        "peak_tflops_bf16": util["peak_tflops_bf16"],
        "mfu": round(util["mfu"], 4) if util["mfu"] is not None else None,
        "final_loss": round(final_loss, 4),
    }
    annotate_loss(result, final_loss)
    # framework-regression gate (VERDICT r04 weak-4): the loss_flag's
    # "reference-recipe chaos" explanation is only available while the
    # plan provably matches the plain ConvNet at this row width
    try:
        pf = numerics_preflight(model, image_size)
    except Exception as e:  # a preflight crash must not lose the line
        pf = {"ok": None, "error": f"{type(e).__name__}: {str(e)[:200]}"}
    result["numerics_preflight"] = pf
    if pf.get("ok") is False:
        result["degraded"] = (
            "numerics preflight FAILED (plan deviates from the plain "
            f"ConvNet beyond bf16 tolerance: {pf}); loss_flag's "
            "reference-chaos explanation withdrawn — treat as a "
            "framework regression"
        )
    def add_degraded(msg: str) -> None:
        # append, never overwrite: the preflight-withdrawal marker must
        # survive a simultaneous timing/MFU degradation (readers key on
        # the degraded field)
        result["degraded"] = (f"{result['degraded']}; {msg}"
                              if "degraded" in result else msg)

    if not timing_ok:
        # differential came out non-positive (timing noise dominated, or the
        # platform queue is lying): no throughput claim at all
        result.update(value=0.0, vs_baseline=0.0, achieved_tflops=0.0,
                      mfu=None)
        add_degraded(
            f"non-positive differential step time ({sec_per_step:.6f}s): "
            "timing noise or untrusted platform queue; no number published"
        )
    elif not util["plausible"]:
        # an untrusted number is not published at all (the r01 lesson)
        result.update(value=0.0, vs_baseline=0.0)
        add_degraded(
            f"implausible mfu {util['mfu']:.2f} (> 1.0): timing on this "
            "platform does not reflect device execution; "
            f"untrusted images/sec was {round(ips, 2)}"
        )
    return result


def _terminal_verdict(client, rid: str, timeout: float) -> dict:
    """result() that treats a burnt retry budget as data: the benches
    audit terminal SHED verdicts alongside oks, so unwrap the exception
    back into the verdict body it carries."""
    from tpu_sandbox.serve.client import RetriesExhausted
    try:
        return client.result(rid, timeout=timeout)
    except RetriesExhausted as err:
        return err.verdict


def _is_oom(msg: str) -> bool:
    """Allocator-failure detection across backends: PJRT's
    RESOURCE_EXHAUSTED / 'out of memory', plus the axon remote-compiler's
    AOT phrasing 'Allocation (size=N) would exceed memory (size=HBM)'."""
    return ("RESOURCE_EXHAUSTED" in msg or "OOM" in msg.upper()
            or "out of memory" in msg.lower()
            or "would exceed memory" in msg)


def bench_sweep(image_size: int, steps: int, warmup: int, baseline: float,
                force_cpu: bool, quick: bool = False,
                plan: str = "auto") -> dict:
    """Batch-size x dtype sweep at the reference's 3000x3000 shape — the
    'chase real MFU' table VERDICT r01 item 2 asks for: for each config,
    step time (fetch-synced differential), images/sec, and MFU; headline =
    the best honest images/sec. OOM configs are recorded as rows, not
    errors (the capacity boundary is part of the table)."""
    if quick:
        image_size, configs = 128, [("fp32", 2, None, None),
                                    ("fp32", 4, None, None)]
    else:
        # ladder around the chipless AOT capacity estimates (r04 step:
        # bs=21 fits at ~15.1 GB peak, 22 over —
        # measured/aot_capacity_s2dt_r04.jsonl): dense near the expected
        # best point up to the capacity edge. The kernel-plan rows race
        # the execution plans (and the r04 sparse-vs-scattered conv1) at
        # the headline batch — which plan actually wins on hardware is a
        # measured question, not an estimated one.
        configs = [("bf16", 5, None, None), ("bf16", 8, None, None),
                   ("bf16", 12, None, None), ("bf16", 16, None, None),
                   ("bf16", 20, None, None), ("fp32", 5, None, None)]
        from tpu_sandbox.models import resolve_plan, resolves_to_s2d
        if resolves_to_s2d(image_size, plan):
            # the overrides are meaningless under the plain plan — labeled
            # race rows there would publish three copies of the same run.
            # The nhwc_pallas row only races when the main rows run the
            # transposed plan (else it would duplicate them byte-for-byte).
            if resolve_plan(image_size, plan) == "s2dt":
                configs += [
                    ("bf16", 16, dict(plan="s2d"), "nhwc_pallas"),
                    # the r04 conv1 race: transposed plan, scattered-3x3
                    # conv1 instead of the sparse union-tile kernel
                    ("bf16", 16, dict(plan="s2dt", sparse_conv1=False),
                     "s2dt_scat_conv1"),
                    # the r05 backward race: unfused conv1/tail backward
                    # (the cotangent round-trips HBM) vs the default
                    # fused kernel — the -9.4 GB/step claim, measured
                    ("bf16", 16, dict(plan="s2dt", fused_conv1_bwd=False),
                     "s2dt_unfused_bwd"),
                    ("bf16", 21, None, None),  # AOT r04/r05: max batch 21
                ]
            configs += [
                ("bf16", 16, dict(plan="s2d", fused_conv=False),
                 "xla_conv+tail"),
                ("bf16", 16, dict(plan="s2d", fused_conv=False,
                                  fused_tail=False), "xla_conv_unfused"),
                ("bf16", 5, dict(plan="s2d", fused_conv=False),
                 "xla_conv+tail")]
    rows, best = [], None
    for dtype_name, bs, overrides, plan_label in configs:
        try:
            r = bench(image_size, bs, steps, warmup, dtype_name, force_cpu,
                      baseline, plan=plan, model_overrides=overrides)
            row = {"dtype": dtype_name, "batch": bs,
                   "sec_per_step": r["sec_per_step"],
                   "images_per_sec": r["value"], "mfu": r["mfu"]}
            if plan_label:
                row["kernel_plan"] = plan_label
            if "degraded" in r:
                row["degraded"] = r["degraded"]
            elif best is None or r["value"] > best["images_per_sec"]:
                best = row
        except Exception as e:
            msg = f"{type(e).__name__}: {e}"
            oom = _is_oom(msg)
            row = {"dtype": dtype_name, "batch": bs,
                   "oom" if oom else "error": True if oom else msg[:200]}
            if plan_label:
                row["kernel_plan"] = plan_label
        rows.append(row)

    import jax
    result = {
        "metric": "train_images_per_sec_sweep",
        "value": best["images_per_sec"] if best else 0.0,
        "unit": f"images/sec (best of sweep @ {image_size}x{image_size})",
        "vs_baseline": round(best["images_per_sec"] / baseline, 3) if best else 0.0,
        "best": best,
        "rows": rows,
        "device_kind": str(jax.devices()[0].device_kind),
    }
    if any(r.get("kernel_plan") for r in rows):
        # only when plan-race rows actually ran (full sweep at 3000^2)
        result["plan_race_caveat"] = (
            "NHWC rows (nhwc_pallas, xla_*) include the canonical-fc-order "
            "transpose of [N,750,750,32] (~0.54 GB bf16/direction at "
            "bs=16, >=1.3 ms/step of HBM traffic — models/convnet.py); "
            "the s2dt rows' fc is transpose-free, so part of any "
            "s2dt-vs-NHWC delta is that canonicalization, not the conv "
            "kernels (ADVICE r04)."
        )
    if best is None:
        result["degraded"] = "no config produced a trusted number (see rows)"
    return result


_PREFLIGHT_CACHE: dict = {}


def numerics_preflight(model, width: int) -> dict:
    """The bench's framework-regression gate (VERDICT r04 weak-4/next-4).

    ``annotate_loss`` explains a divergent loss via the reference
    recipe's own measured chaos — true for the architecture, but on its
    own it would also wave through a framework-INTRODUCED numerics bug,
    since every divergence would get the ready-made excuse. This check
    distinguishes the two inside the bench run itself: the execution
    plan under test must match the plain ConvNet on a [2, 16, width]
    bf16 slab (at width=3000 that is the exact production 750-lane row
    geometry) to the tolerances of tests/test_convnet_s2d_t.py::
    test_equality_at_production_row_width_bf16. When this FAILS, the
    chaos explanation is withdrawn and the whole line is degraded.
    Memoized per (plan config, width): a sweep calls bench() for ~10
    rows of the same plan, and each preflight costs two full jit
    compiles on chip."""
    key = (str(model), width)
    if key in _PREFLIGHT_CACHE:
        return _PREFLIGHT_CACHE[key]
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_sandbox.models.convnet import ConvNet
    from tpu_sandbox.ops.losses import cross_entropy_loss

    if type(model).__name__ == "ConvNet":
        return {"ok": True,
                "skipped": "plain plan IS the reference formulation"}
    # Validate at the model's CONFIGURED dtype (ADVICE r5): an fp32 sweep
    # row gated by a bf16 proxy clone could hide an fp32-only numerics bug
    # (or fail a clean fp32 plan on bf16 rounding). Tolerances scale with
    # the dtype accordingly.
    dtype = jnp.dtype(getattr(model, "dtype", None) or jnp.bfloat16)
    if dtype == jnp.dtype(jnp.bfloat16):
        tol = {"logit_rel": 8e-3, "loss_abs": 8e-3, "fc_grad_rel": 0.05}
    else:
        tol = {"logit_rel": 1e-3, "loss_abs": 1e-3, "fc_grad_rel": 5e-3}
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 16, width, 1)), dtype)
    yl = jnp.asarray(rng.integers(0, 10, size=(2,)), jnp.int32)
    ref = ConvNet(dtype=dtype)
    variables = ref.init(jax.random.key(0), x)
    params, stats = variables["params"], variables["batch_stats"]

    def run(m):
        def f(p):
            logits, _ = m.apply(
                {"params": p, "batch_stats": stats}, x, train=True,
                mutable=["batch_stats"])
            return cross_entropy_loss(logits, yl), logits

        (loss, logits), g = jax.jit(
            jax.value_and_grad(f, has_aux=True))(params)
        return (float(loss), np.asarray(logits, np.float32),
                np.asarray(g["fc"]["kernel"], np.float32))

    l_r, lo_r, g_r = run(ref)
    # the plan under test, at ITS configured kernels and ITS dtype
    plan = type(model).__name__
    l_t, lo_t, g_t = run(model.clone(dtype=dtype))
    scale = float(np.max(np.abs(lo_r))) or 1.0
    logit_rel = float(np.max(np.abs(lo_r - lo_t))) / scale
    loss_abs = abs(l_r - l_t)
    fc_rel = float(np.max(np.abs(g_r - g_t))) / (float(np.max(np.abs(g_r)))
                                                 or 1.0)
    ok = (logit_rel < tol["logit_rel"] and loss_abs < tol["loss_abs"]
          and fc_rel < tol["fc_grad_rel"])
    out = {"ok": bool(ok), "plan": plan, "width": width,
           "validated_dtype": str(dtype),
           "logit_rel_dev": round(logit_rel, 6),
           "loss_abs_dev": round(loss_abs, 6),
           "fc_grad_rel_dev": round(fc_rel, 6),
           "tolerances": tol}
    _PREFLIGHT_CACHE[key] = out
    return out


def bench_convergence(image_size: int, steps: int, force_cpu: bool,
                      plan: str = "auto", batch: int = 5) -> dict:
    """Tamed-lr convergence at the reference geometry (VERDICT r04
    next-4): demonstrate the production plan can DECREASE a loss at
    3000^2 — not merely match a reference recipe that itself diverges
    (BASELINE.md 'Loss dynamics at 3000^2': SGD 1e-4 moves the next
    step's logits by lr*g*||f||^2 = O(100-1000) through the ~18M-feature
    fc head, torch-measured 2.26 -> 421 nats in two steps). The tamed
    recipe keeps the reference's SGD 1e-4 on the conv/BN trunk and
    scales the fc head's lr by ~1/||f||^2 (1e-4 / 1e4 -> 1e-8), so the
    head moves logits O(0.1)/step — the minimal change that makes the
    architecture trainable at this scale (reference recipe being tamed:
    /root/reference/mnist_onegpu.py:68-74). Publishes the full loss
    curve + trend verdict; the numerics preflight runs alongside so a
    decrease cannot be claimed on a numerically-broken plan."""
    from tpu_sandbox.utils.cli import ensure_devices

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    degraded = None
    if force_cpu:
        ensure_devices(1, force_cpu=True)
        if image_size > 256:
            degraded = (f"accelerator unavailable; CPU fallback overrode "
                        f"image_size {image_size}->256, steps {steps}->12, "
                        f"batch {batch}->2")
            image_size, steps, batch = 256, min(steps, 12), 2

    from tpu_sandbox.data import synthetic_mnist
    from tpu_sandbox.data.mnist import normalize
    from tpu_sandbox.models import pick_convnet
    from tpu_sandbox.train import TrainState, make_train_step
    from tpu_sandbox.utils.profiling import host_sync

    model = pick_convnet(image_size, plan=plan, dtype=jnp.bfloat16)
    tx = optax.multi_transform(
        {"head": optax.sgd(1e-8), "trunk": optax.sgd(1e-4)},
        lambda params: {
            k: jax.tree.map(lambda _: "head" if k == "fc" else "trunk", v)
            for k, v in params.items()
        },
    )
    state = TrainState.create(
        model, jax.random.key(0),
        jnp.zeros((1, image_size, image_size, 1), jnp.bfloat16), tx)
    step = make_train_step(model, tx, image_size=(image_size, image_size),
                           donate=False)

    images, labels = synthetic_mnist(n=batch * 64, seed=0)
    images, labels = normalize(images), labels.astype("int32")
    noise_rng = np.random.default_rng(1)
    flip = noise_rng.random(len(labels)) < 0.25
    labels = np.where(
        flip, noise_rng.integers(0, 10, size=len(labels)), labels
    ).astype("int32")
    sel_rng = np.random.default_rng(2)

    losses = []
    for i in range(steps):
        sel = sel_rng.integers(0, len(images), size=batch)
        im = jnp.asarray(images[sel])  # normalize() already emits [N,28,28,1]
        lb = jnp.asarray(labels[sel])
        state, loss = step(state, im, lb)
        losses.append(float(host_sync(loss)))

    k = max(1, min(5, steps // 4))
    first, last = float(np.mean(losses[:k])), float(np.mean(losses[-k:]))
    drop = first - last
    rises = sum(1 for a, b in zip(losses, losses[1:]) if b > a + 1e-6)
    decreased = drop > 0.02 and last < losses[0]
    try:
        pf = numerics_preflight(model, image_size)
    except Exception as e:  # a preflight crash must not lose the curve
        pf = {"ok": None, "error": f"{type(e).__name__}: {str(e)[:200]}"}
    result = {
        "metric": "convergence_tamed_lr",
        "value": round(drop, 4),
        "unit": f"nats decrease (mean first {k} -> mean last {k} steps)",
        "vs_baseline": None,
        "baseline_kind": ("n/a: the reference's own recipe diverges at "
                          "this scale (BASELINE.md, torch-measured "
                          "2.26 -> 421 nats in 2 steps); any decrease "
                          "beats it"),
        "decreased": bool(decreased),
        "image_size": image_size, "batch": batch, "steps": steps,
        "recipe": "SGD trunk 1e-4, fc head 1e-8 (lr/||f||^2 scaling)",
        "loss_first_mean": round(first, 4),
        "loss_last_mean": round(last, 4),
        "loss_curve": [round(x, 4) for x in losses],
        "monotone_violations": rises,
        "execution_plan": type(model).__name__,
        "device_kind": str(jax.devices()[0].device_kind),
        "numerics_preflight": pf,
    }
    if pf.get("ok") is False:
        degraded = ((degraded + "; ") if degraded else "") + (
            "numerics preflight FAILED: the plan deviates from the plain "
            "ConvNet beyond bf16 tolerance — convergence claim void")
    if degraded:
        result["degraded"] = degraded
    return result


def bench_allreduce_bw(force_cpu: bool) -> dict:
    """All-reduce bus bandwidth over all devices — the second north-star
    metric BASELINE.md names (NCCL-style busbw accounting)."""
    from tpu_sandbox.utils.cli import ensure_devices

    if force_cpu:
        ensure_devices(8, force_cpu=True)
    import jax

    from tpu_sandbox.parallel.collectives import world_group

    g = world_group()
    r = g.allreduce_bandwidth()
    result = {
        "metric": "allreduce_bus_bandwidth",
        "value": round(r["busbw_GBps"], 3),
        "unit": "GB/s",
        "vs_baseline": 0.0,  # reference published no bandwidth number
        "algbw_GBps": round(r["algbw_GBps"], 3),
        "payload_bytes": r["bytes"],
        "timing_method": r["timing_method"],
        "devices": jax.device_count(),
        "device_kind": str(jax.devices()[0].device_kind),
    }
    if "degraded" in r:  # e.g. non-positive differential after retry
        result["degraded"] = r["degraded"]
    elif jax.device_count() == 1:
        # busbw = algbw * 2*(n-1)/n is identically 0 at n=1; say why
        result["degraded"] = "single device; no interconnect to measure"
    return result


def bench_grad_compress_traffic(world: int = 8) -> dict:
    """Cross-replica collective bytes per train step under each
    --grad-compress mode, from the optimized SPMD HLO of a CPU-mesh
    compile — the measured-artifact counterpart of the compression claim
    (~2x for bf16, ~4x payload for int8 plus its fp32 block scales).

    Chipless and deliberately CPU-forced: XLA:CPU keeps the collective
    instructions (all-reduce / all-to-all / all-gather) with inline
    operand shapes in ``compile().as_text()``, so the accounting in
    ``tools/hlo_traffic.collective_bytes`` reads the same numbers a TPU
    compile would produce for the gradient-sync payload. Estimates of
    wire payload per participant, not measurements of fabric time."""
    import sys as _sys

    from tpu_sandbox.utils.cli import ensure_devices

    devices = ensure_devices(world, force_cpu=True)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    _sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    from hlo_traffic import collective_bytes

    from tpu_sandbox.models import ConvNet
    from tpu_sandbox.parallel import DataParallel
    from tpu_sandbox.runtime.mesh import make_mesh
    from tpu_sandbox.train import TrainState

    mesh = make_mesh({"data": world}, devices=devices)
    # BN-free so the grad sync is the ONLY cross-replica traffic in the step
    model = ConvNet(use_bn=False)
    tx = optax.sgd(1e-2, momentum=0.9)
    state = TrainState.create(
        model, jax.random.key(0), jnp.zeros((1, 28, 28, 1)), tx
    )
    leaf_sizes = [int(np.prod(np.shape(p)))
                  for p in jax.tree.leaves(state.params)]
    images = np.zeros((2 * world, 28, 28, 1), np.float32)
    labels = np.zeros((2 * world,), np.int32)

    modes = {}
    for mode in ("none", "bf16", "int8"):
        dp = DataParallel(model, tx, mesh, grad_compress=mode, donate=False)
        dstate = dp.shard_state(state)
        text = dp.lower_step(
            dstate, *dp.shard_batch(images, labels)
        ).compile().as_text()
        hlo = collective_bytes(text)
        est = dp.compress.wire_bytes(leaf_sizes, world)
        modes[mode] = {
            "hlo_collective_bytes": hlo["total"],
            "by_opcode": hlo["by_opcode"],
            "estimated_wire_bytes": est["total"],
            "estimated_payload_bytes": est["payload"],
            "estimated_overhead_bytes": est["overhead"],
        }
    hlo_base = modes["none"]["hlo_collective_bytes"] or 1
    est_base = modes["none"]["estimated_wire_bytes"] or 1
    pay_base = modes["none"]["estimated_payload_bytes"] or 1
    for mode, row in modes.items():
        # headline 2x/4x is the payload ratio; the all-in wire ratio
        # additionally pays int8's fp32 block scales + block padding (the
        # padding dominates on this deliberately small model's tiny leaves)
        row["hlo_reduction_vs_fp32"] = round(
            hlo_base / (row["hlo_collective_bytes"] or 1), 2)
        row["est_wire_reduction_vs_fp32"] = round(
            est_base / (row["estimated_wire_bytes"] or 1), 2)
        row["est_payload_reduction_vs_fp32"] = round(
            pay_base / (row["estimated_payload_bytes"] or 1), 2)
    if (modes["bf16"]["hlo_collective_bytes"]
            == modes["none"]["hlo_collective_bytes"]):
        modes["bf16"]["hlo_note"] = (
            "XLA:CPU upcasts the bf16 all-reduce operand to f32, so the "
            "HLO bytes match fp32 here; a TPU compile keeps bf16 on the "
            "wire — trust the estimated path for this mode")
    return {
        "metric": "grad_compress_traffic",
        "world": world,
        "param_count": int(sum(leaf_sizes)),
        "modes": modes,
        "source": "optimized SPMD HLO collective-operand accounting on the "
                  f"{world}-virtual-CPU-device mesh (chipless estimate, not "
                  "a measurement)",
    }


# Stub tenants for --metric cluster: real subprocesses speaking the
# scheduler's protocol (job-namespaced heartbeats, preemption vote,
# verdict) with zero training inside, so the reported latencies isolate
# the scheduler's own reaction times. The resumed life smuggles its
# first-step wall-clock stamp out through the verdict — the one record
# that survives the job-namespace sweep.
_CLUSTER_AGENT = """\
import json, signal, sys, time
sys.path.insert(0, {root!r})
from tpu_sandbox.runtime.kvstore import KVClient, for_job
aid = int(sys.argv[1]); port = int(sys.argv[2]); job = sys.argv[3]
mode = sys.argv[4]
kv = for_job(KVClient(port=port), job)
stop = []
signal.signal(signal.SIGTERM, lambda s, f: stop.append(1))

def verdict(ok, preempted=False, extra=None):
    v = {{"ok": ok, "preempted": preempted, "reason": "bench stub",
          "summary": "", "restarts": 0, "preemptions": 0,
          "generations": 1}}
    v.update(extra or {{}})
    kv.set("job/done", json.dumps(v))

if mode == "work":            # the high-priority arrival: brief and done
    t0 = time.monotonic()
    while time.monotonic() - t0 < 0.4:
        kv.set_ttl(f"agent_hb/{{aid}}", repr(time.time()), 5.0)
        time.sleep(0.02)
    verdict(True)
    time.sleep(0.2)
elif mode == "preemptible":   # the victim tenant
    lives = kv.add("bench/lives", 1)
    if lives >= 2:            # resumed life: stamp the first step, finish
        verdict(True, extra={{"first_step_walltime": time.time()}})
        time.sleep(0.2)
        sys.exit(0)
    while not stop:           # first life: run until the scheduler preempts
        kv.set_ttl(f"agent_hb/{{aid}}", repr(time.time()), 5.0)
        time.sleep(0.02)
    verdict(False, preempted=True)  # checkpoint-through-vote stand-in
    sys.exit(75)
"""


def bench_cluster(pool: int = 1) -> dict:
    """Scheduler control-plane latencies from a scripted two-job run: a
    low-priority tenant fills the pool, a high-priority job arrives and
    preempts it, the victim resumes after the arrival drains. Reports the
    three receipts the multi-tenant claim stands on — queue wait,
    preempt-to-checkpoint, and resume-to-first-step — computed from the
    scheduler's own event stamps (runtime/scheduler.py::job_events) plus
    the stub agents' verdicts. Chipless: no jax, no training; these are
    the scheduler's overheads, to be added on top of a real job's own
    checkpoint-save and first-step times."""
    import tempfile

    from tpu_sandbox.runtime.scheduler import (
        ClusterScheduler,
        JobSpec,
        job_events,
        k_state,
        k_verdict,
    )

    root = os.path.dirname(os.path.abspath(__file__))
    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "bench_cluster_agent.py")
        with open(script, "w", encoding="utf-8") as f:
            f.write(_CLUSTER_AGENT.format(root=root))

        def argv(mode):
            return [sys.executable, script, "{agent_id}", "{kv_port}",
                    "{job_id}", mode]

        with ClusterScheduler(pool, poll=0.02,
                              extra_env={"PYTHONPATH": root},
                              verbose=False) as sched:
            sched.submit(JobSpec(job_id="victim", hosts=1, world_size=1,
                                 agent_argv=argv("preemptible")))
            # outrank the victim only once its agent is demonstrably up
            # (heartbeating, SIGTERM handler installed) — preempting a gang
            # mid-exec() measures the kill escalation, not the vote
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                sched._tick()
                if (sched.kv.try_get(k_state("victim")) or b"") \
                        == b"running" \
                        and sched.kv.keys("job/victim/agent_hb/"):
                    break
                time.sleep(0.02)
            sched.submit(JobSpec(job_id="arrival", hosts=1, world_size=1,
                                 priority=5, agent_argv=argv("work")))
            states = sched.serve(timeout=120)
            if states != {"victim": "done", "arrival": "done"}:
                raise RuntimeError(f"scripted run went sideways: {states}")
            ev_v = job_events(sched.kv, "victim")
            ev_a = job_events(sched.kv, "arrival")
            verdict = json.loads(sched.kv.get(k_verdict("victim")))

    return {
        "metric": "cluster_scheduler_latency",
        "pool_hosts": pool,
        "unit": "seconds",
        # how long each job sat in the queue before its gang launched
        # (the arrival's wait covers the whole preemption round trip)
        "queue_wait_s": {
            "victim": round(ev_v["admitted"] - ev_v["submitted"], 4),
            "arrival": round(ev_a["admitted"] - ev_a["submitted"], 4),
        },
        # SIGTERM sent -> preempted verdict posted (the window a real job
        # spends checkpointing through the preemption vote)
        "preempt_to_checkpoint_s": round(
            ev_v["preempted"] - ev_v["preempt_sent"], 4),
        # requeued-job readmission -> its first step after resume
        "resume_to_first_step_s": round(
            verdict["first_step_walltime"] - ev_v["readmitted"], 4),
        "events": {"victim": ev_v, "arrival": ev_a},
        "source": "scripted two-job preemption round on a 1-host pool with "
                  "protocol-stub agents (scheduler overhead only; add the "
                  "job's own checkpoint-save and first-step cost)",
    }


#: BENCH_r07's staged-transport shipping numbers — the fast-fabric claim
#: is anchored against these (equal slots, equal bytes, >=10x lower
#: total get latency on the device path).
_R07_SHIP = {"get_ms_total": 4448.308, "gets": 160, "bytes_out": 1310720}

#: committed fabric-profile baseline for the tracediff gate; regenerate
#: with ``bench.py --metric mpmd --archive <dir>`` and commit the
#: ``mpmd_fabric_profile.json`` artifact here after intentional fabric
#: changes
_FABRIC_CONTROL = os.path.join("measured", "mpmd_fabric_control.json")


def _fabric_profile(merged) -> dict:
    """Fold an MPMD run's trace into a critpath-schema profile whose
    segments are the fabric's own health numbers — per-stage bubble
    seconds per steady-state step and per-slot ship latencies — so
    ``tools/tracediff.py`` gates fabric regressions exactly like
    request-path regressions. A device path silently degrading to
    staged shipping shows up as a >=10x ``ship:get`` ratio; a schedule
    regression shows up in the ``bubble:stage<s>`` rows."""
    import statistics

    from tpu_sandbox.obs import critpath

    walls: dict[tuple, float] = {}
    comp: dict[tuple, float] = {}
    segs: dict[str, list[float]] = {}
    for r in merged:
        if r.get("ph") != "X":
            continue
        name, args = r.get("name"), r.get("args") or {}
        dur = float(r.get("dur", 0.0))
        if name == "stage:step":
            key = (int(args.get("stage", -1)), int(args.get("step", -1)))
            walls[key] = walls.get(key, 0.0) + dur
        elif name == "stage:op":
            key = (int(args.get("stage", -1)), int(args.get("step", -1)))
            comp[key] = comp.get(key, 0.0) + dur
        elif name == "slot:get":
            segs.setdefault("ship:get", []).append(dur)
        elif name == "slot:put":
            segs.setdefault("ship:put", []).append(dur)
        elif name == "stage:wait":
            segs.setdefault("ship:wait", []).append(dur)
    for (stage, step), wall in walls.items():
        if step < 1:  # step 0 pays compile on every arm
            continue
        segs.setdefault(f"bubble:stage{stage}", []).append(
            max(0.0, wall - comp.get((stage, step), 0.0)))
    step_walls = sorted(w for (_, st), w in walls.items() if st >= 1)
    total = sum(step_walls) or 1.0
    segments = {}
    for name in sorted(segs):
        samples = sorted(round(x, 9) for x in segs[name])
        tot = sum(samples)
        segments[name] = {
            "total_s": round(tot, 9),
            "share": round(tot / total, 6),
            "n": len(samples),
            "median_s": round(statistics.median(samples), 9),
            "samples": samples,
        }
    return {
        "schema": critpath.PROFILE_SCHEMA,
        "requests": len(step_walls),
        "ok": len(step_walls),
        "wall_s_total": round(total, 9),
        "wall_s_median": round(statistics.median(step_walls), 9)
        if step_walls else 0.0,
        "coverage_min": 1.0, "coverage_mean": 1.0,
        "segments": segments, "blame": {}, "by_proc": {},
    }


def bench_mpmd(*, steps: int = 20, quick: bool = False,
               aot: bool = True) -> dict:
    """Fast-fabric MPMD receipts, four arms over the SAME model/init:

    1. **Staged control** — KVTransport over a live KV server, the wire
       every cross-host deployment pays: whole-slot staging, chunked
       puts, the r07 shape (2 stages / 4 microbatches, 160 slots /
       1310720 bytes at the full config).
    2. **Device fast path** — DeviceTransport (device buffers published
       in-process, journal underneath for recovery): same slots, same
       bytes, params bitwise vs the fused SPMD pipeline. The tentpole
       claim: total ``get`` latency >= 10x lower than BENCH_r07's
       staged 4448.3 ms at equal shipped bytes.
    3. **Measured ZB-H1 schedule** — 3 even stages, per-op costs
       measured from a short probe, ``schedule.autotune_plan`` picks
       (kind, microbatches); the chosen zb_h1 run's measured bubble
       (online gauge AND offline trace, agreeing within 0.03) must land
       below the analytic 1F1B ``(S-1)/(M+S-1)``.
    4. **Fault audit** — a mid-run stage kill with in-process recovery:
       params bitwise vs the unfaulted twin, zero duplicate claims
       across generations (the zero-dup/zero-loss microbatch audit).

    The fast arm's trace folds into a fabric profile
    (:func:`_fabric_profile`) and ``tools/tracediff.py`` gates it — in
    every run against the staged arm (the fast path must never regress
    toward staged shipping), and additionally against the committed
    ``measured/mpmd_fabric_control.json`` when present (full runs
    only). ``--metric mpmd`` exits nonzero when the gate fails, like
    the tracediff CLI itself. Chipless: CPU times are harness truth;
    the ratios, parity bits and audits are the claims."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")
    import contextlib
    import statistics
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tpu_sandbox.models.transformer import TransformerConfig
    from tpu_sandbox.mpmd import MPMDPipeline, bubble_fraction
    from tpu_sandbox.mpmd.schedule import autotune_plan
    from tpu_sandbox.mpmd.transport import DeviceTransport, KVTransport
    from tpu_sandbox.obs import (ENV_TRACE_DIR, collect, critpath,
                                 get_recorder, reset_recorder)
    from tpu_sandbox.parallel.pipeline import PipelineParallel
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer
    from tpu_sandbox.runtime.mesh import make_mesh

    @contextlib.contextmanager
    def recorder_arm(trace_dir):
        prior = os.environ.pop(ENV_TRACE_DIR, None)
        if trace_dir is not None:
            os.environ[ENV_TRACE_DIR] = trace_dir
        reset_recorder()
        try:
            yield
        finally:
            get_recorder().flush()
            if prior is None:
                os.environ.pop(ENV_TRACE_DIR, None)
            else:
                os.environ[ENV_TRACE_DIR] = prior
            reset_recorder()

    steps = 6 if quick else steps
    microbatches, n_stages = 4, 2
    cfg = TransformerConfig(vocab_size=64, d_model=32 if quick else 64,
                            n_heads=2 if quick else 4, n_layers=4,
                            d_ff=64 if quick else 128, max_len=64)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(8, 16)).astype(np.int32)
    targets = ((tokens + 7) % cfg.vocab_size).astype(np.int32)
    tx = optax.adam(1e-2)
    devs = jax.devices()

    mesh = make_mesh({"data": 1, "pipe": n_stages}, devices=devs[:n_stages])
    pp = PipelineParallel(cfg, tx, mesh, microbatches=microbatches,
                          donate=False)
    state = pp.init_state(jax.random.key(0), jnp.asarray(tokens))
    flat = pp.merged_params(state)

    def run_arm(transport, devices, *, trace_dir=None):
        pipe = MPMDPipeline(cfg, tx, n_stages=n_stages,
                            microbatches=microbatches, transport=transport,
                            devices=devices)
        pipe.init_from_flat(flat)
        with recorder_arm(trace_dir):
            pipe.train(steps, tokens, targets)
        return pipe

    # -- arm 1: staged control (the KV wire, chunk-pipelined reads) ----------
    server = KVServer()
    kv = KVClient(port=server.port)
    try:
        staged_dir = tempfile.mkdtemp(prefix="mpmd-staged-")
        staged = run_arm(KVTransport(kv, prefix="fab"),
                         devs[n_stages:2 * n_stages], trace_dir=staged_dir)
        staged_stats = staged.transport.stats.snapshot()
    finally:
        kv.close()
        server.stop()

    # -- arm 2: device fast path, same slots/bytes ---------------------------
    fast_dir = tempfile.mkdtemp(prefix="mpmd-fast-")
    pipe = run_arm(DeviceTransport(), devs[n_stages:2 * n_stages],
                   trace_dir=fast_dir)
    stats = pipe.transport.stats.snapshot()
    stage_ms = [sorted(1e3 * t for t in w.step_seconds.values())
                for w in pipe.workers]

    # -- SPMD baseline: same init, same batch, fused scan --------------------
    sstate = pp.shard_state(state)
    batch = pp.shard_batch(tokens, targets)
    spmd_ms = []
    for _ in range(steps):
        t0 = time.perf_counter()
        sstate, loss = pp.train_step(sstate, *batch)
        jax.block_until_ready(loss)
        spmd_ms.append(1e3 * (time.perf_counter() - t0))
    spmd_ms.sort()

    spmd = pp.merged_params(sstate)
    mpmd = pipe.merged_params()
    mismatched = [
        1 for a, b in zip(jax.tree.leaves(spmd), jax.tree.leaves(mpmd))
        if not np.array_equal(np.asarray(a), np.asarray(b))
    ]

    # the tentpole ship claim, at the r07 anchor's exact shape only
    fast_get_ms = 1e3 * stats["get_seconds"]
    shape_matches_r07 = (not quick
                         and stats["gets"] == _R07_SHIP["gets"]
                         and stats["bytes_out"] == _R07_SHIP["bytes_out"])
    ship_speedup_vs_r07 = (round(_R07_SHIP["get_ms_total"] / fast_get_ms, 1)
                           if fast_get_ms > 0 else None)
    ship_speedup_vs_staged = (
        round(1e3 * staged_stats["get_seconds"] / fast_get_ms, 1)
        if fast_get_ms > 0 else None)

    # -- arm 3: measured ZB-H1 schedule on 3 even stages ---------------------
    # heavy enough that per-op compute dominates dispatch overhead —
    # otherwise the measured bubble is all harness, not schedule
    cfg3 = TransformerConfig(vocab_size=64, d_model=128, n_heads=4,
                             n_layers=6, d_ff=512, max_len=64)
    S3, M3 = 3, 8
    zb_steps = 5 if quick else 10
    rng3 = np.random.default_rng(3)
    tokens3 = rng3.integers(0, cfg3.vocab_size, size=(16, 32)).astype(
        np.int32)
    targets3 = ((tokens3 + 7) % cfg3.vocab_size).astype(np.int32)

    def run_zb(kind, m_count, nsteps, *, trace_dir=None):
        p3 = MPMDPipeline(cfg3, tx, n_stages=S3, microbatches=m_count,
                          transport=DeviceTransport(), devices=devs[:S3],
                          kind=kind)
        p3.init(jax.random.key(1), jnp.asarray(tokens3))
        with recorder_arm(trace_dir):
            p3.train(nsteps, tokens3, targets3)
        return p3

    probe = run_zb("zb_h1", M3, 3 if quick else 4)
    op_costs = probe.measured_op_costs()
    plan = autotune_plan(op_costs, n_stages=S3, measured_microbatches=M3,
                         candidates=(2, 4))
    zb_dir = tempfile.mkdtemp(prefix="mpmd-zb-")
    zb = run_zb(plan["kind"], plan["microbatches"], zb_steps,
                trace_dir=zb_dir)
    online = statistics.median(
        b for w in zb.workers
        for s, b in w.bubble_by_step.items() if s >= 1)
    per_step = critpath.bubble_fractions(
        collect.load_merged(zb_dir))["per_step"]
    offline = statistics.median(
        r["bubble"] for r in per_step if r["step"] >= 1)
    analytic_1f1b = bubble_fraction(S3, plan["microbatches"])

    # -- arm 4: kill mid-run, recover, audit ---------------------------------
    fa_steps = 4 if quick else 8
    ckpt = tempfile.mkdtemp(prefix="mpmd-fault-")

    def run_fault(sub, fail_at):
        p = MPMDPipeline(cfg, tx, n_stages=n_stages,
                         microbatches=microbatches,
                         transport=DeviceTransport(),
                         devices=devs[n_stages:2 * n_stages],
                         ckpt_root=os.path.join(ckpt, sub))
        p.init_from_flat(flat)
        if fail_at is not None:
            p.workers[1].fail_at = fail_at
        p.train(fa_steps, tokens, targets, recover=fail_at is not None)
        return p

    twin = run_fault("twin", None)
    faulted = run_fault("kill", (fa_steps // 2, 1))
    fa_mismatch = [
        1 for a, b in zip(jax.tree.leaves(twin.merged_params()),
                          jax.tree.leaves(faulted.merged_params()))
        if not np.array_equal(np.asarray(a), np.asarray(b))
    ]
    dup_claims = {k: v for k, v in faulted.transport.audit()["claims"].items()
                  if v != 1}
    fault_audit_ok = (not fa_mismatch and not dup_claims
                      and faulted.workers[1].generation == 1)

    # -- tracediff gate over the fabric profile ------------------------------
    fast_profile = _fabric_profile(collect.load_merged(fast_dir))
    profile_path = os.path.join(fast_dir, "mpmd_fabric_profile.json")
    critpath.save_profile(fast_profile, profile_path)
    staged_profile_path = os.path.join(staged_dir, "fabric_profile.json")
    critpath.save_profile(_fabric_profile(collect.load_merged(staged_dir)),
                          staged_profile_path)
    td = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "tools", "tracediff.py")
    # thresholds sized to catch transport-tier changes (device -> staged
    # is >=10x on ship:get) and schedule breakage, not CPU step jitter
    gate_args = ["--threshold", "0.5", "--min-ms", "1.0",
                 "--min-share", "0.02"]
    gates = {}
    gates["vs_staged"] = subprocess.run(
        [sys.executable, td, staged_profile_path, profile_path, *gate_args],
        capture_output=True, text=True).returncode
    control = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           _FABRIC_CONTROL)
    if not quick and os.path.isfile(control):
        gates["vs_archived"] = subprocess.run(
            [sys.executable, td, control, profile_path, *gate_args],
            capture_output=True, text=True).returncode
    tracediff_gate_ok = all(rc == 0 for rc in gates.values())

    result = {
        "metric": "mpmd_pipeline",
        "unit": "milliseconds",
        "geometry": {
            "n_stages": n_stages, "microbatches": microbatches,
            "steps": steps, "d_model": cfg.d_model,
            "n_layers": cfg.n_layers,
        },
        # steady-state medians; step 0 carries compile time on both sides
        "per_stage_step_ms": [
            round(ms[len(ms) // 2], 3) for ms in stage_ms],
        "spmd_step_ms": round(spmd_ms[len(spmd_ms) // 2], 3),
        "bubble_fraction": bubble_fraction(n_stages, microbatches),
        "params_bitwise_vs_spmd": not mismatched,
        "transport": {
            "puts": stats["puts"], "gets": stats["gets"],
            "bytes_out": stats["bytes_out"],
            "bytes_in": stats["bytes_in"],
            "put_ms_total": round(1e3 * stats["put_seconds"], 3),
            "get_ms_total": round(fast_get_ms, 3),
            # time consumers sat blocked on unproduced slots — the
            # measured face of the schedule bubble
            "get_wait_ms_total": round(1e3 * stats["get_wait_seconds"], 3),
            "device_hits": stats.get("device_hits", 0),
            "journal_fallbacks": stats.get("journal_fallbacks", 0),
        },
        "transport_staged": {
            "gets": staged_stats["gets"],
            "bytes_out": staged_stats["bytes_out"],
            "put_ms_total": round(1e3 * staged_stats["put_seconds"], 3),
            "get_ms_total": round(1e3 * staged_stats["get_seconds"], 3),
            "get_wait_ms_total": round(
                1e3 * staged_stats["get_wait_seconds"], 3),
        },
        "ship": {
            "r07_staged_get_ms": _R07_SHIP["get_ms_total"],
            "speedup_vs_r07": ship_speedup_vs_r07,
            "speedup_vs_staged_arm": ship_speedup_vs_staged,
            "equal_bytes_vs_r07": bool(shape_matches_r07),
            "note": "r07 predates the wait/wire accounting split (its "
                    "get total folds in schedule wait); the in-run "
                    "staged arm is the like-for-like wire baseline",
        },
        "device_path_10x_ok": bool(
            shape_matches_r07 and ship_speedup_vs_r07 is not None
            and ship_speedup_vs_r07 >= 10.0),
        "autotune": {
            "chosen_kind": plan["kind"],
            "chosen_microbatches": plan["microbatches"],
            "predicted": plan["predicted"],
            "candidates": plan["candidates"],
            "measured_op_cost_ms": {
                s: {op: round(1e3 * v, 3) for op, v in ops.items()}
                for s, ops in op_costs.items()},
        },
        "zb_bubble": {
            "n_stages": S3, "microbatches": plan["microbatches"],
            "steps": zb_steps,
            "online_median": round(online, 6),
            "offline_median": round(offline, 6),
            "analytic_1f1b": round(analytic_1f1b, 6),
        },
        "zb_below_1f1b_ok": bool(plan["kind"] == "zb_h1"
                                 and offline < analytic_1f1b),
        "bubble_gauge_ok": bool(abs(online - offline) <= 0.03),
        "fault_audit": {
            "params_bitwise_vs_twin": not fa_mismatch,
            "dup_claims": len(dup_claims),
            "respawned_generation": faulted.workers[1].generation,
        },
        "fault_audit_ok": bool(fault_audit_ok),
        "tracediff": {
            "gate_exits": gates,
            "control": _FABRIC_CONTROL if "vs_archived" in gates else None,
        },
        "tracediff_gate_ok": bool(tracediff_gate_ok),
        "_artifacts": {
            "mpmd_fabric_profile.json": profile_path,
            "trace_fast": fast_dir,
            "trace_zb": zb_dir,
        },
        "source": "in-process MPMD arms (threads, one CPU device per "
                  "stage): KVTransport staged wire vs DeviceTransport "
                  "fast path at equal slots/bytes vs the fused SPMD "
                  "pipeline; 3-stage probe-measured autotuned ZB-H1 with "
                  "online/offline/analytic bubble; kill-recover claim "
                  "audit; tracediff as the committed CLI on fabric "
                  "profiles; CPU times are harness truth, the ratios, "
                  "parity bits and audits are the claims",
    }
    if aot and not quick:
        from tools.aot_mpmd import mpmd_aot_report
        result["aot"] = mpmd_aot_report(
            n_stages=2, microbatches=microbatches, vocab_size=2048,
            d_model=128, n_layers=4, d_ff=256)
        # the ZB twin: uneven split, backward split into B/W programs
        result["aot_zb"] = mpmd_aot_report(
            n_stages=3, microbatches=microbatches, vocab_size=2048,
            d_model=128, n_layers=6, d_ff=256, layer_split=[3, 2, 1],
            zb=True)
    return result


def bench_serve(*, n_requests: int = 32, mean_interarrival_ms: float = 2.5,
                quick: bool = False, seed: int = 0, aot: bool = True) -> dict:
    """Serving SLOs from a Poisson load generator: tokens/sec and p50/p99
    TTFT (arrival -> first token) / ITL (gap between consecutive tokens),
    continuous batching vs the static-batch baseline on the SAME compiled
    steps, same request trace, same paged cache geometry — the comparison
    isolates the scheduling policy. Chipless: tiny transformer on the CPU
    backend; the absolute numbers are harness truth, the continuous/static
    ratio is the claim. A chipless v5e AOT receipt for the decode step's
    cache donation rides along (tools/aot_serve.py)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from collections import deque

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_sandbox.models.transformer import (TransformerConfig,
                                                TransformerLM)
    from tpu_sandbox.serve import (CacheConfig, ContinuousEngine, Request,
                                   ServeConfig, StaticEngine)
    from tpu_sandbox.serve.decode import build_decode_step

    if quick:
        n_requests = min(n_requests, 10)

    mcfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                             n_layers=2, d_ff=64, max_len=128,
                             dtype=jnp.float32)
    # quick mode is the tier-1 smoke: every prompt in the trace fits the
    # 16 bucket, so skip compiling the 32 one
    buckets = (16,) if quick else (16, 32)
    scfg = ServeConfig(model=mcfg,
                       cache=CacheConfig(num_blocks=40, block_size=8,
                                         max_blocks_per_seq=8),
                       max_batch=4, buckets=buckets)
    params = TransformerLM(mcfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    step = build_decode_step(mcfg, scfg.cache, max_batch=scfg.max_batch,
                             buckets=scfg.buckets)

    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(
        mean_interarrival_ms / 1e3, n_requests))
    # arrival rate saturates the 4-wide decode (~5 tokens/ms on this box),
    # and generation lengths vary 4-19: the static baseline's batch barrier
    # idles finished slots until the longest member completes, which is the
    # makespan continuous batching reclaims
    trace = [(float(arrivals[i]), f"r{i}",
              [int(t) for t in rng.integers(1, 64, size=int(rng.integers(4, 17)))],
              int(rng.integers(4, 20)))
             for i in range(n_requests)]

    def run(engine_cls):
        eng = engine_cls(params, scfg, step=step)
        pending = deque(trace)
        start = time.monotonic()
        while pending or not eng.idle:
            now = time.monotonic() - start
            while pending and pending[0][0] <= now:
                off, rid, prompt, mn = pending.popleft()
                eng.submit(Request(rid=rid, prompt=prompt,
                                   max_new_tokens=mn, arrival=start + off))
            if eng.idle:
                time.sleep(min(1e-3, max(0.0, pending[0][0] - now)))
                continue
            eng.step()
        total = time.monotonic() - start
        ttft = np.array([r.ttft for r in eng.results.values()])
        itl = np.array([g for r in eng.results.values() for g in r.itl])
        toks = sum(len(r.tokens) for r in eng.results.values())
        return eng, {
            "tokens_per_sec": round(toks / total, 1),
            "total_sec": round(total, 3),
            "p50_ttft_ms": round(float(np.percentile(ttft, 50)) * 1e3, 2),
            "p99_ttft_ms": round(float(np.percentile(ttft, 99)) * 1e3, 2),
            "p50_itl_ms": round(float(np.percentile(itl, 50)) * 1e3, 2),
            "p99_itl_ms": round(float(np.percentile(itl, 99)) * 1e3, 2),
            "preemptions": sum(r.preemptions for r in eng.results.values()),
            "prefix_hits": eng.cache.stats["prefix_hits"],
        }

    cont_eng, cont = run(ContinuousEngine)
    stat_eng, stat = run(StaticEngine)
    outputs_match = all(
        cont_eng.results[rid].tokens == stat_eng.results[rid].tokens
        for _, rid, _, _ in trace)

    result = {
        "metric": "serve",
        "unit": "tokens/sec; ms",
        "requests": n_requests,
        "mean_interarrival_ms": mean_interarrival_ms,
        "generated_tokens": sum(len(stat_eng.results[rid].tokens)
                                for _, rid, _, _ in trace),
        "continuous": cont,
        "static": stat,
        # the tentpole claim: more throughput without giving back tail
        # first-token latency (scheduling policy only — same steps, cache,
        # and trace)
        "continuous_beats_static": bool(
            cont["tokens_per_sec"] > stat["tokens_per_sec"]
            and cont["p99_ttft_ms"] <= stat["p99_ttft_ms"]),
        "outputs_match": bool(outputs_match),
        "source": "measured wall time, Poisson open-loop load on the CPU "
                  "backend (tiny transformer); continuous/static share "
                  "compiled steps and trace",
    }
    if aot and not quick:
        result["aot_decode_donation"] = _serve_aot_receipt()
    return result


def _serve_aot_receipt() -> dict:
    """Chipless v5e decode-step donation receipt, subprocess-isolated like
    the other AOT paths (graceful degradation off-toolchain)."""
    import subprocess
    import sys as _sys

    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "aot_serve.py")
    try:
        out = subprocess.run(
            [_sys.executable, tool], capture_output=True, text=True,
            timeout=900,
        )
        if out.returncode == 0 and out.stdout.strip():
            return json.loads(out.stdout.strip().splitlines()[-1])
        tail = (out.stderr or out.stdout).strip().splitlines()
        err = tail[-1] if tail else f"exit {out.returncode}"
    except Exception as e:  # missing libtpu, timeout, ...
        err = f"{type(e).__name__}: {e}"
    return {
        "metric": "serve_aot_donation",
        "degraded": (
            f"TPU AOT compile unavailable ({err}); the CPU backend does "
            "not implement buffer donation — run on a box with the TPU "
            "toolchain"
        ),
    }


def bench_serve_slo(*, n_requests: int = 96, quick: bool = False,
                    seed: int = 0) -> dict:
    """Serving under stress: a 2x-capacity Poisson overload trace through
    three configurations of the SAME compiled steps, cache geometry, and
    request shapes — (a) guardrailed: bounded admission queue plus
    per-request deadlines, shedding on overload with explicit SHED
    verdicts; (b) unguarded: unbounded queue, no deadlines; (c) a
    capacity-matched reference at half the arrival rate. The claim: under
    2x overload the guardrails keep admitted p99 TTFT near the
    capacity-matched tail and goodput (requests finishing inside the SLO
    budget, per second) at or above ~90% of the capacity-matched run,
    where the unguarded queue's TTFT grows with the backlog and its
    goodput collapses. Chipless (tiny transformer, CPU backend): absolute
    numbers are harness truth, the guarded/unguarded/capacity ratios are
    the claim."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from collections import deque

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_sandbox.models.transformer import (TransformerConfig,
                                                TransformerLM)
    from tpu_sandbox.serve import (CacheConfig, ContinuousEngine, Request,
                                   ServeConfig)
    from tpu_sandbox.serve.decode import build_decode_step

    if quick:
        n_requests = min(n_requests, 12)

    mcfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                             n_layers=2, d_ff=64, max_len=128,
                             dtype=jnp.float32)
    buckets = (16,) if quick else (16, 32)
    cache = CacheConfig(num_blocks=40, block_size=8, max_blocks_per_seq=8)
    params = TransformerLM(mcfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    step = build_decode_step(mcfg, cache, max_batch=4, buckets=buckets)

    max_waiting = 8         # guardrail: 2x max_batch admission bound

    def make_trace(mean_ia_ms):
        rng = np.random.default_rng(seed)
        arrivals = np.cumsum(rng.exponential(mean_ia_ms / 1e3, n_requests))
        return [(float(arrivals[i]), f"r{i}",
                 [int(t) for t in
                  rng.integers(1, 64, size=int(rng.integers(4, 17)))],
                 int(rng.integers(4, 20)))
                for i in range(n_requests)]

    def run(trace, *, bound: bool, slo: float | None):
        scfg = ServeConfig(model=mcfg, cache=cache, max_batch=4,
                           buckets=buckets,
                           max_waiting=max_waiting if bound else 0)
        eng = ContinuousEngine(params, scfg, step=step)
        pending = deque(trace)
        start = time.monotonic()
        while pending or not eng.idle:
            now = time.monotonic() - start
            while pending and pending[0][0] <= now:
                off, rid, prompt, mn = pending.popleft()
                eng.submit(Request(
                    rid=rid, prompt=prompt, max_new_tokens=mn,
                    arrival=start + off,
                    deadline=start + off + slo if bound and slo else None))
            if eng.idle:
                time.sleep(min(1e-3, max(0.0, pending[0][0] - now)))
                continue
            eng.step()
        total = time.monotonic() - start
        lat = {rid: r.ttft + sum(r.itl)
               for rid, r in eng.results.items()}
        within = sum(1 for v in lat.values()
                     if slo is None or v <= slo)
        ttft = np.array([r.ttft for r in eng.results.values()] or [0.0])
        return {
            "completed": len(eng.results),
            "shed": len(eng.shed),
            "within_slo": within,
            "goodput_rps": round(within / total, 1),
            "p50_ttft_ms": round(float(np.percentile(ttft, 50)) * 1e3, 2),
            "p99_ttft_ms": round(float(np.percentile(ttft, 99)) * 1e3, 2),
            "total_sec": round(total, 3),
        }

    # calibrate to THIS box: a closed-loop batch run (everything arrives
    # at t=0, no bound, no deadlines) measures the engine's service rate;
    # the capacity trace matches it, the overload trace doubles it, and
    # the SLO budget is ~2x the bounded-queue residence time (queue of 8
    # + batch of 4 in the system, plus generation)
    calib = run(make_trace(0.0), bound=False, slo=None)
    service_rps = max(calib["completed"] / calib["total_sec"], 1.0)
    capacity_ia_ms = 1e3 / service_rps
    overload_ia_ms = capacity_ia_ms / 2
    slo_s = 24.0 / service_rps

    overload = make_trace(overload_ia_ms)
    guarded = run(overload, bound=True, slo=slo_s)
    unguarded = run(overload, bound=False, slo=slo_s)
    capacity = run(make_trace(capacity_ia_ms), bound=False, slo=slo_s)

    return {
        "metric": "serve_slo",
        "unit": "requests/sec within SLO; ms",
        "requests": n_requests,
        "calibrated_service_rps": round(service_rps, 1),
        "slo_ms": round(slo_s * 1e3, 2),
        "overload_interarrival_ms": round(overload_ia_ms, 3),
        "capacity_interarrival_ms": round(capacity_ia_ms, 3),
        "max_waiting": max_waiting,
        "guarded_overload": guarded,
        "unguarded_overload": unguarded,
        "capacity_matched": capacity,
        # the tentpole claims: shedding keeps the admitted tail near the
        # capacity-matched tail, goodput holds, and every request gets a
        # verdict (completed + shed = submitted)
        "tail_bounded": bool(
            guarded["p99_ttft_ms"]
            <= max(3 * capacity["p99_ttft_ms"], slo_s * 1e3)),
        "goodput_holds": bool(
            guarded["goodput_rps"] >= 0.9 * capacity["goodput_rps"]),
        "unguarded_collapses": bool(
            unguarded["p99_ttft_ms"] > 2 * guarded["p99_ttft_ms"]
            or unguarded["goodput_rps"] < guarded["goodput_rps"]),
        "every_request_verdicted": bool(
            guarded["completed"] + guarded["shed"] == n_requests),
        "source": "measured wall time, Poisson open-loop overload on the "
                  "CPU backend (tiny transformer); all three runs share "
                  "compiled steps and request shapes",
    }


def bench_gateway(*, n_requests: int = 96, replicas: int = 3,
                  quick: bool = False, seed: int = 0) -> dict:
    """The gateway's two claims, measured over real sockets.

    **Routing** — a prefix-heavy open-loop trace (8 prompt families, each
    sharing a 3-block prefix) through the full network path: GatewayClient
    -> TCP -> Gateway -> targeted KV queues -> ReplicaWorker threads, once
    with prefix-hash routing and once with the random-routing control arm.
    Claim: p99 TTFT under hash routing beats random, because requests land
    where their prefix is already resident and prefill only pays for the
    uncached suffix.

    **Admission** — the same path at 2x the calibrated fleet capacity with
    per-request deadlines, SLO-feasibility admission vs the classic
    occupancy bound. Claim: feasibility goodput (ok verdicts/sec; the
    engine never lands a result past its deadline, so every ok IS within
    SLO) at least matches occupancy, while shedding infeasible work at the
    door with an explicit verdict instead of letting it rot in a queue.

    Honesty note: the engine here is the real ContinuousEngine over the
    real paged allocator, but the *step* is a stub whose prefill sleeps
    proportionally to the UNCACHED token count (non-null dest indices from
    the allocator). That models the prefill-compute saving that suffix-only
    prefill would give a real model; this repo's real prefill still
    recomputes shared spans (it skips only the K/V stores), so the TTFT
    win is a model of the mechanism, not a measurement of the tiny
    transformer. The sockets, wire protocol, routing, queues, claims,
    leases, and verdicts are all the real thing.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import threading

    import numpy as np

    from tpu_sandbox.gateway import FleetSpec, Gateway, GatewayClient
    from tpu_sandbox.models.transformer import TransformerConfig
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer
    from tpu_sandbox.serve.cache import CacheConfig
    from tpu_sandbox.serve.engine import ContinuousEngine, ServeConfig
    from tpu_sandbox.serve.replica import ReplicaWorker

    if quick:
        n_requests = min(n_requests, 24)
        replicas = min(replicas, 2)

    BLOCK = 8
    PREFIX_BLOCKS = 3
    PREFILL_TOKEN_S = 1.2e-3   # modeled per-uncached-token prefill cost
    DECODE_STEP_S = 0.8e-3     # modeled per-engine-step decode cost
    n_families = 4 if quick else 8
    mcfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                             n_layers=2, d_ff=64, max_len=128)
    ccfg = CacheConfig(num_blocks=48, block_size=BLOCK, max_blocks_per_seq=8)

    class _ModeledStep:
        """Stub step: next token = last + 1 mod vocab (deterministic, so
        requeue/hedge replays stay bitwise), prefill cost = uncached
        tokens (the allocator redirects resident-prefix positions to the
        null block, so their dest index is 0)."""

        buckets = (32,)
        vocab = 64

        def __init__(self):
            self.prefill = {b: self._prefill for b in self.buckets}

        def pick_bucket(self, plen):
            for b in self.buckets:
                if plen <= b:
                    return b
            raise ValueError(f"prompt of {plen} exceeds {self.buckets}")

        def _prefill(self, params, k, v, toks, dest, last):
            uncached = int(np.count_nonzero(np.asarray(dest)))
            time.sleep(PREFILL_TOKEN_S * uncached)
            toks = np.asarray(toks)
            logits = np.zeros((self.vocab,), np.float32)
            logits[(int(toks[0, int(last)]) + 1) % self.vocab] = 1.0
            return logits, k, v

        def decode(self, params, k, v, tokens, lengths, tables):
            time.sleep(DECODE_STEP_S)
            tokens = np.asarray(tokens)
            logits = np.zeros((tokens.shape[0], self.vocab), np.float32)
            for i in range(tokens.shape[0]):
                logits[i, (int(tokens[i, 0]) + 1) % self.vocab] = 1.0
            return logits, k, v

    rng = np.random.default_rng(seed)
    families = [[int(t) for t in rng.integers(1, 64, PREFIX_BLOCKS * BLOCK)]
                for _ in range(n_families)]

    def make_trace(mean_ia_s, tag):
        """Open-loop arrivals; each request = family prefix + fresh
        suffix, so chains collide exactly on the shared blocks."""
        offs = (np.zeros(n_requests) if mean_ia_s == 0.0
                else np.cumsum(rng.exponential(mean_ia_s, n_requests)))
        out = []
        for i in range(n_requests):
            fam = families[int(rng.integers(0, n_families))]
            suffix = [int(t) for t in
                      rng.integers(1, 64, int(rng.integers(4, 9)))]
            out.append((float(offs[i]), f"{tag}-{i}", fam + suffix, 4))
        return out

    def run(trace, *, policy, admission, deadline_s, rate_rps):
        """One fully isolated fleet: fresh store, replicas, gateway."""
        server = KVServer()
        kv = KVClient(port=server.port)
        stop = threading.Event()
        workers, threads, clones = [], [], []
        for i in range(replicas):
            wkv = kv.clone()
            clones.append(wkv)
            eng = ContinuousEngine(
                None,
                ServeConfig(model=mcfg, cache=ccfg, max_batch=4,
                            buckets=_ModeledStep.buckets, max_waiting=0),
                step=_ModeledStep())
            w = ReplicaWorker(wkv, eng, tag=f"r{i}", lease_ttl=1.0,
                              load_interval=0.05)
            workers.append(w)

            def loop(worker=w):
                while not stop.is_set():
                    worker.tick()
                    if worker.engine.idle:
                        time.sleep(0.001)

            t = threading.Thread(target=loop, daemon=True,
                                 name=f"bench-replica-{i}")
            threads.append(t)
            t.start()
        spec = FleetSpec(block_size=BLOCK, service_rate_rps=rate_rps,
                         occupancy_bound=8)
        gw = Gateway(kv, [spec], admission=admission, policy=policy,
                     policy_seed=seed, refresh_min_s=0.01,
                     max_report_age_s=2.0).start()
        client = GatewayClient(gw.port, deadline_s=deadline_s,
                               max_retries=0)
        time.sleep(0.2)  # first load reports land before the trace starts
        try:
            t0 = time.monotonic()
            admitted, refused = [], []
            for off, rid, prompt, max_new in trace:
                now = time.monotonic() - t0
                if off > now:
                    time.sleep(off - now)
                ok = client.submit(rid, prompt, max_new)
                (admitted if ok else refused).append(rid)
            verdicts = {rid: _terminal_verdict(client, rid, 120.0)
                        for rid in admitted}
            total = time.monotonic() - t0
            ok_ttfts = [v["ttft_s"] for v in verdicts.values()
                        if v.get("verdict") == "ok"]
            n_ok = len(ok_ttfts)
            # audit: every rid — admitted, engine-shed, or door-shed —
            # has exactly one terminal verdict (done marker still == 1)
            results = set(kv.keys("serve/result/"))
            audit = all(
                f"serve/result/{rid}" in results
                and kv.try_get(f"serve/done/{rid}") == b"1"
                for rid in admitted + refused
            ) and len(results) == len(trace)
            ttft = np.array(ok_ttfts or [0.0])
            return {
                "submitted": len(trace),
                "admitted": len(admitted),
                "door_shed": len(refused),
                "completed_ok": n_ok,
                "engine_shed": len(admitted) - n_ok,
                "goodput_rps": round(n_ok / total, 1),
                "p50_ttft_ms": round(float(np.percentile(ttft, 50)) * 1e3,
                                     2),
                "p99_ttft_ms": round(float(np.percentile(ttft, 99)) * 1e3,
                                     2),
                "routed_prefix": gw.stats.routed_prefix,
                "routed_balance": gw.stats.routed_balance,
                "routed_shared": gw.stats.routed_shared,
                "total_sec": round(total, 3),
                "verdict_audit_ok": bool(audit),
            }
        finally:
            client.close()
            gw.close()
            stop.set()
            for t in threads:
                t.join(timeout=10.0)
            for w in workers:
                w.engine.drain_to_requests()  # leak-fixture hygiene
            for c in clones:
                c.close()
            kv.close()
            server.stop()

    # calibrate to THIS box: closed-loop (all arrivals at t=0), no door,
    # prefix routing -> the fleet's aggregate service rate
    calib = run(make_trace(0.0, "c"), policy="prefix", admission="none",
                deadline_s=None, rate_rps=1.0)
    fleet_rps = max(calib["completed_ok"] / calib["total_sec"], 1.0)
    replica_rps = fleet_rps / replicas

    # routing arms: moderate load (0.7x capacity) so queueing noise does
    # not swamp the prefill saving the arms differ by
    routed = run(make_trace(1.0 / (0.7 * fleet_rps), "p"),
                 policy="prefix", admission="none", deadline_s=None,
                 rate_rps=replica_rps)
    randomed = run(make_trace(1.0 / (0.7 * fleet_rps), "r"),
                   policy="random", admission="none", deadline_s=None,
                   rate_rps=replica_rps)

    # admission arms: 2x overload, deadline sized to ~12 requests of
    # residence on one replica — feasibility sheds the overflow at the
    # door, occupancy admits by queue depth and lets deadlines burn
    deadline_s = 12.0 / replica_rps
    feasible = run(make_trace(1.0 / (2.0 * fleet_rps), "f"),
                   policy="prefix", admission="feasible",
                   deadline_s=deadline_s, rate_rps=replica_rps)
    occupancy = run(make_trace(1.0 / (2.0 * fleet_rps), "o"),
                    policy="prefix", admission="occupancy",
                    deadline_s=deadline_s, rate_rps=replica_rps)

    return {
        "metric": "gateway",
        "unit": "ms TTFT; ok verdicts/sec",
        "requests_per_run": n_requests,
        "replicas": replicas,
        "calibrated_fleet_rps": round(fleet_rps, 1),
        "deadline_ms": round(deadline_s * 1e3, 2),
        "routing_prefix": routed,
        "routing_random": randomed,
        "admission_feasible": feasible,
        "admission_occupancy": occupancy,
        # the tentpole claims
        "prefix_beats_random_p99": bool(
            routed["p99_ttft_ms"] < randomed["p99_ttft_ms"]),
        "prefix_ttft_speedup": round(
            randomed["p99_ttft_ms"] / max(routed["p99_ttft_ms"], 1e-6), 2),
        "feasible_goodput_holds": bool(
            feasible["goodput_rps"] >= occupancy["goodput_rps"]),
        "every_request_verdicted": bool(all(
            r["verdict_audit_ok"]
            for r in (calib, routed, randomed, feasible, occupancy))),
        "source": "measured wall time over real sockets (gateway wire "
                  "protocol, targeted KV queues, replica threads); "
                  "prefill cost modeled as sleep proportional to "
                  "uncached-token count from the real paged allocator",
    }


def bench_chaos(*, quick: bool = False, seed: int = 0) -> dict:
    """HA front-door receipts: seeded chaos campaigns against a real
    multi-gateway fleet, TLS on every external wire.

    **Zero-loss under SIGKILL** — N real gateway *processes* (the
    ``python -m tpu_sandbox.gateway`` entrypoint, TLS certs from
    tests/fixtures/tls, shared-secret hello inside the channel) front a
    replica-thread fleet; a seeded campaign replays a canonical workload
    trace (obs/workload) and SIGKILLs the connected gateway mid-load.
    Claim: the failover client loses zero requests, every rid reaches
    exactly one terminal verdict (claim audit), and the failover cost is
    visible in submit p99 but bounded.

    **Seeded matrix** — >= 3 distinct seeded campaigns drawn by
    runtime/chaos.build_schedule over the gateway-kill / shed-storm /
    replica-stall families, each ending green on the same invariants;
    one seed replayed against a fresh fleet must produce a byte-identical
    claim audit (the determinism receipt).

    **Tracediff gate** — the SIGKILL campaign's critical-path profile is
    gated by tools/tracediff.py against a fault-free control over the
    same trace: losing a gateway may cost availability blips at the
    door, but the per-request serve path (prefill/decode/queue) must not
    regress.

    Honesty note: replicas are in-process threads over the real engine
    with a sleep-modeled step (bench_gateway's stub); gateways are real
    processes and the SIGKILL is a real ``os.kill``. The wire is TLS end
    to end — a plaintext connect must be refused with a clean close and
    show up in the surviving gateway's handshake-failure counter.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import contextlib
    import signal as _signal
    import socket as _socket
    import struct
    import tempfile
    import threading

    import numpy as np

    from tpu_sandbox.gateway import (FleetSpec, GatewayClient,
                                     make_client_ssl_context)
    from tpu_sandbox.gateway import wire as gwire
    from tpu_sandbox.gateway.server import live_gateway_endpoints
    from tpu_sandbox.models.transformer import TransformerConfig
    from tpu_sandbox.obs import (ENV_TRACE_DIR, collect, critpath,
                                 get_recorder, reset_recorder, workload)
    from tpu_sandbox.runtime.chaos import (ChaosCampaign, ChaosFault,
                                           build_schedule,
                                           check_alert_claims, prefix_probe)
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer
    from tpu_sandbox.serve.cache import CacheConfig, chain_digest
    from tpu_sandbox.serve.engine import ContinuousEngine, ServeConfig
    from tpu_sandbox.serve.replica import ReplicaWorker, read_load_reports

    repo = os.path.dirname(os.path.abspath(__file__))
    tlsdir = os.path.join(repo, "tests", "fixtures", "tls")
    cert = os.path.join(tlsdir, "server.pem")
    key = os.path.join(tlsdir, "server.key")
    ca = os.path.join(tlsdir, "ca.pem")
    TOKEN = "bench-chaos-secret"

    n_gateways = 2 if quick else 3
    n_replicas = 2 if quick else 3
    # moderate utilization: the gate compares per-request serve segments
    # ctrl-vs-kill, which only pairs cleanly when arrivals don't saturate
    # the host (post-failover bunching would deepen batches and inflate
    # every segment on a loaded box)
    n_requests = 12 if quick else 48
    duration_s = 0.8 if quick else 4.0
    matrix_seeds = [seed + 11, seed + 22] if quick \
        else [seed + 11, seed + 22, seed + 33]

    BLOCK = 8
    PREFILL_TOKEN_S = 0.4e-3
    DECODE_STEP_S = 0.8e-3
    mcfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                             n_layers=2, d_ff=64, max_len=128)
    ccfg = CacheConfig(num_blocks=64, block_size=BLOCK, max_blocks_per_seq=8)

    class _ModeledStep:
        buckets = (32,)
        vocab = 64

        def __init__(self):
            self.prefill = {b: self._prefill for b in self.buckets}

        def pick_bucket(self, plen):
            for b in self.buckets:
                if plen <= b:
                    return b
            raise ValueError(f"prompt of {plen} exceeds {self.buckets}")

        def _prefill(self, params, k, v, toks, dest, last):
            time.sleep(PREFILL_TOKEN_S
                       * int(np.count_nonzero(np.asarray(dest))))
            toks = np.asarray(toks)
            logits = np.zeros((self.vocab,), np.float32)
            logits[(int(toks[0, int(last)]) + 1) % self.vocab] = 1.0
            return logits, k, v

        def decode(self, params, k, v, tokens, lengths, tables):
            time.sleep(DECODE_STEP_S)
            tokens = np.asarray(tokens)
            logits = np.zeros((tokens.shape[0], self.vocab), np.float32)
            for i in range(tokens.shape[0]):
                logits[i, (int(tokens[i, 0]) + 1) % self.vocab] = 1.0
            return logits, k, v

    @contextlib.contextmanager
    def recorder_arm(trace_dir):
        prior = os.environ.pop(ENV_TRACE_DIR, None)
        if trace_dir is not None:
            os.environ[ENV_TRACE_DIR] = trace_dir
        reset_recorder()
        try:
            yield
        finally:
            get_recorder().flush()
            if prior is None:
                os.environ.pop(ENV_TRACE_DIR, None)
            else:
                os.environ[ENV_TRACE_DIR] = prior
            reset_recorder()

    def spawn_gateway(kv_port, gid):
        """One real gateway process, TLS-only, parsed for its port."""
        proc = subprocess.Popen(
            [sys.executable, "-m", "tpu_sandbox.gateway",
             "--kv-port", str(kv_port), "--gateway-id", gid,
             "--token", TOKEN, "--admission", "none",
             "--policy", "prefix",
             "--tls-cert", cert, "--tls-key", key],
            env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        line = proc.stdout.readline()
        if "listening on" not in line or "tls=on" not in line:
            proc.kill()
            raise RuntimeError(f"gateway {gid} failed to start: {line!r}")
        port = int(line.split("listening on ")[1]
                   .split()[0].rsplit(":", 1)[1])
        return proc, port

    def plaintext_probe(port):
        """A cleartext frame against the TLS listener: the server must
        close that connection cleanly (EOF, no bytes served back)."""
        s = _socket.create_connection(("127.0.0.1", port), timeout=5.0)
        try:
            s.sendall(struct.pack("!BI", gwire.OP_HELLO, 2) + b"{}")
            s.settimeout(5.0)
            try:
                return s.recv(64) == b""
            except (ConnectionError, OSError):
                return True  # reset is a close too, just less polite
        finally:
            s.close()

    def run_campaign(campaign_seed, *, schedule_for, trace_dir=None,
                     probe=False, plaintext=False):
        """One fully isolated fleet + one seeded campaign against it."""
        server = KVServer()
        kv = KVClient(port=server.port)
        stop = threading.Event()
        workers, threads, clones = [], [], []
        for i in range(n_replicas):
            wkv = kv.clone()
            clones.append(wkv)
            eng = ContinuousEngine(
                None,
                ServeConfig(model=mcfg, cache=ccfg, max_batch=4,
                            buckets=_ModeledStep.buckets, max_waiting=0),
                step=_ModeledStep())
            w = ReplicaWorker(wkv, eng, tag=f"r{i}", lease_ttl=1.0,
                              load_interval=0.05)
            workers.append(w)

            def loop(worker=w):
                while not stop.is_set():
                    worker.tick()
                    if worker.engine.idle:
                        time.sleep(0.001)

            t = threading.Thread(target=loop, daemon=True,
                                 name=f"chaos-replica-{i}")
            threads.append(t)
            t.start()
        procs = {}
        endpoints = []
        for i in range(n_gateways):
            gid = f"gw{i}"
            proc, port = spawn_gateway(server.port, gid)
            procs[gid] = proc
            endpoints.append(("127.0.0.1", port))
        trace = workload.synthesize(campaign_seed, n_requests,
                                    duration_s=duration_s,
                                    prompt_tokens=(8, 24),
                                    decode_tokens=(2, 6))
        schedule = schedule_for(campaign_seed)
        client = GatewayClient(endpoints=list(endpoints), token=TOKEN,
                               tls=make_client_ssl_context(ca),
                               backoff_base=0.02)
        submit_s = []

        def door(rid, prompt, max_new):
            t0 = time.monotonic()
            ok = client.submit(rid, prompt, max_new)
            submit_s.append(time.monotonic() - t0)
            return ok

        def sigkill(gid):
            procs[gid].send_signal(_signal.SIGKILL)

        out = {}
        cm = recorder_arm(trace_dir) if trace_dir is not None \
            else contextlib.nullcontext()
        try:
            time.sleep(0.3)  # first load reports + hb leases land
            out["live_gateways"] = len(live_gateway_endpoints(kv))
            with cm:
                campaign = ChaosCampaign(
                    kv, trace, door, seed=campaign_seed,
                    schedule=schedule,
                    hooks={"kill_gateway": sigkill},
                    block_size=BLOCK, verdict_timeout=180.0)
                res = campaign.run()
            sub = np.array(submit_s or [0.0])
            out.update(
                seed=campaign_seed, submitted=res.submitted,
                admitted=res.admitted, retried=res.retried,
                lost=len(res.lost),
                verdicts_ok=sum(1 for v in res.verdicts.values()
                                if v["verdict"] == "ok"),
                fired=[f["action"] for f in res.fired],
                failovers=client.stats.failovers,
                submit_p50_ms=round(float(np.percentile(sub, 50)) * 1e3, 2),
                submit_p99_ms=round(float(np.percentile(sub, 99)) * 1e3, 2),
                exactly_once_ok=res.ok,
                alert_claims_ok=check_alert_claims(kv) == [],
                audit=res.audit_bytes(),
            )
            if plaintext:
                # the survivor the client is parked on keeps serving;
                # a plaintext probe against it is refused cleanly
                host, port = client.endpoint
                out["plaintext_refused"] = plaintext_probe(port)
                before = client.gateway_stats()["stats"]
                out["tls_handshake_failures"] = int(
                    before.get("tls_handshake_failures", 0))
                out["serves_after_plaintext"] = bool(
                    client.gateway_stats()["admission"] == "none")
            if probe:
                row = dict(workload.replay_order(trace)[0])
                row["prompt_tokens"] = max(int(row["prompt_tokens"]),
                                           BLOCK)
                prompt = campaign.prompt_for(row)
                head = chain_digest(prompt[:BLOCK], BLOCK)[0]
                deadline = time.monotonic() + 30.0
                while time.monotonic() < deadline:
                    if any(head in r.get("prefix_digest", ())
                           for r in read_load_reports(kv).values()):
                        break
                    time.sleep(0.02)
                rid = f"probe-{campaign_seed}"
                out["prefix_probe_routed"] = bool(
                    prefix_probe(client, prompt, rid))
                client.result(rid, timeout=60.0)
        finally:
            client.close()
            for proc in procs.values():
                if proc.poll() is None:
                    proc.send_signal(_signal.SIGTERM)
            for proc in procs.values():
                try:
                    proc.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=10)
                proc.stdout.close()
            stop.set()
            for t in threads:
                t.join(timeout=10.0)
            for w in workers:
                w.engine.drain_to_requests()
            for c in clones:
                c.close()
            kv.close()
            server.stop()
        return out

    kill_seed = seed + 1
    mid_kill = [ChaosFault(at_s=round(duration_s * 0.4, 6),
                           action="kill_gateway", target="gw0")]

    def matrix_schedule(s):
        return mid_kill + build_schedule(s, duration_s=duration_s, targets={
            "shed_storm": [f"r{i}" for i in range(n_replicas)],
            "stall_replica": [f"r{i}:0.2" for i in range(n_replicas)],
        }, n_faults=3)

    dirs = {arm: tempfile.mkdtemp(prefix=f"chaos-{arm}-")
            for arm in ("ctrl", "kill")}
    # fault-free control over the same trace, recorded for the gate
    ctrl = run_campaign(kill_seed, schedule_for=lambda s: [],
                        trace_dir=dirs["ctrl"])
    # the headline arm: SIGKILL the connected gateway mid-load, recorded
    killarm = run_campaign(kill_seed, schedule_for=lambda s: mid_kill,
                           trace_dir=dirs["kill"], probe=True,
                           plaintext=True)
    # determinism receipt: same seed, fresh fleet, byte-identical audit
    killarm_replay = run_campaign(kill_seed, schedule_for=lambda s: mid_kill)
    # the seeded matrix: full fault families, distinct seeds
    matrix = [run_campaign(s, schedule_for=matrix_schedule)
              for s in matrix_seeds]

    for arm, d in dirs.items():
        analysis = critpath.analyze(collect.load_merged(d))
        critpath.save_profile(analysis["profile"],
                              os.path.join(d, "critpath_profile.json"))
    td = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "tracediff.py"),
         os.path.join(dirs["ctrl"], "critpath_profile.json"),
         os.path.join(dirs["kill"], "critpath_profile.json"),
         "--min-share", "0.05"],
        capture_output=True, text=True)

    def green(c):
        return bool(c["exactly_once_ok"] and c["lost"] == 0
                    and c["verdicts_ok"] == c["submitted"]
                    and c["alert_claims_ok"])

    audit_identical = killarm["audit"] == killarm_replay["audit"]
    for c in (ctrl, killarm, killarm_replay, *matrix):
        c.pop("audit", None)
    return {
        "metric": "chaos",
        "unit": "requests lost; campaigns green",
        "gateways": n_gateways,
        "replicas": n_replicas,
        "requests_per_campaign": n_requests,
        "control": ctrl,
        "sigkill_campaign": killarm,
        "seeded_campaigns": matrix,
        "campaigns_green": sum(green(c) for c in (killarm, *matrix)),
        "all_campaigns_green": bool(all(green(c)
                                        for c in (killarm, *matrix))),
        "sigkill_zero_loss": bool(killarm["lost"] == 0
                                  and killarm["failovers"] >= 1),
        "audit_replay_identical": bool(audit_identical),
        "tls_plaintext_refused": bool(killarm.get("plaintext_refused")),
        "tls_handshake_failures_counted": bool(
            killarm.get("tls_handshake_failures", 0) >= 1),
        "prefix_probe_routed": bool(killarm.get("prefix_probe_routed")),
        "tracediff_gate_exit": td.returncode,
        "tracediff_gate_ok": bool(td.returncode == 0),
        "source": "real gateway processes (TLS wire, shared-secret hello) "
                  "SIGKILLed mid-load by os signal; replica threads over "
                  "the real engine with sleep-modeled step; claim audit "
                  "read straight from the KV store; tracediff run as the "
                  "committed CLI on saved critpath profiles",
    }


def bench_obs(*, quick: bool = False, seed: int = 0) -> dict:
    """Flight-recorder overhead receipts: is tracing cheap enough to
    leave ON?

    Three measurements, all chipless:

    1. **Step-time overhead** — a jitted 512x512 matmul step timed with
       the trainer's per-step instrumentation (one retrospective
       ``complete("train:step")`` per step), recorder off vs on. The
       claim: <= 3% regression.
    2. **Gateway p99 TTFT delta** — a self-contained 2-replica modeled
       fleet (the bench_gateway stub step: prefill cost proportional to
       uncached tokens) run off vs on, p99 TTFT pooled over repeats. The
       claim: <= 5% regression. The on-arm also yields the trace-
       completeness receipt: every non-shed request leaves one connected
       submit->...->verdict chain.
    3. **Artifacts** — the on-arm logs must export as valid Chrome
       trace-event JSON, and a sample per-request waterfall is committed
       into the round record.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import contextlib
    import statistics
    import tempfile
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_sandbox.gateway import FleetSpec, Gateway, GatewayClient
    from tpu_sandbox.models.transformer import TransformerConfig
    from tpu_sandbox.obs import (ENV_TRACE_DIR, collect, get_recorder,
                                 reset_recorder)
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer
    from tpu_sandbox.serve.cache import CacheConfig
    from tpu_sandbox.serve.engine import ContinuousEngine, ServeConfig
    from tpu_sandbox.serve.replica import ReplicaWorker

    @contextlib.contextmanager
    def recorder_arm(trace_dir):
        """Point the process-global recorder at ``trace_dir`` (or disable
        it for the control arm) for the duration."""
        prior = os.environ.pop(ENV_TRACE_DIR, None)
        if trace_dir is not None:
            os.environ[ENV_TRACE_DIR] = trace_dir
        reset_recorder()
        try:
            yield
        finally:
            get_recorder().flush()
            if prior is None:
                os.environ.pop(ENV_TRACE_DIR, None)
            else:
                os.environ[ENV_TRACE_DIR] = prior
            reset_recorder()

    # -- 1. step-time overhead ------------------------------------------------
    # Paired design: run-to-run drift on a shared CPU box dwarfs the
    # ~8us emit cost, so each round times an off arm and an on arm
    # back-to-back and the receipt is the MEDIAN of per-round deltas —
    # drift cancels within a round instead of masquerading as overhead.
    n_steps = 30 if quick else 80
    rounds = 6 if quick else 16
    x = jnp.ones((512, 512), jnp.float32)
    step = jax.jit(lambda a: a @ a / 512.0)
    step(x).block_until_ready()  # compile outside both arms

    def run_steps():
        rec = get_recorder()
        times = []
        for _ in range(n_steps):
            t0 = time.monotonic()
            step(x).block_until_ready()
            rec.complete("train:step", t0)
            times.append(time.monotonic() - t0)
        return statistics.median(times)

    run_steps()  # warm the loop shape itself
    step_dir = tempfile.mkdtemp(prefix="obs-step-")
    offs, deltas = [], []
    step_events = 0
    for _ in range(rounds):
        with recorder_arm(None):
            off = run_steps()
        with recorder_arm(step_dir):
            on = run_steps()
            step_events += get_recorder().stats()["events"]
        offs.append(off)
        deltas.append(on - off)
    step_off = statistics.median(offs)
    step_delta = statistics.median(deltas)
    step_overhead = step_delta / step_off

    # -- 2. gateway p99 TTFT delta -------------------------------------------
    BLOCK = 8
    PREFILL_TOKEN_S = 1.2e-3
    DECODE_STEP_S = 0.8e-3
    n_requests = 16 if quick else 48
    repeats = 2 if quick else 3
    mcfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                             n_layers=2, d_ff=64, max_len=128)
    ccfg = CacheConfig(num_blocks=48, block_size=BLOCK, max_blocks_per_seq=8)

    class _ModeledStep:
        buckets = (32,)
        vocab = 64

        def __init__(self):
            self.prefill = {b: self._prefill for b in self.buckets}

        def pick_bucket(self, plen):
            for b in self.buckets:
                if plen <= b:
                    return b
            raise ValueError(f"prompt of {plen} exceeds {self.buckets}")

        def _prefill(self, params, k, v, toks, dest, last):
            uncached = int(np.count_nonzero(np.asarray(dest)))
            time.sleep(PREFILL_TOKEN_S * uncached)
            toks = np.asarray(toks)
            logits = np.zeros((self.vocab,), np.float32)
            logits[(int(toks[0, int(last)]) + 1) % self.vocab] = 1.0
            return logits, k, v

        def decode(self, params, k, v, tokens, lengths, tables):
            time.sleep(DECODE_STEP_S)
            tokens = np.asarray(tokens)
            logits = np.zeros((tokens.shape[0], self.vocab), np.float32)
            for i in range(tokens.shape[0]):
                logits[i, (int(tokens[i, 0]) + 1) % self.vocab] = 1.0
            return logits, k, v

    rng = np.random.default_rng(seed)
    prefix = [int(t) for t in rng.integers(1, 64, 2 * BLOCK)]

    def run_fleet(tag):
        """One isolated 2-replica fleet pass; returns ok TTFTs (s)."""
        server = KVServer()
        kv = KVClient(port=server.port)
        stop = threading.Event()
        workers, threads, clones = [], [], []
        for i in range(2):
            wkv = kv.clone()
            clones.append(wkv)
            eng = ContinuousEngine(
                None,
                ServeConfig(model=mcfg, cache=ccfg, max_batch=4,
                            buckets=_ModeledStep.buckets, max_waiting=0),
                step=_ModeledStep())
            w = ReplicaWorker(wkv, eng, tag=f"{tag}{i}", lease_ttl=1.0,
                              load_interval=0.05)
            workers.append(w)

            def loop(worker=w):
                while not stop.is_set():
                    worker.tick()
                    if worker.engine.idle:
                        time.sleep(0.001)

            t = threading.Thread(target=loop, daemon=True,
                                 name=f"obs-replica-{tag}{i}")
            threads.append(t)
            t.start()
        gw = Gateway(kv, [FleetSpec(block_size=BLOCK)], admission="none",
                     refresh_min_s=0.01, max_report_age_s=2.0).start()
        client = GatewayClient(gw.port, max_retries=0)
        time.sleep(0.2)
        try:
            offs = np.cumsum(rng.exponential(0.03, n_requests))
            t0 = time.monotonic()
            rids = []
            for i in range(n_requests):
                now = time.monotonic() - t0
                if offs[i] > now:
                    time.sleep(offs[i] - now)
                rid = f"{tag}-{i}"
                suffix = [int(t) for t in
                          rng.integers(1, 64, int(rng.integers(4, 9)))]
                if client.submit(rid, prefix + suffix, 4):
                    rids.append(rid)
            verdicts = [_terminal_verdict(client, rid, 120.0)
                        for rid in rids]
            return [v["ttft_s"] for v in verdicts
                    if v.get("verdict") == "ok"]
        finally:
            client.close()
            gw.close()
            stop.set()
            for t in threads:
                t.join(timeout=10.0)
            for w in workers:
                w.engine.drain_to_requests()
            for c in clones:
                c.close()
            kv.close()
            server.stop()

    # same paired discipline as the step arm: one discarded warmup run
    # (cold sockets/threads), then alternating off/on passes
    with recorder_arm(None):
        run_fleet("warm")
    gw_dir = tempfile.mkdtemp(prefix="obs-gw-")
    ttfts_off, ttfts_on = [], []
    for r in range(repeats):
        with recorder_arm(None):
            ttfts_off.extend(run_fleet(f"off{r}"))
        with recorder_arm(gw_dir):
            ttfts_on.extend(run_fleet(f"on{r}"))
    p99_off = float(np.percentile(ttfts_off, 99))
    p99_on = float(np.percentile(ttfts_on, 99))
    p99_delta = (p99_on - p99_off) / p99_off

    # -- 3. artifacts from the on-arm logs ------------------------------------
    merged = collect.load_merged(gw_dir)
    chains = collect.trace_chains(merged)
    checks = [collect.chain_check(rs) for rs in chains.values()]
    full = sum(1 for c in checks
               if {"submit", "route", "enqueue", "claim", "admit",
                   "decode", "verdict"} <= set(c["names"]))
    doc = json.loads(json.dumps(collect.to_chrome_trace(merged)))
    chrome_ok = (doc["displayTimeUnit"] == "ms"
                 and len(doc["traceEvents"]) > len(merged))
    waterfall = collect.format_waterfall(
        collect.request_waterfall(merged, rid="on0-0"))

    return {
        "metric": "obs",
        "unit": "fractional overhead, recorder on vs off",
        "step": {
            "steps_per_arm": n_steps,
            "paired_rounds": rounds,
            "off_ms": round(step_off * 1e3, 4),
            "on_ms": round((step_off + step_delta) * 1e3, 4),
            "overhead_frac": round(step_overhead, 4),
            "events_recorded": step_events,
        },
        "gateway": {
            "requests_per_arm": n_requests * repeats,
            "ok_off": len(ttfts_off),
            "ok_on": len(ttfts_on),
            "p99_ttft_off_ms": round(p99_off * 1e3, 2),
            "p99_ttft_on_ms": round(p99_on * 1e3, 2),
            "p99_delta_frac": round(p99_delta, 4),
        },
        "trace": {
            "traces": len(chains),
            "full_chains": full,
            "connected_frac": round(
                sum(1 for c in checks if c["connected"]) / len(checks), 4)
            if checks else None,
        },
        "chrome_trace_valid": bool(chrome_ok),
        "sample_waterfall": waterfall.splitlines(),
        # the tentpole claims: tracing is cheap enough to leave on
        "step_overhead_ok": bool(step_overhead <= 0.03),
        "gateway_p99_ok": bool(p99_delta <= 0.05),
        "source": "measured wall time, recorder-off vs recorder-on arms; "
                  "gateway fleet modeled as in bench_gateway (real "
                  "sockets/queues/engine, sleep-modeled step)",
    }


def bench_critpath(*, quick: bool = False, seed: int = 0) -> dict:
    """Trace-analytics receipts: does critical-path attribution explain
    the wall clock, does tracediff gate real regressions (and only real
    ones), and does the online pipeline-bubble gauge agree with the
    trace?

    Four measurements, all chipless:

    1. **Attribution coverage** — a gateway-served open-loop modeled
       fleet (real sockets/KV/engine, sleep-modeled step) run with the
       recorder on; every served request's causal critical path is
       attributed to named segments. The claim: >= 95% of request wall
       attributed, residue reported as ``unattributed``.
    2. **Regression gating** — the same fleet rerun twice: once
       identically, once with decode modeled ~20% slower.
       ``tools/tracediff.py`` must flag the slowdown (exit 1, decode
       named) while passing the identical rerun (exit 0) — the noise
       floor separates real regressions from run-to-run jitter.
    3. **Online bubble accounting** — a 2-stage / 4-microbatch 1F1B
       pipeline over sleep-modeled stage programs. The online
       ``mpmd.bubble_fraction`` gauge (read back through the tsdb
       ring), the offline trace-derived bubble, and the analytic
       ``(S-1)/(M+S-1) = 0.2`` (BENCH_r07's offline measurement) must
       agree within 5 points.
    4. **Workload export** — the control run's trace exports as a
       canonical replayable workload trace that round-trips
       byte-identically through dumps -> loads -> dumps.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import contextlib
    import statistics
    import tempfile
    import threading

    import numpy as np

    from tpu_sandbox.gateway import FleetSpec, Gateway, GatewayClient
    from tpu_sandbox.models.transformer import TransformerConfig
    from tpu_sandbox.mpmd.driver import StageWorker
    from tpu_sandbox.mpmd.transport import LocalTransport
    from tpu_sandbox.obs import (ENV_TRACE_DIR, collect, critpath,
                                 get_recorder, reset_recorder, tsdb,
                                 workload)
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer
    from tpu_sandbox.serve.cache import CacheConfig
    from tpu_sandbox.serve.engine import ContinuousEngine, ServeConfig
    from tpu_sandbox.serve.replica import ReplicaWorker

    @contextlib.contextmanager
    def recorder_arm(trace_dir):
        prior = os.environ.pop(ENV_TRACE_DIR, None)
        if trace_dir is not None:
            os.environ[ENV_TRACE_DIR] = trace_dir
        reset_recorder()
        try:
            yield
        finally:
            get_recorder().flush()
            if prior is None:
                os.environ.pop(ENV_TRACE_DIR, None)
            else:
                os.environ[ENV_TRACE_DIR] = prior
            reset_recorder()

    # -- 1+2. gateway fleet: control / identical rerun / slow decode ---------
    BLOCK = 8
    PREFILL_TOKEN_S = 0.4e-3
    DECODE_STEP_S = 10e-3
    n_requests = 12 if quick else 32
    max_new = 8
    mcfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                             n_layers=2, d_ff=64, max_len=128)
    ccfg = CacheConfig(num_blocks=48, block_size=BLOCK, max_blocks_per_seq=8)

    class _ModeledStep:
        buckets = (32,)
        vocab = 64

        def __init__(self, decode_step_s=DECODE_STEP_S):
            self.decode_step_s = decode_step_s
            self.prefill = {b: self._prefill for b in self.buckets}

        def pick_bucket(self, plen):
            for b in self.buckets:
                if plen <= b:
                    return b
            raise ValueError(f"prompt of {plen} exceeds {self.buckets}")

        def _prefill(self, params, k, v, toks, dest, last):
            uncached = int(np.count_nonzero(np.asarray(dest)))
            time.sleep(PREFILL_TOKEN_S * uncached)
            toks = np.asarray(toks)
            logits = np.zeros((self.vocab,), np.float32)
            logits[(int(toks[0, int(last)]) + 1) % self.vocab] = 1.0
            return logits, k, v

        def decode(self, params, k, v, tokens, lengths, tables):
            time.sleep(self.decode_step_s)
            tokens = np.asarray(tokens)
            logits = np.zeros((tokens.shape[0], self.vocab), np.float32)
            for i in range(tokens.shape[0]):
                logits[i, (int(tokens[i, 0]) + 1) % self.vocab] = 1.0
            return logits, k, v

    prefix = [int(t) for t in
              np.random.default_rng(seed).integers(1, 64, 2 * BLOCK)]

    def run_fleet(tag, decode_step_s=DECODE_STEP_S):
        """One isolated 2-replica fleet pass. A fresh rng seeded the
        same way every pass: identical arrivals/prompts, so profiles
        pair request-for-request and only the modeled costs differ."""
        rng = np.random.default_rng(seed + 1)
        server = KVServer()
        kv = KVClient(port=server.port)
        stop = threading.Event()
        workers, threads, clones = [], [], []
        for i in range(2):
            wkv = kv.clone()
            clones.append(wkv)
            eng = ContinuousEngine(
                None,
                ServeConfig(model=mcfg, cache=ccfg, max_batch=4,
                            buckets=_ModeledStep.buckets, max_waiting=0),
                step=_ModeledStep(decode_step_s))
            w = ReplicaWorker(wkv, eng, tag=f"{tag}{i}", lease_ttl=1.0,
                              load_interval=0.05)
            workers.append(w)

            def loop(worker=w):
                while not stop.is_set():
                    worker.tick()
                    if worker.engine.idle:
                        time.sleep(0.001)

            t = threading.Thread(target=loop, daemon=True,
                                 name=f"critpath-replica-{tag}{i}")
            threads.append(t)
            t.start()
        gw = Gateway(kv, [FleetSpec(block_size=BLOCK)], admission="none",
                     refresh_min_s=0.01, max_report_age_s=2.0).start()
        client = GatewayClient(gw.port, max_retries=0)
        time.sleep(0.2)
        try:
            offs = np.cumsum(rng.exponential(0.12, n_requests))
            t0 = time.monotonic()
            rids = []
            for i in range(n_requests):
                now = time.monotonic() - t0
                if offs[i] > now:
                    time.sleep(offs[i] - now)
                rid = f"{tag}-{i}"
                suffix = [int(t) for t in
                          rng.integers(1, 64, int(rng.integers(4, 9)))]
                if client.submit(rid, prefix + suffix, max_new):
                    rids.append(rid)
            for rid in rids:
                _terminal_verdict(client, rid, 120.0)
        finally:
            client.close()
            gw.close()
            stop.set()
            for t in threads:
                t.join(timeout=10.0)
            for w in workers:
                w.engine.drain_to_requests()
            for c in clones:
                c.close()
            kv.close()
            server.stop()

    with recorder_arm(None):
        run_fleet("warm")  # cold sockets/threads, discarded
    dirs = {arm: tempfile.mkdtemp(prefix=f"critpath-{arm}-")
            for arm in ("ctrl", "same", "slow")}
    with recorder_arm(dirs["ctrl"]):
        run_fleet("ctl")
    with recorder_arm(dirs["same"]):
        run_fleet("sam")
    with recorder_arm(dirs["slow"]):
        run_fleet("slo", decode_step_s=DECODE_STEP_S * 1.2)

    profiles = {}
    merged_ctrl = None
    for arm, d in dirs.items():
        merged = collect.load_merged(d)
        if arm == "ctrl":
            merged_ctrl = merged
        analysis = critpath.analyze(merged)
        profiles[arm] = analysis["profile"]
        critpath.save_profile(
            analysis["profile"], os.path.join(d, "critpath_profile.json"))
    prof = profiles["ctrl"]
    covs = [r["coverage"] for r in critpath.analyze(merged_ctrl)["requests"]]
    frac_covered = (sum(1 for c in covs if c >= critpath.COVERAGE_TARGET)
                    / len(covs)) if covs else 0.0

    # the gate itself, end to end: the committed CLI on the saved profiles
    td = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "tools", "tracediff.py")
    # gate on segments carrying >= 5% of wall: the modeled workload's
    # signal lives in decode/prefill; the sub-3% control segments
    # (route, claim, queue_wait) jitter with host scheduling noise
    MIN_SHARE = 0.05
    gate = {}
    for arm in ("same", "slow"):
        r = subprocess.run(
            [sys.executable, td,
             os.path.join(dirs["ctrl"], "critpath_profile.json"),
             os.path.join(dirs[arm], "critpath_profile.json"),
             "--min-share", str(MIN_SHARE)],
            capture_output=True, text=True)
        gate[arm] = r.returncode
    cmp_slow = critpath.compare_profiles(profiles["ctrl"], profiles["slow"],
                                         min_share=MIN_SHARE)
    cmp_same = critpath.compare_profiles(profiles["ctrl"], profiles["same"],
                                         min_share=MIN_SHARE)
    decode_row = next((r for r in cmp_slow["segments"]
                       if r["segment"] == "decode"), {})

    # -- 3. online vs offline vs analytic pipeline bubble --------------------
    S, M = 2, 4
    OP_S = 8e-3
    mpmd_steps = 5 if quick else 8

    class _StubStage:
        """Sleep-modeled stage program with uniform op cost, so the
        1F1B schedule's measured bubble lands on the analytic
        (S-1)/(M+S-1). ``loss_grad`` covers the last stage's F AND B,
        hence 2x the unit cost."""

        def __init__(self, stage):
            self.stage = stage
            self.n_stages = S
            self.microbatches = M
            self.is_first = stage == 0
            self.is_last = stage == S - 1

        def place(self, x):
            return x

        def init_opt_state(self, params):
            return {"t": np.zeros((), np.float32)}

        def fwd(self, params, x):
            time.sleep(OP_S)
            return np.asarray(x, np.float32)

        def loss_grad(self, params, x, y):
            time.sleep(2 * OP_S)
            return (np.float32(0.0), {"w": np.zeros((1,), np.float32)},
                    np.asarray(x, np.float32))

        def bwd(self, params, x, g):
            time.sleep(OP_S)
            return ({"w": np.zeros((1,), np.float32)},
                    np.asarray(g, np.float32))

        def apply_grads(self, params, opt_state, grads):
            return params, opt_state

    mpmd_dir = tempfile.mkdtemp(prefix="critpath-mpmd-")
    tr = LocalTransport()
    stages = [StageWorker(_StubStage(s), {"w": np.zeros((1,), np.float32)},
                          None, tr) for s in range(S)]
    tokens = np.zeros((M, 1, 4), np.float32)
    targets = np.zeros((M, 1, 4), np.float32)
    errors: dict[int, BaseException] = {}

    def stage_loop(w):
        try:
            for step in range(mpmd_steps):
                w.run_step(
                    step,
                    tokens=tokens if w.program.is_first else None,
                    targets=targets if w.program.is_last else None)
        except BaseException as e:  # noqa: BLE001 — reraised below
            errors[w.program.stage] = e

    with recorder_arm(mpmd_dir):
        ts = [threading.Thread(target=stage_loop, args=(w,), daemon=True,
                               name=f"critpath-stage-{w.program.stage}")
              for w in stages]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60.0)
    if errors:
        raise next(iter(errors.values()))

    # steady state only (step 0 pays the one-time pipeline fill), and
    # the per-stage MEDIAN over steps: one descheduled thread must not
    # skew the receipt on a noisy host
    online = {s: round(statistics.median(
        v for k, v in stages[s].bubble_by_step.items() if k >= 1), 6)
        for s in range(S)}
    bub = critpath.bubble_fractions(collect.load_merged(mpmd_dir))
    offline = {}
    for row in bub["per_step"]:
        if row["step"] >= 1:
            offline.setdefault(row["stage"], []).append(row["bubble"])
    offline = {s: round(statistics.median(v), 6)
               for s, v in sorted(offline.items())}
    analytic = (S - 1) / (M + S - 1)

    # the gauge path the fleet console reads: flush the global registry
    # (run_step set the per-stage gauges) into a live KV, read it back
    server = KVServer()
    kv = KVClient(port=server.port)
    try:
        tsdb.TimeSeriesFlusher(kv, "critpath-bench").flush()
        gauge = {}
        for row in tsdb.read_series(kv, "mpmd.bubble_fraction"):
            series = row["series"]
            if "stage=" in series and row["kind"] != "counter":
                stage = series.split("stage=", 1)[1].rstrip("}")
                gauge[int(stage)] = float(row["v"])
        published_series = critpath.publish_profile(kv, prof)
        cov_gauge = tsdb.latest_value(
            tsdb.read_series(kv, "critpath.coverage"))
    finally:
        kv.close()
        server.stop()
    bubble_err = max(abs(v - analytic)
                     for v in list(online.values()) + list(offline.values()))

    # -- 4. workload export round-trip ---------------------------------------
    wl = workload.from_trace(merged_ctrl, source="bench critpath ctrl arm")
    blob = workload.dumps(wl)
    wl_path = os.path.join(dirs["ctrl"], "workload.json")
    workload.save(wl, wl_path)
    roundtrip = workload.dumps(workload.load(wl_path))
    byte_identical = roundtrip == blob

    top = sorted(prof["segments"].items(), key=lambda kv_: -kv_[1]["total_s"])
    return {
        "metric": "critpath",
        "unit": "attribution coverage / regression gate verdicts / "
                "bubble fraction",
        "attribution": {
            "requests": prof["requests"],
            "ok": prof["ok"],
            "coverage_mean": prof["coverage_mean"],
            "coverage_min": prof["coverage_min"],
            "frac_requests_ge_95": round(frac_covered, 4),
            "top_segments": {seg: s["share"] for seg, s in top[:6]},
        },
        "tracediff": {
            "identical_rerun_exit": gate["same"],
            "slowdown_exit": gate["slow"],
            "identical_regressions": cmp_same["regressions"],
            "slowdown_regressions": cmp_slow["regressions"],
            "decode_ratio": decode_row.get("ratio"),
        },
        "bubble": {
            "stages": S, "microbatches": M, "steps": mpmd_steps,
            "online_per_stage": online,
            "offline_per_stage": offline,
            "gauge_per_stage": gauge,
            "analytic": round(analytic, 6),
            "max_abs_err": round(bubble_err, 6),
        },
        "workload": {
            "schema": wl["schema"],
            "rows": len(wl["requests"]),
            "byte_identical": bool(byte_identical),
        },
        "fleetop_feed": {
            "series_published": published_series,
            "coverage_gauge": cov_gauge,
        },
        # the tentpole claims
        "attribution_ok": bool(prof["coverage_mean"]
                               >= critpath.COVERAGE_TARGET),
        "gating_ok": bool(gate["slow"] == 1 and gate["same"] == 0
                          and "decode" in cmp_slow["regressions"]),
        "bubble_ok": bool(bubble_err <= 0.05),
        "workload_ok": bool(byte_identical),
        "_artifacts": {
            "trace_ctrl": dirs["ctrl"],
            "trace_slow": dirs["slow"],
            "trace_mpmd": mpmd_dir,
        },
        "source": "measured wall time over the bench_obs modeled fleet "
                  "(real sockets/queues/engine, sleep-modeled step); "
                  "tracediff run as the committed CLI on saved profiles; "
                  "bubble from sleep-modeled 1F1B stage workers vs the "
                  "trace-derived and analytic fractions",
    }


def bench_health(*, quick: bool = False, seed: int = 0) -> dict:
    """Health-plane receipts: is the durable metrics plane cheap enough
    to leave ON, and does it catch the pathologies fast enough to act?

    Three measurements, all chipless:

    1. **Flush overhead** — per-flush wall cost of a replica-sized
       registry (counters/gauges/histograms with label variants) into a
       live KV, plus paired step-loop arms flushing on the production
       cadence (once per tsdb bucket). The claim: <= 1% of step time.
    2. **Detection latency** — a stub-clock ``HealthMonitor`` against
       each seeded pathology (autoscale flapping, tenant starvation,
       preemption cascade): evaluation windows from pathology visible to
       alert claimed. The claim: <= 2 windows each.
    3. **fleetop** — the ops console renders from a live 2-replica
       modeled fleet (real sockets/KV/engine, sleep-modeled step) whose
       time series came off the replicas' own load-report cadence.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import statistics
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_sandbox.gateway import FleetSpec, Gateway, GatewayClient
    from tpu_sandbox.models.transformer import TransformerConfig
    from tpu_sandbox.obs.health import (CascadeDetector, HealthMonitor,
                                        OscillationDetector,
                                        StarvationDetector)
    from tpu_sandbox.obs.metrics import MetricsRegistry
    from tpu_sandbox.obs.record import Recorder
    from tpu_sandbox.obs.tsdb import TimeSeriesFlusher, list_series
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer
    from tpu_sandbox.serve.cache import CacheConfig
    from tpu_sandbox.serve.engine import ContinuousEngine, ServeConfig
    from tpu_sandbox.serve.replica import ReplicaWorker

    # -- 1. flush overhead ---------------------------------------------------
    server = KVServer()
    kv = KVClient(port=server.port)
    try:
        reg = MetricsRegistry()
        for i in range(12):
            reg.counter(f"bench.counter.c{i}",
                        labels={"kind": str(i % 3)}).inc(i)
        for i in range(6):
            reg.gauge(f"bench.gauge.g{i}").set(float(i))
        h = reg.histogram("bench.lat.s")
        for v in range(256):
            h.observe(v / 256.0)
        bucket_s = 1.0
        flusher = TimeSeriesFlusher(kv, "bench-rep", bucket_s=bucket_s,
                                    registry=reg, recorder=Recorder(None))
        keys_per_flush = flusher.flush()  # warm (first flush writes all)
        n_flush = 20 if quick else 60
        flush_times = []
        for i in range(n_flush):
            reg.counter("bench.counter.c0", labels={"kind": "0"}).inc()
            t0 = time.monotonic()
            flusher.flush()
            flush_times.append(time.monotonic() - t0)
        flush_ms = statistics.median(flush_times) * 1e3
        # the production cadence is one flush per bucket: the fraction of
        # every bucket interval spent flushing IS the step-time overhead
        flush_frac = flush_ms / (bucket_s * 1e3)

        # paired corroboration: identical step loops, the on arm also
        # flushing whenever the bucket rolls over
        x = jnp.ones((512, 512), jnp.float32)
        step = jax.jit(lambda a: a @ a / 512.0)
        step(x).block_until_ready()
        t0 = time.monotonic()
        for _ in range(50):
            step(x).block_until_ready()
        step_ms = (time.monotonic() - t0) / 50 * 1e3
        loop_s = 0.4 if quick else 1.0
        n_steps = max(50, int(loop_s / (step_ms / 1e3)))
        rounds = 3 if quick else 6

        def run_loop(flush_bucket_s=None):
            nxt = time.monotonic() + (flush_bucket_s or 1e9)
            t0 = time.monotonic()
            for _ in range(n_steps):
                step(x).block_until_ready()
                if time.monotonic() >= nxt:
                    reg.counter("bench.counter.c1",
                                labels={"kind": "1"}).inc()
                    flusher.flush()
                    nxt += flush_bucket_s
            return time.monotonic() - t0

        run_loop()  # warm the loop shape
        paired = []
        for _ in range(rounds):
            off = run_loop()
            on = run_loop(flush_bucket_s=bucket_s)
            paired.append((on - off) / off)
        paired_delta = statistics.median(paired)
    finally:
        kv.close()
        server.stop()

    # -- 2. detection latency (stub clock) -----------------------------------
    def _windows_to_alert(seed_pathology, detector, setup=None):
        """Evaluation windows from 'pathology visible in durable state'
        to 'alert claimed', on a monitor stepped once per window.
        ``setup`` seeds the healthy pre-pathology state the baseline
        evaluation observes."""
        srv = KVServer()
        dkv = KVClient(port=srv.port)
        try:
            t = [9000.0]
            mon = HealthMonitor(dkv, "bench-h0", window_s=1.0, rules=[],
                                detectors=[detector],
                                clock=lambda: t[0])
            if setup is not None:
                setup(dkv)
            mon.step()  # baseline evaluation before the pathology
            windows = 0
            while windows < 8:
                seed_pathology(dkv, windows)
                t[0] += 1.0
                windows += 1
                if mon.step():
                    return windows
            return None
        finally:
            dkv.close()
            srv.stop()

    def seed_flapping(dkv, i):
        if i > 0:
            return
        tail = 0
        for action in ("scale_up", "scale_down") * 2:
            dkv.set(f"serve/autoscale/events/{tail}", json.dumps(
                {"action": action, "reason": "queue_depth", "wall": 0.0}))
            tail += 1
        dkv.set("serve/autoscale/tail", str(tail))

    def setup_tenants(dkv):
        # both tenants known (and the mouse already queued) before onset
        dkv.set("sched/vtime/hog", repr(0.0))
        dkv.set("sched/vtime/mouse", repr(0.0))
        dkv.set("sched/queued/mouse", "2")

    def seed_starvation(dkv, i):
        # onset: the hog's vtime advances every window, the mouse's not
        dkv.set("sched/vtime/hog", repr(10.0 * (i + 1)))

    def seed_cascade(dkv, i):
        if i == 0:
            for _ in range(3):
                dkv.add("sched/preempts/victim")

    latencies = {
        "autoscale_oscillation": _windows_to_alert(
            seed_flapping, OscillationDetector()),
        "tenant_starvation": _windows_to_alert(
            seed_starvation, StarvationDetector(), setup=setup_tenants),
        "preemption_cascade": _windows_to_alert(
            seed_cascade, CascadeDetector()),
    }

    # -- 3. fleetop renders from a live fleet --------------------------------
    BLOCK = 8
    mcfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                             n_layers=2, d_ff=64, max_len=128)
    ccfg = CacheConfig(num_blocks=48, block_size=BLOCK, max_blocks_per_seq=8)
    rng = np.random.default_rng(seed)

    class _ModeledStep:
        buckets = (32,)
        vocab = 64

        def __init__(self):
            self.prefill = {b: self._prefill for b in self.buckets}

        def pick_bucket(self, plen):
            for b in self.buckets:
                if plen <= b:
                    return b
            raise ValueError(f"prompt of {plen} exceeds {self.buckets}")

        def _prefill(self, params, k, v, toks, dest, last):
            time.sleep(1e-3)
            toks = np.asarray(toks)
            logits = np.zeros((self.vocab,), np.float32)
            logits[(int(toks[0, int(last)]) + 1) % self.vocab] = 1.0
            return logits, k, v

        def decode(self, params, k, v, tokens, lengths, tables):
            time.sleep(5e-4)
            tokens = np.asarray(tokens)
            logits = np.zeros((tokens.shape[0], self.vocab), np.float32)
            for i in range(tokens.shape[0]):
                logits[i, (int(tokens[i, 0]) + 1) % self.vocab] = 1.0
            return logits, k, v

    server = KVServer()
    kv = KVClient(port=server.port)
    stop = threading.Event()
    workers, threads, clones = [], [], []
    gw = client = None
    try:
        for i in range(2):
            wkv = kv.clone()
            clones.append(wkv)
            eng = ContinuousEngine(
                None,
                ServeConfig(model=mcfg, cache=ccfg, max_batch=4,
                            buckets=_ModeledStep.buckets, max_waiting=0),
                step=_ModeledStep())
            w = ReplicaWorker(wkv, eng, tag=f"hw{i}", lease_ttl=1.0,
                              load_interval=0.05)
            workers.append(w)

            def loop(worker=w):
                while not stop.is_set():
                    worker.tick()
                    if worker.engine.idle:
                        time.sleep(0.001)

            t = threading.Thread(target=loop, daemon=True,
                                 name=f"health-replica-hw{i}")
            threads.append(t)
            t.start()
        gw = Gateway(kv, [FleetSpec(block_size=BLOCK)], admission="none",
                     refresh_min_s=0.01, max_report_age_s=2.0).start()
        client = GatewayClient(gw.port, max_retries=0)
        time.sleep(0.2)
        n_req = 6 if quick else 16
        rids = []
        for i in range(n_req):
            prompt = [int(t) for t in rng.integers(1, 64, 2 * BLOCK)]
            if client.submit(f"h{i}", prompt, 3):
                rids.append(f"h{i}")
        served = sum(1 for rid in rids
                     if _terminal_verdict(client, rid, 60.0).get("verdict")
                     == "ok")
        time.sleep(0.2)  # one more load-report/flush cadence
        mon = HealthMonitor(kv, "bench-live-h0", window_s=0.5)
        mon.step()
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools"))
        import fleetop
        console = fleetop.render(kv)
        n_series = len(list_series(kv))
    finally:
        if client is not None:
            client.close()
        if gw is not None:
            gw.close()
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        for w in workers:
            w.engine.drain_to_requests()
        for c in clones:
            c.close()
        kv.close()
        server.stop()

    fleetop_ok = ("replicas:" in console and "hw0" in console
                  and "hw1" in console and n_series > 0)
    return {
        "metric": "health",
        "unit": "fractional overhead / evaluation windows",
        "flush": {
            "keys_per_flush": keys_per_flush,
            "flush_ms": round(flush_ms, 4),
            "bucket_s": bucket_s,
            "overhead_frac": round(flush_frac, 5),
            "paired_loop_delta_frac": round(paired_delta, 5),
            "paired_rounds": rounds,
            "steps_per_arm": n_steps,
        },
        "detection_windows": latencies,
        "fleet": {
            "replicas": 2,
            "requests_served": served,
            "live_series": n_series,
            "fleetop_renders": bool(fleetop_ok),
        },
        "fleetop_sample": console.splitlines()[:24],
        # the tentpole claims
        "flush_overhead_ok": bool(flush_frac <= 0.01),
        "detection_ok": bool(all(w is not None and w <= 2
                                 for w in latencies.values())),
        "fleetop_ok": bool(fleetop_ok),
        "source": "measured wall time against a live KV store; detectors "
                  "driven by a stub-clock monitor over seeded durable "
                  "state; fleet modeled as in bench_obs (real "
                  "sockets/queues/engine, sleep-modeled step)",
    }


def bench_deploy(*, quick: bool = False, seed: int = 0) -> dict:
    """Continuous-deployment receipts: can the train->serve loop close
    without dropping traffic, and does the canary actually pull the cord?

    Three measurements, all chipless:

    1. **Zero-downtime rolling update** — a 2-replica fleet (real
       sockets/KV/gateway/engine, sleep-modeled step as in bench_health)
       under steady open-loop load, with a version published and a live
       :class:`DeployController` rolling it out mid-stream, against a
       no-deploy control arm of the identical load. The claims: zero
       lost verdicts, zero late (end-to-end > budget), and no shed spike
       over the control arm, while the fleet converges on the new
       version and the canary split is cleaned up.
    2. **Canary rollback latency** — a stub fleet whose canary's p99
       TTFT degrades 10x in the tsdb (rows seeded: the in-process
       metrics registry is shared, so real flushes cannot separate
       canary from baseline). Measured in controller evaluations from
       regression-visible to the fail verdict; claimed <= the
       configured ``regress_streak``, plus full convergence back and
       the durable ``canary_regression`` alert.
    3. **The closed loop** — generate -> distill-train -> publish ->
       promote, two generations of real transformer weights through the
       sealed-artifact path, each generation's request served on that
       generation's promoted version and the distillation objective
       strictly improving.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import tempfile
    import threading

    import numpy as np

    from tpu_sandbox.deploy.controller import DeployConfig, DeployController
    from tpu_sandbox.deploy.registry import (audit_registry, current_target,
                                             deploy_events, read_shares,
                                             rollout_phase)
    from tpu_sandbox.gateway import FleetSpec, Gateway, GatewayClient
    from tpu_sandbox.models.transformer import TransformerConfig
    from tpu_sandbox.obs.health import active_subjects
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer
    from tpu_sandbox.serve.cache import CacheConfig
    from tpu_sandbox.serve.engine import ContinuousEngine, ServeConfig
    from tpu_sandbox.serve.replica import (ReplicaWorker, k_load,
                                           read_load_reports, read_result,
                                           submit_request)
    from tpu_sandbox.train.trainer import publish_checkpoint

    BLOCK = 8
    mcfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                             n_layers=2, d_ff=64, max_len=128)
    ccfg = CacheConfig(num_blocks=48, block_size=BLOCK, max_blocks_per_seq=8)
    rng = np.random.default_rng(seed)

    class _ModeledStep:
        buckets = (32,)
        vocab = 64

        def __init__(self):
            self.prefill = {b: self._prefill for b in self.buckets}

        def pick_bucket(self, plen):
            for b in self.buckets:
                if plen <= b:
                    return b
            raise ValueError(f"prompt of {plen} exceeds {self.buckets}")

        def _prefill(self, params, k, v, toks, dest, last):
            time.sleep(1e-3)
            toks = np.asarray(toks)
            logits = np.zeros((self.vocab,), np.float32)
            logits[(int(toks[0, int(last)]) + 1) % self.vocab] = 1.0
            return logits, k, v

        def decode(self, params, k, v, tokens, lengths, tables):
            time.sleep(5e-4)
            tokens = np.asarray(tokens)
            logits = np.zeros((tokens.shape[0], self.vocab), np.float32)
            for i in range(tokens.shape[0]):
                logits[i, (int(tokens[i, 0]) + 1) % self.vocab] = 1.0
            return logits, k, v

    def _stub_engine():
        return ContinuousEngine(
            None,
            ServeConfig(model=mcfg, cache=ccfg, max_batch=4,
                        buckets=_ModeledStep.buckets, max_waiting=0),
            step=_ModeledStep())

    ckpt_params = {"w": np.arange(8, dtype=np.float32)}

    # -- 1. rolling update under open-loop load vs no-deploy control ---------
    # arrival rate sized under the fleet's real drain rate (the bottleneck
    # is KV round-trips + the GIL across worker/gateway/collector threads,
    # not the modeled sleeps): open-loop load that keeps both replicas
    # busy without unbounded backlog, so "late" isolates
    # deployment-induced stalls from plain overload
    n_req = 80 if quick else 400
    interval_s = 20e-3
    late_budget_s = 2.0

    def run_arm(deploy: bool) -> dict:
        server = KVServer()
        kv = KVClient(port=server.port)
        stop = threading.Event()
        workers, threads, clones = [], [], []
        gw = client = ctrl = None
        tmp = tempfile.TemporaryDirectory()
        lat, bodies = {}, {}
        pending, pend_lock = {}, threading.Lock()
        try:
            for i in range(2):
                wkv = kv.clone()
                clones.append(wkv)
                # stub weight loads (any version is resident instantly);
                # publish_ts stays on — the controller's canary reads the
                # replicas' own flushed ttft/logprob series
                w = ReplicaWorker(
                    wkv, _stub_engine(), tag=f"dw{i}", lease_ttl=1.0,
                    load_interval=0.05,
                    swap_loader=lambda cmd: ("stub", int(cmd["ver"])))
                workers.append(w)

                def loop(worker=w):
                    while not stop.is_set():
                        worker.tick()
                        if worker.engine.idle:
                            time.sleep(0.001)

                t = threading.Thread(target=loop, daemon=True,
                                     name=f"deploy-replica-dw{i}")
                threads.append(t)
                t.start()
            gw = Gateway(kv, [FleetSpec(block_size=BLOCK)], admission="none",
                         refresh_min_s=0.01, max_report_age_s=2.0).start()
            client = GatewayClient(gw.port, max_retries=0)
            time.sleep(0.2)  # first load reports

            # collector: stamps each verdict as it lands in durable state
            ckv = kv.clone()
            clones.append(ckv)

            def collect():
                while not stop.is_set():
                    with pend_lock:
                        rids = list(pending)
                    for rid in rids:
                        raw = ckv.try_get(f"serve/result/{rid}")
                        if raw is None:
                            continue
                        t_done = time.monotonic()
                        with pend_lock:
                            t_sub = pending.pop(rid)
                        lat[rid] = t_done - t_sub
                        bodies[rid] = json.loads(raw)
                    time.sleep(0.002)

            col = threading.Thread(target=collect, daemon=True,
                                   name="deploy-collector")
            threads.append(col)
            col.start()

            ver = None
            if deploy:
                ctrl_kv = kv.clone()
                clones.append(ctrl_kv)
                ctrl = DeployController(
                    ctrl_kv, member_id="bench-roll", election_ttl=1.0,
                    cfg=DeployConfig(swap_resend_s=0.1))

                # 50ms cadence: an eternity for the canary windows, but
                # the controller's registry scans stop competing with the
                # serving path for the KV server and the GIL
                def ctrl_loop():
                    while not stop.is_set():
                        ctrl.tick()
                        time.sleep(0.05)

            # open loop: arrivals on a fixed clock, blind to completions
            next_t = time.monotonic()
            for i in range(n_req):
                if deploy and i == n_req // 3:
                    ver = publish_checkpoint(kv, ckpt_params,
                                             export_dir=tmp.name, step=1)
                    t = threading.Thread(target=ctrl_loop, daemon=True,
                                         name="deploy-ctrl")
                    threads.append(t)
                    t.start()
                rid = f"d{i}"
                prompt = [int(t) for t in rng.integers(1, 64, 2 * BLOCK)]
                t_sub = time.monotonic()
                if client.submit(rid, prompt, 3):
                    with pend_lock:
                        pending[rid] = t_sub
                else:  # door verdict is still terminal, still counted
                    bodies[rid] = _terminal_verdict(client, rid, 10.0)
                    lat[rid] = time.monotonic() - t_sub
                next_t += interval_s
                time.sleep(max(0.0, next_t - time.monotonic()))

            # drain: every rid must reach SOME terminal verdict (lost = 0)
            drain_deadline = time.monotonic() + 30.0
            while time.monotonic() < drain_deadline:
                with pend_lock:
                    if not pending:
                        break
                time.sleep(0.01)
            with pend_lock:
                lost = sorted(pending)
                pending.clear()

            rollout = None
            if deploy:
                # the rollout keeps rolling after the stream: wait for the
                # fleet to converge on the published version
                conv_deadline = time.monotonic() + 30.0
                while time.monotonic() < conv_deadline:
                    reps = read_load_reports(kv)
                    if (current_target(kv) == ver and len(reps) == 2
                            and all(r.get("ver") == ver
                                    for r in reps.values())):
                        break
                    time.sleep(0.02)
                reps = read_load_reports(kv)
                rollout = {
                    "ver": ver,
                    "promoted": bool(current_target(kv) == ver),
                    "replicas_on_target": sum(
                        1 for r in reps.values() if r.get("ver") == ver),
                    "events": [e["action"] for e in deploy_events(kv)],
                    "shares_cleared": read_shares(kv) is None,
                }
        finally:
            if client is not None:
                client.close()
            if gw is not None:
                gw.close()
            stop.set()
            for t in threads:
                t.join(timeout=10.0)
            if ctrl is not None:
                ctrl.resign()
            for w in workers:
                w.engine.drain_to_requests()
            for c in clones:
                c.close()
            kv.close()
            server.stop()
            tmp.cleanup()

        lats = sorted(lat.values())

        def pct(p):
            return (round(lats[min(len(lats) - 1, int(p * len(lats)))], 4)
                    if lats else None)

        return {
            "requests": n_req,
            "ok": sum(1 for b in bodies.values()
                      if b.get("verdict") == "ok"),
            "shed": sum(1 for b in bodies.values()
                        if b.get("verdict") == "SHED"),
            "lost": len(lost),
            "late": sum(1 for v in lat.values() if v > late_budget_s),
            "p50_s": pct(0.50),
            "p99_s": pct(0.99),
            "rollout": rollout,
        }

    control = run_arm(deploy=False)
    rolling = run_arm(deploy=True)

    # -- 2. canary regression -> auto-rollback latency -----------------------
    server = KVServer()
    kv = KVClient(port=server.port)
    tmp = tempfile.TemporaryDirectory()
    clones = []

    def clone():
        c = kv.clone()
        clones.append(c)
        return c

    try:
        workers = [
            ReplicaWorker(clone(), _stub_engine(), tag=f"cw{i}",
                          lease_ttl=0.5, load_interval=0.02,
                          publish_ts=False,
                          swap_loader=lambda cmd: ("stub", int(cmd["ver"])))
            for i in range(2)
        ]
        cfg = DeployConfig(swap_resend_s=0.05)
        ctrl = DeployController(clone(), member_id="bench-canary",
                                election_ttl=1.0, cfg=cfg)
        ver = publish_checkpoint(kv, ckpt_params, export_dir=tmp.name,
                                 step=1)

        def drive(until, timeout=30.0):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                for w in workers:
                    w.tick()
                ctrl.tick()
                if until():
                    return
                time.sleep(0.005)
            raise RuntimeError("bench_deploy: drive condition not reached")

        # canary swapped, split live — then its p99 TTFT degrades 10x
        drive(lambda: read_shares(kv) is not None)

        def seed_ttft(proc, p99):
            bucket = int(time.time())
            kv.set_ttl(
                f"obs/ts/{proc}/engine.ttft/{bucket % 120}",
                json.dumps({"kind": "histogram",
                            "v": {"count": 1, "p50": p99, "p90": p99,
                                  "p99": p99, "mean": p99},
                            "bucket": bucket, "wall": time.time()}), 60.0)

        seed_ttft("cw0", 10.0)
        seed_ttft("cw1", 1.0)
        evals, fail_evals = 0, None
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            for w in workers:
                w.tick()
            ctrl.tick()
            evals += 1
            if any(e["action"] == "canary_fail" for e in deploy_events(kv)):
                fail_evals = evals
                break
            time.sleep(0.005)
        drive(lambda: rollout_phase(kv, "", ver)["done"] is not None)
        phase = rollout_phase(kv, "", ver)
        canary = {
            "evals_to_fail_verdict": fail_evals,
            "regress_streak": cfg.regress_streak,
            "rolled_back": bool(phase["done"] is not None
                                and phase["done"]["outcome"]
                                == "rolled_back"),
            "target_after": current_target(kv),
            "canary_reverted": bool(
                json.loads(kv.get(k_load("cw0")))["ver"] == 0),
            "alerted": "default" in active_subjects(kv,
                                                    "canary_regression"),
            "shares_cleared": read_shares(kv) is None,
        }
        ctrl.resign()
    finally:
        for c in clones:
            c.close()
        kv.close()
        server.stop()
        tmp.cleanup()

    # -- 3. the closed loop: generate -> train -> publish -> promote ---------
    import jax
    import jax.numpy as jnp
    import optax

    from tpu_sandbox.models.transformer import TransformerLM
    from tpu_sandbox.serve.decode import build_decode_step

    mcfg3 = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                              n_layers=2, d_ff=64, max_len=128,
                              dtype=jnp.float32)
    ccfg3 = CacheConfig(num_blocks=24, block_size=4, max_blocks_per_seq=8)
    model = TransformerLM(mcfg3)
    dstep = build_decode_step(mcfg3, ccfg3, max_batch=2, buckets=(8, 16))

    def params_for(s):
        return model.init(jax.random.key(s),
                          jnp.zeros((1, 8), jnp.int32))["params"]

    teacher = params_for(7)
    student = params_for(0)
    opt = optax.adam(3e-3)
    opt_state = opt.init(student)
    rng3 = np.random.default_rng(seed)
    eval_toks = jnp.asarray(rng3.integers(0, 64, (8, 16)), jnp.int32)

    @jax.jit
    def distill_loss(params, toks):
        t_prob = jax.nn.softmax(model.apply({"params": teacher}, toks), -1)
        s_logits = model.apply({"params": params}, toks)
        return -jnp.mean(jnp.sum(
            t_prob * jax.nn.log_softmax(s_logits, -1), -1))

    grad_fn = jax.jit(jax.value_and_grad(distill_loss))
    train_steps = 12 if quick else 30

    server = KVServer()
    kv = KVClient(port=server.port)
    tmp = tempfile.TemporaryDirectory()
    wkv, ckv = kv.clone(), kv.clone()
    worker = ReplicaWorker(
        wkv,
        ContinuousEngine(params_for(0), ServeConfig(
            model=mcfg3, cache=ccfg3, max_batch=2, buckets=(8, 16)),
            step=dstep),
        tag="loop0", lease_ttl=0.5, load_interval=0.02, publish_ts=False)
    ctrl = DeployController(ckv, member_id="bench-loop", election_ttl=1.0,
                            cfg=DeployConfig(swap_resend_s=0.05))
    losses = [float(distill_loss(student, eval_toks))]
    served_vers = []
    try:
        def drive3(until, timeout=120.0):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                worker.tick()
                ctrl.tick()
                if until():
                    return
                time.sleep(0.005)
            raise RuntimeError("bench_deploy: closed loop stalled")

        for gen in range(2):
            for _ in range(train_steps):
                batch = jnp.asarray(rng3.integers(0, 64, (8, 16)),
                                    jnp.int32)
                _, grads = grad_fn(student, batch)
                updates, opt_state = opt.update(grads, opt_state)
                student = optax.apply_updates(student, updates)
            losses.append(float(distill_loss(student, eval_toks)))
            # sealed export + registry + controller promotion: the same
            # artifact path production checkpoints take (no stub loads)
            ver = publish_checkpoint(kv, student, export_dir=tmp.name,
                                     step=gen + 1)
            drive3(lambda v=ver: current_target(kv) == v)
            rid = f"loopgen{gen}"
            submit_request(kv, rid, [3, 1, 4, 1, 5], 3)
            drive3(lambda r=rid: kv.try_get(f"serve/result/{r}") is not None,
                   timeout=60.0)
            served_vers.append(read_result(kv, rid).get("ver"))
        statuses = {row["ver"]: row["status"]
                    for row in audit_registry(kv)["versions"]}
    finally:
        ctrl.resign()
        wkv.close()
        ckv.close()
        kv.close()
        server.stop()
        tmp.cleanup()

    closed_loop = {
        "generations": 2,
        "train_steps_per_gen": train_steps,
        "losses": [round(v, 5) for v in losses],
        "served_vers": served_vers,
        "registry_statuses": statuses,
    }

    zero_regression = bool(
        control["lost"] == 0 and rolling["lost"] == 0
        and control["late"] == 0 and rolling["late"] == 0
        and rolling["shed"] <= control["shed"])
    rollout_ok = bool(
        rolling["rollout"] is not None and rolling["rollout"]["promoted"]
        and rolling["rollout"]["replicas_on_target"] == 2
        and rolling["rollout"]["shares_cleared"])
    rollback_ok = bool(
        canary["rolled_back"] and canary["alerted"]
        and canary["canary_reverted"] and canary["target_after"] == 0
        and canary["evals_to_fail_verdict"] is not None
        and canary["evals_to_fail_verdict"] <= canary["regress_streak"])
    loop_ok = bool(
        closed_loop["served_vers"] == [1, 2]
        and closed_loop["losses"][2] < closed_loop["losses"][1]
        < closed_loop["losses"][0])
    return {
        "metric": "deploy",
        "unit": "verdict counts / controller evaluations / loss",
        "open_loop": {"arrival_interval_s": interval_s,
                      "late_budget_s": late_budget_s,
                      "control": control, "rolling": rolling},
        "canary": canary,
        "closed_loop": closed_loop,
        # the tentpole claims
        "zero_downtime_ok": bool(zero_regression and rollout_ok),
        "rollback_ok": rollback_ok,
        "closed_loop_ok": loop_ok,
        "source": "measured against live KV/gateway/replica sockets; load "
                  "fleet modeled as in bench_health (real queues/engine, "
                  "sleep-modeled step, stub weight loads); canary tsdb "
                  "rows seeded (the in-process metrics registry is shared, "
                  "so real flushes cannot separate canary from baseline); "
                  "closed loop is real transformer weights through the "
                  "sealed-artifact path",
    }


def _measure_input_stall(n_batches: int = 30, load_ms: float = 10.0,
                         step_ms: float = 10.0) -> dict:
    """Measured wall-time of a sleep-modeled train loop with and without
    the background prefetcher. The sleeps model a host-side batch assembly
    (``load_ms``) and a device step (``step_ms``) of comparable cost — the
    regime double-buffering exists for; the THREADING under test
    (data/loader.PrefetchLoader's queue + producer) is the real one.
    ``input_stall`` is time the consumer spends blocked in ``next()``."""
    import time

    from tpu_sandbox.data.loader import PrefetchLoader

    class SlowLoader:
        def __len__(self):
            return n_batches

        def __iter__(self):
            for i in range(n_batches):
                time.sleep(load_ms / 1e3)
                yield i, i  # payload irrelevant: the stall is the metric

    def consume(loader):
        t0 = time.monotonic()
        stall = 0.0
        it = iter(loader)
        while True:
            t1 = time.monotonic()
            try:
                next(it)
            except StopIteration:
                break
            stall += time.monotonic() - t1
            time.sleep(step_ms / 1e3)  # the "train step"
        return time.monotonic() - t0, stall

    total_sync, stall_sync = consume(SlowLoader())
    total_pre, stall_pre = consume(PrefetchLoader(SlowLoader()))
    return {
        "batches": n_batches,
        "host_load_ms_per_batch": load_ms,
        "step_ms": step_ms,
        "total_sec_sync": round(total_sync, 4),
        "total_sec_prefetch": round(total_pre, 4),
        "input_stall_sec_sync": round(stall_sync, 4),
        "input_stall_sec_prefetch": round(stall_pre, 4),
        "stall_reduction_frac": round(
            1.0 - stall_pre / stall_sync, 4) if stall_sync > 0 else None,
        "source": "measured wall time; load/step modeled by sleeps, "
                  "prefetch threading real (data/loader.PrefetchLoader)",
    }


def bench_overlap(world: int = 8) -> dict:
    """The overlapped-step-pipeline receipts: (1) XLA schedule structure of
    the bucketed gradient sync from a chipless multi-chip v5e AOT compile
    (tools/hlo_schedule.py — how many per-bucket all-reduces are issued
    before the last backward compute op, and the exposed-comm fraction);
    (2) measured input-stall reduction from the double-buffered prefetch
    loader. Chipless + host-threads: no accelerator probe."""
    import subprocess
    import sys as _sys

    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "hlo_schedule.py")
    # subprocess isolation: the AOT tool initializes libtpu and flips
    # jax_platforms — neither survives nor belongs in this process
    sched, err = None, None
    try:
        out = subprocess.run(
            [_sys.executable, tool], capture_output=True, text=True,
            timeout=600,
        )
        if out.returncode == 0 and out.stdout.strip():
            sched = json.loads(out.stdout.strip().splitlines()[-1])
        else:
            tail = (out.stderr or out.stdout).strip().splitlines()
            err = tail[-1] if tail else f"exit {out.returncode}"
    except Exception as e:  # missing libtpu, timeout, ...
        err = f"{type(e).__name__}: {e}"

    if sched is None:
        # CPU SPMD fallback: still PROVES the bucket split happened (one
        # collective per bucket in the HLO), but XLA:CPU lowers collectives
        # synchronously and prints no schedule worth reading — say so.
        from tpu_sandbox.utils.cli import ensure_devices

        devices = ensure_devices(world, force_cpu=True)
        _sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools"))
        from hlo_schedule import build_overlapped_hlo, schedule_report

        text = build_overlapped_hlo(devices, compiler_options={})
        sched = schedule_report(text)
        sched.pop("collectives", None)
        sched["degraded"] = (
            f"TPU AOT compile unavailable ({err}); CPU SPMD compile shows "
            "the per-bucket collective split but carries no latency-hiding "
            "schedule to audit"
        )

    sched.pop("collectives", None)
    return {
        "metric": "overlap",
        "exposed_comm_fraction": sched.get("exposed_comm_fraction"),
        "all_reduce_issues_before_last_bwd_compute": sched.get(
            "all_reduce_issues_before_last_bwd_compute"),
        "schedule": sched,
        "input_stall": _measure_input_stall(),
    }


def bench_donation() -> dict:
    """The donation receipt (graftlint GL-H201's measured counterpart):
    chipless AOT peak-memory delta between donate=True and donate=False
    for the DP and ZeRO step compiles, from XLA's memory analysis.
    Subprocess-isolated like the other AOT paths; the CPU backend cannot
    witness aliasing, so off-toolchain this degrades to a statement, not
    a fake zero."""
    import subprocess
    import sys as _sys

    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "aot_donation.py")
    try:
        out = subprocess.run(
            [_sys.executable, tool], capture_output=True, text=True,
            timeout=900,
        )
        if out.returncode == 0 and out.stdout.strip():
            return json.loads(out.stdout.strip().splitlines()[-1])
        tail = (out.stderr or out.stdout).strip().splitlines()
        err = tail[-1] if tail else f"exit {out.returncode}"
    except Exception as e:  # missing libtpu, timeout, ...
        err = f"{type(e).__name__}: {e}"
    return {
        "metric": "donation",
        "degraded": (
            f"TPU AOT compile unavailable ({err}); the CPU backend does "
            "not implement buffer donation, so there is no aliasing to "
            "measure — run on a box with the TPU toolchain"
        ),
    }


def bench_capacity(image_size: int, dtype_name: str, force_cpu: bool,
                   max_batch: int = 512, plan: str = "auto") -> dict:
    """The reference's published experiment, measured: max batch at
    image_size² on ONE device (reference README.md:9-15 — bs=10 OOMs a
    24 GB A5000, bs=5 runs; DDP trains effective 10). Doubling probe then
    binary search, each trial in a fresh jit with its own allocation;
    allocator failures (RESOURCE_EXHAUSTED / XlaRuntimeError OOM) are the
    signal, not an error. Each working batch runs ONE fetch-synced train
    step so the number means 'trains', not 'allocates'."""
    from tpu_sandbox.utils.cli import ensure_devices

    if force_cpu:
        ensure_devices(1, force_cpu=True)
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tpu_sandbox.data import synthetic_mnist
    from tpu_sandbox.data.mnist import normalize
    from tpu_sandbox.models import pick_convnet
    from tpu_sandbox.train import TrainState, make_train_step
    from tpu_sandbox.utils.profiling import host_sync

    dtype = jnp.bfloat16 if dtype_name == "bf16" else jnp.float32
    model = pick_convnet(image_size, plan=plan, dtype=dtype)
    tx = optax.sgd(1e-4)
    images, labels = synthetic_mnist(n=max(max_batch, 10), seed=0)
    images, labels = normalize(images), labels.astype("int32")

    def trial(bs: int, remat: bool = False) -> bool:
        try:
            state = TrainState.create(
                model, jax.random.key(0),
                jnp.zeros((1, image_size, image_size, 1), dtype), tx,
            )
            step = make_train_step(
                model, tx, image_size=(image_size, image_size), donate=True,
                remat=remat,
            )
            state, loss = step(state, jnp.asarray(images[:bs]),
                               jnp.asarray(labels[:bs]))
            ok = bool(np.isfinite(host_sync(loss)))
            del state
            return ok
        except Exception as e:  # allocator failure IS the measurement
            if _is_oom(f"{type(e).__name__}: {e}"):
                return False
            raise

    def bisect(remat: bool, start: int = 1):
        lo, hi, bs = 0, None, start
        while bs <= max_batch:
            if trial(bs, remat):
                lo = bs
                bs *= 2
            else:
                hi = bs
                break
        if hi is None:
            hi = max_batch + 1  # never failed up to the cap
        while hi - lo > 1:
            mid = (lo + hi) // 2
            if trial(mid, remat):
                lo = mid
            else:
                hi = mid
        return lo, hi

    lo, hi = bisect(remat=False)
    # the capacity lever: recompute-forward backward (make_train_step
    # remat) drops the saved conv activations from peak memory — the
    # one-device counterpart of "just buy a second GPU". Start the
    # doubling from the plain max (remat can only help).
    lo_r, hi_r = bisect(remat=True, start=max(lo, 1))

    # the reference's workaround story, demonstrated on one chip: if the
    # effective batch 10 doesn't fit directly, 2-step gradient accumulation
    # at bs=5/microbatch must still train it (reference README.md:14-15
    # does this with DDP across 2 GPUs instead)
    accum_ok = None
    if lo < 10:
        try:
            state = TrainState.create(
                model, jax.random.key(0),
                jnp.zeros((1, image_size, image_size, 1), dtype), tx,
            )
            step = make_train_step(
                model, tx, image_size=(image_size, image_size), donate=True,
                accum_steps=2,
            )
            _, loss = step(state, jnp.asarray(images[:10]),
                           jnp.asarray(labels[:10]))
            accum_ok = bool(np.isfinite(host_sync(loss)))
        except Exception as e:
            accum_ok = f"{type(e).__name__}: {e}"[:200]

    dev = jax.devices()[0]
    result = {
        "metric": "max_train_batch_one_device",
        "value": lo,
        "unit": f"images @ {image_size}x{image_size} {dtype_name}",
        "vs_baseline": round(lo / 5.0, 2),  # reference: bs=5 fits, 10 OOMs
        "baseline_kind": "reference A5000 24GB: bs=5 runs, bs=10 OOMs "
                         "(README.md:9-15)",
        "first_oom_batch": hi if hi <= max_batch else None,
        "max_batch_remat": lo_r,
        "first_oom_batch_remat": hi_r if hi_r <= max_batch else None,
        "probe_cap": max_batch,
        "effective_batch_10_via_accum2": accum_ok,
        "execution_plan": type(model).__name__,
        "device_kind": str(dev.device_kind),
    }
    if dev.platform == "cpu":
        result["degraded"] = ("CPU host memory, not accelerator HBM — "
                              "capacity number not comparable")
    return result


def bench_seq_scaling(force_cpu: bool, seq_lens=None, devices_wanted: int = 4,
                      quick: bool = False) -> dict:
    """Sequence-parallel attention scaling: ring (jnp) vs flash-ring
    (Pallas) vs Ulysses (all-to-all + flash) forward+backward step time at
    growing S on a 1-axis 'sp' mesh — VERDICT r01 item 5's seq-len table.
    On one real chip the mesh folds to 1 device (collectives are identity;
    still measures the kernels); on CPU it runs the full ring semantics on
    virtual devices."""
    from tpu_sandbox.utils.cli import ensure_devices

    if force_cpu:
        ensure_devices(devices_wanted, force_cpu=True)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_sandbox.parallel import (
        make_flash_ring_attention,
        make_ring_attention,
        make_ulysses_attention,
    )
    from tpu_sandbox.runtime.mesh import make_mesh
    from tpu_sandbox.utils.profiling import measure_per_step_repeated

    n_dev = jax.device_count()
    mesh = make_mesh({"sp": n_dev})
    b, h, d = (1, 4, 64) if quick else (1, 8, 128)
    if seq_lens is None:
        seq_lens = [256, 512] if quick else [4096, 8192, 16384, 32768]

    makers = {
        "ring": lambda: make_ring_attention(mesh, "sp"),
        "flash_ring": lambda: make_flash_ring_attention(mesh, "sp"),
        "ulysses": lambda: make_ulysses_attention(mesh, "sp"),
    }
    rng = np.random.default_rng(0)
    rows = []
    for s in seq_lens:
        row = {"seq_len": s}
        q, k, v = (jnp.asarray(
            rng.standard_normal((b, s, h, d)), jnp.bfloat16) for _ in range(3))
        for name, make in makers.items():
            try:
                attn = make()
                fwdbwd = jax.jit(jax.grad(
                    lambda q: jnp.sum(attn(q, k, v).astype(jnp.float32))
                ))

                def run(steps):
                    x = q
                    for _ in range(steps):
                        x = fwdbwd(x).astype(jnp.bfloat16)
                    return x

                t = measure_per_step_repeated(run, 2)
                # noise-negative differentials are not published (see
                # BASELINE.md "the r01 anomaly"); record why instead
                if t["sec_per_step"] > 0:
                    row[name + "_sec"] = t["sec_per_step"]
                    row[name + "_spread_frac"] = t["spread_frac"]
                else:
                    row[name + "_sec"] = None
                    row[name + "_error"] = (
                        f"non-positive differential {t['sec_per_step']:.3e}s"
                    )
            except Exception as e:
                row[name + "_sec"] = None
                row[name + "_error"] = f"{type(e).__name__}: {e}"[:200]
        rows.append(row)

    base = rows[-1].get("ring_sec")
    best = rows[-1].get("flash_ring_sec")
    result = {
        "metric": "sp_attention_seq_scaling",
        "value": round(base / best, 3) if base and best else 0.0,
        "unit": f"ring/flash_ring speedup @ S={rows[-1]['seq_len']} (fwd+bwd)",
        "vs_baseline": 0.0,
        "devices": n_dev,
        "device_kind": str(jax.devices()[0].device_kind),
        "shape": {"batch": b, "heads": h, "head_dim": d},
        "rows": rows,
    }
    if not (base and best):
        result["degraded"] = "headline pair unmeasured (see rows *_error)"
    return result


def bench_lm(force_cpu: bool, quick: bool = False) -> dict:
    """Transformer-LM training throughput (tokens/sec + MFU) on one device:
    the long-context model family's headline number, with the Pallas flash
    attention kernel on the hot path and the same fetch-synced differential
    timing + FLOP cross-check as the ConvNet bench."""
    from tpu_sandbox.utils.cli import ensure_devices

    if force_cpu:
        ensure_devices(1, force_cpu=True)
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tpu_sandbox.models.transformer import TransformerConfig, TransformerLM
    from tpu_sandbox.ops.losses import cross_entropy_loss
    from tpu_sandbox.ops.pallas_attention import flash_attention_fn
    from tpu_sandbox.train import TrainState
    from tpu_sandbox.utils.flops import mfu as mfu_check, transformer_flops
    from tpu_sandbox.utils.profiling import measure_per_step

    on_tpu = jax.devices()[0].platform == "tpu"
    if quick:
        cfg = TransformerConfig(vocab_size=256, d_model=64, n_heads=2,
                                n_layers=2, d_ff=128, max_len=256,
                                dtype=jnp.float32)
        batch, seq, steps = 2, 128, 3
    else:
        cfg = TransformerConfig(vocab_size=32768, d_model=1024, n_heads=8,
                                n_layers=12, d_ff=4096, max_len=2048,
                                dtype=jnp.bfloat16, remat=True,
                                remat_policy="dots",
                                # the fused Pallas CE upcasts in VMEM —
                                # skip the 4.3 GB fp32 logits round-trip
                                fp32_logits=False)
        # batch 16: fits under dots-remat (chipless AOT: ~12.7 GB peak) and
        # amortizes the fixed AdamW pass — 4.10 vs 4.78 MB/token at b8
        batch, seq, steps = 16, 2048, 5
    attn = flash_attention_fn() if on_tpu else None
    model = TransformerLM(cfg, attention_fn=attn)
    tx = optax.adamw(3e-4)
    state = TrainState.create(
        model, jax.random.key(0), jnp.zeros((1, seq), jnp.int32), tx
    )

    def loss_fn(params, tokens, targets):
        logits = model.apply({"params": params}, tokens)
        return cross_entropy_loss(
            logits.reshape(-1, logits.shape[-1]), targets.reshape(-1)
        )

    # donate the state like the ConvNet benches (and real training) do:
    # in-place AdamW updates instead of fresh param/mu/nu output buffers
    # (~2+ GB at this config), and it matches what tools/aot_lm_cycles.py
    # attributes chiplessly
    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens, targets)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        return state.replace(
            step=state.step + 1,
            params=optax.apply_updates(state.params, updates),
            opt_state=new_opt,
        ), loss

    rng = np.random.default_rng(0)
    staged = []
    for _ in range(4):
        toks = jnp.asarray(
            rng.integers(0, cfg.vocab_size, size=(batch, seq)), jnp.int32
        )
        staged.append((toks, (toks + 1) % cfg.vocab_size))

    def run(k):
        nonlocal state
        loss = None
        for i in range(k):
            t, tg = staged[i % len(staged)]
            state, loss = step(state, t, tg)
        return loss

    timing = measure_per_step(run, steps)
    spt = timing["sec_per_step"]
    tokens_per_step = batch * seq
    flops = transformer_flops(
        cfg.n_layers, cfg.d_model, cfg.d_ff, seq, cfg.vocab_size
    )["train"] * tokens_per_step
    util = mfu_check(flops, spt if spt > 0 else 1.0,
                     str(jax.devices()[0].device_kind))
    result = {
        "metric": "lm_train_tokens_per_sec",
        "value": round(tokens_per_step / spt, 1) if spt > 0 else 0.0,
        "unit": "tokens/sec",
        "vs_baseline": 0.0,  # reference has no LM at all (SURVEY §2.2)
        "config": {"d_model": cfg.d_model, "n_layers": cfg.n_layers,
                   "d_ff": cfg.d_ff, "seq": seq, "batch": batch,
                   "vocab": cfg.vocab_size,
                   "dtype": str(cfg.dtype.__name__ if hasattr(cfg.dtype, "__name__")
                                else cfg.dtype),
                   "flash_attention": bool(attn), "remat": cfg.remat,
                   "remat_policy": cfg.remat_policy},
        "sec_per_step": spt,
        "timing_method": timing["timing_method"],
        "flops_per_step_model": flops,
        "achieved_tflops": round(util["achieved_tflops"], 2),
        "peak_tflops_bf16": util["peak_tflops_bf16"],
        "mfu": round(util["mfu"], 4) if util["mfu"] is not None else None,
        "device_kind": str(jax.devices()[0].device_kind),
    }
    if spt <= 0:
        result.update(value=0.0, achieved_tflops=0.0, mfu=None)
        result["degraded"] = (
            f"non-positive differential step time ({spt:.6f}s)"
        )
    elif not util["plausible"]:
        result.update(value=0.0)
        result["degraded"] = (
            f"implausible mfu {util['mfu']:.2f}; number untrusted"
        )
    return result


def bench_pallas(force_cpu: bool) -> dict:
    """Compile-and-run the Pallas kernels on the real device and compare
    against the jnp reference — the driver-visible Mosaic-lowering check
    VERDICT r01 item 4 asked for. Exits nonzero (exception) if lowering or
    numerics break."""
    from tpu_sandbox.utils.cli import ensure_devices

    if force_cpu:
        ensure_devices(1, force_cpu=True)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_sandbox.ops.attention import causal_attention
    from tpu_sandbox.ops.losses import cross_entropy_loss
    from tpu_sandbox.ops.pallas_attention import flash_attention
    from tpu_sandbox.ops.pallas_ce import pallas_cross_entropy
    from tpu_sandbox.utils.profiling import host_sync, measure_per_step

    on_tpu = jax.devices()[0].platform == "tpu"
    interpret = not on_tpu  # real Mosaic lowering on TPU; interpreter on CPU
    rng = np.random.default_rng(0)
    checks = {}

    # Non-multiple-of-block seq len AND bf16 — the hard cases VERDICT names.
    # Layout is [B, S, H, D] (the transformer's).
    for (b, s, h, d, dt) in [(2, 512, 4, 64, "float32"),
                             (2, 384, 4, 64, "bfloat16"),
                             (1, 1024, 8, 128, "bfloat16")]:
        q, k, v = (jnp.asarray(rng.normal(size=(b, s, h, d)), dtype=dt)
                   for _ in range(3))
        out = flash_attention(q, k, v, interpret=interpret)
        # Reference at HIGHEST matmul precision: on TPU the default f32
        # einsum rounds operands to bf16 on the MXU, which would make the
        # reference as noisy as the thing under test.
        with jax.default_matmul_precision("highest"):
            ref = causal_attention(q, k, v)
        err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                    - ref.astype(jnp.float32))))
        # f32 bound on TPU is the MXU operand-rounding floor (the kernel
        # feeds the systolic array bf16-rounded inputs with f32
        # accumulation; one rounding step is 2^-8 relative, amplified ~2x
        # through softmax) — measured 6.5e-3 on v5e. It is NOT an
        # accumulation-bug budget: interpret mode has no MXU rounding, so
        # the CPU path keeps the tight bound and still catches real
        # accumulation regressions off-chip.
        if dt == "bfloat16":
            tol = 2e-2
        else:
            tol = 1.5e-2 if on_tpu else 2e-3
        assert err < tol, (b, s, h, d, dt, err)
        checks[f"flash_s{s}_{dt}"] = err

    logits = jnp.asarray(rng.normal(size=(64, 32000)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 32000, size=(64,)), jnp.int32)
    ce = pallas_cross_entropy(logits, labels, interpret=interpret)
    # optax DIRECTLY: losses.cross_entropy_loss now dispatches LM-scale
    # vocabs to the very kernel under test, which would compare the
    # kernel against itself
    import optax as _optax
    ce_ref = _optax.softmax_cross_entropy_with_integer_labels(
        logits, labels).mean()
    ce_err = float(jnp.abs(ce - ce_ref))
    assert ce_err < 1e-3, ce_err
    checks["ce_64x32000"] = ce_err

    # the s2d ConvNet's fused BN/ReLU/pool tail vs the unfused jnp chain
    from tpu_sandbox.ops.pallas_bn_tail import (
        fused_bn_relu_pool,
        unfused_reference,
    )

    co, blk = (16, 4) if on_tpu else (4, 2)
    hw = 40 if on_tpu else 8
    c = blk * blk * co
    yb = jnp.asarray(rng.normal(size=(2, hw, hw, c)), jnp.bfloat16)
    gam = jnp.asarray(1 + 0.1 * rng.normal(size=co), jnp.float32)
    bet = jnp.asarray(rng.normal(size=co), jnp.float32)
    fout, fmu, fvar = fused_bn_relu_pool(yb, gam, bet, co, blk, 1e-5,
                                         interpret)
    tail_ref, mu_r, var_r = unfused_reference(yb, gam, bet, co, blk)
    assert float(jnp.max(jnp.abs(fmu - mu_r))) < 1e-4
    assert float(jnp.max(jnp.abs(fvar - var_r))) < 1e-4
    tail_err = float(jnp.max(jnp.abs(fout.astype(jnp.float32)
                                     - tail_ref.astype(jnp.float32))))
    assert tail_err < 2e-2, tail_err
    checks[f"bn_tail_blk{blk}_co{co}"] = tail_err

    # the s2d conv kernels (fwd + stats variant + full VJP) vs lax.conv —
    # fused_conv is the pick_convnet TPU default, so an on-chip run of the
    # headline path depends on these compiling AND agreeing numerically
    from tpu_sandbox.ops.pallas_conv import (
        conv3x3,
        conv3x3_reference,
        conv3x3_stats,
    )

    ch, cco, chw = (16, 256, 40) if on_tpu else (4, 8, 10)
    xc = jnp.asarray(rng.normal(size=(2, chw, chw, ch)), jnp.bfloat16)
    kc = jnp.asarray(0.1 * rng.normal(size=(3, 3, ch, cco)), jnp.bfloat16)
    bc = jnp.asarray(rng.normal(size=(cco,)), jnp.bfloat16)
    yc, sc, ssc = conv3x3_stats(xc, kc, bc, interpret)
    yc_ref = conv3x3_reference(xc, kc, bc)
    conv_err = float(jnp.max(jnp.abs(yc.astype(jnp.float32)
                                     - yc_ref.astype(jnp.float32))))
    assert conv_err < 0.15, conv_err  # bf16 conv, K up to 9*16 taps
    yf = yc.astype(jnp.float32).reshape(-1, cco)
    assert float(jnp.max(jnp.abs(sc[0] - yf.sum(0)))
                 / max(1.0, float(jnp.max(jnp.abs(sc))))) < 1e-3
    checks[f"conv3x3_{ch}to{cco}"] = conv_err
    gc = jax.grad(
        lambda x, k, b: jnp.sum(conv3x3(x, k, b, interpret)
                                .astype(jnp.float32) ** 2),
        argnums=(0, 1, 2),
    )(xc, kc, bc)
    gr = jax.grad(
        lambda x, k, b: jnp.sum(conv3x3_reference(
            x.astype(jnp.float32), k.astype(jnp.float32),
            b.astype(jnp.float32)) ** 2),
        argnums=(0, 1, 2),
    )(xc, kc, bc)
    for a, r, nm in zip(gc, gr, ("dx", "dw", "db")):
        scale = max(1.0, float(jnp.max(jnp.abs(r))))
        rel = float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                    - r.astype(jnp.float32)))) / scale
        assert rel < 0.05, (nm, rel)
        checks[f"conv3x3_grad_{nm}"] = rel

    # the TRANSPOSED plan's kernels (pallas_conv_t + pallas_bn_tail_t) —
    # what plan=auto actually runs on TPU since round 3, so the on-chip
    # headline number depends on these agreeing numerically too
    from tpu_sandbox.ops.pallas_bn_tail_t import (
        fused_bn_relu_pool_t,
        unfused_reference_t,
    )
    from tpu_sandbox.ops.pallas_conv_t import conv3x3_t, conv3x3_t_stats

    xt = jnp.transpose(xc, (0, 1, 3, 2))
    yt, st, sst = conv3x3_t_stats(xt, kc, bc, interpret)
    convt_err = float(jnp.max(jnp.abs(
        yt.astype(jnp.float32)
        - jnp.transpose(yc_ref, (0, 1, 3, 2)).astype(jnp.float32))))
    assert convt_err < 0.15, convt_err
    assert float(jnp.max(jnp.abs(st[:, 0] - yf.sum(0)))
                 / max(1.0, float(jnp.max(jnp.abs(st))))) < 1e-3
    checks[f"conv3x3_t_{ch}to{cco}"] = convt_err
    gt = jax.grad(
        lambda x, k, b: jnp.sum(conv3x3_t(x, k, b, interpret)
                                .astype(jnp.float32) ** 2),
        argnums=(0, 1, 2),
    )(xt, kc, bc)
    for a, r, nm in zip(gt, gr, ("dx", "dw", "db")):
        if nm == "dx":
            a = jnp.transpose(a, (0, 1, 3, 2))
        scale = max(1.0, float(jnp.max(jnp.abs(r))))
        rel = float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                    - r.astype(jnp.float32)))) / scale
        assert rel < 0.05, (nm, rel)
        checks[f"conv3x3_t_grad_{nm}"] = rel

    # the r04 sparse-tap conv1 (what the transposed plan actually runs)
    from tpu_sandbox.ops.pallas_conv5_t import (
        conv1_s2d_t,
        conv1_s2d_t_reference,
    )

    s_hw = 40 if on_tpu else 12
    xs = jnp.asarray(rng.normal(size=(2, s_hw, 16, s_hw)), jnp.bfloat16)
    k5s = jnp.asarray(0.1 * rng.normal(size=(5, 5, 1, 16)), jnp.bfloat16)
    b5s = jnp.asarray(rng.normal(size=(16,)), jnp.bfloat16)
    ysp = conv1_s2d_t(xs, k5s, b5s, interpret)
    ysp_ref = conv1_s2d_t_reference(xs, k5s, b5s)
    sp_err = float(jnp.max(jnp.abs(ysp.astype(jnp.float32)
                                   - ysp_ref.astype(jnp.float32))))
    assert sp_err < 0.15, sp_err
    checks["conv1_sparse_tap"] = sp_err
    gsp = jax.grad(
        lambda k, b: jnp.sum(conv1_s2d_t(xs, k, b, interpret)
                             .astype(jnp.float32) ** 2),
        argnums=(0, 1),
    )(k5s, b5s)
    gsp_ref = jax.grad(
        lambda k, b: jnp.sum(conv1_s2d_t_reference(
            xs.astype(jnp.float32), k.astype(jnp.float32),
            b.astype(jnp.float32)) ** 2),
        argnums=(0, 1),
    )(k5s, b5s)
    for a, r, nm in zip(gsp, gsp_ref, ("dk5", "db")):
        scale = max(1.0, float(jnp.max(jnp.abs(r))))
        rel = float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                    - r.astype(jnp.float32)))) / scale
        assert rel < 0.05, (nm, rel)
        checks[f"conv1_sparse_grad_{nm}"] = rel

    ytail = jnp.transpose(yb, (0, 1, 3, 2))
    tout, tmu, tvar = fused_bn_relu_pool_t(ytail, gam, bet, co, blk, 1e-5,
                                           interpret)
    tref, tmu_r, tvar_r = unfused_reference_t(ytail, gam, bet, co, blk)
    assert float(jnp.max(jnp.abs(tmu - tmu_r))) < 1e-4
    assert float(jnp.max(jnp.abs(tvar - tvar_r))) < 1e-4
    tailt_err = float(jnp.max(jnp.abs(tout.astype(jnp.float32)
                                      - tref.astype(jnp.float32))))
    assert tailt_err < 2e-2, tailt_err
    checks[f"bn_tail_t_blk{blk}_co{co}"] = tailt_err

    # Micro-throughput of the flash kernel at a real shape (honest timing).
    # Interpret mode runs the kernel body per grid cell in Python — the
    # s=4096 shape would take hours on CPU, so the fallback shrinks it
    # (shape is in the JSON; a tiny interpret number is obviously not a
    # TPU claim).
    if interpret:
        b, s, h, d, iters = 1, 256, 2, 64, 1
    else:
        b, s, h, d, iters = 4, 4096, 8, 128, 8
    q, k, v = (jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.bfloat16)
               for _ in range(3))
    fa = jax.jit(lambda q, k, v: flash_attention(q, k, v, interpret=interpret))
    host_sync(fa(q, k, v))
    from tpu_sandbox.utils.profiling import measure_per_step_repeated
    timing = measure_per_step_repeated(
        lambda n: _chain_attn(fa, q, k, v, n), iters,
        repeats=1 if interpret else 3)
    # causal attention: ~2 * 2 * b*h*s^2*d / 2 FLOPs (QK^T + PV, causal half)
    flops = 2 * 2 * b * h * s * s * d / 2
    tflops = flops / timing["sec_per_step"] / 1e12

    return {
        "metric": "pallas_kernel_check",
        "value": round(tflops, 2),
        "unit": f"TFLOP/s (flash fwd, b{b} s{s} h{h} d{d} bf16)",
        "vs_baseline": 0.0,
        "mode": "mosaic" if on_tpu else "interpret",
        "device_kind": str(jax.devices()[0].device_kind),
        "max_abs_errors": {k: round(v, 6) for k, v in checks.items()},
        "sec_per_call": timing["sec_per_step"],
        "timing_method": timing["timing_method"],
        "sec_per_call_samples": timing.get("sec_per_step_samples"),
        "spread_frac": timing.get("spread_frac"),
    }


def _chain_attn(fa, q, k, v, n):
    """n data-dependent attention calls (output feeds next q)."""
    out = q
    for _ in range(n):
        out = fa(out, k, v)
    return out


def _emit(result: dict, args) -> None:
    """Print the one-line round record and, with ``--archive DIR`` (or
    ``BENCH_ARCHIVE`` in the env), land the run's analysis artifacts —
    trace dirs, critpath profiles, the workload trace — next to the
    BENCH_rNN.json the driver commits, so every round's number stays
    re-derivable from its raw trace. Benches opt in by returning an
    ``_artifacts`` mapping of name -> file-or-dir; it never appears in
    the printed record."""
    import shutil

    artifacts = result.pop("_artifacts", None)
    line = json.dumps(result)
    dest = getattr(args, "archive", None) or os.environ.get("BENCH_ARCHIVE")
    if dest:
        os.makedirs(dest, exist_ok=True)
        with open(os.path.join(dest, "result.json"), "w",
                  encoding="utf-8") as fh:
            fh.write(line + "\n")
        for name, path in (artifacts or {}).items():
            target = os.path.join(dest, name)
            if os.path.isdir(path):
                shutil.copytree(path, target, dirs_exist_ok=True)
            elif os.path.isfile(path):
                shutil.copy2(path, target)
    print(line)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--metric",
                   choices=["grad_compress", "overlap", "donation",
                            "cluster", "serve", "serve_slo", "gateway",
                            "chaos",
                            "obs", "health", "deploy", "mpmd", "critpath",
                            "images_per_sec",
                            "allreduce_bw", "pallas",
                            "capacity", "seq_scaling", "lm", "sweep",
                            "convergence"],
                   default="images_per_sec",
                   help="which benchmark to run (driver default: images/sec)")
    p.add_argument("--image-size", type=int, default=3000)
    p.add_argument("--batch-per-device", type=int, default=5)
    p.add_argument("--steps", type=int, default=10,
                   help="n for the differential timer (runs ~4n steps total)")
    p.add_argument("--warmup", type=int, default=1)
    p.add_argument("--dtype", choices=["bf16", "fp32"], default="bf16")
    p.add_argument("--plan", choices=["auto", "s2dt", "s2d", "plain"],
                   default="auto",
                   help="ConvNet execution plan: s2dt = transposed "
                        "space-to-depth (models/convnet_s2d_t.py), s2d = "
                        "NHWC space-to-depth (models/convnet_s2d.py) — "
                        "same function either way, tested; auto picks "
                        "s2dt on TPU when the image size allows")
    p.add_argument("--baseline", type=float, default=75.0)
    p.add_argument("--quick", action="store_true",
                   help="tiny CPU config to validate the harness itself")
    p.add_argument("--archive", default=None, metavar="DIR",
                   help="also land the run's trace/profile artifacts and "
                        "result.json under DIR (next to the committed "
                        "BENCH_rNN.json); BENCH_ARCHIVE in the env does "
                        "the same")
    p.add_argument("--probe-timeout", type=float,
                   default=float(os.environ.get("BENCH_PROBE_TIMEOUT", 240)),
                   help="seconds to wait for the accelerator before falling "
                        "back to a small CPU run (0 = skip probe)")
    args = p.parse_args()
    if args.metric == "grad_compress":
        # chipless by design (CPU SPMD compile); no accelerator probe
        _emit(bench_grad_compress_traffic(), args)
        return
    if args.metric == "overlap":
        # chipless AOT schedule + host-thread stall timing; no probe
        _emit(bench_overlap(), args)
        return
    if args.metric == "donation":
        # chipless AOT memory analysis (subprocess-isolated); no probe
        _emit(bench_donation(), args)
        return
    if args.metric == "cluster":
        # chipless scheduler control-plane timing (stub tenants); no probe
        _emit(bench_cluster(), args)
        return
    if args.metric == "serve":
        # chipless serving SLOs (tiny model, CPU backend); no probe.
        # --quick shrinks the trace and skips the AOT donation receipt.
        _emit(bench_serve(quick=args.quick), args)
        return
    if args.metric == "serve_slo":
        # chipless overload/shedding guardrail receipt; no probe
        _emit(bench_serve_slo(quick=args.quick), args)
        return
    if args.metric == "gateway":
        # chipless routing/admission receipt over real sockets; no probe
        _emit(bench_gateway(quick=args.quick), args)
        return
    if args.metric == "chaos":
        # chipless HA/chaos receipt: real gateway processes over TLS,
        # seeded fault campaigns, claim audit from the store; no probe
        _emit(bench_chaos(quick=args.quick), args)
        return
    if args.metric == "obs":
        # chipless flight-recorder overhead receipt; no probe
        _emit(bench_obs(quick=args.quick), args)
        return
    if args.metric == "health":
        # chipless health-plane overhead + detection-latency receipt
        _emit(bench_health(quick=args.quick), args)
        return
    if args.metric == "critpath":
        # chipless trace-analytics receipt: attribution coverage,
        # tracediff gating, online-vs-offline pipeline bubble; no probe
        _emit(bench_critpath(quick=args.quick), args)
        return
    if args.metric == "deploy":
        # chipless train->serve deployment receipt; no probe
        _emit(bench_deploy(quick=args.quick), args)
        return
    if args.metric == "mpmd":
        # chipless fast-fabric receipt: staged vs device transport, the
        # autotuned ZB-H1 bubble, fault claim audit, tracediff gate on
        # fabric profiles (fails the process, CI-style); no probe.
        # --quick shrinks and skips the AOT + archived-control gate.
        mpmd_steps = (20 if args.steps == p.get_default("steps")
                      else args.steps)
        result = bench_mpmd(steps=mpmd_steps, quick=args.quick)
        _emit(result, args)
        if not result.get("tracediff_gate_ok", True):
            sys.exit(1)
        return
    if args.metric != "images_per_sec":
        # probe-timeout 0 means "trust the environment" (same semantics as
        # the images/sec path), not "force CPU"
        usable = not args.probe_timeout or accelerator_usable(args.probe_timeout)
        if args.metric == "allreduce_bw":
            result = bench_allreduce_bw(force_cpu=not usable)
        elif args.metric == "pallas":
            result = bench_pallas(force_cpu=not usable)
        elif args.metric == "capacity":
            shrunk = args.quick or not usable
            result = bench_capacity(
                args.image_size if not shrunk else 256,
                args.dtype, force_cpu=not usable,
                max_batch=8 if shrunk else 512, plan=args.plan,
            )
            if args.quick and usable:
                # shrunken shapes: the A5000-baseline ratio is meaningless
                result["degraded"] = ("--quick shrank image_size/probe cap; "
                                      "vs_baseline not comparable")
        elif args.metric == "sweep":
            result = bench_sweep(args.image_size, args.steps, args.warmup,
                                 args.baseline, force_cpu=not usable,
                                 quick=args.quick or not usable,
                                 plan=args.plan)
            if args.quick and usable:
                # shrunken shapes: the A5000-baseline ratio is meaningless
                result["degraded"] = ("--quick shrank the sweep shapes; "
                                      "vs_baseline not comparable")
        elif args.metric == "lm":
            result = bench_lm(force_cpu=not usable,
                              quick=args.quick or not usable)
            if args.quick and usable:
                result["degraded"] = ("--quick shrank the model; not the "
                                      "headline LM config")
        elif args.metric == "convergence":
            # --steps' global default (10) is sized for the differential
            # timer; a convergence CURVE needs more. Only the untouched
            # default is upgraded — an explicit --steps N is honored.
            conv_steps = (40 if args.steps == p.get_default("steps")
                          else args.steps)
            result = bench_convergence(
                args.image_size if not args.quick else 128,
                conv_steps, force_cpu=not usable, plan=args.plan)
        else:
            result = bench_seq_scaling(
                force_cpu=not usable, quick=args.quick or not usable
            )
        if not usable:
            # append, never overwrite: a benchmark's own degraded reason
            # (e.g. capacity's host-memory caveat) is the actionable one
            fallback = "accelerator unavailable; CPU fallback"
            result["degraded"] = (
                f"{result['degraded']}; {fallback}"
                if "degraded" in result else fallback
            )
        _emit(result, args)
        return
    if args.quick:
        result = bench(128, 2, 3, 1, "fp32", True, args.baseline,
                       plan=args.plan)
    elif args.probe_timeout and not accelerator_usable(args.probe_timeout):
        # accelerator wedged/absent: report an honest degraded-mode number
        # rather than hanging the driver (or taking hours at 3000x3000 on
        # CPU). The line names exactly what was overridden; pass
        # --probe-timeout 0 to force the requested shapes on CPU.
        used = dict(image_size=256, batch_per_device=2, steps=3, warmup=1,
                    dtype="fp32")
        requested = dict(image_size=args.image_size,
                         batch_per_device=args.batch_per_device,
                         steps=args.steps, warmup=args.warmup,
                         dtype=args.dtype)
        result = bench(used["image_size"], used["batch_per_device"],
                       used["steps"], used["warmup"], used["dtype"], True,
                       args.baseline, plan=args.plan)
        overridden = {k: f"{requested[k]}->{used[k]}"
                      for k in used if requested[k] != used[k]}
        result["degraded"] = ("accelerator unavailable; CPU fallback "
                              f"overrode {overridden or 'nothing'}")
        # the round artifact should not be information-free when the
        # tunnel is down: carry the CONCRETE resolved plan's chipless AOT
        # floors and its last measured number, explicitly labeled
        # (BASELINE.md holds the analyses). Keyed by plan so a
        # --plan plain/s2d/fp32 line never carries another plan's numbers.
        est_by_plan = {
            "s2dt": {
                "plan": "s2dt (transposed) + pallas kernels + fused input "
                        "stage + in-layout fc + sparse-tap conv1 (r04) + "
                        "gt-restaged wgrads + pallas fc input-grad + fused "
                        "conv1/tail backward (r05), bs=16 bf16",
                "aot_op_traffic_gb": 73.3,
                "aot_op_traffic_note": "padded-buffer per-op accounting "
                                       "(hlo_traffic) - was 82.8 before the "
                                       "r05 conv1-cotangent fusion; XLA's "
                                       "bytes_accessed (15.7 GB) is blind "
                                       "to custom-call operands",
                "last_measured_images_per_sec": 80.36,
                "last_measured": "bs=16 bf16, r03 PRE-surgery step "
                                 "(measured/images_per_sec_s2dt_b16.json)",
                "source": "chipless v5e AOT compile "
                          "(measured/aot_s2dt_b16_r05.jsonl, "
                          "hlo_traffic_s2dt_b16_r05.json); measured r03",
            },
            "s2d": {
                "plan": "s2d + pallas conv/tail kernels, bs=16 bf16",
                "aot_bytes_accessed_gb": 27.2,
                "aot_bw_floor_ms_per_step": 33.2,
                "compute_floor_ms_per_step": 48,
                "last_measured_images_per_sec": 62.95,
                "last_measured": "bs=16 bf16, r03 "
                                 "(measured/images_per_sec_b16_r03.json)",
                "source": "chipless v5e AOT compile + kernel-shape analysis "
                          "(measured/aot_s2d_fusedconv_b16.jsonl, BASELINE.md "
                          "'The 10× target, argued')",
            },
        }
        # NOTE: can't use resolve_plan here — in this degraded branch the
        # process is already on the CPU backend, where 'auto' resolves to
        # 's2d'; the line stands in for the TPU run, where it is 's2dt'.
        from tpu_sandbox.models import resolves_to_s2d
        if resolves_to_s2d(args.image_size, args.plan):
            est_plan = "s2dt" if args.plan == "auto" else args.plan
            est = est_by_plan.get(est_plan)
            if est is not None and args.dtype == "bf16":
                result["estimated_not_measured"] = est
    else:
        result = run_plan_ladder(
            lambda overrides: bench(
                args.image_size, args.batch_per_device, args.steps,
                args.warmup, args.dtype, False, args.baseline,
                plan=args.plan, model_overrides=overrides,
            ),
            image_size=args.image_size, plan=args.plan,
        )
    _emit(result, args)


if __name__ == "__main__":
    main()
