"""Headline benchmark: images/sec training the 3000x3000-MNIST ConvNet.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Baseline accounting (BASELINE.md): the reference publishes no throughput —
only that 2x RTX A5000 under DDP train effective batch 10 at 3000x3000.
``--baseline`` therefore defaults to an *estimated upper bound* for that rig:
~366 GFLOP/image (conv1 7.2 + conv2 115 fwd, x3 for training) at an
optimistic 50% fp32 utilization of 2x27.8 TF/s => ~75 img/s, ignoring the
reference's real bottleneck (single-threaded host-side PIL 28->3000 resize,
num_workers=0, which caps it far lower). We compare against the generous
estimate so vs_baseline understates, never overstates, the win.

Run config mirrors the reference experiment: bs=5 per device, 3000x3000,
bf16 compute (fp32 params), synthetic MNIST (zero-egress), data-parallel
over all available devices (1 chip = plain jit path of the same step).
"""

import argparse
import json
import os
import subprocess
import sys
import time


def accelerator_usable(timeout: float = 240.0) -> bool:
    """Probe the accelerator in a THROWAWAY subprocess with a hard timeout.

    Backend init happens inside native code a signal can't interrupt, so a
    wedged TPU tunnel would hang this process forever; probing in a child
    and killing it on timeout keeps the bench guaranteed to print its JSON
    line (a CPU fallback number beats a silent hang).
    """
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices()[0].platform != 'cpu'"],
            timeout=timeout, capture_output=True,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def bench(image_size: int, batch_per_device: int, steps: int, warmup: int,
          dtype_name: str, force_cpu: bool, baseline: float) -> dict:
    from tpu_sandbox.utils.cli import ensure_devices

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    if force_cpu:
        ensure_devices(1, force_cpu=True)
    n_dev = jax.device_count()
    devices = jax.devices()

    from tpu_sandbox.data import synthetic_mnist
    from tpu_sandbox.data.mnist import normalize
    from tpu_sandbox.models import ConvNet
    from tpu_sandbox.parallel import DataParallel
    from tpu_sandbox.runtime.mesh import make_mesh
    from tpu_sandbox.train import TrainState

    dtype = jnp.bfloat16 if dtype_name == "bf16" else jnp.float32
    model = ConvNet(dtype=dtype)
    tx = optax.sgd(1e-4)
    global_batch = batch_per_device * n_dev

    images, labels = synthetic_mnist(n=global_batch * 8, seed=0)
    images, labels = normalize(images), labels.astype("int32")

    state = TrainState.create(
        model, jax.random.key(0), jnp.zeros((1, image_size, image_size, 1), dtype), tx
    )
    mesh = make_mesh({"data": n_dev}, devices=devices)
    dp = DataParallel(model, tx, mesh, image_size=(image_size, image_size))
    state = dp.shard_state(state)

    def step(s, i, l):
        return dp.train_step(s, *dp.shard_batch(i, l))

    rng = np.random.default_rng(0)

    def batch():
        sel = rng.integers(0, len(images), size=global_batch)
        return images[sel], labels[sel]

    for _ in range(warmup):
        state, loss = step(state, *batch())
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, loss = step(state, *batch())
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    ips = global_batch * steps / dt
    return {
        "metric": "train_images_per_sec_3000x3000_mnist",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / baseline, 3),
        "baseline_images_per_sec": baseline,
        "baseline_kind": "estimated 2xA5000 DDP upper bound (see bench.py docstring)",
        "devices": n_dev,
        "device_kind": str(devices[0].device_kind),
        "global_batch": global_batch,
        "image_size": image_size,
        "dtype": dtype_name,
        "steps_timed": steps,
        "sec_per_step": round(dt / steps, 4),
        "final_loss": round(float(jnp.ravel(loss)[0]), 4),
    }


def bench_allreduce_bw(force_cpu: bool) -> dict:
    """All-reduce bus bandwidth over all devices — the second north-star
    metric BASELINE.md names (NCCL-style busbw accounting)."""
    from tpu_sandbox.utils.cli import ensure_devices

    if force_cpu:
        ensure_devices(8, force_cpu=True)
    import jax

    from tpu_sandbox.parallel.collectives import world_group

    g = world_group()
    r = g.allreduce_bandwidth()
    result = {
        "metric": "allreduce_bus_bandwidth",
        "value": round(r["busbw_GBps"], 3),
        "unit": "GB/s",
        "vs_baseline": 0.0,  # reference published no bandwidth number
        "algbw_GBps": round(r["algbw_GBps"], 3),
        "payload_bytes": r["bytes"],
        "devices": jax.device_count(),
        "device_kind": str(jax.devices()[0].device_kind),
    }
    if jax.device_count() == 1:
        # busbw = algbw * 2*(n-1)/n is identically 0 at n=1; say why
        result["degraded"] = "single device; no interconnect to measure"
    return result


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--metric", choices=["images_per_sec", "allreduce_bw"],
                   default="images_per_sec",
                   help="which benchmark to run (driver default: images/sec)")
    p.add_argument("--image-size", type=int, default=3000)
    p.add_argument("--batch-per-device", type=int, default=5)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--dtype", choices=["bf16", "fp32"], default="bf16")
    p.add_argument("--baseline", type=float, default=75.0)
    p.add_argument("--quick", action="store_true",
                   help="tiny CPU config to validate the harness itself")
    p.add_argument("--probe-timeout", type=float,
                   default=float(os.environ.get("BENCH_PROBE_TIMEOUT", 240)),
                   help="seconds to wait for the accelerator before falling "
                        "back to a small CPU run (0 = skip probe)")
    args = p.parse_args()
    if args.metric == "allreduce_bw":
        # probe-timeout 0 means "trust the environment" (same semantics as
        # the images/sec path), not "force CPU"
        usable = not args.probe_timeout or accelerator_usable(args.probe_timeout)
        result = bench_allreduce_bw(force_cpu=not usable)
        if not usable:
            result["degraded"] = "accelerator unavailable; 8 virtual CPU devices"
        print(json.dumps(result))
        return
    if args.quick:
        result = bench(128, 2, 3, 1, "fp32", True, args.baseline)
    elif args.probe_timeout and not accelerator_usable(args.probe_timeout):
        # accelerator wedged/absent: report an honest degraded-mode number
        # rather than hanging the driver (or taking hours at 3000x3000 on
        # CPU). The line names exactly what was overridden; pass
        # --probe-timeout 0 to force the requested shapes on CPU.
        used = dict(image_size=256, batch_per_device=2, steps=3, warmup=1,
                    dtype="fp32")
        requested = dict(image_size=args.image_size,
                         batch_per_device=args.batch_per_device,
                         steps=args.steps, warmup=args.warmup,
                         dtype=args.dtype)
        result = bench(used["image_size"], used["batch_per_device"],
                       used["steps"], used["warmup"], used["dtype"], True,
                       args.baseline)
        overridden = {k: f"{requested[k]}->{used[k]}"
                      for k in used if requested[k] != used[k]}
        result["degraded"] = ("accelerator unavailable; CPU fallback "
                              f"overrode {overridden or 'nothing'}")
    else:
        result = bench(args.image_size, args.batch_per_device, args.steps,
                       args.warmup, args.dtype, False, args.baseline)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
