"""Transformer-LM training across every parallelism the framework ships.

The reference stops at a CNN + data parallelism (SURVEY §2.2: TP/PP/SP/EP
and attention "ABSENT"); this entry script is the showcase for the
capabilities the TPU build adds on top — the same decoder-only LM trained
under any of:

  dp    — DataParallel-equivalent via PjitEngine (batch sharded on 'data')
  tp    — tensor parallel: qkv/mlp kernels sharded on 'model'
  sp    — sequence parallel: ring attention over 'sp' (long context)
  pp    — pipeline parallel: GPipe microbatches over 'pipe'
  pp_sp — pipeline stages with the sequence sharded over 'sp' (ring or
          flash-ring attention inside every stage block)
  ep    — expert parallel: switch-MoE, expert weights sharded on 'expert'

Data is a deterministic synthetic character stream (zero egress): the task
is modular next-token prediction, which a small LM drives to near-zero loss
in a few hundred steps — enough signal to watch convergence per
parallelism. ``--flash`` swaps in the Pallas flash-attention kernel
(ops/pallas_attention.py); ``--remat`` wraps each block in jax.checkpoint
to trade FLOPs for activation memory at long sequence lengths.

Examples::

    python lm_train.py --parallelism dp --devices 4 --force-cpu
    python lm_train.py --parallelism sp --devices 8 --seq-len 1024
    python lm_train.py --parallelism tp --devices 4 --steps 100 --flash
"""

import argparse

from tpu_sandbox.utils.cli import add_grad_compress_cli, add_overlap_cli


def make_batches(vocab: int, batch: int, seq_len: int, steps: int, seed: int):
    """Deterministic synthetic LM stream: targets = (tokens + k) % vocab with
    position-dependent k — learnable by position embeddings + mixing."""
    import numpy as np

    rng = np.random.default_rng(seed)
    for _ in range(steps):
        tokens = rng.integers(0, vocab, size=(batch, seq_len), dtype=np.int32)
        shift = (np.arange(seq_len, dtype=np.int32) % 3) + 1
        targets = ((tokens + shift[None, :]) % vocab).astype(np.int32)
        yield tokens, targets


def train(args):
    from tpu_sandbox.utils.cli import ensure_devices

    devices = ensure_devices(args.devices, force_cpu=args.force_cpu)

    import datetime

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    from tpu_sandbox.models.transformer import TransformerConfig, TransformerLM
    from tpu_sandbox.parallel import (
        MoeMlp,
        PipelineParallel,
        PjitEngine,
        SeqParallel,
        megatron_rules,
    )
    from tpu_sandbox.runtime import bootstrap
    from tpu_sandbox.runtime.mesh import make_mesh
    from tpu_sandbox.train import TrainState

    bootstrap.init()
    n = len(devices)
    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    attention_fn = None
    if args.flash:
        from tpu_sandbox.ops.pallas_attention import flash_attention_fn

        attention_fn = flash_attention_fn()

    from tpu_sandbox.ops.losses import _FUSED_CE_MIN_CLASSES

    cfg = TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, d_ff=args.d_ff, max_len=args.seq_len,
        dtype=dtype, remat=args.remat,
        remat_policy=args.remat_policy,
        n_experts=(n if args.parallelism == "ep" else 0),
        router_top_k=args.router_top_k,
        # when the loss will run the fused Pallas CE (LM-scale vocab),
        # skip the fp32 logits round-trip — the kernel upcasts in VMEM
        fp32_logits=args.vocab < _FUSED_CE_MIN_CLASSES,
    )
    # schedule + clipping: the standard LM training kit. Cosine decay
    # warms up linearly for --warmup steps then decays to 10% of --lr over
    # the run; --clip-norm prepends global-norm clipping.
    if args.schedule == "cosine":
        if args.warmup > 0:
            lr = optax.warmup_cosine_decay_schedule(
                0.0, args.lr, warmup_steps=args.warmup,
                decay_steps=max(args.steps, args.warmup + 1),
                end_value=args.lr * 0.1,
            )
        else:  # no warmup: start at peak (a forced 1-step warmup would
            # make the first update run at lr == 0)
            lr = optax.cosine_decay_schedule(
                args.lr, decay_steps=max(args.steps, 1), alpha=0.1
            )
    else:
        lr = args.lr
    tx = optax.adam(lr)
    if args.clip_norm < 0:
        raise SystemExit(f"--clip-norm must be >= 0, got {args.clip_norm} "
                         "(negative max_norm would sign-flip every update)")
    if args.clip_norm:
        if args.parallelism in ("pp", "pp_sp", "3d"):
            # inside the pipeline's shard_map the 'stages' grads are
            # rank-local, so clip_by_global_norm would compute a DIFFERENT
            # norm per pipe rank and scale the replicated embed/head grads
            # inconsistently — silent divergence. Refuse until the engine
            # clips with a psum'd global norm.
            raise SystemExit(
                "--clip-norm is not supported with --parallelism "
                "pp/pp_sp/3d "
                "(per-stage norms would diverge); clip under dp/tp/sp/ep"
            )
        tx = optax.chain(optax.clip_by_global_norm(args.clip_norm), tx)
    rng = jax.random.key(0)
    sample = jnp.zeros((1, args.seq_len), jnp.int32)

    p = args.parallelism
    if (args.grad_compress != "none" or args.overlap_grad_sync) and p != "dp":
        # the compressed/bucketed sync intercepts grads as they cross the
        # batch axis; under tp/sp/pp/ep XLA owns the collective placement
        raise SystemExit(
            f"--grad-compress/--overlap-grad-sync only compose with "
            f"--parallelism dp (got {p!r}): other plans let XLA place the "
            "grad collectives"
        )
    if p == "dp":
        mesh = make_mesh({"data": n}, devices=devices)
        model = TransformerLM(cfg, attention_fn=attention_fn)
        state = TrainState.create(model, rng, sample, tx)
        eng = PjitEngine(model, tx, mesh, task="lm",
                         grad_compress=args.grad_compress,
                         overlap_grad_sync=args.overlap_grad_sync,
                         bucket_mb=args.bucket_mb)
    elif p == "tp":
        if args.dp < 1 or n % args.dp:
            raise SystemExit(f"--dp {args.dp} must be >= 1 and divide {n} devices")
        dp, m = args.dp, n // args.dp
        if args.n_heads % m or args.d_ff % m or args.vocab % m or args.d_model % m:
            raise SystemExit(
                f"tp shards heads, d_ff, vocab and d_model: --n-heads "
                f"{args.n_heads}, --d-ff {args.d_ff}, --vocab {args.vocab}, "
                f"--d-model {args.d_model} must be divisible by {m} "
                "model-parallel ranks"
            )
        # composes with data parallelism: batch sharded on 'data', kernels
        # (full Megatron set incl. out-proj, lm_head, embeddings) on 'model'
        mesh = make_mesh({"data": dp, "model": m}, devices=devices)
        model = TransformerLM(cfg, attention_fn=attention_fn)
        state = TrainState.create(model, rng, sample, tx)
        eng = PjitEngine(model, tx, mesh, task="lm", rules=megatron_rules())
    elif p == "sp":
        if n % 2:
            raise SystemExit("sp needs an even device count (data=2 x sp=n/2)")
        mesh = make_mesh({"data": 2, "sp": n // 2}, devices=devices)
        eng = SeqParallel(
            lambda attn: TransformerLM(cfg, attention_fn=attn), tx, mesh,
            attn=args.attn,
        )
        state = eng.init_state(rng, sample)
    elif p == "pp":
        if cfg.n_layers % n:
            raise SystemExit(f"pp needs n_layers divisible by {n} devices")
        mesh = make_mesh({"data": 1, "pipe": n}, devices=devices)
        eng = PipelineParallel(cfg, tx, mesh, microbatches=args.microbatches,
                               circular_chunks=args.circular_chunks,
                               attention_fn=attention_fn)
        state = eng.init_state(rng, sample)
    elif p == "pp_sp":
        # pipeline stages with the sequence sharded over 'sp' — ring (or
        # flash-ring) attention inside each stage block; the long-context
        # composition (activations ride the pipe as [mb, S/sp, D])
        if n % 4:
            raise SystemExit("pp_sp wants devices divisible by 4 "
                             "(mesh data=2 x pipe=2 x sp=n/4)")
        mesh = make_mesh({"data": 2, "pipe": 2, "sp": n // 4},
                         devices=devices)
        if cfg.n_layers % 2:
            raise SystemExit("pp_sp needs even n_layers (2 stages)")
        eng = PipelineParallel(
            cfg, tx, mesh, microbatches=args.microbatches,
            circular_chunks=args.circular_chunks, seq_axis="sp",
            seq_attn="flash_ring" if args.flash else "ring",
        )
        state = eng.init_state(rng, sample)
    elif p == "3d":
        # data x model x pipe: DP batch sharding, Megatron TP inside each
        # pipeline stage, GPipe microbatching across stages
        if n % 8:
            raise SystemExit("3d wants devices divisible by 8 (2x2x2 mesh)")
        shape = {"data": 2, "model": 2, "pipe": n // 4}
        if cfg.n_layers % shape["pipe"] or args.n_heads % 2 or args.d_ff % 2:
            raise SystemExit(
                f"3d at {n} devices needs n_layers % {shape['pipe']} == 0 "
                "and even --n-heads/--d-ff"
            )
        mesh = make_mesh(shape, devices=devices)
        eng = PipelineParallel(
            cfg, tx, mesh, microbatches=args.microbatches,
            model_axis="model", circular_chunks=args.circular_chunks,
            attention_fn=attention_fn,
        )
        state = eng.init_state(rng, sample)
    elif p == "ep":
        mesh = make_mesh({"data": 1, "expert": n}, devices=devices)
        model = TransformerLM(cfg, mlp_cls=MoeMlp, attention_fn=attention_fn)
        state = TrainState.create(model, rng, sample, tx)
        eng = PjitEngine(
            model, tx, mesh, task="lm",
            rules=[(r"w_(up|down)", P("expert", None, None))],
        )
    else:
        raise SystemExit(f"unknown parallelism {p!r}")

    state = eng.shard_state(state)
    start = datetime.datetime.now()
    losses = []
    for step, (tokens, targets) in enumerate(
        make_batches(args.vocab, args.batch, args.seq_len, args.steps, 0), 1
    ):
        state, loss = eng.train_step(state, *eng.shard_batch(tokens, targets))
        if step % args.log_every == 0 or step == args.steps:
            loss_v = float(np.ravel(np.asarray(loss))[0])
            losses.append(loss_v)
            print(f"[{p}] Step [{step}/{args.steps}], Loss: {loss_v:.4f}",
                  flush=True)
    print(f"Training complete in: {datetime.datetime.now() - start}")
    if len(losses) >= 2 and not losses[-1] < losses[0]:
        raise SystemExit(f"loss did not decrease: {losses[0]} -> {losses[-1]}")
    bootstrap.cleanup()


def build_parser() -> argparse.ArgumentParser:
    """Single source of the CLI; tests parse_args([]) for complete
    defaulted Namespaces instead of hand-building partial ones."""
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--parallelism",
                        choices=["dp", "tp", "sp", "pp", "pp_sp", "ep", "3d"],
                        default="dp")
    parser.add_argument("--dp", type=int, default=1,
                        help="tp only: data-parallel axis size composed "
                             "with model parallelism (devices = dp x tp)")
    parser.add_argument("--devices", type=int, default=1)
    parser.add_argument("--steps", type=int, default=60)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--vocab", type=int, default=64)
    parser.add_argument("--d-model", type=int, default=64)
    parser.add_argument("--n-heads", type=int, default=4)
    parser.add_argument("--n-layers", type=int, default=2)
    parser.add_argument("--d-ff", type=int, default=128)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--schedule", choices=["const", "cosine"],
                        default="const")
    parser.add_argument("--warmup", type=int, default=0,
                        help="linear warmup steps (cosine schedule)")
    parser.add_argument("--clip-norm", type=float, default=0.0,
                        help="global-norm gradient clipping (0 = off)")
    parser.add_argument("--microbatches", type=int, default=2,
                        help="pp only: GPipe microbatches per step")
    parser.add_argument("--circular-chunks", type=int, default=1,
                        help="pp/3d: layer chunks per stage (v>1 = circular "
                             "schedule, bubble ~v x smaller)")
    parser.add_argument("--router-top-k", type=int, default=1,
                        help="ep only: 1 = Switch top-1, 2 = GShard top-2")
    parser.add_argument("--log-every", type=int, default=10)
    parser.add_argument("--dtype", choices=["bf16", "fp32"], default="fp32")
    parser.add_argument("--attn", choices=["ring", "ulysses", "flash_ring"],
                        default="ring",
                        help="sp only: jnp K/V ring, Ulysses all-to-all "
                             "head/seq swap, or the Pallas flash-ring")
    parser.add_argument("--flash", action="store_true",
                        help="use the Pallas flash-attention kernel")
    parser.add_argument("--remat-policy", choices=["full", "dots"],
                        default="full",
                        help="remat=full recomputes whole blocks; dots "
                             "saves matmul outputs (checkpoint_dots) so "
                             "backward pays no extra MXU FLOPs")
    parser.add_argument("--remat", action="store_true",
                        help="jax.checkpoint each block (memory for FLOPs)")
    parser.add_argument("--force-cpu", action="store_true")
    # dp only; no --no-error-feedback here — PjitEngine's compressed sync
    # is stateless (no residual to carry), unlike DataParallel's
    add_grad_compress_cli(parser, error_feedback=False)
    # dp only likewise; no --prefetch (synthetic in-memory stream)
    add_overlap_cli(parser, prefetch=False)
    return parser


def main():
    train(build_parser().parse_args())


if __name__ == "__main__":
    main()
