"""Decoder-only transformer LM — the long-context / wider-parallelism model.

The reference's only model is a CNN (SURVEY §2.2: TP/PP/SP/EP and attention
all absent). This framework treats long-context and multi-axis parallelism
as first-class, so it ships a transformer whose attention implementation is
*injected*: the same module runs

- single-device with ops.attention.causal_attention (the reference math),
- sequence-parallel with parallel.ring_attention inside a shard_map over an
  'sp' mesh axis (see parallel/seq_parallel.py),
- tensor-parallel via PjitEngine rules on the Dense kernels (qkv/mlp),
- and with a MoE MLP for expert parallelism (parallel/expert.py).

TPU-first: bf16 compute / fp32 params option, LayerNorm stats in fp32,
static shapes, no data-dependent control flow.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpu_sandbox.ops.attention import causal_attention


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 256
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 256
    max_len: int = 2048
    dtype: Any = jnp.float32
    # MoE: 0 experts = dense MLP everywhere; >0 = MoE MLP in every block
    n_experts: int = 0
    capacity_factor: float = 2.0
    # 1 = Switch top-1 (gate = router prob); >1 = GShard-style top-k with
    # normalized gates and choice-major capacity priority
    router_top_k: int = 1
    # rematerialize each block's activations in backward (jax.checkpoint):
    # trades recompute FLOPs for O(n_layers) less activation memory — the
    # TPU-first long-context memory lever (HBM, not sequence sharding)
    remat: bool = False
    # remat policy: "full" recomputes everything (max memory savings);
    # "dots" = jax.checkpoint_policies.checkpoint_dots — matmul outputs are
    # SAVED and only cheap elementwise work is recomputed, so the backward
    # pays no extra MXU FLOPs (~25% step-time win at the bench config for a
    # modest memory give-back). Ignored when remat=False.
    remat_policy: str = "full"
    # emit logits in fp32 (the safe default for any consumer). False
    # skips the cast and returns compute-dtype logits — at b16/s2048/
    # v32768 the fp32 [32768, 32768] materialization is a 4.3 GB
    # write+read (~32 ms/step in the r04 AOT cycle ranking) that the
    # fused Pallas CE makes redundant: it upcasts per row-block in VMEM
    # (ops/losses.py casts explicitly on the plain path, so loss math is
    # bit-identical either way — bf16->f32 casts are exact).
    fp32_logits: bool = True


class SelfAttention(nn.Module):
    config: TransformerConfig
    attention_fn: Callable | None = None  # None -> local causal attention

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        head_dim = cfg.d_model // cfg.n_heads
        qkv = nn.DenseGeneral(
            (3, cfg.n_heads, head_dim), dtype=cfg.dtype, name="qkv"
        )(x)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        # Serving prefill taps per-layer K/V here. A no-op unless the caller
        # passes mutable=["kv_cache"] (training never does), so the trained
        # step graphs are untouched.
        self.sow("kv_cache", "kv", (k, v), reduce_fn=lambda _, x: x)
        attn = self.attention_fn or (lambda q, k, v: causal_attention(q, k, v))
        out = attn(q, k, v)  # [B, S, H, D]
        return nn.DenseGeneral(
            cfg.d_model, axis=(-2, -1), dtype=cfg.dtype, name="out"
        )(out)


class Mlp(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        h = nn.Dense(cfg.d_ff, dtype=cfg.dtype, name="up")(x)
        h = nn.gelu(h)
        return nn.Dense(cfg.d_model, dtype=cfg.dtype, name="down")(h)


class Block(nn.Module):
    config: TransformerConfig
    attention_fn: Callable | None = None
    mlp_cls: Any = Mlp

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        h = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        x = x + SelfAttention(cfg, self.attention_fn, name="attn")(h)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        x = x + self.mlp_cls(cfg, name="mlp")(h)
        return x


class TransformerLM(nn.Module):
    """tokens [B, S] (+ global positions [B, S] when sequence-sharded)
    -> logits [B, S, vocab]."""

    config: TransformerConfig
    attention_fn: Callable | None = None
    mlp_cls: Any = Mlp

    @nn.compact
    def __call__(
        self, tokens: jnp.ndarray, positions: jnp.ndarray | None = None
    ) -> jnp.ndarray:
        cfg = self.config
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1]), tokens.shape
            )
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype, name="tok_emb")(
            tokens
        )
        x = x + nn.Embed(cfg.max_len, cfg.d_model, dtype=cfg.dtype, name="pos_emb")(
            positions
        )
        if cfg.remat:
            policy = (jax.checkpoint_policies.checkpoint_dots
                      if cfg.remat_policy == "dots" else None)
            block_cls = nn.remat(Block, policy=policy)
        else:
            block_cls = Block
        for i in range(cfg.n_layers):
            x = block_cls(cfg, self.attention_fn, self.mlp_cls, name=f"block{i}")(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        logits = nn.Dense(cfg.vocab_size, dtype=cfg.dtype, name="lm_head")(x)
        if cfg.fp32_logits:
            return jnp.asarray(logits, jnp.float32)
        return logits
