"""The parity CNN — the reference's ``ConvNet`` re-expressed in Flax.

Reference architecture (mnist_onegpu.py:11-31 == mnist_distributed.py:25-45,
duplicated there, single-sourced here):

    [Conv2d(1->16, k5, s1, p2) -> BatchNorm2d(16) -> ReLU -> MaxPool(2,2)]
    [Conv2d(16->32, k5, s1, p2) -> BatchNorm2d(32) -> ReLU -> MaxPool(2,2)]
    flatten -> LazyLinear(num_classes)

At the reference's 3000x3000 input the flatten is 32*750*750 = 18M features,
so the final layer is an 18M x 10 (~180M param) matmul that dominates memory
— the whole point of its OOM experiment. Flax's init-by-tracing gives
LazyLinear semantics for free: the Dense in-features are fixed at first
``init``/tabulate, no dummy-forward dance (reference mnist_onegpu.py:39).

TPU-first choices:
- NHWC layout (XLA:TPU's native conv layout; torch is NCHW).
- Optional ``dtype=bfloat16`` compute with fp32 params — the MXU path.
- BatchNorm carries per-replica batch stats (flax 'batch_stats' collection),
  NOT cross-replica synced: DDP does not sync BN statistics either, and
  loss-curve parity requires matching that (SURVEY §7 hard-part 5).
- BN momentum/eps match torch defaults (torch momentum 0.1 == flax 0.9;
  eps 1e-5) so running stats evolve identically.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class ConvNet(nn.Module):
    """Two conv blocks then a lazily-sized classifier head."""

    num_classes: int = 10
    features: tuple[int, ...] = (16, 32)
    dtype: jnp.dtype = jnp.float32  # compute dtype; params stay fp32
    use_bn: bool = True  # False gives a stateless net (exact-DP-equivalence tests)

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        """x: [N, H, W, C] (NHWC). Returns logits [N, num_classes]."""
        for i, feat in enumerate(self.features):
            x = nn.Conv(
                features=feat,
                kernel_size=(5, 5),
                strides=1,
                padding=2,
                dtype=self.dtype,
                name=f"conv{i + 1}",
            )(x)
            if self.use_bn:
                x = nn.BatchNorm(
                    use_running_average=not train,
                    momentum=0.9,  # == torch BatchNorm2d momentum 0.1
                    epsilon=1e-5,
                    dtype=self.dtype,
                    name=f"bn{i + 1}",
                )(x)
            x = nn.relu(x)
            x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        # Canonical fc row order is (h, c, w) — the transposed production
        # plan's native feature layout, so its fc contraction runs with
        # ZERO relayout copies (models/convnet_s2d_t.py::_DenseT); the
        # NHWC plans pay this transpose instead. NOT free at production
        # geometry (ADVICE r04): it relayouts [N,750,750,32] (~0.54 GB
        # bf16 at bs=16) per direction, >=1.3 ms/step of pure HBM traffic
        # at a v5e's ~819 GB/s even before relayout inefficiency — so
        # sweep plan-race rows for the NHWC plans (nhwc_pallas, xla_*)
        # carry this cost and mildly understate those plans vs s2dt
        # (bench_sweep notes this next to the rows). The torch reference
        # flattens NCHW as (c, h, w) — utils/parity.py re-blocks between
        # the conventions either way.
        x = x.transpose(0, 1, 3, 2).reshape(x.shape[0], -1)
        # Flax sizes the kernel from x at init time — LazyLinear semantics.
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)
        return jnp.asarray(x, jnp.float32)  # logits/loss in fp32 always
