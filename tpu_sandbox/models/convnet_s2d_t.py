"""ConvNetS2DT: the space-to-depth ConvNet in TRANSPOSED layout
[N, H, C, W] — round 3's production execution plan.

Same function as models.convnet.ConvNet and models.convnet_s2d.ConvNetS2D
(reference mnist_onegpu.py:11-31), exactly — forward, gradients, and
batch-stats updates agree to float tolerance (tests/test_convnet_s2d_t.py)
— and the parameter/batch_stats tree is bit-compatible with both, so
checkpoints, TrainState, and every engine accept any of the three.

Why a third plan: on-chip micro-benchmarks (measured/conv_micro_r03.jsonl)
showed the NHWC s2d Pallas convs running at 19-27 TF/s — below the XLA
convs they replaced — because with channels on the 128-lane minor dim the
[W, 9C] im2col tile build wastes 7/8 of every VPU op at C=16 and the
operands are lane-padded up to 8x in HBM. Putting channels on SUBLANES
and W on lanes (ops/pallas_conv_t.py) made the tile build tile-aligned
sublane stacking: conv1 fwd 24.6 -> 15.3 ms, conv1 fwd+BN-stats
29.1 -> 15.3 ms (the stats fusion became free), conv2 bwd 57.6 -> 41.1 ms
at bs=16, with the fused tail pair (ops/pallas_bn_tail_t.py) keeping the
BN/ReLU/pool chain at one HBM pass per direction.

Layout plumbing (the only places the transpose exists):
- input: ``space_to_depth_t`` emits [N, H/4, 16, W/4] straight from the
  [N, H, W] image — one device transpose of the raw input;
- output: pool2's [N, H/4, f2, W/4] is transposed back before flatten so
  the fc sees the reference's (h, w, c) feature order — fc weights stay
  interchangeable with ConvNet's.
Channel indexing within C is identical to ConvNetS2D (co minor, (a,b)
block-position major), so BN grouping, pooling pairs, and the kernel
scatter are shared unchanged.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpu_sandbox.models.convnet_s2d import scatter_kernel


def space_to_depth_t(x: jnp.ndarray, r: int) -> jnp.ndarray:
    """[N,H,W] -> [N, H/r, r*r, W/r] (channel index a*r+b, channels on
    the sublane dim)."""
    n, h, w = x.shape
    x = x.reshape(n, h // r, r, w // r, r)
    return x.transpose(0, 1, 2, 4, 3).reshape(n, h // r, r * r, w // r)


def block_max_pool_t(y: jnp.ndarray, blk: int, co: int) -> jnp.ndarray:
    """2x2/2 max-pool inside the channel (sublane) dim: y
    [..., blk*blk*co, W] with ordering (a*blk+b)*co+c; pool pairs are the
    LOW bits of (a, b). Returns [..., (blk//2)**2*co, W]. Slice/maximum
    form for the same layout reason as block_max_pool."""
    *lead, c, w = y.shape
    assert c == blk * blk * co, (c, blk, co)
    y = y.reshape(*lead, blk // 2, 2, blk // 2, 2, co, w)
    m = jnp.maximum(
        jnp.maximum(y[..., :, 0, :, 0, :, :], y[..., :, 0, :, 1, :, :]),
        jnp.maximum(y[..., :, 1, :, 0, :, :], y[..., :, 1, :, 1, :, :]),
    )
    return m.reshape(*lead, (blk // 2) ** 2 * co, w)


class _ConvT(nn.Module):
    """Same canonical [5,5,ci,co] kernel + bias variables as ConvNet /
    ConvNetS2D, applied s2d-scattered in transposed layout via the
    Pallas kernel (ops/pallas_conv_t.py)."""

    shape: tuple[int, ...]
    r: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, want_stats: bool = False):
        from tpu_sandbox.ops.pallas_conv_t import conv3x3_t, conv3x3_t_stats

        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), self.shape, jnp.float32
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (self.shape[-1],), jnp.float32
        )
        wg = scatter_kernel(kernel.astype(self.dtype), self.r)
        reps = wg.shape[-1] // self.shape[-1]
        bias_g = jnp.tile(bias.astype(self.dtype), reps)
        if want_stats:
            y, s, ss = conv3x3_t_stats(x, wg, bias_g)
            return y, (s, ss)
        return conv3x3_t(x, wg, bias_g)


class _GroupedBNT(nn.Module):
    """_GroupedBN semantics (models/convnet_s2d.py) over the transposed
    layout [..., g*co, W]; identical variable names/shapes."""

    features: int  # co
    dtype: jnp.dtype
    momentum: float = 0.9
    epsilon: float = 1e-5

    def setup(self):
        co = self.features
        self.scale = self.param(
            "scale", nn.initializers.ones, (co,), jnp.float32
        )
        self.offset = self.param(
            "bias", nn.initializers.zeros, (co,), jnp.float32
        )
        self.ra_mean = self.variable(
            "batch_stats", "mean", lambda s: jnp.zeros(s, jnp.float32), (co,)
        )
        self.ra_var = self.variable(
            "batch_stats", "var", lambda s: jnp.ones(s, jnp.float32), (co,)
        )

    def _update_running(self, mu, var):
        if not self.is_initializing():
            m = self.momentum
            self.ra_mean.value = m * self.ra_mean.value + (1 - m) * mu
            self.ra_var.value = m * self.ra_var.value + (1 - m) * var

    def __call__(self, y, train: bool):
        co = self.features
        *lead, c, w = y.shape
        yg = y.reshape(*lead, c // co, co, w)
        if train:
            yf = yg.astype(jnp.float32)
            red = tuple(i for i in range(yf.ndim) if i != yf.ndim - 2)
            mu = jnp.mean(yf, axis=red)
            mu2 = jnp.mean(jnp.square(yf), axis=red)
            var = jnp.maximum(0.0, mu2 - jnp.square(mu))
            self._update_running(mu, var)
        else:
            mu, var = self.ra_mean.value, self.ra_var.value
        out = (yg.astype(jnp.float32) - mu[:, None]) * (
            jax.lax.rsqrt(var + self.epsilon) * self.scale
        )[:, None] + self.offset[:, None]
        return out.astype(self.dtype).reshape(*lead, c, w)

    def fused(self, y, blk: int, ysums=None):
        from tpu_sandbox.ops.pallas_bn_tail_t import fused_bn_relu_pool_t

        out, mu, var = fused_bn_relu_pool_t(
            y, self.scale, self.offset, self.features, blk, self.epsilon,
            None, ysums,
        )
        self._update_running(mu, var)
        return out


class ConvNetS2DT(nn.Module):
    """Drop-in ConvNet with the transposed space-to-depth execution plan.

    Always runs the Pallas conv kernels; ``fused_tail=True`` (the TPU
    default via ``pick_convnet``) additionally fuses each BN/ReLU/pool
    tail and rides the conv kernels' fused BN statistics. Requires H, W
    divisible by 4 and one input channel (the reference's 3000x3000
    MNIST qualifies); other configs use models.convnet.ConvNet.
    """

    num_classes: int = 10
    features: tuple[int, ...] = (16, 32)
    dtype: jnp.dtype = jnp.float32  # compute dtype; params stay fp32
    use_bn: bool = True
    fused_tail: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        """x: [N,H,W,1] NHWC or [N,H,W]. Returns logits [N, num_classes]."""
        assert len(self.features) == 2, "s2d plan is the 2-block parity CNN"
        f1, f2 = self.features
        if x.ndim == 4:
            assert x.shape[-1] == 1, "s2d plan is for the 1-channel CNN"
            x = x[..., 0]
        n, h, w = x.shape
        assert h % 4 == 0 and w % 4 == 0, (h, w)

        fuse_stats = self.fused_tail and self.use_bn and train

        x = space_to_depth_t(x, 4).astype(self.dtype)    # [N,H/4,16,W/4]
        y = _ConvT((5, 5, 1, f1), r=4, dtype=self.dtype,
                   name="conv1")(x, fuse_stats)
        y, ysums = y if fuse_stats else (y, None)
        y = self._tail(y, f1, 4, "bn1", train, ysums)    # [N,H/4,4*f1,W/4]

        y = _ConvT((5, 5, f1, f2), r=2, dtype=self.dtype,
                   name="conv2")(y, fuse_stats)
        y, ysums = y if fuse_stats else (y, None)
        y = self._tail(y, f2, 2, "bn2", train, ysums)    # [N,H/4,f2,W/4]

        # back to the reference's (h, w, c) feature order for the fc
        y = y.transpose(0, 1, 3, 2).reshape(n, -1)
        y = nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(y)
        return jnp.asarray(y, jnp.float32)

    def _tail(self, y, co: int, blk: int, name: str, train: bool,
              ysums=None):
        """BN + ReLU + 2x2 block pool — fused Pallas pair when enabled."""
        if self.use_bn and self.fused_tail and train:
            return _GroupedBNT(co, self.dtype, name=name).fused(
                y, blk, ysums)
        if self.use_bn:
            y = _GroupedBNT(co, self.dtype, name=name)(y, train)
        y = nn.relu(y)
        return block_max_pool_t(y, blk, co)
