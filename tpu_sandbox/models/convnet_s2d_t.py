"""ConvNetS2DT: the space-to-depth ConvNet in TRANSPOSED layout
[N, H, C, W] — round 3's production execution plan.

Same function as models.convnet.ConvNet and models.convnet_s2d.ConvNetS2D
(reference mnist_onegpu.py:11-31), exactly — forward, gradients, and
batch-stats updates agree to float tolerance (tests/test_convnet_s2d_t.py)
— and the parameter/batch_stats tree is bit-compatible with both, so
checkpoints, TrainState, and every engine accept any of the three.

Why a third plan: on-chip micro-benchmarks (measured/conv_micro_r03.jsonl)
showed the NHWC s2d Pallas convs running at 19-27 TF/s — below the XLA
convs they replaced — because with channels on the 128-lane minor dim the
[W, 9C] im2col tile build wastes 7/8 of every VPU op at C=16 and the
operands are lane-padded up to 8x in HBM. Putting channels on SUBLANES
and W on lanes (ops/pallas_conv_t.py) made the tile build tile-aligned
sublane stacking: conv1 fwd 24.6 -> 15.3 ms, conv1 fwd+BN-stats
29.1 -> 15.3 ms (the stats fusion became free), conv2 bwd
57.6 -> 27.3-41.1 ms at bs=16 (the range spans the two recorded r03
sweeps — 25-50% run-to-run spread, see conv_micro_r03_t.jsonl), with the
fused tail pair (ops/pallas_bn_tail_t.py) keeping the BN/ReLU/pool chain
at one HBM pass per direction.

Layout plumbing (the only places the transpose exists):
- input: ``space_to_depth_t`` emits [N, H/4, 16, W/4] straight from the
  [N, H, W] image — one device transpose of the raw input;
- output: pool2's [N, H/4, f2, W/4] feeds the fc directly — the
  framework-canonical fc row order is (h, c, w), this plan's native
  feature order (models/convnet.py), so no transpose exists here at all
  and fc weights stay interchangeable with ConvNet's.
Channel indexing within C is identical to ConvNetS2D (co minor, (a,b)
block-position major), so BN grouping, pooling pairs, and the kernel
scatter are shared unchanged.
"""

from __future__ import annotations

import functools
import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tpu_sandbox.models.convnet_s2d import scatter_kernel


@functools.lru_cache(maxsize=8)
def resize_weights(src: int, dst: int) -> np.ndarray:
    """[dst, src] bilinear interpolation matrix with EXACTLY
    jax.image.resize's weights (train.resize_on_device's method): resize
    is linear and separable, so resizing the identity yields its weight
    matrix. Host-cached f32 constant (a few hundred KB at 28->3000 —
    safely under the remote-compile body limit that bars closing over
    full-size images)."""
    with jax.ensure_compile_time_eval():  # concrete even mid-trace
        eye = jnp.eye(src, dtype=jnp.float32)
        w = jax.image.resize(eye, (dst, src), method="bilinear")
        return np.asarray(jax.device_get(w))


def _as_nhw(x: jnp.ndarray) -> jnp.ndarray:
    """[N,H,W,1] or [N,H,W] -> [N,H,W] (shared by __call__ and
    fused_input_stage)."""
    if x.ndim == 4:
        assert x.shape[-1] == 1, "s2d plan is for the 1-channel CNN"
        x = x[..., 0]
    return x


def space_to_depth_t(x: jnp.ndarray, r: int) -> jnp.ndarray:
    """[N,H,W] -> [N, H/r, r*r, W/r] (channel index a*r+b, channels on
    the sublane dim)."""
    n, h, w = x.shape
    x = x.reshape(n, h // r, r, w // r, r)
    return x.transpose(0, 1, 2, 4, 3).reshape(n, h // r, r * r, w // r)


def block_max_pool_t(y: jnp.ndarray, blk: int, co: int) -> jnp.ndarray:
    """2x2/2 max-pool inside the channel (sublane) dim: y
    [..., blk*blk*co, W] with ordering (a*blk+b)*co+c; pool pairs are the
    LOW bits of (a, b). Returns [..., (blk//2)**2*co, W]. Slice/maximum
    form for the same layout reason as block_max_pool."""
    *lead, c, w = y.shape
    assert c == blk * blk * co, (c, blk, co)
    y = y.reshape(*lead, blk // 2, 2, blk // 2, 2, co, w)
    m = jnp.maximum(
        jnp.maximum(y[..., :, 0, :, 0, :, :], y[..., :, 0, :, 1, :, :]),
        jnp.maximum(y[..., :, 1, :, 0, :, :], y[..., :, 1, :, 1, :, :]),
    )
    return m.reshape(*lead, (blk // 2) ** 2 * co, w)


class _ConvT(nn.Module):
    """Same canonical [5,5,ci,co] kernel + bias variables as ConvNet /
    ConvNetS2D. conv1 (r=4, 1-channel input) runs the sparse-tap
    union-tile kernel (ops/pallas_conv5_t.py: K=64 -> half the MXU
    passes of the scattered-3x3 form, whose weight is only 25/144
    dense); conv2 (r=2, 16-channel input, 69%-dense scatter) keeps the
    scattered-3x3 kernel (ops/pallas_conv_t.py).
    TPU_SANDBOX_NO_SPARSE_CONV1=1 reverts conv1 to the scattered-3x3
    kernel — the whole-model A/B lever for the first on-chip runs of the
    r04 kernel (tools/conv_micro.py races the two directly)."""

    shape: tuple[int, ...]
    r: int
    dtype: jnp.dtype
    sparse: bool = True  # conv1's union-tile kernel (in-process A/B lever)

    @nn.compact
    def __call__(self, x, want_stats: bool = False,
                 params_only: bool = False):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), self.shape, jnp.float32
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (self.shape[-1],), jnp.float32
        )
        if params_only:
            # the conv1+tail fused-backward composite (pallas_conv1_tail_t)
            # spans this module's params and bn1's — the parent fetches
            # them here (declared under the same names, so the tree is
            # unchanged) and calls the composite itself
            return kernel.astype(self.dtype), bias.astype(self.dtype)
        # env var read at TRACE time: set it before the process first
        # traces the step (each bench/test invocation is its own process
        # under the one-chip-process discipline); flipping it after a
        # jitted step compiled is a no-op — the jit cache key ignores
        # env. In-process A/B goes through the `sparse` field instead
        # (ConvNetS2DT(sparse_conv1=False) retraces properly).
        no_sparse = os.environ.get("TPU_SANDBOX_NO_SPARSE_CONV1") == "1"
        if (self.r == 4 and self.shape[2] == 1 and self.sparse
                and not no_sparse):
            from tpu_sandbox.ops.pallas_conv5_t import (
                conv1_s2d_t,
                conv1_s2d_t_stats,
            )

            k5 = kernel.astype(self.dtype)
            b = bias.astype(self.dtype)
            if want_stats:
                y, s, ss = conv1_s2d_t_stats(x, k5, b)
                return y, (s, ss)
            return conv1_s2d_t(x, k5, b)
        from tpu_sandbox.ops.pallas_conv_t import conv3x3_t, conv3x3_t_stats

        wg = scatter_kernel(kernel.astype(self.dtype), self.r)
        reps = wg.shape[-1] // self.shape[-1]
        bias_g = jnp.tile(bias.astype(self.dtype), reps)
        if want_stats:
            y, s, ss = conv3x3_t_stats(x, wg, bias_g)
            return y, (s, ss)
        return conv3x3_t(x, wg, bias_g)


class _GroupedBNT(nn.Module):
    """_GroupedBN semantics (models/convnet_s2d.py) over the transposed
    layout [..., g*co, W]; identical variable names/shapes."""

    features: int  # co
    dtype: jnp.dtype
    momentum: float = 0.9
    epsilon: float = 1e-5

    def setup(self):
        co = self.features
        self.scale = self.param(
            "scale", nn.initializers.ones, (co,), jnp.float32
        )
        self.offset = self.param(
            "bias", nn.initializers.zeros, (co,), jnp.float32
        )
        self.ra_mean = self.variable(
            "batch_stats", "mean", lambda s: jnp.zeros(s, jnp.float32), (co,)
        )
        self.ra_var = self.variable(
            "batch_stats", "var", lambda s: jnp.ones(s, jnp.float32), (co,)
        )

    def _update_running(self, mu, var):
        if not self.is_initializing():
            m = self.momentum
            self.ra_mean.value = m * self.ra_mean.value + (1 - m) * mu
            self.ra_var.value = m * self.ra_var.value + (1 - m) * var

    def __call__(self, y, train: bool):
        co = self.features
        *lead, c, w = y.shape
        yg = y.reshape(*lead, c // co, co, w)
        if train:
            yf = yg.astype(jnp.float32)
            red = tuple(i for i in range(yf.ndim) if i != yf.ndim - 2)
            mu = jnp.mean(yf, axis=red)
            mu2 = jnp.mean(jnp.square(yf), axis=red)
            var = jnp.maximum(0.0, mu2 - jnp.square(mu))
            self._update_running(mu, var)
        else:
            mu, var = self.ra_mean.value, self.ra_var.value
        out = (yg.astype(jnp.float32) - mu[:, None]) * (
            jax.lax.rsqrt(var + self.epsilon) * self.scale
        )[:, None] + self.offset[:, None]
        return out.astype(self.dtype).reshape(*lead, c, w)

    def fused(self, y, blk: int, ysums=None):
        from tpu_sandbox.ops.pallas_bn_tail_t import fused_bn_relu_pool_t

        out, mu, var = fused_bn_relu_pool_t(
            y, self.scale, self.offset, self.features, blk, self.epsilon,
            None, ysums,
        )
        self._update_running(mu, var)
        return out

    def fused_conv1(self, x, k5, cbias, blk: int):
        """conv1 + this BN's tail as ONE differentiable unit: the r05
        backward fusion (ops/pallas_conv1_tail_t.py) — conv1's ~4.7 GB
        output cotangent never round-trips HBM (its only consumer is
        the conv wgrad; dx is dead). Forward identical to
        _ConvT(sparse) + self.fused."""
        from tpu_sandbox.ops.pallas_conv1_tail_t import conv1_tail_t

        out, mu, var = conv1_tail_t(
            x, k5, cbias, self.scale, self.offset, self.features, blk,
            self.epsilon,
        )
        self._update_running(mu, var)
        return out


class _DenseT(nn.Module):
    """nn.Dense over the transposed feature map WITHOUT materializing the
    (h, w, c) activation transpose. The kernel variable stays
    [h*w*c, k] with rows flattened in canonical (h, c, w) order — the
    parameter tree is bit-identical to ConvNet's fc: same init path (so
    the same values under the same key), rows in the framework-canonical
    (h, c, w) order that all three plans share — see models/convnet.py
    (the torch reference's own NCHW flatten is (c, h, w); utils/parity.py
    re-blocks between the conventions). The contraction reads y in
    its native [N, h, c, w] layout against the kernel viewed as
    [h, c, w, k]: contraction order aligned on both sides, so neither
    the 2.3 GB activation nor the 180M-param weight is ever relayouted
    (the r03 step spent ~40 ms/step at bs=16 on exactly those copies —
    measured/hlo_cycles_s2dt_b16_r04.json)."""

    features: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, y: jnp.ndarray) -> jnp.ndarray:
        n, h, c, w = y.shape
        kernel = self.param(
            "kernel", nn.linear.default_kernel_init,
            (h * w * c, self.features), jnp.float32,
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (self.features,), jnp.float32
        )
        # r05: the input-grad of this contraction runs a Pallas kernel
        # that emits dy in the native [N,h,C,W] layout (ops/pallas_fc_t
        # — kills the ~540 MB dgrad relayout the XLA einsum paid; fwd
        # and weight-grad stay the same XLA dots). Env kill switch reads
        # at trace time like TPU_SANDBOX_NO_SPARSE_CONV1.
        if os.environ.get("TPU_SANDBOX_NO_PALLAS_FC") != "1":
            from tpu_sandbox.ops.pallas_fc_t import fc_t

            return fc_t(y, kernel, bias, self.dtype)
        k4 = kernel.astype(self.dtype).reshape(h, c, w, self.features)
        out = jnp.einsum("nhcw,hcwk->nk", y, k4)
        return out + bias.astype(self.dtype)


class ConvNetS2DT(nn.Module):
    """Drop-in ConvNet with the transposed space-to-depth execution plan.

    Always runs the Pallas conv kernels; ``fused_tail=True`` (the TPU
    default via ``pick_convnet``) additionally fuses each BN/ReLU/pool
    tail and rides the conv kernels' fused BN statistics. Requires H, W
    divisible by 4 and one input channel (the reference's 3000x3000
    MNIST qualifies); other configs use models.convnet.ConvNet.
    """

    num_classes: int = 10
    features: tuple[int, ...] = (16, 32)
    dtype: jnp.dtype = jnp.float32  # compute dtype; params stay fp32
    use_bn: bool = True
    fused_tail: bool = False
    sparse_conv1: bool = True  # False: scattered-3x3 conv1 (A/B lever)
    fused_conv1_bwd: bool = True  # False: unfused conv1/tail backward

    def fused_input_stage(self, images: jnp.ndarray,
                          image_size: tuple[int, int]) -> jnp.ndarray:
        """Bilinear resize (exactly train.resize_on_device's weights, see
        ``resize_weights``) fused with ``space_to_depth_t``: two small
        contractions against the interpolation matrices emit
        [N, H/4, 16, W/4] straight from the raw [N, h0, w0(, 1)] batch.
        The full-size [N, H, W] image never materializes — in the r03
        step that intermediate cost two whole-image relayout copies
        (~55 ms/step at bs=16, the largest single residue in the 199 ms
        step; measured/hlo_cycles_s2dt_b16_r04.json). Feed the result to
        ``__call__``, which detects the pre-s2d shape."""
        H, W = image_size
        assert H % 4 == 0 and W % 4 == 0, (H, W)
        images = _as_nhw(images)
        n, h0, w0 = images.shape
        ah4 = jnp.asarray(resize_weights(h0, H)).reshape(H // 4, 4, h0)
        aw4 = jnp.asarray(resize_weights(w0, W)).reshape(W // 4, 4, w0)
        x = images.astype(jnp.float32)
        u = jnp.einsum("nij,wbj->nibw", x, aw4)          # [N, h0, 4, W/4]
        # The 5D->4D (a,b)->16 merge costs one whole-tensor retiling
        # copy (~6 ms est at bs=16, copy.67 in measured/hlo_cycles; real
        # bytes ~0.6 GB). A per-a-slice + channel-concat variant was
        # AOT-raced in r05 and came out est-neutral (47.8 vs 48.0 ms:
        # the concat just splits the same relayout into four slice
        # copies + a pad fusion, identical traffic) — recorded here so
        # it isn't retried.
        v = jnp.einsum("hai,nibw->nhabw", ah4, u)
        return v.reshape(n, H // 4, 16, W // 4).astype(self.dtype)

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        """x: [N,H,W,1] NHWC, [N,H,W], or a pre-s2d [N,H/4,16,W/4] from
        ``fused_input_stage`` (distinguished by its non-1 trailing dim).
        Returns logits [N, num_classes]."""
        assert len(self.features) == 2, "s2d plan is the 2-block parity CNN"
        f1, f2 = self.features

        if x.ndim == 4 and x.shape[-1] != 1:             # pre-s2d input
            # pre-s2d tensors come only from fused_input_stage
            if x.shape[2] != 16:
                raise ValueError(
                    "expected [N,H,W,1]/[N,H,W] (the s2d plan is the "
                    "1-channel CNN) or a fused_input_stage output "
                    f"[N,H/4,16,W/4]; got {x.shape}"
                )
            x = x.astype(self.dtype)
            n = x.shape[0]
        else:
            x = _as_nhw(x)
            n, h, w = x.shape
            assert h % 4 == 0 and w % 4 == 0, (h, w)
            x = space_to_depth_t(x, 4).astype(self.dtype)  # [N,H/4,16,W/4]

        fuse_stats = self.fused_tail and self.use_bn and train
        conv1 = _ConvT((5, 5, 1, f1), r=4, dtype=self.dtype,
                       sparse=self.sparse_conv1, name="conv1")
        # r05 fused conv1/tail BACKWARD: requires the sparse conv1 and
        # the fused tail both active (the composite is built from those
        # kernels). Trace-time env kill switch like the other levers.
        sparse_on = (self.sparse_conv1
                     and os.environ.get("TPU_SANDBOX_NO_SPARSE_CONV1")
                     != "1")
        fully_fused = (
            fuse_stats and sparse_on and self.fused_conv1_bwd
            and os.environ.get("TPU_SANDBOX_NO_FUSED_CONV1_BWD") != "1"
        )
        if fully_fused:
            k5, cbias = conv1(x, params_only=True)
            y = _GroupedBNT(f1, self.dtype, name="bn1").fused_conv1(
                x, k5, cbias, 4)                         # [N,H/4,4*f1,W/4]
        else:
            y = conv1(x, fuse_stats)
            y, ysums = y if fuse_stats else (y, None)
            y = self._tail(y, f1, 4, "bn1", train, ysums)  # [N,H/4,4*f1,W/4]

        y = _ConvT((5, 5, f1, f2), r=2, dtype=self.dtype,
                   name="conv2")(y, fuse_stats)
        y, ysums = y if fuse_stats else (y, None)
        y = self._tail(y, f2, 2, "bn2", train, ysums)    # [N,H/4,f2,W/4]

        # fc contracts the transposed map in place; the kernel variable
        # keeps the canonical (h, c, w) row order all plans share (_DenseT)
        y = _DenseT(self.num_classes, self.dtype, name="fc")(y)
        return jnp.asarray(y, jnp.float32)

    def _tail(self, y, co: int, blk: int, name: str, train: bool,
              ysums=None):
        """BN + ReLU + 2x2 block pool — fused Pallas pair when enabled."""
        if self.use_bn and self.fused_tail and train:
            return _GroupedBNT(co, self.dtype, name=name).fused(
                y, blk, ysums)
        if self.use_bn:
            y = _GroupedBNT(co, self.dtype, name=name)(y, train)
        y = nn.relu(y)
        return block_max_pool_t(y, blk, co)
