"""Space-to-depth ConvNet: the parity CNN restructured for the MXU.

Same function as models.convnet.ConvNet (reference mnist_onegpu.py:11-31 —
conv 1->16 k5 s1 p2, BN, ReLU, pool2; conv 16->32 k5 s1 p2, BN, ReLU,
pool2; flatten; fc->10), exactly: forward, gradients, and batch-stats
updates agree with ConvNet to float tolerance (tests/test_convnet_s2d.py),
and the parameter tree is bit-compatible (conv1/{kernel,bias},
bn1/{scale,bias} + batch_stats bn1/{mean,var}, conv2, bn2, fc) so
checkpoints, TrainState, and every engine accept either model.

Why it exists: measured on a v5e, the plain NHWC network runs at ~1% MFU.
XLA:TPU lays activations out as [..., C] with C on the 128-wide lane
(minor) dim — C=16 pads 8x, and with the batch padded onto sublanes a
[5,3000,3000,16] bf16 activation occupies ~18 GB of HBM instead of 1.4 GB
(seen in the AOT allocator dump: ``bf16[8,3000,3000,16]{3,0,2,1:T(8,128)
(2,1)} size=18432000000``). Every conv/BN/pool pass then moves ~10x the
real bytes, and the MXU contracts over K=25 or K=400-but-lane-starved.

The fix is the space-to-depth transform from the public MLPerf ResNet TPU
submissions (there applied to the 7x7 stem): rewrite a conv on an HxW grid
with tiny C as an *exactly equivalent* conv on an (H/r)x(W/r) grid of rxr
pixel blocks with C*r*r channels, scattering the k5 kernel into a k3
kernel that is zero wherever a tap falls outside the original 5x5 support:

  stage       plain tensor              s2d tensor               lanes
  resize out  [N,3000,3000] (rank-3)    same                     3000
  s2d(4)      [N,3000,3000,1]           [N,750,750,16]           16
  conv1       k5 s1, 1->16              k3 s1, 16->256           256
  pool1 2x2   [N,1500,1500,16]          in-lane max -> [...,64]  64
  conv2       k5 s1, 16->32             k3 s1, 64->128           128
  pool2 2x2   [N,750,750,32]            in-lane max -> plain     32

Channel orderings keep co minor so BN/bias are grouped reshapes:
  conv1 out  c = (a*4+b)*16 + co   (a,b) = position in the 4x4 block
  pool1 out  c = (a1*2+b1)*16 + co (2x2 max over the low bits of a,b)
  conv2 out  c = (a2*2+b2)*32 + co
  pool2 out  plain [N,750,750,32] — bit-identical memory order to
             ConvNet's pool2 output; both transpose to the canonical
             (h, c, w) fc row order before flatten (models/convnet.py).

Kernel scatter: an original tap (dx,dy) seen from an output pixel at
in-block position (a,b) reads the input block at offset P=(a+dx-2)//r,
in-block position p with dx = r*P + p - a + 2; taps with dx or dy outside
[0,5) are zero. The zeros also make SAME padding exact at the edges: the
k3 block conv zero-pads a whole r-pixel block (rows -2r..-1) but the rows
beyond the reference's padding-2 are touched only by structurally-zero
taps. FLOPs rise (conv1 41 vs 7.2, conv2 83 vs 57.6 GFLOP/img fwd) but
utilization rises far more; published MFU stays pinned to the MODEL's
analytic FLOPs (utils/flops.py), so the extra executed FLOPs can only
lower the reported MFU, never inflate it.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


def scatter_kernel(w: jnp.ndarray, r: int) -> jnp.ndarray:
    """[k,k,ci,co] -> [3,3,ci*r*r,co*r*r] s2d-scattered kernel.

    Pure (static-index) gather of ``w`` with zeros outside the kxk
    support — differentiable, so the conv wgrad flows back to the
    canonical kernel through this same map. Requires k <= 2r+1 so the
    block-grid kernel is 3x3 (k=5 with r=4 or r=2 here).
    """
    k, _, ci, co = w.shape
    assert k <= 2 * r + 1, (k, r)
    pad = (k - 1) // 2
    P, Q, p, q, a, b = np.meshgrid(
        np.arange(3), np.arange(3), np.arange(r), np.arange(r),
        np.arange(r), np.arange(r), indexing="ij",
    )
    dx = r * (P - 1) + p - a + pad
    dy = r * (Q - 1) + q - b + pad
    valid = (dx >= 0) & (dx < k) & (dy >= 0) & (dy < k)
    wg = w[np.clip(dx, 0, k - 1), np.clip(dy, 0, k - 1)]
    wg = jnp.where(jnp.asarray(valid)[..., None, None], wg, 0)
    # [P,Q,p,q,a,b,ci,co] -> [P,Q,(p,q,ci),(a,b,co)]
    wg = wg.transpose(0, 1, 2, 3, 6, 4, 5, 7)
    return wg.reshape(3, 3, r * r * ci, r * r * co)


def space_to_depth(x: jnp.ndarray, r: int) -> jnp.ndarray:
    """[N,H,W] -> [N,H/r,W/r,r*r], channel index a*r+b."""
    n, h, w = x.shape
    x = x.reshape(n, h // r, r, w // r, r)
    return x.transpose(0, 1, 3, 2, 4).reshape(n, h // r, w // r, r * r)


def block_max_pool(y: jnp.ndarray, blk: int, co: int) -> jnp.ndarray:
    """2x2/2 max-pool done inside the channel dim: y [..., blk*blk*co]
    with ordering (a*blk+b)*co+c. Pool pairs are the LOW bits of (a, b):
    original row = blk*i + a, so rows (2u, 2u+1) pair within a block.
    Returns [..., (blk//2)**2 * co] ordered (a1*(blk//2)+b1)*co+c.

    Written as elementwise maxima of four strided channel slices rather
    than jnp.max over reshaped axes: the reduce form made XLA:TPU pick a
    spatial-minor layout for the 8-d intermediate and materialize
    transposes — the slice/maximum form compiles to pure fused vector ops
    (chipless v5e AOT: −3.1 GB peak HBM on the 3000² step)."""
    *lead, c = y.shape
    assert c == blk * blk * co, (c, blk, co)
    y = y.reshape(*lead, blk // 2, 2, blk // 2, 2, co)
    m = jnp.maximum(
        jnp.maximum(y[..., :, 0, :, 0, :], y[..., :, 0, :, 1, :]),
        jnp.maximum(y[..., :, 1, :, 0, :], y[..., :, 1, :, 1, :]),
    )
    return m.reshape(*lead, (blk // 2) ** 2 * co)


class _Conv(nn.Module):
    """Holds a canonical [5,5,ci,co] kernel + bias (same names, shapes,
    inits as the nn.Conv in ConvNet) and applies it s2d-scattered.

    ``fused=True`` runs the scattered 3x3 conv as the Pallas kernel
    (ops/pallas_conv.py — one HBM pass per direction, no packed-form
    copies) instead of lax.conv; same math (tests/test_pallas_conv.py),
    f32 accumulation either way on TPU, identical variables."""

    shape: tuple[int, ...]
    r: int
    dtype: jnp.dtype
    fused: bool = False

    @nn.compact
    def __call__(self, x, want_stats: bool = False):
        """Returns y — or (y, (sum, sumsq)) when ``want_stats`` (fused
        path only): the BN batch-statistics reductions computed inside the
        conv kernel's output pass (ops/pallas_conv.py::conv3x3_stats)."""
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), self.shape, jnp.float32
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (self.shape[-1],), jnp.float32
        )
        wg = scatter_kernel(kernel.astype(self.dtype), self.r)
        reps = wg.shape[-1] // self.shape[-1]
        bias_g = jnp.tile(bias.astype(self.dtype), reps)
        if self.fused:
            from tpu_sandbox.ops.pallas_conv import conv3x3, conv3x3_stats

            if want_stats:
                y, s, ss = conv3x3_stats(x, wg, bias_g)
                return y, (s, ss)
            return conv3x3(x, wg, bias_g)
        assert not want_stats, "stats fusion requires the fused conv"
        y = jax.lax.conv_general_dilated(
            x, wg, window_strides=(1, 1), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return y + bias_g


class _GroupedBN(nn.Module):
    """flax nn.BatchNorm semantics (f32 fast variance clipped at 0, biased
    running var, momentum blend, (x-mean)*rsqrt(var+eps)*scale+bias) over
    grouped channels [..., g*co] with (co,)-shaped stats — numerically the
    plain BN over the un-s2d tensor, and the same variable names/shapes.

    ``fused(y, blk)`` runs the whole BN-apply+relu+pool tail as the Pallas
    kernel pair instead (ops/pallas_bn_tail.py) — same variables, same
    math, one HBM pass per direction."""

    features: int  # co
    dtype: jnp.dtype
    momentum: float = 0.9
    epsilon: float = 1e-5

    def setup(self):
        co = self.features
        self.scale = self.param(
            "scale", nn.initializers.ones, (co,), jnp.float32
        )
        self.offset = self.param(
            "bias", nn.initializers.zeros, (co,), jnp.float32
        )
        self.ra_mean = self.variable(
            "batch_stats", "mean", lambda s: jnp.zeros(s, jnp.float32), (co,)
        )
        self.ra_var = self.variable(
            "batch_stats", "var", lambda s: jnp.ones(s, jnp.float32), (co,)
        )

    def _update_running(self, mu, var):
        if not self.is_initializing():
            m = self.momentum
            self.ra_mean.value = m * self.ra_mean.value + (1 - m) * mu
            self.ra_var.value = m * self.ra_var.value + (1 - m) * var

    def __call__(self, y, train: bool):
        co = self.features
        *lead, c = y.shape
        yg = y.reshape(*lead, c // co, co)
        if train:
            yf = yg.astype(jnp.float32)
            red = tuple(range(yf.ndim - 1))
            mu = jnp.mean(yf, axis=red)
            mu2 = jnp.mean(jnp.square(yf), axis=red)
            var = jnp.maximum(0.0, mu2 - jnp.square(mu))
            self._update_running(mu, var)
        else:
            mu, var = self.ra_mean.value, self.ra_var.value
        out = (yg.astype(jnp.float32) - mu) * (
            jax.lax.rsqrt(var + self.epsilon) * self.scale
        ) + self.offset
        return out.astype(self.dtype).reshape(*lead, c)

    def fused(self, y, blk: int, ysums=None):
        from tpu_sandbox.ops.pallas_bn_tail import fused_bn_relu_pool

        out, mu, var = fused_bn_relu_pool(
            y, self.scale, self.offset, self.features, blk, self.epsilon,
            None, ysums,
        )
        self._update_running(mu, var)
        return out


class ConvNetS2D(nn.Module):
    """Drop-in ConvNet with the space-to-depth execution plan.

    Requires H, W divisible by 4 (the reference's 3000x3000 qualifies) and
    a single input channel. Other configs: use models.convnet.ConvNet.

    ``fused_tail=True`` runs each BN-apply + ReLU + pool tail as the fused
    Pallas kernel pair (ops/pallas_bn_tail.py — one HBM pass per direction
    instead of several) in train mode; eval and use_bn=False keep the
    plain ops. Same math either way (tests/test_pallas_bn_tail.py), and
    the variable tree is identical, so checkpoints interoperate.
    """

    num_classes: int = 10
    features: tuple[int, ...] = (16, 32)
    dtype: jnp.dtype = jnp.float32  # compute dtype; params stay fp32
    use_bn: bool = True
    fused_tail: bool = False
    # run the scattered 3x3 convs as Pallas kernels (ops/pallas_conv.py):
    # kills XLA's packed-form conv copies — same gating as fused_tail
    fused_conv: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        """x: [N,H,W,1] NHWC or [N,H,W]. Returns logits [N, num_classes]."""
        assert len(self.features) == 2, "s2d plan is the 2-block parity CNN"
        f1, f2 = self.features
        if x.ndim == 4:
            assert x.shape[-1] == 1, "s2d plan is for the 1-channel CNN"
            x = x[..., 0]
        n, h, w = x.shape
        assert h % 4 == 0 and w % 4 == 0, (h, w)

        # stats ride along inside the conv kernels when the whole fused
        # chain is active (train mode: eval BN uses running stats)
        fuse_stats = self.fused_conv and self.fused_tail and self.use_bn \
            and train

        x = space_to_depth(x, 4).astype(self.dtype)      # [N,H/4,W/4,16]
        y = _Conv((5, 5, 1, f1), r=4, dtype=self.dtype,
                  fused=self.fused_conv, name="conv1")(x, fuse_stats)
        y, ysums = y if fuse_stats else (y, None)
        y = self._tail(y, f1, 4, "bn1", train, ysums)     # [N,H/4,W/4,4*f1]

        y = _Conv((5, 5, f1, f2), r=2, dtype=self.dtype,
                  fused=self.fused_conv, name="conv2")(y, fuse_stats)
        y, ysums = y if fuse_stats else (y, None)
        y = self._tail(y, f2, 2, "bn2", train, ysums)     # [N,H/4,W/4,f2]

        # canonical (h, c, w) fc row order — see models/convnet.py
        y = y.transpose(0, 1, 3, 2).reshape(n, -1)
        y = nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(y)
        return jnp.asarray(y, jnp.float32)

    def _tail(self, y, co: int, blk: int, name: str, train: bool,
              ysums=None):
        """BN + ReLU + 2x2 block pool — fused Pallas pair when enabled."""
        if self.use_bn and self.fused_tail and train:
            return _GroupedBN(co, self.dtype, name=name).fused(y, blk, ysums)
        if self.use_bn:
            y = _GroupedBN(co, self.dtype, name=name)(y, train)
        y = nn.relu(y)
        return block_max_pool(y, blk, co)
