from tpu_sandbox.models.convnet import ConvNet  # noqa: F401
from tpu_sandbox.models.convnet_s2d import ConvNetS2D  # noqa: F401
from tpu_sandbox.models.convnet_s2d_t import ConvNetS2DT  # noqa: F401


def resolves_to_s2d(image_size, plan: str = "auto") -> bool:
    """Single home for the auto-plan rule: does this (image_size, plan)
    request run a space-to-depth execution plan (NHWC or transposed)?
    Callers that label or annotate results by plan (bench sweep's kernel
    race, the degraded line's AOT estimate block) must use this rather
    than re-deriving the rule."""
    h, w = (image_size, image_size) if isinstance(image_size, int) else image_size
    return plan != "plain" and (
        plan in ("s2d", "s2dt") or (plan == "auto" and h % 4 == 0
                                    and w % 4 == 0)
    )


def resolve_plan(image_size, plan: str = "auto") -> str:
    """Concrete plan for a request: 's2dt' | 's2d' | 'plain'.

    'auto' picks the transposed plan (models/convnet_s2d_t.py — the
    measured-fastest execution, always-Pallas) wherever the kernels
    COMPILE (TPU, or chipless AOT via TPU_SANDBOX_FORCE_COMPILED_KERNELS),
    the NHWC s2d plan where they would run interpreted (CPU tests), and
    the plain ConvNet when the image is not 4-divisible."""
    if not resolves_to_s2d(image_size, plan):
        return "plain"
    if plan in ("s2d", "s2dt"):
        return plan
    from tpu_sandbox.ops.pallas_common import default_interpret

    return "s2dt" if not default_interpret(None) else "s2d"


def pick_convnet(image_size, *, plan: str = "auto", **kwargs):
    """The execution-plan switch. Three plans, one function
    (tests/test_convnet_s2d.py, tests/test_convnet_s2d_t.py):

    - 's2dt' (TPU default): transposed space-to-depth, [N,H,C,W] Pallas
      conv + fused-tail kernels throughout — the round-3 measured-fastest
      plan (see models/convnet_s2d_t.py docstring for the numbers);
    - 's2d': NHWC space-to-depth; Pallas kernels gated by fused_tail /
      fused_conv (defaulting on where kernels compile);
    - 'plain': the direct NHWC ConvNet (the reference-shaped execution).

    fused_tail/fused_conv kwargs are accepted for every plan and applied
    where they mean something (the transposed plan has no unfused conv;
    the plain plan ignores both)."""
    resolved = resolve_plan(image_size, plan)
    fused = kwargs.pop("fused_tail", None)
    fused_conv = kwargs.pop("fused_conv", None)

    def drop_s2dt_only(kw):
        # s2dt-only toggles (sparse_conv1, fused_conv1_bwd) are
        # meaningless — and unknown — to the other plans; drop them so a
        # plan-ladder rung like dict(fused_conv1_bwd=False) still works
        # when 'auto' resolves elsewhere (e.g. s2d on CPU)
        return {k: v for k, v in kw.items()
                if k not in ("sparse_conv1", "fused_conv1_bwd")}

    if resolved == "plain":
        return ConvNet(**drop_s2dt_only(kwargs))
    from tpu_sandbox.ops.pallas_common import default_interpret

    compiled = not default_interpret(None)
    if resolved == "s2dt" and fused_conv is False:
        # the transposed plan has no unfused-conv mode; honor the kill
        # switch by dropping to the NHWC s2d plan instead of ignoring it
        # (ADVICE r03: fused_conv=False under plan='auto' must still
        # disable the Pallas convs)
        resolved = "s2d"
    if resolved == "s2dt":
        return ConvNetS2DT(fused_tail=compiled if fused is None else fused,
                           **kwargs)
    return ConvNetS2D(
        fused_tail=compiled if fused is None else fused,
        fused_conv=compiled if fused_conv is None else fused_conv,
        **drop_s2dt_only(kwargs),
    )
