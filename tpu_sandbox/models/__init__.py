from tpu_sandbox.models.convnet import ConvNet  # noqa: F401
from tpu_sandbox.models.convnet_s2d import ConvNetS2D  # noqa: F401


def pick_convnet(image_size, *, plan: str = "auto", **kwargs):
    """The execution-plan switch: ConvNetS2D (space-to-depth, the TPU fast
    path — see models/convnet_s2d.py) when the plan applies, else the plain
    ConvNet. Both are the same function (tests/test_convnet_s2d.py)."""
    h, w = (image_size, image_size) if isinstance(image_size, int) else image_size
    if plan == "plain":
        return ConvNet(**kwargs)
    if plan == "s2d" or (plan == "auto" and h % 4 == 0 and w % 4 == 0):
        return ConvNetS2D(**kwargs)
    return ConvNet(**kwargs)
