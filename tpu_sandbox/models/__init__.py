from tpu_sandbox.models.convnet import ConvNet  # noqa: F401
