from tpu_sandbox.models.convnet import ConvNet  # noqa: F401
from tpu_sandbox.models.convnet_s2d import ConvNetS2D  # noqa: F401


def resolves_to_s2d(image_size, plan: str = "auto") -> bool:
    """Single home for the auto-plan rule: does this (image_size, plan)
    request run the s2d execution plan? Callers that label or annotate
    results by plan (bench sweep's kernel race, the degraded line's AOT
    estimate block) must use this rather than re-deriving the rule."""
    h, w = (image_size, image_size) if isinstance(image_size, int) else image_size
    return plan != "plain" and (
        plan == "s2d" or (plan == "auto" and h % 4 == 0 and w % 4 == 0)
    )


def pick_convnet(image_size, *, plan: str = "auto", **kwargs):
    """The execution-plan switch: ConvNetS2D (space-to-depth, the TPU fast
    path — see models/convnet_s2d.py) when the plan applies, else the plain
    ConvNet. Both are the same function (tests/test_convnet_s2d.py).

    On backends where Pallas kernels COMPILE (TPU, or chipless AOT with
    TPU_SANDBOX_FORCE_COMPILED_KERNELS=1) the s2d plan defaults to the
    fused Pallas BN/ReLU/pool tail (2.6x less HBM traffic per image by v5e
    AOT analysis of the compiled Mosaic kernels: 5.45 vs 14.18 GB/img at
    bs=16; equality-tested). Elsewhere the kernels would run interpreted —
    a large slowdown in a training loop — so the default stays unfused.
    Pass fused_tail explicitly to override either way (accepted and
    ignored by the plain plan)."""
    h, w = (image_size, image_size) if isinstance(image_size, int) else image_size
    fused = kwargs.pop("fused_tail", None)
    fused_conv = kwargs.pop("fused_conv", None)
    if resolves_to_s2d(image_size, plan):
        if fused is None or fused_conv is None:
            from tpu_sandbox.ops.pallas_common import default_interpret

            compiled = not default_interpret(None)
            fused = compiled if fused is None else fused
            fused_conv = compiled if fused_conv is None else fused_conv
        return ConvNetS2D(fused_tail=fused, fused_conv=fused_conv, **kwargs)
    return ConvNet(**kwargs)
