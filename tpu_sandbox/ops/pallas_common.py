"""Shared bits for the Pallas TPU kernels (pallas_ce, pallas_attention).

One home for the tile/padding conventions so the kernels can't drift:
the 128-lane tile width, the large-negative mask filler (chosen so
``exp(filler - max)`` underflows to 0 in fp32), alignment rounding, and
the off-TPU interpret-mode fallback that lets the same call path run
compiled on TPU and interpreted in CPU tests.
"""

from __future__ import annotations

import os

import jax

NEG = -1e30
LANE = 128


def round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def default_interpret(interpret: bool | None) -> bool:
    """Kernels compile only on TPU; anywhere else, interpret.

    TPU_SANDBOX_FORCE_COMPILED_KERNELS=1 overrides the backend check for
    chipless AOT analysis (tools/aot_v5e.py): there the default backend is
    CPU but lowering targets a TPU topology, and interpret-mode kernels
    would make the compiler's memory/traffic numbers describe the
    interpreter's loop, not the Mosaic kernel. Compile-only — executing on
    CPU with this set would fail."""
    if interpret is None:
        if os.environ.get("TPU_SANDBOX_FORCE_COMPILED_KERNELS") == "1":
            return False
        return jax.default_backend() != "tpu"
    return interpret
