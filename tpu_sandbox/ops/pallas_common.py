"""Shared bits for the Pallas TPU kernels (pallas_ce, pallas_attention).

One home for the tile/padding conventions so the kernels can't drift:
the 128-lane tile width, the large-negative mask filler (chosen so
``exp(filler - max)`` underflows to 0 in fp32), alignment rounding, and
the off-TPU interpret-mode fallback that lets the same call path run
compiled on TPU and interpreted in CPU tests.
"""

from __future__ import annotations

import jax

NEG = -1e30
LANE = 128


def round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def default_interpret(interpret: bool | None) -> bool:
    """Kernels compile only on TPU; anywhere else, interpret."""
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret
