"""Pallas TPU kernel: flash attention (online-softmax, O(seq) memory).

The reference repo has no attention anywhere (SURVEY §2.2: ring attention /
CP "ABSENT" — its model is a CNN), but this framework treats long-context as
first-class, and attention is the one transformer op where XLA's default
lowering materializes the [S, S] score matrix in HBM. This kernel never
does: the forward pass streams K/V blocks through VMEM with the online
softmax recurrence, so peak memory is O(block_q · block_k) per core instead
of O(S²), and the matmuls stay on the MXU in the input dtype with fp32
accumulation.

Shapes and grid:
- inputs [B, H, S, D] (callers with [B, S, H, D] use ``flash_attention_fn``,
  which transposes, pads S to the q/k block and D to the 128-lane tile, and
  undoes both on the way out);
- grid (B, H, S/block_q, S/block_k), kv innermost ("arbitrary" — it carries
  the softmax state); m/l/acc live in VMEM scratch across kv steps and the
  output + logsumexp are written on the last kv step.

Backward is the standard flash backward recomputation — no O(S²) residual is
saved, only (q, k, v, out, lse) — and runs as two Pallas kernels (VERDICT
r01 weak #4: the first version scanned kv blocks in jnp, holding
[S, block_k] score slabs): a dk/dv kernel with q blocks innermost and a dq
kernel with kv blocks innermost, both accumulating in VMEM scratch with the
[block_q, block_k] probability tile recomputed from the saved logsumexp.
Peak memory is O(block² ) per core in both passes. The jnp scan version is
kept as ``_blockwise_bwd`` — the reference implementation the kernels are
tested against.

Falls back to interpret mode off-TPU automatically, like ops.pallas_ce.
"""

from __future__ import annotations

import functools
import math

import jax

from tpu_sandbox.utils.compat import tpu_compiler_params
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tpu_sandbox.ops.pallas_common import (
    LANE as _LANE,
    NEG as _NEG,
    default_interpret,
    round_up as _round_up,
)


def _to_bhsd(x, s_target: int, d_target: int):
    """[B, S, H, D] -> [B, H, s_target, d_target]: the kernel layout
    (heads to dim 1, sequence zero-padded to the block multiple, head dim
    to the lane tile). Single home for the padding convention — forward,
    lse-forward and backward all go through here."""
    x = jnp.moveaxis(x, 2, 1)
    return jnp.pad(
        x,
        ((0, 0), (0, 0), (0, s_target - x.shape[2]),
         (0, d_target - x.shape[3])),
    )


def _from_bhsd(x, s: int, d: int):
    """Inverse of _to_bhsd: slice off padding, heads back to dim 2."""
    return jnp.moveaxis(x[:, :, :s, :d], 1, 2)


def _fwd_kernel(q_off_ref, kv_off_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr,
                *, scale: float, causal: bool, block_q: int, block_k: int,
                kv_len: int):
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # with causal masking, kv block j contributes to q block i only when
    # the block diagonals overlap in GLOBAL positions — a runtime predicate
    # on the prefetched offsets, so ring steps whose whole block is in the
    # future skip both MXU matmuls instead of computing a fully-masked tile
    should_run = True
    if causal:
        should_run = (
            kv_off_ref[0, 0] + j * block_k
            <= q_off_ref[0, 0] + (i + 1) * block_q - 1
        )

    @pl.when(should_run)
    def _step():
        q = q_ref[0, 0]                      # [block_q, d]
        k = k_ref[0, 0]                      # [block_k, d]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                            # [block_q, block_k] fp32

        q_pos = q_off_ref[0, 0] + i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = kv_off_ref[0, 0] + j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        valid = k_pos < kv_off_ref[0, 0] + kv_len  # mask padded tail keys
        if causal:
            valid = jnp.logical_and(valid, q_pos >= k_pos)
        s = jnp.where(valid, s, _NEG)

        m_prev = m_scr[:, :1]                # [block_q, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)               # [block_q, block_k]
        alpha = jnp.exp(m_prev - m_new)      # [block_q, 1]
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == pl.num_programs(3) - 1)
    def _emit():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)
        # lane-broadcast row stats: Mosaic requires the last two block dims
        # to be (8k, 128m)-aligned, so lse is carried as [block_q, LANE]
        # (the official TPU flash kernel's MIN_BLOCK_SIZE convention) and
        # sliced back to a row outside the kernel
        lse_ref[0, 0] = jnp.broadcast_to(
            m_scr[:, :1] + jnp.log(l), lse_ref.shape[2:]
        ).astype(jnp.float32)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret, kv_len,
               q_offset=0, kv_offset=0, out_dtype=None):
    """q,k,v [B,H,S,D] (S multiple of blocks, D lane-aligned; ``kv_len`` is
    the true pre-padding length) -> (out [B,H,S,D], lse [B,H,S]).

    ``q_offset``/``kv_offset`` are *global* positions of the first local
    query/key (python ints or traced scalars — ring attention passes the
    rotating source offset); the causal block skip stays active either way
    because the kernel predicates on the runtime offsets. ``out_dtype``
    defaults to q's dtype; partial-attention callers pass fp32 so the
    cross-block merge never sees a rounded partial.
    """
    from jax.experimental.pallas import tpu as pltpu

    interpret = default_interpret(interpret)
    b, h, s, d = q.shape
    sk = k.shape[2]
    grid = (b, h, s // block_q, sk // block_k)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_len=kv_len,
    )
    offs = [jnp.asarray(x, jnp.int32).reshape(1, 1)
            for x in (q_offset, kv_offset)]
    smem = functools.partial(pl.BlockSpec, (1, 1),
                             lambda b, h, i, j: (0, 0),
                             memory_space=pltpu.SMEM)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, h, s, d), out_dtype or q.dtype),
            jax.ShapeDtypeStruct((b, h, s, _LANE), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            smem(),
            smem(),
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, _LANE),
                         lambda b, h, i, j: (b, h, i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # running max m
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # running sum l
            pltpu.VMEM((block_q, d), jnp.float32),      # output accumulator
        ],
        compiler_params=tpu_compiler_params(pltpu)(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(*offs, q, k, v)
    return out, lse[..., 0]


def _bwd_dkv_kernel(q_off_ref, kv_off_ref, q_ref, k_ref, v_ref, do_ref,
                    lse_ref, delta_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                    *, scale: float, causal: bool, block_q: int, block_k: int,
                    kv_len: int):
    """dk/dv: grid (B, H, kv blocks, q blocks), q innermost (accumulates).

    Standard flash backward with saved lse: p = exp(s - lse);
    dv += pᵀ·do; ds = p ⊙ (do·vᵀ - delta) · scale; dk += dsᵀ·q.
    Peak memory is the [block_q, block_k] tile + two [block_k, d] scratch
    accumulators — O(block), the VERDICT r01 weak #4 fix (the jnp scan
    backward held [S, block_k] score slabs per step).
    """
    j, i = pl.program_id(2), pl.program_id(3)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    should_run = True
    if causal:  # q block entirely before the kv block -> nothing flows
        should_run = (
            q_off_ref[0, 0] + (i + 1) * block_q - 1
            >= kv_off_ref[0, 0] + j * block_k
        )

    @pl.when(should_run)
    def _step():
        f32 = jnp.float32
        q = q_ref[0, 0].astype(f32)
        k = k_ref[0, 0].astype(f32)
        v = v_ref[0, 0].astype(f32)
        do = do_ref[0, 0].astype(f32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=f32
        ) * scale                                     # [bq, bk]
        q_pos = q_off_ref[0, 0] + i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = kv_off_ref[0, 0] + j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        valid = k_pos < kv_off_ref[0, 0] + kv_len
        if causal:
            valid = jnp.logical_and(valid, q_pos >= k_pos)
        p = jnp.where(valid, jnp.exp(s - lse_ref[0, 0][:, :1]), 0.0)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=f32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=f32
        )
        ds = p * (dp - delta_ref[0, 0][:, :1]) * scale
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=f32
        )

    @pl.when(i == pl.num_programs(3) - 1)
    def _emit():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_off_ref, kv_off_ref, q_ref, k_ref, v_ref, do_ref,
                   lse_ref, delta_ref, dq_ref, dq_scr,
                   *, scale: float, causal: bool, block_q: int, block_k: int,
                   kv_len: int):
    """dq: grid (B, H, q blocks, kv blocks), kv innermost (accumulates).
    dq += ds·k·scale with the same p/ds tiles as the dk/dv kernel."""
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    should_run = True
    if causal:
        should_run = (
            kv_off_ref[0, 0] + j * block_k
            <= q_off_ref[0, 0] + (i + 1) * block_q - 1
        )

    @pl.when(should_run)
    def _step():
        f32 = jnp.float32
        q = q_ref[0, 0].astype(f32)
        k = k_ref[0, 0].astype(f32)
        v = v_ref[0, 0].astype(f32)
        do = do_ref[0, 0].astype(f32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=f32
        ) * scale
        q_pos = q_off_ref[0, 0] + i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = kv_off_ref[0, 0] + j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        valid = k_pos < kv_off_ref[0, 0] + kv_len
        if causal:
            valid = jnp.logical_and(valid, q_pos >= k_pos)
        p = jnp.where(valid, jnp.exp(s - lse_ref[0, 0][:, :1]), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=f32
        )
        ds = p * (dp - delta_ref[0, 0][:, :1]) * scale
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=f32
        )

    @pl.when(j == pl.num_programs(3) - 1)
    def _emit():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd(q, k, v, delta, lse, g, scale, causal, block_q, block_k,
               interpret, kv_len, q_offset=0, kv_offset=0, out_dtype=None):
    """Pallas backward: (dq, dk, dv), peak memory O(block) per core.

    q,k,v,g [B,H,S,D] (block-padded, lane-aligned), lse [B,H,S] fp32,
    delta = rowsum(g ⊙ out) [B,H,S] precomputed by the caller (once — ring
    callers reuse it across hops). ``out_dtype`` overrides the gradient
    dtype (ring callers pass fp32 so per-hop partials accumulate unrounded).
    """
    from jax.experimental.pallas import tpu as pltpu

    interpret = default_interpret(interpret)
    b, h, s, d = q.shape
    sk = k.shape[2]
    # row stats enter lane-broadcast ([B,H,S] -> [B,H,S,LANE]) for the same
    # Mosaic block-alignment reason the forward emits lse that way
    lse = jnp.broadcast_to(lse[..., None], (*lse.shape, _LANE))
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, _LANE))
    offs = [jnp.asarray(x, jnp.int32).reshape(1, 1)
            for x in (q_offset, kv_offset)]
    smem = functools.partial(pl.BlockSpec, (1, 1),
                             lambda b, h, x, y: (0, 0),
                             memory_space=pltpu.SMEM)

    def spec(blk, pos):  # [*, *, blk, d] tensors indexed by grid dim `pos`
        return pl.BlockSpec(
            (1, 1, blk, d),
            (lambda b, h, x, y: (b, h, x, 0)) if pos == 2
            else (lambda b, h, x, y: (b, h, y, 0)),
        )

    qspec = functools.partial(spec, block_q)
    kspec = functools.partial(spec, block_k)

    def rowspec(pos):  # lse/delta [B, H, S, LANE] lane-broadcast blocks
        return pl.BlockSpec(
            (1, 1, block_q, _LANE),
            (lambda b, h, x, y: (b, h, x, 0)) if pos == 2
            else (lambda b, h, x, y: (b, h, y, 0)),
        )

    params = dict(scale=scale, causal=causal, block_q=block_q,
                  block_k=block_k, kv_len=kv_len)
    compiler = tpu_compiler_params(pltpu)(
        dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
    )

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **params),
        out_shape=(
            jax.ShapeDtypeStruct(k.shape, out_dtype or k.dtype),
            jax.ShapeDtypeStruct(v.shape, out_dtype or v.dtype),
        ),
        grid=(b, h, sk // block_k, s // block_q),
        in_specs=[smem(), smem(), qspec(3), kspec(2), kspec(2), qspec(3),
                  rowspec(3), rowspec(3)],
        out_specs=(kspec(2), kspec(2)),
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=compiler,
        interpret=interpret,
    )(*offs, q, k, v, g, lse, delta)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **params),
        out_shape=jax.ShapeDtypeStruct(q.shape, out_dtype or q.dtype),
        grid=(b, h, s // block_q, sk // block_k),
        in_specs=[smem(), smem(), qspec(2), kspec(3), kspec(3), qspec(2),
                  rowspec(2), rowspec(2)],
        out_specs=qspec(2),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=compiler,
        interpret=interpret,
    )(*offs, q, k, v, g, lse, delta)
    return dq, dk, dv


def _blockwise_bwd(q, k, v, out, lse, g, scale, causal, block_k, kv_len):
    """Flash backward: scan over kv blocks, O(S·block_k) live memory.

    Standard formulas with saved lse: p = exp(q·kᵀ·scale − lse);
    D = rowsum(g ⊙ out); dS = p ⊙ (g·vᵀ − D); dq = dS·k·scale;
    dk = dSᵀ·q·scale; dv = pᵀ·g.  All per (batch, head) via vmap.
    """
    s_len = q.shape[2]
    n_blocks = s_len // block_k
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    q_pos = jnp.arange(s_len)

    def per_head(q1, k1, v1, lse1, g1, delta1):
        # q1,k1,v1,g1 [S, D]; lse1, delta1 [S]
        qf = q1.astype(jnp.float32)
        gf = g1.astype(jnp.float32)

        def body(dq_acc, jb):
            ks = jax.lax.dynamic_slice_in_dim(k1, jb * block_k, block_k, 0)
            vs = jax.lax.dynamic_slice_in_dim(v1, jb * block_k, block_k, 0)
            ksf = ks.astype(jnp.float32)
            s_blk = (qf @ ksf.T) * scale                   # [S, block_k]
            k_pos = jb * block_k + jnp.arange(block_k)
            mask = (k_pos < kv_len)[None, :]
            if causal:
                mask = jnp.logical_and(mask, q_pos[:, None] >= k_pos[None, :])
            s_blk = jnp.where(mask, s_blk, _NEG)
            p = jnp.exp(s_blk - lse1[:, None])             # [S, block_k]
            dv = p.T @ gf                                  # [block_k, D]
            dp = gf @ vs.astype(jnp.float32).T             # [S, block_k]
            ds = p * (dp - delta1[:, None])                # [S, block_k]
            dq_acc = dq_acc + (ds @ ksf) * scale
            dk = (ds.T @ qf) * scale                       # [block_k, D]
            return dq_acc, (dk, dv)

        dq, (dks, dvs) = jax.lax.scan(
            body, jnp.zeros(q1.shape, jnp.float32), jnp.arange(n_blocks)
        )
        dk = dks.reshape(s_len, -1)
        dv = dvs.reshape(s_len, -1)
        return dq.astype(q1.dtype), dk.astype(k1.dtype), dv.astype(v1.dtype)

    f = jax.vmap(jax.vmap(per_head))
    return f(q, k, v, lse, g, delta)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_core(q, k, v, scale, causal, block_q, block_k, interpret, kv_len):
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
                        kv_len)
    return out


def _core_fwd(q, k, v, scale, causal, block_q, block_k, interpret, kv_len):
    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
                          kv_len)
    return out, (q, k, v, out, lse)


def _core_bwd(scale, causal, block_q, block_k, interpret, kv_len, res, g):
    q, k, v, out, lse = res
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    return _flash_bwd(q, k, v, delta, lse, g, scale, causal, block_q, block_k,
                      interpret, kv_len)


_flash_core.defvjp(_core_fwd, _core_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Flash attention over [B, S, H, D] inputs (the layout used by
    models.transformer.SelfAttention and ops.attention.causal_attention,
    which this matches numerically — tested).

    Pads S up to the block size and D up to the 128-lane tile (zero-padded
    keys are masked inside the kernel; zero-padded value lanes produce
    zero output lanes, sliced off).
    """
    b, s, h, d = q.shape
    scale = 1.0 / float(d) ** 0.5
    lcm = math.lcm(block_q, block_k)
    sp = _round_up(max(s, lcm), lcm)
    dp = _round_up(d, _LANE)
    out = _flash_core(
        _to_bhsd(q, sp, dp), _to_bhsd(k, sp, dp), _to_bhsd(v, sp, dp),
        scale, causal, block_q, block_k, interpret, s,
    )
    return _from_bhsd(out, s, d)


def flash_attention_lse(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    q_offset=0,
    kv_offset=0,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Forward-only flash attention returning (out [B,S,H,D], lse [B,S,H]).

    The partial-attention building block for ring attention: offsets give
    queries/keys their global positions, and the logsumexp output lets the
    caller merge partials from different K/V blocks exactly
    (parallel/flash_ring.py). NOT differentiable on its own — the ring
    defines the custom VJP at its own level.
    """
    b, s, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / float(d) ** 0.5
    sp = _round_up(max(s, block_q), block_q)
    skp = _round_up(max(sk, block_k), block_k)
    dp = _round_up(d, _LANE)
    # padded q rows also run; their garbage rows are sliced off below, and
    # the grid only needs square-compatible blocks, not equal q/kv lengths.
    # fp32 partials: the caller's logsumexp merge must not see bf16 rounding
    out, lse = _flash_fwd(
        _to_bhsd(q, sp, dp), _to_bhsd(k, skp, dp), _to_bhsd(v, skp, dp),
        scale, causal, block_q, block_k, interpret, sk, q_offset=q_offset,
        kv_offset=kv_offset, out_dtype=jnp.float32,
    )
    return (
        _from_bhsd(out, s, d),
        jnp.moveaxis(lse[:, :, :s], 1, 2),  # [B, S, H]
    )


def make_flash_bwd_lse(
    q, out, g, lse, *,
    causal: bool = True,
    q_offset=0,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
):
    """Partial-attention backward factory, [B, S, H, D] layout: pads the
    loop-invariant q-side tensors and computes delta = rowsum(g ⊙ out)
    ONCE, returning ``fn(k_blk, v_blk, kv_offset) -> (dq, dk, dv)`` for the
    per-hop calls of flash-ring's backward (parallel/flash_ring.py) — only
    the rotating K/V blocks are padded per hop. Gradients come back fp32 so
    ring callers can accumulate hops unrounded. ``lse`` [B, S, H] is the
    FINAL (merged) logsumexp.
    """
    b, s, hh, d = q.shape
    scale = 1.0 / float(d) ** 0.5
    sp = _round_up(max(s, block_q), block_q)
    dp = _round_up(d, _LANE)
    qp, outp, gp = (_to_bhsd(x, sp, dp) for x in (q, out, g))
    # padded q rows: zero q/g rows give p = exp(0 - 0) = 1 but ds = dv = 0
    # through the zero cotangent, so padding lse with 0 is safe
    lse_p = jnp.pad(jnp.moveaxis(lse, 2, 1), ((0, 0), (0, 0), (0, sp - s)))
    delta = jnp.sum(gp.astype(jnp.float32) * outp.astype(jnp.float32), -1)

    def partial_bwd(k_blk, v_blk, kv_offset):
        sk = k_blk.shape[1]
        skp = _round_up(max(sk, block_k), block_k)
        dq, dk, dv = _flash_bwd(
            qp, _to_bhsd(k_blk, skp, dp), _to_bhsd(v_blk, skp, dp), delta,
            lse_p, gp, scale, causal, block_q, block_k, interpret, sk,
            q_offset=q_offset, kv_offset=kv_offset, out_dtype=jnp.float32,
        )
        return _from_bhsd(dq, s, d), _from_bhsd(dk, sk, d), _from_bhsd(dv, sk, d)

    return partial_bwd


def flash_attention_fn(
    *, causal: bool = True, block_q: int = 128, block_k: int = 128,
    interpret: bool | None = None,
):
    """An ``attention_fn`` drop-in for models.transformer.TransformerLM."""

    def fn(q, k, v):
        return flash_attention(
            q, k, v, causal=causal, block_q=block_q, block_k=block_k,
            interpret=interpret,
        )

    return fn
