"""Attention ops: the single-device reference implementation.

The reference repo has no attention at all (its model is a CNN —
SURVEY §2.2 "CP/ring attention: ABSENT"); this framework treats
long-context as first-class, so the op exists at the ops layer with a
distributed ring implementation in tpu_sandbox.parallel.ring_attention
(verified against this one in tests).

Math: standard scaled dot-product attention with optional causal mask,
softmax statistics accumulated in fp32 regardless of input dtype (the
bf16-on-MXU pattern: matmuls in bf16, reductions in fp32).
"""

from __future__ import annotations

import jax.numpy as jnp


def causal_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    q_offset: int | jnp.ndarray = 0,
    kv_offset: int | jnp.ndarray = 0,
) -> jnp.ndarray:
    """q,k,v: [B, S, H, D] -> [B, S, H, D].

    ``q_offset``/``kv_offset`` are the global positions of the first local
    query/key — the hooks sequence-sharded callers use to mask correctly.
    """
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.asarray(d, jnp.float32))
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        kv_pos = kv_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= kv_pos[None, :]
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    weights = jnp.nan_to_num(jnp.exp(scores - scores.max(-1, keepdims=True)))
    weights = weights / jnp.maximum(weights.sum(-1, keepdims=True), 1e-30)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights.astype(v.dtype), v)
    return out
