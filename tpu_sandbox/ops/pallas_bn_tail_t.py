"""Fused BN-apply + ReLU + 2x2 block max-pool for the TRANSPOSED layout
[N, H, C, W] — the tail companion of ops/pallas_conv_t.py.

Same math and the same exactness contract as ops/pallas_bn_tail.py (the
NHWC pair): z = relu(round(y*a + b)) with a = gamma*rsqrt(var+eps),
b = beta - mu*a; 2x2 pool inside the channel dim; train-mode BN backward
with gradients flowing through the batch statistics; pool VJP with exact
0.5/0.5 tie splitting on values rounded to the activation dtype. The
only difference is orientation: channels live on SUBLANES here, so

- the per-channel vectors (a, b, mu, inv, ...) are [C, 1] columns
  broadcast over lanes instead of [1, C] lane vectors;
- the pool partners of channel c = (a*blk+b)*co + k sit at SUBLANE
  offsets co (b's low bit) and blk*co (a's low bit) — the roll-and-max
  runs along sublanes;
- the compaction/scatter matmuls flip sides: out = selT [C/4, C] @ m2
  [C, W] and g_exp = sel [C, C/4] @ g [C/4, W], both clean [M,K]x[K,N]
  MXU forms with W on lanes.

Reference chain being fused: models/convnet_s2d.py _GroupedBN(train) +
relu + block_max_pool, transposed (see convnet_s2d_t.py); ultimately the
BN/ReLU/MaxPool tails of /root/reference/mnist_onegpu.py:11-31.
"""

from __future__ import annotations

import functools

import jax

from tpu_sandbox.utils.compat import tpu_compiler_params
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from tpu_sandbox.ops.pallas_bn_tail import selection_matrix
from tpu_sandbox.ops.pallas_common import default_interpret


def _pool_fronts(z, co: int, blk: int):
    """(zb, m1, m1a): rolled partners and pairwise maxima along SUBLANES;
    m2 = max(m1, m1a) holds each 4-way max at its representative row."""
    zb = jnp.roll(z, -co, axis=0)
    m1 = jnp.maximum(z, zb)
    m1a = jnp.roll(m1, -blk * co, axis=0)
    return zb, m1, m1a


def _route(z, g_exp, co: int, blk: int):
    """Pool VJP on one [C, W] row: winner takes the cotangent, exact ties
    split 0.5/0.5 (same contract as pallas_bn_tail._route; the rolls run
    along sublanes here). Nonzero values never wrap: representatives +
    blk*co + co < C."""
    s, ss = co, blk * co
    zb, m1, m1a = _pool_fronts(z, co, blk)

    def weights(x, xb):
        return 0.5 * ((x > xb).astype(jnp.float32)
                      + (x >= xb).astype(jnp.float32))

    w2 = weights(m1, m1a)
    dm1 = g_exp * w2 + jnp.roll(g_exp * (1.0 - w2), ss, axis=0)
    w1 = weights(z, zb)
    dz = dm1 * w1 + jnp.roll(dm1 * (1.0 - w1), s, axis=0)
    return dz


def _rounded_relu(y_ref, a_ref, b_ref, r, dtype):
    """One row's z in the OUTPUT dtype, held in f32 (same rounding/tie
    rationale as pallas_bn_tail._rounded_relu; vectors are columns)."""
    zpre = y_ref[0, r].astype(jnp.float32) * a_ref[...] + b_ref[...]
    return jnp.maximum(zpre.astype(dtype), 0).astype(jnp.float32)


def _fwd_kernel(y_ref, a_ref, b_ref, st_ref, out_ref, *, co: int, blk: int):
    hb = y_ref.shape[1]
    for r in range(hb):
        z = _rounded_relu(y_ref, a_ref, b_ref, r, out_ref.dtype)
        _, m1, m1a = _pool_fronts(z, co, blk)
        m2 = jnp.maximum(m1, m1a)
        out_ref[0, r] = jax.lax.dot_general(
            st_ref[...], m2, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(out_ref.dtype)


def _row_dz(y_ref, a_ref, b_ref, g_ref, s_ref, r, co, blk, dtype):
    """Recompute one row's (rounded) z and route its pooled cotangent."""
    z = _rounded_relu(y_ref, a_ref, b_ref, r, dtype)
    g_exp = jax.lax.dot_general(  # [C, C/4] @ [C/4, W]: scatter to reps
        s_ref[...], g_ref[0, r].astype(jnp.float32),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )
    return _route(z, g_exp, co, blk) * (z > 0)


def _bwd_reduce_kernel(y_ref, a_ref, b_ref, g_ref, s_ref, mu_ref, inv_ref,
                       s1_ref, s2_ref, s1_scr, s2_scr,
                       *, co: int, blk: int):
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(jnp.logical_and(i == 0, j == 0))
    def _init():
        s1_scr[:] = jnp.zeros_like(s1_scr)
        s2_scr[:] = jnp.zeros_like(s2_scr)

    hb = y_ref.shape[1]
    for r in range(hb):
        dz = _row_dz(y_ref, a_ref, b_ref, g_ref, s_ref, r, co, blk,
                     y_ref.dtype)
        y = y_ref[0, r].astype(jnp.float32)
        t_hat = (y - mu_ref[...]) * inv_ref[...]
        s1_scr[:] = s1_scr[:] + jnp.sum(dz, axis=1, keepdims=True)
        s2_scr[:] = s2_scr[:] + jnp.sum(dz * t_hat, axis=1, keepdims=True)

    @pl.when(jnp.logical_and(i == pl.num_programs(0) - 1,
                             j == pl.num_programs(1) - 1))
    def _emit():
        s1_ref[...] = s1_scr[:]
        s2_ref[...] = s2_scr[:]


def _bwd_apply_kernel(y_ref, a_ref, b_ref, g_ref, s_ref, mu_ref, inv_ref,
                      gi_ref, c1_ref, c2_ref, dy_ref, *, co: int, blk: int):
    hb = y_ref.shape[1]
    for r in range(hb):
        dz = _row_dz(y_ref, a_ref, b_ref, g_ref, s_ref, r, co, blk,
                     y_ref.dtype)
        y = y_ref[0, r].astype(jnp.float32)
        t_hat = (y - mu_ref[...]) * inv_ref[...]
        dy = gi_ref[...] * (dz - c1_ref[...] - t_hat * c2_ref[...])
        dy_ref[0, r] = dy.astype(dy_ref.dtype)


def _col_expand(v_co, reps: int):
    """per-co vector -> sublane column [reps*co, 1] (co minor)."""
    return jnp.tile(v_co.astype(jnp.float32), reps)[:, None]


def _grid_rows(h: int, w: int, c: int) -> int:
    """Same VMEM-budget rule as pallas_bn_tail (the row loop keeps ~a
    dozen [c, w] f32 intermediates live)."""
    cap = max(1, int(6 * 1024 * 1024 // max(w * c * 14, 1)))
    for hb in (10, 6, 5, 4, 3, 2, 1):
        if hb <= cap and h % hb == 0:
            return hb
    return 1


def unfused_reference_t(y, gamma, beta, co: int, blk: int, eps: float = 1e-5):
    """The unfused transposed tail exactly as ConvNetS2DT computes it in
    train mode: (pooled, mu, var). Contract for tests and bench."""
    from tpu_sandbox.models.convnet_s2d_t import block_max_pool_t

    *lead, c, w = y.shape
    yf = y.astype(jnp.float32).reshape(*lead, c // co, co, w)
    red = tuple(i for i in range(yf.ndim) if i != yf.ndim - 2)
    mu = jnp.mean(yf, axis=red)
    var = jnp.maximum(0.0, jnp.mean(jnp.square(yf), axis=red)
                      - jnp.square(mu))
    z = (yf - mu[:, None]) * (jax.lax.rsqrt(var + eps)
                              * gamma.astype(jnp.float32))[:, None] \
        + beta.astype(jnp.float32)[:, None]
    z = jax.nn.relu(z.reshape(*lead, c, w).astype(y.dtype))
    return block_max_pool_t(z, blk, co), mu, var


def _stats_t(y, co):
    yf = y.astype(jnp.float32)
    n, h, c, w = yf.shape
    yg = yf.reshape(n, h, c // co, co, w)
    mu = jnp.mean(yg, axis=(0, 1, 2, 4))
    var = jnp.maximum(
        0.0, jnp.mean(jnp.square(yg), axis=(0, 1, 2, 4)) - jnp.square(mu)
    )
    return mu, var


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def fused_bn_relu_pool_t(y, gamma, beta, co, blk, eps=1e-5, interpret=None,
                         ysums=None):
    """[N,H,blk*blk*co,W] conv output -> ([N,H,(blk//2)**2*co,W] pooled,
    mu [co], var [co]) with train-mode batch statistics.

    Numerically the transposed _GroupedBN(train=True) + relu +
    block_max_pool_t chain, in one HBM pass. mu/var cotangents ignored
    (stats update not differentiated — flax BatchNorm behaves the same).

    ``ysums=(sum [C,1], sumsq [C,1])`` f32 per-CHANNEL reductions of y,
    e.g. from ops/pallas_conv_t.py::conv3x3_t_stats — skips this
    function's own stats pass. Their cotangents are zero by the same
    contract as mu/var: the train-mode backward here already routes the
    statistics' dependence on y through dy."""
    out, mu, var, _ = _forward(y, gamma, beta, co, blk, eps, interpret,
                               ysums)
    return out, mu, var


def _forward(y, gamma, beta, co, blk, eps, interpret, ysums=None):
    n, h, c, w = y.shape
    assert c == blk * blk * co, (c, blk, co)
    if ysums is None:
        mu, var = _stats_t(y, co)
    else:
        s_co = ysums[0][:, 0].astype(jnp.float32).reshape(-1, co).sum(0)
        ss_co = ysums[1][:, 0].astype(jnp.float32).reshape(-1, co).sum(0)
        count = y.size // co
        mu = s_co / count
        var = jnp.maximum(0.0, ss_co / count - jnp.square(mu))
    inv = jax.lax.rsqrt(var + eps)
    a_co = inv * gamma.astype(jnp.float32)
    a_col = _col_expand(a_co, blk * blk)
    b_col = _col_expand(beta.astype(jnp.float32) - mu * a_co, blk * blk)
    sel_t = jnp.asarray(selection_matrix(blk, co).T, jnp.float32)
    hb = _grid_rows(h, w, c)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, co=co, blk=blk),
        out_shape=jax.ShapeDtypeStruct((n, h, sel_t.shape[0], w), y.dtype),
        grid=(n, h // hb),
        in_specs=[
            pl.BlockSpec((1, hb, c, w), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((c, 1), lambda i, j: (0, 0)),
            pl.BlockSpec((c, 1), lambda i, j: (0, 0)),
            pl.BlockSpec(sel_t.shape, lambda i, j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hb, sel_t.shape[0], w),
                               lambda i, j: (i, j, 0, 0)),
        interpret=default_interpret(interpret),
    )(y, a_col, b_col, sel_t)
    return out, mu, var, (a_col, b_col, inv)


def _vjp_fwd(y, gamma, beta, co, blk, eps, interpret, ysums=None):
    out, mu, var, (a_col, b_col, inv) = _forward(
        y, gamma, beta, co, blk, eps, interpret, ysums
    )
    return (out, mu, var), (y, gamma, mu, inv, a_col, b_col, ysums)


def bwd_reduce(y, g, co, blk, a_col, b_col, mu, inv, interpret):
    """The backward's FIRST pass — per-channel s1 = Σdz and
    s2 = Σ dz·t_hat over the whole batch — exposed as a function so the
    conv1+tail fused backward (ops/pallas_conv1_tail_t.py) can run the
    identical reduction before its own fused apply+wgrad pass.
    Returns (s1_co [co], s2_co [co], mu_col, inv_col, sel)."""
    from jax.experimental.pallas import tpu as pltpu

    n, h, c, w = y.shape
    hb = _grid_rows(h, w, c)
    interp = default_interpret(interpret)
    sel = jnp.asarray(selection_matrix(blk, co), jnp.float32)
    mu_col = _col_expand(mu, blk * blk)
    inv_col = _col_expand(inv, blk * blk)

    def vec():
        return pl.BlockSpec((c, 1), lambda i, j: (0, 0))

    s1, s2 = pl.pallas_call(
        functools.partial(_bwd_reduce_kernel, co=co, blk=blk),
        out_shape=(jax.ShapeDtypeStruct((c, 1), jnp.float32),
                   jax.ShapeDtypeStruct((c, 1), jnp.float32)),
        grid=(n, h // hb),
        in_specs=[
            pl.BlockSpec((1, hb, c, w), lambda i, j: (i, j, 0, 0)),
            vec(), vec(),
            pl.BlockSpec((1, hb, sel.shape[1], w),
                         lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec(sel.shape, lambda i, j: (0, 0)),
            vec(), vec(),
        ],
        out_specs=(pl.BlockSpec((c, 1), lambda i, j: (0, 0)),
                   pl.BlockSpec((c, 1), lambda i, j: (0, 0))),
        scratch_shapes=[
            pltpu.VMEM((c, 1), jnp.float32),
            pltpu.VMEM((c, 1), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(pltpu)(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interp,
    )(y, a_col, b_col, g, sel, mu_col, inv_col)
    groups = blk * blk
    s1_co = jnp.sum(s1[:, 0].reshape(groups, co), axis=0)
    s2_co = jnp.sum(s2[:, 0].reshape(groups, co), axis=0)
    return s1_co, s2_co, mu_col, inv_col, sel


def bwd_scales(s1_co, s2_co, gamma, inv, groups: int, m_count: int):
    """The BN backward's per-channel normalization columns — the
    gamma·inv gain and the two centering terms dy = gi·(dz − c1 −
    t̂·c2) needs. Shared with the fused conv1/tail backward
    (ops/pallas_conv1_tail_t.py): its equality contract is that this
    math is THE SAME function, not a copy that can drift."""
    gi_col = _col_expand(gamma.astype(jnp.float32) * inv, groups)
    c1_col = _col_expand(s1_co / m_count, groups)
    c2_col = _col_expand(s2_co / m_count, groups)
    return gi_col, c1_col, c2_col


def _vjp_bwd(co, blk, eps, interpret, res, cts):
    g = cts[0]  # stats cotangents (cts[1:]) ignored — see docstring
    y, gamma, mu, inv, a_col, b_col, ysums = res
    n, h, c, w = y.shape
    hb = _grid_rows(h, w, c)
    interp = default_interpret(interpret)

    s1_co, s2_co, mu_col, inv_col, sel = bwd_reduce(
        y, g, co, blk, a_col, b_col, mu, inv, interpret)
    groups = blk * blk
    m_count = n * h * w * groups
    gi_col, c1_col, c2_col = bwd_scales(s1_co, s2_co, gamma, inv,
                                        groups, m_count)

    def vec():
        return pl.BlockSpec((c, 1), lambda i, j: (0, 0))

    dy = pl.pallas_call(
        functools.partial(_bwd_apply_kernel, co=co, blk=blk),
        out_shape=jax.ShapeDtypeStruct(y.shape, y.dtype),
        grid=(n, h // hb),
        in_specs=[
            pl.BlockSpec((1, hb, c, w), lambda i, j: (i, j, 0, 0)),
            vec(), vec(),
            pl.BlockSpec((1, hb, sel.shape[1], w),
                         lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec(sel.shape, lambda i, j: (0, 0)),
            vec(), vec(), vec(), vec(), vec(),
        ],
        out_specs=pl.BlockSpec((1, hb, c, w), lambda i, j: (i, j, 0, 0)),
        interpret=interp,
    )(y, a_col, b_col, g, sel, mu_col, inv_col, gi_col, c1_col, c2_col)
    dsums = jax.tree.map(jnp.zeros_like, ysums)  # see docstring; None -> None
    return dy, s2_co.astype(gamma.dtype), s1_co.astype(gamma.dtype), dsums


fused_bn_relu_pool_t.defvjp(_vjp_fwd, _vjp_bwd)
