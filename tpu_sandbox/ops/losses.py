"""Loss ops.

Parity: ``nn.CrossEntropyLoss()`` (reference mnist_onegpu.py:48,
mnist_distributed.py:64) — softmax cross-entropy with integer labels,
mean-reduced over the batch.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax

#: engage the fused Pallas CE kernel only at LM-scale vocabularies: below
#: this the [N, C] materialization XLA produces is small and the kernel's
#: 128-lane padding would dominate (the ConvNet's C=10 pads 12.8x)
_FUSED_CE_MIN_CLASSES = 4096


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean softmax cross-entropy; logits [N, C] fp32, labels [N] int.

    At LM-scale class counts (C >= 4096) on a compiled-kernel backend
    this runs the fused Pallas kernel (ops/pallas_ce.py): one VMEM pass
    for max/logsumexp/label-gather, no [N, C] log-softmax
    materialization in HBM — the r04 LM-step HLO charged ~32 ms/step at
    b16/s2048/v32768 to exactly that materialization (convert + reduce
    over a 4.3 GB f32 buffer). Off-TPU (CPU tests) and at small C the
    plain optax path runs — same math, pinned against each other by
    bench --metric pallas and tests/test_pallas_ce-style checks."""
    if logits.ndim == 2 and logits.shape[-1] >= _FUSED_CE_MIN_CLASSES:
        from tpu_sandbox.ops.pallas_common import default_interpret

        if not default_interpret(None):
            from tpu_sandbox.ops.pallas_ce import pallas_cross_entropy

            return pallas_cross_entropy(logits, labels)
    # plain path: explicit f32 (exact no-op for fp32_logits models; for
    # compute-dtype logits it restores the identical pre-r04 math)
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels).mean()
