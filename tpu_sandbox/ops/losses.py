"""Loss ops.

Parity: ``nn.CrossEntropyLoss()`` (reference mnist_onegpu.py:48,
mnist_distributed.py:64) — softmax cross-entropy with integer labels,
mean-reduced over the batch.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean softmax cross-entropy; logits [N, C] fp32, labels [N] int."""
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
