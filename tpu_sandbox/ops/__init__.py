from tpu_sandbox.ops.losses import cross_entropy_loss  # noqa: F401
from tpu_sandbox.ops.attention import causal_attention  # noqa: F401


def __getattr__(name):
    # Pallas kernels import jax.experimental.pallas; keep that lazy so the
    # base package stays importable on minimal installs.
    if name in ("pallas_cross_entropy",):
        from tpu_sandbox.ops.pallas_ce import pallas_cross_entropy
        return pallas_cross_entropy
    if name in ("flash_attention", "flash_attention_fn"):
        from tpu_sandbox.ops import pallas_attention
        return getattr(pallas_attention, name)
    raise AttributeError(name)
