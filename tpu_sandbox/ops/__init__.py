from tpu_sandbox.ops.losses import cross_entropy_loss  # noqa: F401
