"""Pallas fc head for the TRANSPOSED plan: the dgrad relayout killer.

The s2dt step's fc is ``einsum('nhcw,hcwk->nk')`` over the ~18M-feature
map (reference mnist_onegpu.py:27-30's LazyLinear at 3000^2). Forward
and weight-grad are fine as XLA dots — they are bandwidth-bound and run
near their byte counts. The INPUT-grad is not: XLA computes
``einsum('nk,hcwk->nhcw')`` with an output layout that puts N off-minor
({3,0,2,1}) and then pays a whole-activation relayout copy to feed the
bn2 backward kernel, which (like every Pallas call) requires the
standard {3,2,1,0} layout — at bs=16 that is fusion.8 + copy.92 in the
AOT dump, ~11 ms of the 59 ms non-kernel residue and ~1.6 GB of HBM
traffic (measured/hlo_cycles_s2dt_b16_r04.json; VERDICT r04 next-3).
A 2D reformulation does not help: the padded TPU tiling of
[N,750,32,750] has pad gaps at W=750->768, so any [N, 18M] view is
itself a relayout.

This kernel computes dy directly in the native layout:
``dy[n,h,c,w] = sum_k g[n,k] * wT[k,h,c,w]`` with K=10 scalars per
output element — a scalar-FMA accumulation on the VPU (the MXU wants
K>=128; at K=10 it would run ~8% occupied). Per grid block it streams
wT [K, bh, C, W] and writes dy [N, bh, C, W] — ~1.2 GB/step total
traffic, no relayout, output already in the layout bn2's backward
wants. g rides SMEM (it is [N, 10] scalars).

The wrapper ``fc_t`` is a custom_vjp over (y, kernel2d, bias) with the
f32 [H*C*W, K] kernel PARAMETER as the primal (not its bf16 4D view).
The weight-grad is the same contraction the autodiff path ran, with f32
accumulation; it is NOT bit-identical to the kill-switch einsum path —
autodiff routes the k4 cotangent through a bf16 rounding at the astype
boundary that this formulation skips, so the Pallas-path wgrad carries
full f32 mantissas (strictly less rounding). Equality is pinned to
tolerance, not bits, in tests/test_pallas_fc_t.py. Used by models/convnet_s2d_t.py::_DenseT (kill switch:
TPU_SANDBOX_NO_PALLAS_FC=1, read at trace time like the other levers).
"""

from __future__ import annotations

import functools

import jax

from tpu_sandbox.utils.compat import tpu_compiler_params
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_sandbox.ops.pallas_common import default_interpret

_VMEM_LIMIT = 100_000_000


def _pick_block_h(h: int, c: int, w: int, n: int, k: int) -> int:
    """Rows per grid block: wT block (k) + dy block (n), bf16,
    double-buffered."""
    per_bh = w * c * (n + k) * 2 * 2
    cap = max(1, int(40_000_000 // max(per_bh, 1)))
    for bh in (15, 10, 6, 5, 3, 2, 1):
        if bh <= cap and h % bh == 0:
            return bh
    return 1


def _dgrad_kernel(g_ref, wt_ref, dy_ref, *, n_batch: int, k_cls: int):
    for n in range(n_batch):
        acc = g_ref[n, 0] * wt_ref[0].astype(jnp.float32)
        for k in range(1, k_cls):
            acc = acc + g_ref[n, k] * wt_ref[k].astype(jnp.float32)
        dy_ref[n] = acc.astype(dy_ref.dtype)


def fc_dgrad_t(g, wt, out_dtype, interpret=None):
    """g [N, K] f32, wT [K, H, C, W] (kernel rows k-major) ->
    dy [N, H, C, W] in ``out_dtype``, f32 accumulation."""
    n, k = g.shape
    kk, h, c, w = wt.shape
    assert kk == k, (kk, k)
    bh = _pick_block_h(h, c, w, n, k)
    nblk = h // bh
    return pl.pallas_call(
        functools.partial(_dgrad_kernel, n_batch=n, k_cls=k),
        out_shape=jax.ShapeDtypeStruct((n, h, c, w), out_dtype),
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((k, bh, c, w), lambda i: (0, i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((n, bh, c, w), lambda i: (0, i, 0, 0)),
        compiler_params=tpu_compiler_params(pltpu)(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=_VMEM_LIMIT,
        ),
        interpret=default_interpret(interpret),
    )(g, wt)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fc_t(y, kernel2d, bias, dtype, interpret=None):
    """The transposed plan's fc: y [N, H, C, W], kernel2d [H*C*W, K] f32
    (canonical (h, c, w) row order — models/convnet.py), bias [K] f32 ->
    logits [N, K] in ``dtype``. The weight is staged ONCE per step in
    the K-MAJOR form wT [K, H, C, W] (kernel2d's own physical layout is
    already k-major — {0,1} in the AOT dump — so .T is a bitcast and
    this is one convert): forward contracts against it, the input-grad
    kernel reads it as-is (saved as a residual: one weight-sized bf16
    buffer held through the backward, vs re-deriving it from the f32
    param at 1.1 GB of traffic), and the weight-grad is emitted k-major
    too, so its flatten back to the canonical 2D rows is a
    transpose-BITCAST instead of the {2,3,1,0}->k-major relayout copy
    XLA's hcwk-minor einsum paid (copy_bitcast_fusion, ~4.6 ms est /
    1.4 GB in measured/hlo_cycles). Wgrad numerics vs the kill-switch
    einsum path: same contraction, tolerance-equal, not bit-equal (see
    module docstring)."""
    return _fc_fwd_core(y, kernel2d, bias, dtype)[0]


def _fc_fwd_core(y, kernel2d, bias, dtype):
    n, h, c, w = y.shape
    k = kernel2d.shape[-1]
    wt = kernel2d.T.reshape(k, h, c, w).astype(dtype)
    out = jnp.einsum("nhcw,khcw->nk", y, wt)
    return out + bias.astype(dtype), wt


def _fc_vjp_fwd(y, kernel2d, bias, dtype, interpret):
    out, wt = _fc_fwd_core(y, kernel2d, bias, dtype)
    return out, (y, wt)


def _fc_vjp_bwd(dtype, interpret, res, g):
    y, wt = res
    k = wt.shape[0]
    gf = g.astype(jnp.float32)
    dy = fc_dgrad_t(gf, wt, y.dtype, interpret)
    # weight-grad k-major, then a transpose-bitcast to the canonical
    # [H*C*W, K] rows (physically identical buffers — the param's {0,1}
    # layout IS k-major)
    dkt = jnp.einsum("nhcw,nk->khcw", y, gf,
                     preferred_element_type=jnp.float32)
    dkernel = dkt.reshape(k, -1).T.astype(jnp.float32)
    db = gf.sum(0).astype(jnp.float32)
    return dy, dkernel, db


fc_t.defvjp(_fc_vjp_fwd, _fc_vjp_bwd)
