"""Sparse-tap 5x5 s2d conv for conv1 in TRANSPOSED layout — the round-4
attack on the s2d FLOP inflation (VERDICT r03 next-5).

conv1's scattered 3x3 form (ops/pallas_conv_t.py over
models/convnet_s2d.py::scatter_kernel) executes a [256, 9C=144] x
[144, W] row matmul whose weight is only 25/144 dense: the original
5x5 kernel has 25 taps per (output position, channel) but the block-conv
scatter pads them into 144 K-slots, and the MXU then runs
ceil(256/128) * ceil(144/128) = 4 tile-passes per row.

This kernel contracts the 25 real taps against a UNION tap tile indexed
by (m', j) = (full-res row offset in -2..5, full-res col offset in
-2..5) relative to the output block (m' = a'+ty-2 with a' in 0..3,
ty in 0..4 spans exactly -2..5): T[(m', j), w4] = image[4*h4+m',
4*w4+j]. Every output channel (a', b', co) needs the 25 entries
(m' = a'+ty-2, j = b'+tx-2), all inside the 64 tile rows, so one
[256, 64] x [64, W] matmul computes the whole row:
ceil(256/128) * ceil(64/128) = **2 tile-passes — half the MXU work** —
and K=64 is an exact sublane tile (zero K padding). The tile build is
24 contiguous sublane slices (vs 9 full-block concats). The weight is
built at trace time by scattering the canonical k5 [5, 5, 1, 16] into
[256, 64] (39% dense; MXU cost is shape-, not density-, driven, so
K=64 <= 128 is the whole win).

Executed flops drop from 2*B*H*W*(256*144) to 2*B*H*W*(256*64) per call
(2.25x); MXU passes halve. conv2 is left on the 3x3 kernel: its scatter
is 25/36 = 69% dense (real 16-channel input), so the same trick buys
under 1.3x there.

Interface: conv1_s2d_t(x [N,H4,16,W4], k5 [5,5,1,F1], bias [F1]) ->
y [N, H4, 16*F1, W4]; custom VJP (dx is never needed — conv1's input is
the image — and is returned as zeros for jax to DCE; wgrad accumulates
dW [256, 64] in one fused pass and gathers it back to dk5). A *_stats
variant fuses the BN sum/sumsq like conv3x3_t_stats.

Reference being accelerated: the first 5x5 conv of
/root/reference/mnist_onegpu.py:14-18.
"""

from __future__ import annotations

import functools

import jax

from tpu_sandbox.utils.compat import tpu_compiler_params
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_sandbox.ops.pallas_common import default_interpret
from tpu_sandbox.ops.pallas_conv_t import (
    _VMEM_LIMIT,
    _halo_specs,
    _row_getter,
    _shift_lanes,
)

R = 4          # s2d factor (conv1's)
K5 = 5         # original kernel size
NT = 8 * 8     # union tap tile rows: (m', j) in (-2..5)^2


@functools.lru_cache(maxsize=2)
def _scatter_indices(f1: int):
    """Static index arrays mapping k5[ty, tx, 0, co] into W1[c_out, t]:
    c_out = (a'*4 + b')*f1 + co, t = (a'+ty)*8 + (b'+tx)."""
    a, b, ty, tx, co = np.meshgrid(
        np.arange(R), np.arange(R), np.arange(K5), np.arange(K5),
        np.arange(f1), indexing="ij",
    )
    rows = (a * R + b) * f1 + co
    cols = (a + ty) * 8 + (b + tx)
    return (rows.reshape(-1), cols.reshape(-1),
            ty.reshape(-1), tx.reshape(-1), co.reshape(-1))


def scatter_k5(k5: jnp.ndarray) -> jnp.ndarray:
    """k5 [5,5,1,f1] -> W1 [16*f1, 64] (the union-tile weight)."""
    f1 = k5.shape[-1]
    rows, cols, ty, tx, co = _scatter_indices(f1)
    w1 = jnp.zeros((R * R * f1, NT), k5.dtype)
    return w1.at[rows, cols].set(k5[ty, tx, 0, co])


def gather_dk5(dw1: jnp.ndarray, f1: int) -> jnp.ndarray:
    """Transpose of scatter_k5: dW1 [16*f1, 64] -> dk5 [5,5,1,f1]
    (each k5 tap accumulates its 16 (a', b') occurrences)."""
    rows, cols, ty, tx, co = _scatter_indices(f1)
    dk5 = jnp.zeros((K5, K5, 1, f1), dw1.dtype)
    return dk5.at[ty, tx, 0, co].add(dw1[rows, cols])


def _tap_tile_u(get, r: int):
    """The union tap tile [64, W]: rows (m', j) for m', j in -2..5,
    j-major within m'. Row (m', j) = sublane p*4+q of block row
    r + floor(m'/4) (p = m' mod 4), lane-shifted by floor(j/4). Per m'
    that is three contiguous sublane slices: q=2,3 shifted right (j=-2,
    -1), q=0..3 unshifted (j=0..3), q=0..1 shifted left (j=4..5)."""
    pieces = []
    for mp in range(-2, 6):
        blk = get(r + mp // R)   # {-2,-1}->r-1, {0..3}->r, {4,5}->r+1
        p = mp % R
        s = p * R
        pieces += [
            _shift_lanes(blk[s + 2:s + 4], 0),   # j = -2, -1 (right)
            blk[s:s + 4],                        # j = 0..3
            _shift_lanes(blk[s:s + 2], 2),       # j = 4, 5 (left)
        ]
    return jnp.concatenate(pieces, axis=0)


def _conv_row_u(get, w1_ref, b_ref, r: int):
    acc = jax.lax.dot_general(
        w1_ref[...], _tap_tile_u(get, r),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                            # [256, W]
    return acc + b_ref[...].astype(jnp.float32)


def _fwd_kernel(x_ref, up_ref, dn_ref, w1_ref, b_ref, y_ref,
                *, bh: int, nblk: int):
    get = _row_getter(x_ref, up_ref, dn_ref, bh, nblk)
    for r in range(bh):
        y_ref[0, r] = _conv_row_u(get, w1_ref, b_ref, r).astype(y_ref.dtype)


def _fwd_stats_kernel(x_ref, up_ref, dn_ref, w1_ref, b_ref,
                      y_ref, s_ref, ss_ref, s_scr, ss_scr,
                      *, bh: int, nblk: int):
    n, i = pl.program_id(0), pl.program_id(1)

    @pl.when(jnp.logical_and(n == 0, i == 0))
    def _init():
        s_scr[:] = jnp.zeros_like(s_scr)
        ss_scr[:] = jnp.zeros_like(ss_scr)

    get = _row_getter(x_ref, up_ref, dn_ref, bh, nblk)
    for r in range(bh):
        y_row = _conv_row_u(get, w1_ref, b_ref, r).astype(y_ref.dtype)
        y_ref[0, r] = y_row
        yf = y_row.astype(jnp.float32)
        s_scr[:] = s_scr[:] + jnp.sum(yf, axis=1, keepdims=True)
        ss_scr[:] = ss_scr[:] + jnp.sum(yf * yf, axis=1, keepdims=True)

    @pl.when(jnp.logical_and(n == pl.num_programs(0) - 1, i == nblk - 1))
    def _emit():
        s_ref[...] = s_scr[:]
        ss_ref[...] = ss_scr[:]


def _wgrad_kernel(x_ref, up_ref, dn_ref, g_ref, dw_ref, db_ref,
                  dw_scr, db_scr, *, bh: int, nblk: int, gt: bool):
    """Weight-gradient + db accumulated across the grid; the union tile
    is rebuilt per row (same build as forward). Same two restage
    variants as pallas_conv_t._wgrad_kernel: ``gt=True`` transposes
    g_row ([CO=256, W] — 128-aligned) and runs the native
    tile [64, W] x gT [W, 256] -> dW [64, 256]; ``gt=False`` leaves the
    lane-lane contraction to Mosaic (which restages the ragged [64, W]
    tile instead)."""
    n, i = pl.program_id(0), pl.program_id(1)

    @pl.when(jnp.logical_and(n == 0, i == 0))
    def _init():
        dw_scr[:] = jnp.zeros_like(dw_scr)
        db_scr[:] = jnp.zeros_like(db_scr)

    get = _row_getter(x_ref, up_ref, dn_ref, bh, nblk)
    for r in range(bh):
        g_row = g_ref[0, r]                      # [CO, W]
        db_scr[:] = db_scr[:] + jnp.sum(
            g_row.astype(jnp.float32), axis=1, keepdims=True)
        if gt:
            acc = jax.lax.dot_general(           # [64, CO], native form
                _tap_tile_u(get, r), g_row.T,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        else:
            acc = jax.lax.dot_general(           # [CO, 64]
                g_row, _tap_tile_u(get, r),
                (((1,), (1,)), ((), ())),        # contract W on both
                preferred_element_type=jnp.float32,
            )
        dw_scr[:] = dw_scr[:] + acc

    @pl.when(jnp.logical_and(n == pl.num_programs(0) - 1, i == nblk - 1))
    def _emit():
        dw_ref[...] = dw_scr[:]
        db_ref[...] = db_scr[:]


def _pick_block_h(h: int, w: int, co: int) -> int:
    """Rows per grid block (cf. pallas_conv_t._pick_block_h): the fixed
    per-row cost is the [64, W] tile + [CO, W] f32 row accumulator."""
    per_bh = w * (16 + co) * 2 * 2
    per_row = w * (NT + co) * 4
    cap = max(1, int((28_000_000 - per_row) // max(per_bh, 1)))
    for bh in (30, 25, 20, 15, 12, 10, 8, 6, 5, 4, 3, 2, 1):
        if bh <= cap and h % bh == 0:
            return bh
    return 1


def _conv_call(x, w1, bias_g, out_dtype, interpret, stats=False):
    n, h, c, wd = x.shape
    assert c == R * R, (c, "conv1_s2d_t is the r=4, 1-channel-input conv")
    co = w1.shape[0]
    bh = _pick_block_h(h, wd, co)
    nblk = h // bh
    if stats:
        kernel = functools.partial(_fwd_stats_kernel, bh=bh, nblk=nblk)
        out_shape = (jax.ShapeDtypeStruct((n, h, co, wd), out_dtype),
                     jax.ShapeDtypeStruct((co, 1), jnp.float32),
                     jax.ShapeDtypeStruct((co, 1), jnp.float32))
        out_specs = (
            pl.BlockSpec((1, bh, co, wd), lambda n, i: (n, i, 0, 0)),
            pl.BlockSpec((co, 1), lambda n, i: (0, 0)),
            pl.BlockSpec((co, 1), lambda n, i: (0, 0)),
        )
        scratch = [pltpu.VMEM((co, 1), jnp.float32),
                   pltpu.VMEM((co, 1), jnp.float32)]
    else:
        kernel = functools.partial(_fwd_kernel, bh=bh, nblk=nblk)
        out_shape = jax.ShapeDtypeStruct((n, h, co, wd), out_dtype)
        out_specs = pl.BlockSpec((1, bh, co, wd), lambda n, i: (n, i, 0, 0))
        scratch = []
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(n, nblk),
        in_specs=_halo_specs(bh, nblk, c, wd) + [
            pl.BlockSpec((co, NT), lambda n, i: (0, 0)),
            pl.BlockSpec((co, 1), lambda n, i: (0, 0)),
        ],
        out_specs=out_specs,
        scratch_shapes=scratch,
        compiler_params=tpu_compiler_params(pltpu)(
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=_VMEM_LIMIT,
        ),
        interpret=default_interpret(interpret),
    )(x, x, x, w1, bias_g)


def _prep(k5, bias, dtype):
    f1 = k5.shape[-1]
    w1 = scatter_k5(k5.astype(dtype))
    bias_g = jnp.tile(bias.astype(dtype), R * R).reshape(-1, 1)
    return w1, bias_g, f1


@jax.custom_jvp
def _data_only(x):
    """Identity that REFUSES differentiation through its argument.

    conv1_s2d_t returns a ZERO input cotangent by contract — correct for
    the production model, whose conv1 input is the image through the
    fixed linear fused input stage (models/convnet_s2d_t.py), and the
    zeros let jax DCE the dead dx. But composed after any TRAINABLE
    preprocessing that contract would silently zero real gradients
    (VERDICT r04 weak-5). The misuse check must live at the AD-RULE
    level: a wrapper inspecting tracer types is blind across trace
    boundaries (under grad-of-jit / remat / scan the forward runs with
    plain jaxpr tracers and AD happens on the jaxpr afterwards). This
    shim's JVP rule runs wherever AD actually happens, with
    symbolic_zeros=True so a data input presents as SymbolicZero and a
    differentiated input presents as a real tangent — which raises.
    Under jit with no AD the rule never runs and the identity compiles
    away."""
    return x


@functools.partial(_data_only.defjvp, symbolic_zeros=True)
def _data_only_jvp(primals, tangents):
    (x,), (x_dot,) = primals, tangents
    if not isinstance(x_dot, jax.custom_derivatives.SymbolicZero):
        raise ValueError(
            "conv1_s2d_t's input is being differentiated. This kernel "
            "returns a ZERO input cotangent by contract — its input "
            "must be data (the fused input stage output), never a "
            "function of trainable parameters; composing it after "
            "trainable preprocessing would produce silently wrong "
            "gradients. Use the scattered-3x3 conv1 instead "
            "(ConvNetS2DT(sparse_conv1=False) or "
            "ops.pallas_conv_t.conv3x3_t, which propagates a real "
            "input cotangent)."
        )
    return _data_only(x), x_dot


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _conv1_s2d_t_prim(x, k5, bias, interpret=None):
    w1, bias_g, _ = _prep(k5, bias, x.dtype)
    return _conv_call(x, w1, bias_g, x.dtype, interpret)


def conv1_s2d_t(x, k5, bias, interpret=None):
    """Sparse-tap conv1: x [N,H4,16,W4] (s2d-transposed image),
    k5 [5,5,1,f1] CANONICAL 5x5 weights, bias [f1] ->
    y [N,H4,16*f1,W4] in x.dtype, f32 accumulation. The x cotangent is
    zeros (the image is data; jax DCEs it) — a differentiated x is
    rejected by the AD rule itself, see _data_only."""
    return _conv1_s2d_t_prim(_data_only(x), k5, bias, interpret)


def conv1_s2d_t_wgrad(x, g, interpret=None, restage=None):
    """Fused wgrad+dbias: x [N,H4,16,W4], g [N,H4,CO,W4] ->
    (dW1 [CO, 64] f32, db [CO, 1] f32). ``restage`` as in
    conv3x3_t_wgrad ('gt' native-dot variant is the r05 default)."""
    from tpu_sandbox.ops.pallas_conv_t import wgrad_restage

    gt = wgrad_restage(restage) == "gt"
    n, h, c, wd = x.shape
    co = g.shape[2]
    bh = _pick_block_h(h, wd, co)
    nblk = h // bh
    dw_shape = (NT, co) if gt else (co, NT)
    dw, db = pl.pallas_call(
        functools.partial(_wgrad_kernel, bh=bh, nblk=nblk, gt=gt),
        out_shape=(jax.ShapeDtypeStruct(dw_shape, jnp.float32),
                   jax.ShapeDtypeStruct((co, 1), jnp.float32)),
        grid=(n, nblk),
        in_specs=_halo_specs(bh, nblk, c, wd) + [
            pl.BlockSpec((1, bh, co, wd), lambda n, i: (n, i, 0, 0)),
        ],
        out_specs=(pl.BlockSpec(dw_shape, lambda n, i: (0, 0)),
                   pl.BlockSpec((co, 1), lambda n, i: (0, 0))),
        scratch_shapes=[
            pltpu.VMEM(dw_shape, jnp.float32),
            pltpu.VMEM((co, 1), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(pltpu)(
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=_VMEM_LIMIT,
        ),
        interpret=default_interpret(interpret),
    )(x, x, x, g)
    return (dw.T if gt else dw), db


def _vjp_fwd(x, k5, bias, interpret):
    return _conv1_s2d_t_prim(x, k5, bias, interpret), (x, k5, bias)


def _vjp_bwd(interpret, res, g):
    x, k5, bias = res
    f1 = k5.shape[-1]
    dw1, db = conv1_s2d_t_wgrad(x, g, interpret)
    dk5 = gather_dk5(dw1, f1).astype(k5.dtype)
    db_f1 = db[:, 0].reshape(R * R, f1).sum(0).astype(bias.dtype)
    return jnp.zeros_like(x), dk5, db_f1


_conv1_s2d_t_prim.defvjp(_vjp_fwd, _vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _conv1_s2d_t_stats_prim(x, k5, bias, interpret=None):
    w1, bias_g, _ = _prep(k5, bias, x.dtype)
    return _conv_call(x, w1, bias_g, x.dtype, interpret, stats=True)


def conv1_s2d_t_stats(x, k5, bias, interpret=None):
    """conv1_s2d_t that also returns (sum [CO,1], sumsq [CO,1]) of the
    rounded output — same contract as conv3x3_t_stats (stats cotangents
    ignored; the fused tail's backward accounts for them). Same
    differentiated-input rejection as conv1_s2d_t."""
    return _conv1_s2d_t_stats_prim(_data_only(x), k5, bias, interpret)


def _stats_vjp_fwd(x, k5, bias, interpret):
    out = _conv1_s2d_t_stats_prim(x, k5, bias, interpret)
    return out, (x, k5, bias)


def _stats_vjp_bwd(interpret, res, cts):
    return _vjp_bwd(interpret, res, cts[0])


_conv1_s2d_t_stats_prim.defvjp(_stats_vjp_fwd, _stats_vjp_bwd)


def conv1_s2d_t_reference(x, k5, bias):
    """Equality contract: the existing scattered-3x3 path
    (scatter_kernel + conv3x3_t_reference) on the same operands."""
    from tpu_sandbox.models.convnet_s2d import scatter_kernel
    from tpu_sandbox.ops.pallas_conv_t import conv3x3_t_reference

    wg = scatter_kernel(k5, R)
    reps = wg.shape[-1] // k5.shape[-1]
    return conv3x3_t_reference(x, wg, jnp.tile(bias, reps))
