"""Pallas TPU kernels: fused BatchNorm-apply + ReLU + in-lane 2x2 max-pool.

The space-to-depth ConvNet (models/convnet_s2d.py) keeps channels on the
lane dim, so after each conv the whole BN/ReLU/pool tail is elementwise-
and-lane-local — yet XLA executes it as several HBM passes over the
~1.4 GB conv output (apply, pool, layout copies). These kernels do the
tail in ONE read of the conv output per direction.

Forward: z = relu(y*a + b) with a = gamma*rsqrt(var+eps) and
b = beta - mu*a as per-lane vectors. The 2x2 pool happens inside the lane
dim: the pool partners of lane c = (a*blk+b)*co + k sit at lane offsets
co (b's low bit) and blk*co (a's low bit), so two roll-and-max steps put
every 4-way max at its representative lane (a,b both even), and a
constant 0/1 selection matrix compacts representatives to the
(blk//2)^2*co output lanes with one MXU dot per row — exact, because each
output column selects a single lane.

Backward: train-mode BN backward (gradients flow through the batch
statistics) needs per-channel reductions, so it is two kernels:
``_bwd_reduce_kernel`` recomputes z from y (cheap VPU work — no big
residual is saved), routes the pooled cotangent back through the pool
with jnp.maximum's exact VJP semantics (winner takes it; exact ties split
0.5/0.5 — common in bf16, where comparisons happen on values rounded to
the activation dtype just like the unfused chain) and the ReLU mask, and
accumulates s1 = sum(dz) and s2 = sum(dz * t_hat) per lane across the
grid; ``_bwd_apply_kernel`` recomputes the same routing and
emits dy = gamma*inv*(dz - s1/M - t_hat*s2/M). dgamma = s2 (folded per
co), dbeta = s1.

Traffic per layer: fwd reads y once and writes the 4x-smaller pooled
output; bwd reads y twice, the pooled cotangent twice, and writes dy once
— vs the unfused path's additional full-tensor passes. Exactness vs the
unfused chain is pinned by tests/test_pallas_bn_tail.py; Mosaic lowering
by tests/test_mosaic_lowering.py. Used by ConvNetS2D(fused_tail=True) in
train mode (eval keeps the plain path: running stats are constants there,
which is a different backward).
"""

from __future__ import annotations

import functools

import jax

from tpu_sandbox.utils.compat import tpu_compiler_params
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from tpu_sandbox.ops.pallas_common import default_interpret


def selection_matrix(blk: int, co: int) -> np.ndarray:
    """[C, C/4] 0/1 matrix: column o=(a1*(blk//2)+b1)*co+k selects the
    representative lane c=(2*a1*blk + 2*b1)*co + k (a0 = b0 = 0)."""
    c_in, c_out = blk * blk * co, (blk // 2) ** 2 * co
    s = np.zeros((c_in, c_out), np.float32)
    for a1 in range(blk // 2):
        for b1 in range(blk // 2):
            for k in range(co):
                o = (a1 * (blk // 2) + b1) * co + k
                c = (2 * a1 * blk + 2 * b1) * co + k
                s[c, o] = 1.0
    return s


def _pool_fronts(z, co: int, blk: int):
    """(zb, m1, m1a): the rolled partners and pairwise maxima; m2 =
    max(m1, m1a) holds each 4-way max at its representative lane."""
    zb = jnp.roll(z, -co, axis=-1)
    m1 = jnp.maximum(z, zb)
    m1a = jnp.roll(m1, -blk * co, axis=-1)
    return zb, m1, m1a


def _route(z, g_exp, co: int, blk: int):
    """Pool VJP on one [W, C] row: cotangent g_exp lives at representative
    lanes; route it through the two pairwise maxima with jnp.maximum's
    exact VJP semantics — the winner takes the cotangent, EXACT ties split
    it 0.5/0.5 (ties are common in bf16, where the unfused chain compares
    rounded values; winner-take-all would diverge from it there). Nonzero
    values never wrap in the rolls: representatives + blk*co + co < C."""
    s, ss = co, blk * co
    zb, m1, m1a = _pool_fronts(z, co, blk)

    def weights(x, xb):
        # 1 / 0.5 / 0 for win / tie / loss, written as the mean of two
        # strict-and-weak comparisons: Mosaic cannot relayout the i1 mask
        # an `eq`-plus-select chain produces here ("Invalid relayout:
        # non-singleton logical dimension is replicated")
        return 0.5 * ((x > xb).astype(jnp.float32)
                      + (x >= xb).astype(jnp.float32))

    w2 = weights(m1, m1a)
    dm1 = g_exp * w2 + jnp.roll(g_exp * (1.0 - w2), ss, axis=-1)
    w1 = weights(z, zb)
    dz = dm1 * w1 + jnp.roll(dm1 * (1.0 - w1), s, axis=-1)
    return dz


def _rounded_relu(y_ref, a_ref, b_ref, r, dtype):
    """One row's z in the OUTPUT dtype: the unfused chain rounds the BN
    result to the activation dtype before relu/pool, so comparisons (pool
    argmax, relu mask) must happen on the rounded values to match it —
    in bf16 the rounding creates the very ties _route's 0.5-splitting
    exists for."""
    zpre = y_ref[0, r].astype(jnp.float32) * a_ref[0][None] + b_ref[0][None]
    # round to the activation dtype, then hold the values in f32: bf16 is
    # exactly embeddable, Mosaic's vector compare only supports f32, and
    # the ties live on the ROUNDED values either way
    return jnp.maximum(zpre.astype(dtype), 0).astype(jnp.float32)


def _fwd_kernel(y_ref, a_ref, b_ref, s_ref, out_ref, *, co: int, blk: int):
    hb = y_ref.shape[1]
    for r in range(hb):
        z = _rounded_relu(y_ref, a_ref, b_ref, r, out_ref.dtype)
        _, m1, m1a = _pool_fronts(z, co, blk)
        m2 = jnp.maximum(m1, m1a)
        out_ref[0, r] = jax.lax.dot_general(
            m2, s_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(out_ref.dtype)


def _row_dz(y_ref, a_ref, b_ref, g_ref, st_ref, r, co, blk, dtype):
    """Recompute one row's (rounded) z and route its pooled cotangent."""
    z = _rounded_relu(y_ref, a_ref, b_ref, r, dtype)
    g_exp = jax.lax.dot_general(  # [W, C/4] @ [C/4, C]: scatter to reps
        g_ref[0, r].astype(jnp.float32), st_ref[...],
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )
    return _route(z, g_exp, co, blk) * (z > 0)


def _bwd_reduce_kernel(y_ref, a_ref, b_ref, g_ref, st_ref, mu_ref, inv_ref,
                       s1_ref, s2_ref, s1_scr, s2_scr,
                       *, co: int, blk: int):
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(jnp.logical_and(i == 0, j == 0))
    def _init():
        s1_scr[:] = jnp.zeros_like(s1_scr)
        s2_scr[:] = jnp.zeros_like(s2_scr)

    hb = y_ref.shape[1]
    for r in range(hb):
        dz = _row_dz(y_ref, a_ref, b_ref, g_ref, st_ref, r, co, blk,
                     y_ref.dtype)
        y = y_ref[0, r].astype(jnp.float32)
        t_hat = (y - mu_ref[0][None]) * inv_ref[0][None]
        s1_scr[:] = s1_scr[:] + jnp.sum(dz, axis=0, keepdims=True)
        s2_scr[:] = s2_scr[:] + jnp.sum(dz * t_hat, axis=0, keepdims=True)

    @pl.when(jnp.logical_and(i == pl.num_programs(0) - 1,
                             j == pl.num_programs(1) - 1))
    def _emit():
        s1_ref[...] = s1_scr[:]
        s2_ref[...] = s2_scr[:]


def _bwd_apply_kernel(y_ref, a_ref, b_ref, g_ref, st_ref, mu_ref, inv_ref,
                      gi_ref, c1_ref, c2_ref, dy_ref, *, co: int, blk: int):
    hb = y_ref.shape[1]
    for r in range(hb):
        dz = _row_dz(y_ref, a_ref, b_ref, g_ref, st_ref, r, co, blk,
                     y_ref.dtype)
        y = y_ref[0, r].astype(jnp.float32)
        t_hat = (y - mu_ref[0][None]) * inv_ref[0][None]
        dy = gi_ref[0][None] * (dz - c1_ref[0][None] - t_hat * c2_ref[0][None])
        dy_ref[0, r] = dy.astype(dy_ref.dtype)


def _lane_expand(v_co, reps: int):
    """per-co vector -> lane vector [1, reps*co] (co minor, like the data)."""
    return jnp.tile(v_co.astype(jnp.float32), reps)[None]


def _grid_rows(h: int, w: int, c: int) -> int:
    """Rows per grid block, budgeted against scoped VMEM: the row loop
    keeps ~a dozen [w, c] f32 intermediates live, so rows are capped such
    that rows*w*c*14B stays under ~6 MB (at the ConvNet's 750x256 that is
    2 rows; tiny test shapes keep up to 10)."""
    cap = max(1, int(6 * 1024 * 1024 // max(w * c * 14, 1)))
    for hb in (10, 6, 5, 4, 3, 2, 1):
        if hb <= cap and h % hb == 0:
            return hb
    return 1


def unfused_reference(y, gamma, beta, co: int, blk: int, eps: float = 1e-5):
    """The unfused tail exactly as ConvNetS2D computes it in train mode:
    (pooled, mu, var). Single home for the contract the kernels are checked
    against (tests/test_pallas_bn_tail.py and bench.py --metric pallas)."""
    from tpu_sandbox.models.convnet_s2d import block_max_pool

    *lead, c = y.shape
    yf = y.astype(jnp.float32).reshape(*lead, c // co, co)
    red = tuple(range(yf.ndim - 1))
    mu = jnp.mean(yf, axis=red)
    var = jnp.maximum(0.0, jnp.mean(jnp.square(yf), axis=red)
                      - jnp.square(mu))
    z = (yf - mu) * (jax.lax.rsqrt(var + eps) * gamma) + beta
    z = jax.nn.relu(z.reshape(*lead, c).astype(y.dtype))
    return block_max_pool(z, blk, co), mu, var


def _stats(y, co):
    yf = y.astype(jnp.float32).reshape(-1, y.shape[-1] // co, co)
    mu = jnp.mean(yf, axis=(0, 1))
    var = jnp.maximum(
        0.0, jnp.mean(jnp.square(yf), axis=(0, 1)) - jnp.square(mu)
    )
    return mu, var


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def fused_bn_relu_pool(y, gamma, beta, co, blk, eps=1e-5, interpret=None,
                       ysums=None):
    """[N,H,W,blk*blk*co] conv output -> ([N,H,W,(blk//2)**2*co] pooled,
    mu [co], var [co]) with train-mode batch statistics.

    Numerically the _GroupedBN(train=True) + relu + block_max_pool chain of
    models/convnet_s2d.py, in one HBM pass. mu/var are returned for the
    running-stats update; their cotangents are ignored (the stats update is
    not differentiated — flax BatchNorm behaves the same).

    ``ysums=(sum, sumsq)`` — per-LANE [1, c] f32 reductions of y, e.g. from
    ops/pallas_conv.py::conv3x3_stats — skips this function's own stats
    pass (a full extra HBM read of y). Their cotangents are zero by the
    same contract as mu/var: the train-mode backward here already routes
    the statistics' dependence on y through dy."""
    out, mu, var, _ = _forward(y, gamma, beta, co, blk, eps, interpret,
                               ysums)
    return out, mu, var


def _forward(y, gamma, beta, co, blk, eps, interpret, ysums=None):
    n, h, w, c = y.shape
    assert c == blk * blk * co, (c, blk, co)
    if ysums is None:
        mu, var = _stats(y, co)
    else:
        s_co = ysums[0][0].astype(jnp.float32).reshape(-1, co).sum(0)
        ss_co = ysums[1][0].astype(jnp.float32).reshape(-1, co).sum(0)
        count = y.size // co
        mu = s_co / count
        var = jnp.maximum(0.0, ss_co / count - jnp.square(mu))
    inv = jax.lax.rsqrt(var + eps)
    a_co = inv * gamma.astype(jnp.float32)
    a_lane = _lane_expand(a_co, blk * blk)
    b_lane = _lane_expand(beta.astype(jnp.float32) - mu * a_co, blk * blk)
    sel = jnp.asarray(selection_matrix(blk, co), jnp.float32)
    hb = _grid_rows(h, w, c)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, co=co, blk=blk),
        out_shape=jax.ShapeDtypeStruct((n, h, w, sel.shape[1]), y.dtype),
        grid=(n, h // hb),
        in_specs=[
            pl.BlockSpec((1, hb, w, c), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, c), lambda i, j: (0, 0)),
            pl.BlockSpec((1, c), lambda i, j: (0, 0)),
            pl.BlockSpec(sel.shape, lambda i, j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hb, w, sel.shape[1]),
                               lambda i, j: (i, j, 0, 0)),
        interpret=default_interpret(interpret),
    )(y, a_lane, b_lane, sel)
    return out, mu, var, (a_lane, b_lane, inv)


def _vjp_fwd(y, gamma, beta, co, blk, eps, interpret, ysums=None):
    out, mu, var, (a_lane, b_lane, inv) = _forward(
        y, gamma, beta, co, blk, eps, interpret, ysums
    )
    return (out, mu, var), (y, gamma, mu, inv, a_lane, b_lane, ysums)


def _vjp_bwd(co, blk, eps, interpret, res, cts):
    from jax.experimental.pallas import tpu as pltpu

    g = cts[0]  # stats cotangents (cts[1:]) ignored — see docstring
    y, gamma, mu, inv, a_lane, b_lane, ysums = res
    n, h, w, c = y.shape
    hb = _grid_rows(h, w, c)
    interp = default_interpret(interpret)
    sel_t = jnp.asarray(selection_matrix(blk, co).T, jnp.float32)
    mu_lane = _lane_expand(mu, blk * blk)
    inv_lane = _lane_expand(inv, blk * blk)

    def vec():
        return pl.BlockSpec((1, c), lambda i, j: (0, 0))

    s1, s2 = pl.pallas_call(
        functools.partial(_bwd_reduce_kernel, co=co, blk=blk),
        out_shape=(jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)),
        grid=(n, h // hb),
        in_specs=[
            pl.BlockSpec((1, hb, w, c), lambda i, j: (i, j, 0, 0)),
            vec(), vec(),
            pl.BlockSpec((1, hb, w, sel_t.shape[0]),
                         lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec(sel_t.shape, lambda i, j: (0, 0)),
            vec(), vec(),
        ],
        out_specs=(pl.BlockSpec((1, c), lambda i, j: (0, 0)),
                   pl.BlockSpec((1, c), lambda i, j: (0, 0))),
        scratch_shapes=[
            pltpu.VMEM((1, c), jnp.float32),
            pltpu.VMEM((1, c), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(pltpu)(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interp,
    )(y, a_lane, b_lane, g, sel_t, mu_lane, inv_lane)

    groups = blk * blk
    m_count = n * h * w * groups
    s1_co = jnp.sum(s1[0].reshape(groups, co), axis=0)
    s2_co = jnp.sum(s2[0].reshape(groups, co), axis=0)
    gi_lane = _lane_expand(gamma.astype(jnp.float32) * inv, groups)
    c1_lane = _lane_expand(s1_co / m_count, groups)
    c2_lane = _lane_expand(s2_co / m_count, groups)

    dy = pl.pallas_call(
        functools.partial(_bwd_apply_kernel, co=co, blk=blk),
        out_shape=jax.ShapeDtypeStruct(y.shape, y.dtype),
        grid=(n, h // hb),
        in_specs=[
            pl.BlockSpec((1, hb, w, c), lambda i, j: (i, j, 0, 0)),
            vec(), vec(),
            pl.BlockSpec((1, hb, w, sel_t.shape[0]),
                         lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec(sel_t.shape, lambda i, j: (0, 0)),
            vec(), vec(), vec(), vec(), vec(),
        ],
        out_specs=pl.BlockSpec((1, hb, w, c), lambda i, j: (i, j, 0, 0)),
        interpret=interp,
    )(y, a_lane, b_lane, g, sel_t, mu_lane, inv_lane, gi_lane, c1_lane,
      c2_lane)
    dsums = jax.tree.map(jnp.zeros_like, ysums)  # see docstring; None -> None
    return dy, s2_co.astype(gamma.dtype), s1_co.astype(gamma.dtype), dsums


fused_bn_relu_pool.defvjp(_vjp_fwd, _vjp_bwd)
