"""conv1 + BN/ReLU/pool tail as ONE differentiable unit — the r05
backward fusion that deletes conv1's cotangent from HBM.

In the unfused step, bn1's tail backward WRITES the conv1-output
cotangent g [N, H, 256, W] (~4.7 GB bf16 at bs=16 — the single largest
tensor in the step) and conv1's wgrad immediately READS it back; no
other consumer exists because conv1's input cotangent is dead (the
input is the image through the fixed input stage). That HBM round-trip
is ~9.4 GB of the step's traffic for pure plumbing.

This composite keeps the forward exactly as before (the sparse-tap
conv1-with-stats kernel + the fused tail forward, two Pallas calls) and
fuses the BACKWARD: the tail's reduce pass runs unchanged
(ops/pallas_bn_tail_t.py::bwd_reduce — it produces the batch-wide
s1/s2 the row math needs), then ONE kernel recomputes each row's
tail-backward dy IN VMEM (identical math to _bwd_apply_kernel,
including the rounded-relu recompute, exact 0.5/0.5 pool tie splitting,
and the bf16 rounding the HBM tensor would have applied) and feeds it
straight into the sparse conv1 wgrad dot (restaged per
TPU_SANDBOX_WGRAD_RESTAGE like every other wgrad kernel: 'gt' native
form by default, 'auto' to let Mosaic restage the ragged tile).
g never exists in HBM; reads are y1 + pooled-cotangent + x instead of
g + x — the fused backward's traffic is ~12.7 GB vs ~22.1 GB unfused
across the reduce+apply+wgrad trio.

Gradient outputs: dk5 (canonical 5x5), conv bias, dgamma, dbeta; dx is
zeros by the same guarded contract as conv1_s2d_t (the composite's x
input passes through the _data_only AD guard).

Wired in by models/convnet_s2d_t.py when the sparse conv1 and fused
tail are both active; TPU_SANDBOX_NO_FUSED_CONV1_BWD=1 (trace-time, as
the other levers) or ConvNetS2DT(fused_conv1_bwd=False) falls back to
the unfused composition.

Reference chain being fused: the first conv block of
/root/reference/mnist_onegpu.py:14-18 (conv 5x5 + BN + ReLU + pool),
backward pass.
"""

from __future__ import annotations

import functools

import jax

from tpu_sandbox.utils.compat import tpu_compiler_params
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_sandbox.ops.pallas_bn_tail_t import (
    _forward as _tail_forward,
    _row_dz,
    bwd_reduce,
    bwd_scales,
)
from tpu_sandbox.ops.pallas_common import default_interpret
from tpu_sandbox.ops.pallas_conv5_t import (
    NT,
    R,
    _data_only,
    _tap_tile_u,
    conv1_s2d_t_stats,
    gather_dk5,
)
from tpu_sandbox.ops.pallas_conv_t import (
    _VMEM_LIMIT,
    _halo_specs,
    _row_getter,
    wgrad_restage,
)


def _wgrad_tail_kernel(x_ref, up_ref, dn_ref, y1_ref, gp_ref,
                       a_ref, b_ref, sel_ref, mu_ref, inv_ref,
                       gi_ref, c1_ref, c2_ref,
                       dw_ref, db_ref, dw_scr, db_scr,
                       *, bh: int, nblk: int, co: int, blk: int,
                       gt: bool):
    """Per row: the tail backward's dy (exact _bwd_apply_kernel math,
    rounded to the activation dtype like the HBM tensor would be), then
    the sparse conv1 wgrad dot against the union tap tile. Same two
    restage variants as pallas_conv5_t._wgrad_kernel: ``gt=True``
    transposes dy ([CO, W] — 128-aligned) and runs the native
    tile [NT, W] x dyT [W, CO] -> dW [NT, CO]; ``gt=False`` leaves the
    lane-lane contraction to Mosaic (which restages the ragged [NT, W]
    tile instead)."""
    n, i = pl.program_id(0), pl.program_id(1)

    @pl.when(jnp.logical_and(n == 0, i == 0))
    def _init():
        dw_scr[:] = jnp.zeros_like(dw_scr)
        db_scr[:] = jnp.zeros_like(db_scr)

    get = _row_getter(x_ref, up_ref, dn_ref, bh, nblk)
    for r in range(bh):
        dz = _row_dz(y1_ref, a_ref, b_ref, gp_ref, sel_ref, r, co, blk,
                     y1_ref.dtype)
        yf = y1_ref[0, r].astype(jnp.float32)
        t_hat = (yf - mu_ref[...]) * inv_ref[...]
        dy = gi_ref[...] * (dz - c1_ref[...] - t_hat * c2_ref[...])
        g_row = dy.astype(x_ref.dtype)          # the rounding HBM applied
        db_scr[:] = db_scr[:] + jnp.sum(
            g_row.astype(jnp.float32), axis=1, keepdims=True)
        if gt:
            acc = jax.lax.dot_general(           # [NT, CO], native form
                _tap_tile_u(get, r), g_row.T,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        else:
            acc = jax.lax.dot_general(           # [CO, NT]
                g_row, _tap_tile_u(get, r),
                (((1,), (1,)), ((), ())),        # contract W on both
                preferred_element_type=jnp.float32,
            )
        dw_scr[:] = dw_scr[:] + acc

    @pl.when(jnp.logical_and(n == pl.num_programs(0) - 1, i == nblk - 1))
    def _emit():
        dw_ref[...] = dw_scr[:]
        db_ref[...] = db_scr[:]


def _pick_block_h_fused(h: int, wd: int, c16: int, cbig: int,
                        cpool: int) -> int:
    """VMEM-budgeted rows per block for the fused kernel: per-row it
    streams the x block THREE times (the x/up/dn halo triple of
    _halo_specs each stages its own double-buffered copy — counting it
    once under-budgets VMEM by 4*wd*c16*bh bytes per block) plus y1 +
    g_pool (all double-buffered bf16), and keeps ~6 [cbig, W] f32
    tail-backward intermediates plus the tap tile and dw scratch live."""
    per_bh = wd * (3 * c16 + cbig + cpool) * 2 * 2
    fixed = wd * cbig * 4 * 6 + wd * NT * 4 + NT * cbig * 4
    cap = max(1, int((28_000_000 - fixed) // max(per_bh, 1)))
    for bh in (15, 10, 6, 5, 3, 2, 1):
        if bh <= cap and h % bh == 0:
            return bh
    return 1


def _fused_wgrad(x, y1, g_pool, a_col, b_col, sel, mu_col, inv_col,
                 gi_col, c1_col, c2_col, co, blk, interpret,
                 restage=None):
    """``restage`` as in conv3x3_t_wgrad ('gt' native-dot variant is the
    r05 default; None resolves TPU_SANDBOX_WGRAD_RESTAGE at trace
    time). Returns dw1 [cbig, NT] regardless of variant — gt stores the
    native [NT, cbig] and transposes on the way out."""
    gt = wgrad_restage(restage) == "gt"
    n, h, c16, wd = x.shape
    assert c16 == R * R, (c16,)
    cbig = y1.shape[2]
    cpool = g_pool.shape[2]
    bh = _pick_block_h_fused(h, wd, c16, cbig, cpool)
    nblk = h // bh
    dw_shape = (NT, cbig) if gt else (cbig, NT)

    def vec():
        return pl.BlockSpec((cbig, 1), lambda n, i: (0, 0))

    dw, db = pl.pallas_call(
        functools.partial(_wgrad_tail_kernel, bh=bh, nblk=nblk,
                          co=co, blk=blk, gt=gt),
        out_shape=(jax.ShapeDtypeStruct(dw_shape, jnp.float32),
                   jax.ShapeDtypeStruct((cbig, 1), jnp.float32)),
        grid=(n, nblk),
        in_specs=_halo_specs(bh, nblk, c16, wd) + [
            pl.BlockSpec((1, bh, cbig, wd), lambda n, i: (n, i, 0, 0)),
            pl.BlockSpec((1, bh, cpool, wd), lambda n, i: (n, i, 0, 0)),
            vec(), vec(),
            pl.BlockSpec(sel.shape, lambda n, i: (0, 0)),
            vec(), vec(), vec(), vec(), vec(),
        ],
        out_specs=(pl.BlockSpec(dw_shape, lambda n, i: (0, 0)),
                   pl.BlockSpec((cbig, 1), lambda n, i: (0, 0))),
        scratch_shapes=[
            pltpu.VMEM(dw_shape, jnp.float32),
            pltpu.VMEM((cbig, 1), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(pltpu)(
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=_VMEM_LIMIT,
        ),
        interpret=default_interpret(interpret),
    )(x, x, x, y1, g_pool, a_col, b_col, sel, mu_col, inv_col,
      gi_col, c1_col, c2_col)
    return (dw.T if gt else dw), db


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _conv1_tail_t_prim(x, k5, cbias, gamma, beta, co, blk, eps=1e-5,
                       interpret=None):
    out, mu, var, _, _ = _fwd_impl(x, k5, cbias, gamma, beta, co, blk,
                                   eps, interpret)
    return out, mu, var


def conv1_tail_t(x, k5, cbias, gamma, beta, co, blk, eps=1e-5,
                 interpret=None):
    """x [N,H4,16,W4] (s2d image — DATA ONLY: a differentiated x is
    rejected by the same AD-rule guard as conv1_s2d_t, applied here
    outside the custom_vjp boundary where it can still see the AD
    trace), k5 [5,5,1,co] canonical, cbias [co], gamma/beta [co] ->
    (pooled [N,H4,4*co,W4], mu [co], var [co]). Forward ==
    conv1_s2d_t_stats + fused tail; backward fuses the tail's dy into
    the conv wgrad (module docstring). mu/var cotangents ignored (stats
    update not differentiated — same contract as fused_bn_relu_pool_t)."""
    return _conv1_tail_t_prim(_data_only(x), k5, cbias, gamma, beta,
                              co, blk, eps, interpret)


def _fwd_impl(x, k5, cbias, gamma, beta, co, blk, eps, interpret):
    y1, s, ss = conv1_s2d_t_stats(x, k5, cbias, interpret)
    out, mu, var, (a_col, b_col, inv) = _tail_forward(
        y1, gamma, beta, co, blk, eps, interpret, ysums=(s, ss))
    return out, mu, var, y1, (a_col, b_col, inv)


def _vjp_fwd(x, k5, cbias, gamma, beta, co, blk, eps, interpret):
    out, mu, var, y1, (a_col, b_col, inv) = _fwd_impl(
        x, k5, cbias, gamma, beta, co, blk, eps, interpret)
    return (out, mu, var), (x, k5, cbias, y1, gamma, beta, mu, inv,
                            a_col, b_col)


def _vjp_bwd(co, blk, eps, interpret, res, cts):
    g = cts[0]  # stats cotangents ignored — see docstring
    x, k5, cbias, y1, gamma, beta, mu, inv, a_col, b_col = res
    n, h, c, w = y1.shape
    groups = blk * blk
    s1_co, s2_co, mu_col, inv_col, sel = bwd_reduce(
        y1, g, co, blk, a_col, b_col, mu, inv, interpret)
    m_count = n * h * w * groups
    gi_col, c1_col, c2_col = bwd_scales(s1_co, s2_co, gamma, inv,
                                        groups, m_count)

    dw1, db = _fused_wgrad(x, y1, g, a_col, b_col, sel, mu_col, inv_col,
                           gi_col, c1_col, c2_col, co, blk, interpret)
    f1 = k5.shape[-1]
    dk5 = gather_dk5(dw1, f1).astype(k5.dtype)
    db_f1 = db[:, 0].reshape(R * R, f1).sum(0).astype(cbias.dtype)
    dgamma = s2_co.astype(gamma.dtype)
    dbeta = s1_co.astype(beta.dtype)
    return jnp.zeros_like(x), dk5, db_f1, dgamma, dbeta


_conv1_tail_t_prim.defvjp(_vjp_fwd, _vjp_bwd)


def conv1_tail_t_reference(x, k5, cbias, gamma, beta, co, blk, eps=1e-5,
                           interpret=None):
    """The unfused composition (the exact ops the model runs with
    fused_conv1_bwd=False): equality contract for the tests."""
    from tpu_sandbox.ops.pallas_bn_tail_t import fused_bn_relu_pool_t

    y1, s, ss = conv1_s2d_t_stats(x, k5, cbias, interpret)
    return fused_bn_relu_pool_t(y1, gamma, beta, co, blk, eps, interpret,
                                (s, ss))
