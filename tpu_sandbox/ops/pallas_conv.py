"""Pallas TPU 3x3 SAME conv for the space-to-depth ConvNet — fwd, dgrad,
wgrad, ~one HBM pass each.

Why XLA's conv is the wrong tool here (tools/hlo_traffic.py on the
AOT-compiled s2d train step, bs=16): XLA:TPU lowers each conv through a
materialized packed ("im2col") copy of its input, so conv1 fwd alone moves
~16 GB/step — a 2.3 GB (lane-padded) input read, a 4.6 GB packed write,
a 4.6 GB packed read, and the 4.6 GB output write — and the backward pass
repeats the pattern for wgrad. The convolution itself is nine shifted
[pixels, C] x [C, CO] matmuls; no packed copy needs to exist:

- **fwd**: read the input ~once, write the output once. Bias add fused.
- **wgrad**: read input + cotangent ~once each, accumulate all nine
  [C, CO] tap gradients in VMEM scratch across the (sequential) grid,
  dbias fused into the same pass.
- **dgrad** is the same 3x3 SAME conv with spatially-flipped,
  ci/co-transposed weights — it reuses the fwd kernel.

Halo handling: the grid walks row-blocks of ``block_h`` rows. The H-edge
neighbors come in as two extra single-row BlockSpecs whose index maps
CLAMP to the image (rows are re-read, so the input costs (bh+2)/bh ~ 1.2
passes, not 3); at the top/bottom image edge the kernel zero-masks the
halo row, which makes SAME zero-padding exact. The W-direction pad is a
zero-column concatenate inside the kernel. Everything rides the standard
pipelined BlockSpec path — the first cut of this kernel DMA'd
[bh+2, W, C] strips from a ``pl.ANY`` ref instead and died in Mosaic
("slice shape along dimension 2 must be aligned to tiling (8), but is
750"): manual memref slices need 8-aligned extents, pipelined block
delivery does not.

Numerics: accumulation in f32 via preferred_element_type regardless of
the (bf16) activation dtype, bias added in f32, one rounding to the
output dtype — at least as accurate as the lax.conv_general_dilated call
it replaces (tests/test_pallas_conv.py pins equality to the jnp
reference; the s2d model equality tests pin the end-to-end plan).

Used by models/convnet_s2d.py ``_Conv`` when ``ConvNetS2D(fused_conv=
True)`` (the TPU default via ``pick_convnet``, like ``fused_tail``).
Reference being accelerated: the two 5x5 convs of
/root/reference/mnist_onegpu.py:11-31, s2d-scattered to 3x3 (see
models/convnet_s2d.py docstring).
"""

from __future__ import annotations

import functools

import jax

from tpu_sandbox.utils.compat import tpu_compiler_params
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_sandbox.ops.pallas_common import default_interpret


def _pick_block_h(h: int, w: int, c: int, co: int) -> int:
    """Rows per grid block, budgeted against scoped VMEM (16 MB): the
    pipeline double-buffers the bh·W·(C + CO) in/out blocks and the row
    loop keeps ~a [W, 9C] bf16 tap tile plus [W, CO] f32 accumulators
    live. bh=10 at conv1-wgrad's 750-wide 16->256 shape hit 21.9 MB on
    the Mosaic stack (chipless AOT compile); this budget lands it at 3."""
    per_bh = w * (c + co) * 2 * 2            # double-buffered blocks, bf16
    per_row = w * (9 * c + co) * 4           # tap tile + f32 row buffers
    cap = max(1, int(7_000_000 // max(per_bh + per_row, 1)))
    for bh in (10, 8, 6, 5, 4, 3, 2, 1):
        if bh <= cap and h % bh == 0:
            return bh
    return 1


def _shift_w(row, dx: int):
    """row [W, C] -> the dx-tap's view: row shifted by (dx-1) pixels with
    zero columns entering at the W edge (SAME padding, W direction)."""
    if dx == 1:
        return row
    zero = jnp.zeros_like(row[:1])
    if dx == 0:
        return jnp.concatenate([zero, row[:-1]], axis=0)
    return jnp.concatenate([row[1:], zero], axis=0)


def _halo_specs(bh: int, nblk: int, w: int, c: int):
    """Body block + clamped single-row halo blocks above and below."""
    return [
        pl.BlockSpec((1, bh, w, c), lambda n, i: (n, i, 0, 0)),
        pl.BlockSpec((1, 1, w, c),
                     lambda n, i: (n, jnp.maximum(i * bh - 1, 0), 0, 0)),
        pl.BlockSpec((1, 1, w, c),
                     lambda n, i: (n, jnp.minimum(i * bh + bh, nblk * bh - 1),
                                   0, 0)),
    ]


def _row_getter(x_ref, up_ref, dn_ref, bh: int, nblk: int):
    """Row r_in of the (bh+2)-row halo'd strip, r_in in [-1, bh]; the
    out-of-image halo rows read the clamped neighbor block and are
    zero-masked (exact SAME padding at the H edges)."""
    i = pl.program_id(1)

    def get(r_in: int):
        if r_in == -1:
            return jnp.where(i > 0, up_ref[0, 0], 0)
        if r_in == bh:
            return jnp.where(i < nblk - 1, dn_ref[0, 0], 0)
        return x_ref[0, r_in]

    return get


def _tap_tile(get, r: int):
    """The row's im2col tile [W, 9·C], built in VMEM (lane concatenates —
    VPU work, zero HBM cost). Tap order (dy, dx) major then C, matching
    the [9C, CO] flattening of w. One [W, 9C] x [9C, CO] matmul then runs
    the MXU at K = 9C (K=144 for conv1) instead of nine K=C matmuls —
    at C=16, nine separate taps would leave 7/8 of the MXU's contraction
    rows idle and make the kernel compute-bound."""
    return jnp.concatenate(
        [_shift_w(get(r + dy - 1), dx)
         for dy in range(3) for dx in range(3)],
        axis=1,
    )


def _conv_row(get, w_ref, b_ref, r: int):
    acc = jax.lax.dot_general(
        _tap_tile(get, r), w_ref[...],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return acc + b_ref[...].astype(jnp.float32)  # [1, CO] broadcasts over W


def _fwd_kernel(x_ref, up_ref, dn_ref, w_ref, b_ref, y_ref,
                *, bh: int, nblk: int):
    get = _row_getter(x_ref, up_ref, dn_ref, bh, nblk)
    for r in range(bh):
        y_ref[0, r] = _conv_row(get, w_ref, b_ref, r).astype(y_ref.dtype)


def _fwd_stats_kernel(x_ref, up_ref, dn_ref, w_ref, b_ref,
                      y_ref, s_ref, ss_ref, s_scr, ss_scr,
                      *, bh: int, nblk: int):
    """fwd + per-lane sum/sumsq of the ROUNDED output accumulated across
    the sequential grid — the BN-stats pass for free (the unfused chain
    computes batch statistics from the stored activation-dtype y, so the
    sums must see the rounded values too)."""
    n, i = pl.program_id(0), pl.program_id(1)

    @pl.when(jnp.logical_and(n == 0, i == 0))
    def _init():
        s_scr[:] = jnp.zeros_like(s_scr)
        ss_scr[:] = jnp.zeros_like(ss_scr)

    get = _row_getter(x_ref, up_ref, dn_ref, bh, nblk)
    for r in range(bh):
        y_row = _conv_row(get, w_ref, b_ref, r).astype(y_ref.dtype)
        y_ref[0, r] = y_row
        yf = y_row.astype(jnp.float32)
        s_scr[:] = s_scr[:] + jnp.sum(yf, axis=0, keepdims=True)
        ss_scr[:] = ss_scr[:] + jnp.sum(yf * yf, axis=0, keepdims=True)

    @pl.when(jnp.logical_and(n == pl.num_programs(0) - 1, i == nblk - 1))
    def _emit():
        s_ref[...] = s_scr[:]
        ss_ref[...] = ss_scr[:]


def _wgrad_kernel(x_ref, up_ref, dn_ref, g_ref, dw_ref, db_ref,
                  dw_scr, db_scr, *, bh: int, nblk: int):
    n, i = pl.program_id(0), pl.program_id(1)

    @pl.when(jnp.logical_and(n == 0, i == 0))
    def _init():
        dw_scr[:] = jnp.zeros_like(dw_scr)
        db_scr[:] = jnp.zeros_like(db_scr)

    get = _row_getter(x_ref, up_ref, dn_ref, bh, nblk)
    for r in range(bh):
        g_row = g_ref[0, r].astype(jnp.float32)  # [W, CO]
        db_scr[:] = db_scr[:] + jnp.sum(g_row, axis=0, keepdims=True)
        dw_scr[:] = dw_scr[:] + jax.lax.dot_general(
            _tap_tile(get, r), g_row,  # contract W: [9C, CO], K=W on MXU
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(jnp.logical_and(n == pl.num_programs(0) - 1, i == nblk - 1))
    def _emit():
        dw_ref[...] = dw_scr[:]
        db_ref[...] = db_scr[:]


def _conv_call(x, w, bias, out_dtype, interpret, stats=False):
    n, h, wd, c = x.shape
    co = w.shape[-1]
    bh = _pick_block_h(h, wd, c, co)
    nblk = h // bh
    if stats:
        kernel = functools.partial(_fwd_stats_kernel, bh=bh, nblk=nblk)
        out_shape = (jax.ShapeDtypeStruct((n, h, wd, co), out_dtype),
                     jax.ShapeDtypeStruct((1, co), jnp.float32),
                     jax.ShapeDtypeStruct((1, co), jnp.float32))
        out_specs = (
            pl.BlockSpec((1, bh, wd, co), lambda n, i: (n, i, 0, 0)),
            pl.BlockSpec((1, co), lambda n, i: (0, 0)),
            pl.BlockSpec((1, co), lambda n, i: (0, 0)),
        )
        scratch = [pltpu.VMEM((1, co), jnp.float32),
                   pltpu.VMEM((1, co), jnp.float32)]
    else:
        kernel = functools.partial(_fwd_kernel, bh=bh, nblk=nblk)
        out_shape = jax.ShapeDtypeStruct((n, h, wd, co), out_dtype)
        out_specs = pl.BlockSpec((1, bh, wd, co), lambda n, i: (n, i, 0, 0))
        scratch = []
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(n, nblk),
        in_specs=_halo_specs(bh, nblk, wd, c) + [
            pl.BlockSpec((9 * c, co), lambda n, i: (0, 0)),
            pl.BlockSpec((1, co), lambda n, i: (0, 0)),
        ],
        out_specs=out_specs,
        scratch_shapes=scratch,
        compiler_params=tpu_compiler_params(pltpu)(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=default_interpret(interpret),
    )(x, x, x, w.reshape(9 * c, co), bias.reshape(1, co))


def _flip_transpose(w):
    """fwd weights -> dgrad weights: spatial flip + ci/co transpose (the
    transpose of a stride-1 SAME conv is the same conv with these)."""
    return w[::-1, ::-1].transpose(0, 1, 3, 2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def conv3x3(x, w, bias, interpret=None):
    """3x3 stride-1 SAME conv + bias: x [N,H,W,C], w [3,3,C,CO], bias [CO]
    -> y [N,H,W,CO] in x.dtype, f32 accumulation. Differentiable (custom
    VJP: dgrad reuses the fwd kernel with flipped weights; wgrad+dbias are
    one fused pass)."""
    return _conv_call(x, w, bias, x.dtype, interpret)


def _conv_vjp_fwd(x, w, bias, interpret):
    return _conv_call(x, w, bias, x.dtype, interpret), (x, w)


def _conv_vjp_bwd(interpret, res, g):
    x, w = res
    n, h, wd, c = x.shape
    co = w.shape[-1]
    # dx: unused for conv1 (the image is not differentiated) — the
    # pallas_call is side-effect free, so XLA DCEs it there
    dx = _conv_call(g, _flip_transpose(w), jnp.zeros((c,), g.dtype),
                    x.dtype, interpret)
    bh = _pick_block_h(h, wd, c, co)
    nblk = h // bh
    dw_flat, db = pl.pallas_call(
        functools.partial(_wgrad_kernel, bh=bh, nblk=nblk),
        out_shape=(jax.ShapeDtypeStruct((9 * c, co), jnp.float32),
                   jax.ShapeDtypeStruct((1, co), jnp.float32)),
        grid=(n, nblk),
        in_specs=_halo_specs(bh, nblk, wd, c) + [
            pl.BlockSpec((1, bh, wd, co), lambda n, i: (n, i, 0, 0)),
        ],
        out_specs=(pl.BlockSpec((9 * c, co), lambda n, i: (0, 0)),
                   pl.BlockSpec((1, co), lambda n, i: (0, 0))),
        scratch_shapes=[
            pltpu.VMEM((9 * c, co), jnp.float32),
            pltpu.VMEM((1, co), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(pltpu)(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=default_interpret(interpret),
    )(x, x, x, g)
    dw = dw_flat.reshape(3, 3, c, co).astype(w.dtype)
    return dx, dw, db[0].astype(w.dtype)


conv3x3.defvjp(_conv_vjp_fwd, _conv_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def conv3x3_stats(x, w, bias, interpret=None):
    """conv3x3 that also returns (sum [1,CO], sumsq [1,CO]) of the rounded
    output in f32 — the BN batch-statistics reductions fused into the conv
    pass, saving the separate full read of y they otherwise cost.

    The stats outputs' cotangents are IGNORED (treated as zero): the
    consumer (ops/pallas_bn_tail.py) accounts for the statistics'
    dependence on y inside its own custom VJP — same contract as its own
    mu/var outputs — so routing them again here would double-count."""
    return _conv_call(x, w, bias, x.dtype, interpret, stats=True)


def _conv_stats_vjp_fwd(x, w, bias, interpret):
    return _conv_call(x, w, bias, x.dtype, interpret, stats=True), (x, w)


def _conv_stats_vjp_bwd(interpret, res, cts):
    return _conv_vjp_bwd(interpret, res, cts[0])


conv3x3_stats.defvjp(_conv_stats_vjp_fwd, _conv_stats_vjp_bwd)


def conv3x3_reference(x, w, bias):
    """The lax.conv call this kernel replaces (models/convnet_s2d.py
    ``_Conv``) — single home for the equality contract."""
    y = jax.lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return y + bias
