"""Pallas TPU 3x3 SAME conv in TRANSPOSED layout [N, H, C, W] — the
round-3 rework of ops/pallas_conv.py after on-chip micro-benchmarks.

Measured (tools/conv_micro.py, v5e, bs=16): the [N,H,W,C] kernel runs
conv1 fwd at ~27 TF/s and the backward at ~19 TF/s against a ~110 TF/s
MXU ceiling — SLOWER than the XLA conv it replaced (~41 TF/s). Two
pathologies, both layout-induced:

1. **Tap-tile build.** The [W, 9C] im2col tile is assembled by nine
   lane-direction concatenates of [W, C] pieces; at C=16 each piece
   occupies 16 of 128 lanes, so every VPU op wastes 7/8 of the machine
   and the inserts at lane offsets 16k are multi-op shuffles. The build
   costs several times the [W,9C]x[9C,CO] matmul it feeds.
2. **HBM lane padding.** Pallas operands use the default layout (last
   dim on lanes): a [...,W,C] block with C=16 is padded 8x in HBM and
   VMEM, so the kernel also moves ~8x the bytes it thinks it does.

The transposed layout fixes both at once. Activations are [N, H, C, W]:
W=750 rides the 128-lane dim (pad 750->768, 2.4%), C rides sublanes
(C=16 = exactly one bf16 sublane-tile). The im2col tile becomes
tileT [9C, W], built by stacking nine [C, W] row views along SUBLANES —
tile-aligned register placement, no lane shuffles; the dx taps are
single-lane shifts of full-width rows. The matmul is
wt [CO, 9C] x tileT [9C, W] -> y_rowT [CO, W], i.e. the same
K = 9C contraction, now fed at full VPU/lane width.

Interface mirrors pallas_conv (same scattered w [3,3,C,CO], bias [CO],
f32 accumulation, custom VJP with dgrad = fwd kernel on flipped
weights and a fused wgrad+dbias pass; a *_stats variant folds the BN
sum/sumsq over (N,H,W) into the output pass). conv3x3_t_reference
transposes to NHWC, runs the exact lax.conv the NHWC plan uses, and
transposes back — the equality contract for tests/test_pallas_conv_t.py.

Reference being accelerated: the two 5x5 convs of
/root/reference/mnist_onegpu.py:11-31, s2d-scattered to 3x3
(models/convnet_s2d.py docstring).
"""

from __future__ import annotations

import functools

import jax

from tpu_sandbox.utils.compat import tpu_compiler_params
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_sandbox.ops.pallas_common import default_interpret


_VMEM_LIMIT = 100_000_000  # raised from the 16 MB default (v5e: 128 MB)


def _pick_block_h(h: int, w: int, c: int, co: int) -> int:
    """Rows per grid block, budgeted against VMEM (raised to
    ``_VMEM_LIMIT`` via CompilerParams). Bigger blocks matter here:
    measured at bh=3 the kernel pays 2 small halo DMAs per 3 rows and
    ~4000 grid blocks/step; bh=30 cuts both 10x. The fixed per-row cost
    (tap tile + f32 accumulator) does not scale with bh, so it is
    subtracted from the budget rather than multiplied."""
    per_bh = w * (c + co) * 2 * 2             # double-buffered blocks, bf16
    per_row = w * (9 * c + co) * 4            # tap tile + f32 row buffers
    cap = max(1, int((28_000_000 - per_row) // max(per_bh, 1)))
    for bh in (30, 25, 20, 15, 12, 10, 8, 6, 5, 4, 3, 2, 1):
        if bh <= cap and h % bh == 0:
            return bh
    return 1


def _shift_lanes(row, dx: int):
    """row [C, W] -> the dx-tap's view: shifted along lanes (the W
    direction) with a zero column entering at the edge (SAME padding)."""
    if dx == 1:
        return row
    zero = jnp.zeros_like(row[:, :1])
    if dx == 0:
        return jnp.concatenate([zero, row[:, :-1]], axis=1)
    return jnp.concatenate([row[:, 1:], zero], axis=1)


def _halo_specs(bh: int, nblk: int, c: int, w: int):
    """Body block + clamped single-row halo blocks above and below."""
    return [
        pl.BlockSpec((1, bh, c, w), lambda n, i: (n, i, 0, 0)),
        pl.BlockSpec((1, 1, c, w),
                     lambda n, i: (n, jnp.maximum(i * bh - 1, 0), 0, 0)),
        pl.BlockSpec((1, 1, c, w),
                     lambda n, i: (n, jnp.minimum(i * bh + bh, nblk * bh - 1),
                                   0, 0)),
    ]


def _row_getter(x_ref, up_ref, dn_ref, bh: int, nblk: int):
    """Row r_in of the (bh+2)-row halo'd strip as [C, W]; out-of-image
    halo rows read the clamped neighbor block and are zero-masked."""
    i = pl.program_id(1)

    def get(r_in: int):
        if r_in == -1:
            return jnp.where(i > 0, up_ref[0, 0], 0)
        if r_in == bh:
            return jnp.where(i < nblk - 1, dn_ref[0, 0], 0)
        return x_ref[0, r_in]

    return get


def _tap_tile_t(get, r: int):
    """The row's im2col tile TRANSPOSED, [9C, W]: nine [C, W] views
    stacked along sublanes (tap order (dy, dx) major then C — the same
    flattening as w.reshape(9C, CO), so the two kernels share weight
    layout). Sublane concatenation of C-row pieces is tile-aligned
    placement; the lane shifts are single-lane rotates of full-width
    rows — this build is the whole point of the transposed layout."""
    return jnp.concatenate(
        [_shift_lanes(get(r + dy - 1), dx)
         for dy in range(3) for dx in range(3)],
        axis=0,
    )


def _conv_row_t(get, wt_ref, b_ref, r: int):
    acc = jax.lax.dot_general(
        wt_ref[...], _tap_tile_t(get, r),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                          # [CO, W]
    return acc + b_ref[...].astype(jnp.float32)  # [CO, 1] broadcasts over W


def _fwd_kernel(x_ref, up_ref, dn_ref, wt_ref, b_ref, y_ref,
                *, bh: int, nblk: int):
    get = _row_getter(x_ref, up_ref, dn_ref, bh, nblk)
    for r in range(bh):
        y_ref[0, r] = _conv_row_t(get, wt_ref, b_ref, r).astype(y_ref.dtype)


def _fwd_stats_kernel(x_ref, up_ref, dn_ref, wt_ref, b_ref,
                      y_ref, s_ref, ss_ref, s_scr, ss_scr,
                      *, bh: int, nblk: int):
    """fwd + per-channel sum/sumsq of the ROUNDED output accumulated
    across the sequential grid (channels on sublanes: the reductions run
    over lanes/W and rows)."""
    n, i = pl.program_id(0), pl.program_id(1)

    @pl.when(jnp.logical_and(n == 0, i == 0))
    def _init():
        s_scr[:] = jnp.zeros_like(s_scr)
        ss_scr[:] = jnp.zeros_like(ss_scr)

    get = _row_getter(x_ref, up_ref, dn_ref, bh, nblk)
    for r in range(bh):
        y_row = _conv_row_t(get, wt_ref, b_ref, r).astype(y_ref.dtype)
        y_ref[0, r] = y_row
        yf = y_row.astype(jnp.float32)
        s_scr[:] = s_scr[:] + jnp.sum(yf, axis=1, keepdims=True)
        ss_scr[:] = ss_scr[:] + jnp.sum(yf * yf, axis=1, keepdims=True)

    @pl.when(jnp.logical_and(n == pl.num_programs(0) - 1, i == nblk - 1))
    def _emit():
        s_ref[...] = s_scr[:]
        ss_ref[...] = ss_scr[:]


def _wgrad_kernel(x_ref, up_ref, dn_ref, g_ref, dw_ref, db_ref,
                  dw_scr, db_scr, *, bh: int, nblk: int, gt: bool):
    """Accumulates the weight gradient and db [CO, 1] in VMEM scratch
    across the sequential grid. The contraction is over W, which sits on
    the LANES of both operands (g_row [CO, W], tile [9C, W]) — not a
    native MXU form, so SOME operand must be restaged per row. Two
    variants (VERDICT r04 next-2, the named wgrad bottleneck):

    - ``gt=False`` (r03 form): ``dot_general(g_row, tile, contract W on
      both)`` -> dwT [CO, 9C]. Mosaic resolves the lane-lane contraction
      itself, transposing the TILE — a per-row relayout of [9C, W]
      (9C = 144/576: ragged, non-128-multiple sublane counts).
    - ``gt=True`` (r05): transpose ``g_row`` explicitly ([CO, W] ->
      [W, CO]; CO = 256/128 — exact lane-tile multiples) and run the
      native [M,K]x[K,N] dot ``tile [9C, W] x gT [W, CO] -> dw [9C,
      CO]``: all three MXU dims >= 128 at production geometry, and the
      per-row transpose moves 4.5x fewer bytes for conv2 (128x768 vs
      576x768) and is 128-aligned for both convs.

    Which wins on hardware is a measured question — tools/conv_micro.py
    races both (rows wgrad[gt] / wgrad[auto])."""
    n, i = pl.program_id(0), pl.program_id(1)

    @pl.when(jnp.logical_and(n == 0, i == 0))
    def _init():
        dw_scr[:] = jnp.zeros_like(dw_scr)
        db_scr[:] = jnp.zeros_like(db_scr)

    get = _row_getter(x_ref, up_ref, dn_ref, bh, nblk)
    for r in range(bh):
        g_row = g_ref[0, r]                    # [CO, W]
        db_scr[:] = db_scr[:] + jnp.sum(
            g_row.astype(jnp.float32), axis=1, keepdims=True)
        if gt:
            acc = jax.lax.dot_general(         # [9C, CO], native form
                _tap_tile_t(get, r), g_row.T,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        else:
            acc = jax.lax.dot_general(         # [CO, 9C]
                g_row, _tap_tile_t(get, r),
                (((1,), (1,)), ((), ())),      # contract W on both
                preferred_element_type=jnp.float32,
            )
        dw_scr[:] = dw_scr[:] + acc

    @pl.when(jnp.logical_and(n == pl.num_programs(0) - 1, i == nblk - 1))
    def _emit():
        dw_ref[...] = dw_scr[:]
        db_ref[...] = db_scr[:]


def _conv_call(x, w, bias, out_dtype, interpret, stats=False):
    n, h, c, wd = x.shape
    co = w.shape[-1]
    bh = _pick_block_h(h, wd, c, co)
    nblk = h // bh
    wt = w.reshape(9 * c, co).T                # [CO, 9C]
    if stats:
        kernel = functools.partial(_fwd_stats_kernel, bh=bh, nblk=nblk)
        out_shape = (jax.ShapeDtypeStruct((n, h, co, wd), out_dtype),
                     jax.ShapeDtypeStruct((co, 1), jnp.float32),
                     jax.ShapeDtypeStruct((co, 1), jnp.float32))
        out_specs = (
            pl.BlockSpec((1, bh, co, wd), lambda n, i: (n, i, 0, 0)),
            pl.BlockSpec((co, 1), lambda n, i: (0, 0)),
            pl.BlockSpec((co, 1), lambda n, i: (0, 0)),
        )
        scratch = [pltpu.VMEM((co, 1), jnp.float32),
                   pltpu.VMEM((co, 1), jnp.float32)]
    else:
        kernel = functools.partial(_fwd_kernel, bh=bh, nblk=nblk)
        out_shape = jax.ShapeDtypeStruct((n, h, co, wd), out_dtype)
        out_specs = pl.BlockSpec((1, bh, co, wd), lambda n, i: (n, i, 0, 0))
        scratch = []
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(n, nblk),
        in_specs=_halo_specs(bh, nblk, c, wd) + [
            pl.BlockSpec((co, 9 * c), lambda n, i: (0, 0)),
            pl.BlockSpec((co, 1), lambda n, i: (0, 0)),
        ],
        out_specs=out_specs,
        scratch_shapes=scratch,
        compiler_params=tpu_compiler_params(pltpu)(
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=_VMEM_LIMIT,
        ),
        interpret=default_interpret(interpret),
    )(x, x, x, wt, bias.reshape(co, 1))


def _flip_transpose(w):
    """fwd weights -> dgrad weights: spatial flip + ci/co transpose."""
    return w[::-1, ::-1].transpose(0, 1, 3, 2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def conv3x3_t(x, w, bias, interpret=None):
    """3x3 stride-1 SAME conv + bias in transposed layout: x [N,H,C,W],
    w [3,3,C,CO], bias [CO] -> y [N,H,CO,W] in x.dtype, f32 accumulation.
    Differentiable (custom VJP: dgrad reuses the fwd kernel with flipped
    weights; wgrad+dbias are one fused pass)."""
    return _conv_call(x, w, bias, x.dtype, interpret)


def _conv_vjp_fwd(x, w, bias, interpret):
    return _conv_call(x, w, bias, x.dtype, interpret), (x, w)


def wgrad_restage(restage: str | None) -> str:
    """Resolve the wgrad restage choice: explicit argument, else the
    TPU_SANDBOX_WGRAD_RESTAGE env (read at TRACE time, same discipline
    as TPU_SANDBOX_NO_SPARSE_CONV1 — models/convnet_s2d_t.py), else the
    r05 default 'gt'."""
    import os

    if restage is None:
        restage = os.environ.get("TPU_SANDBOX_WGRAD_RESTAGE", "gt")
    if restage not in ("gt", "auto"):
        raise ValueError(f"wgrad restage must be 'gt' or 'auto': {restage}")
    return restage


def conv3x3_t_wgrad(x, g, interpret=None, restage=None):
    """The fused wgrad+dbias pass alone: x [N,H,C,W], g [N,H,CO,W] ->
    (dwT [CO, 9C] f32, db [CO, 1] f32). Used by the VJP below and timed
    in isolation by tools/conv_micro.py. ``restage`` picks the per-row
    MXU staging (see _wgrad_kernel): 'gt' transposes g explicitly and
    runs the native dot; 'auto' leaves the lane-lane contraction to
    Mosaic; None resolves via wgrad_restage."""
    gt = wgrad_restage(restage) == "gt"
    n, h, c, wd = x.shape
    co = g.shape[2]
    bh = _pick_block_h(h, wd, c, co)
    nblk = h // bh
    dw_shape = (9 * c, co) if gt else (co, 9 * c)
    dw, db = pl.pallas_call(
        functools.partial(_wgrad_kernel, bh=bh, nblk=nblk, gt=gt),
        out_shape=(jax.ShapeDtypeStruct(dw_shape, jnp.float32),
                   jax.ShapeDtypeStruct((co, 1), jnp.float32)),
        grid=(n, nblk),
        in_specs=_halo_specs(bh, nblk, c, wd) + [
            pl.BlockSpec((1, bh, co, wd), lambda n, i: (n, i, 0, 0)),
        ],
        out_specs=(pl.BlockSpec(dw_shape, lambda n, i: (0, 0)),
                   pl.BlockSpec((co, 1), lambda n, i: (0, 0))),
        scratch_shapes=[
            pltpu.VMEM(dw_shape, jnp.float32),
            pltpu.VMEM((co, 1), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(pltpu)(
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=_VMEM_LIMIT,
        ),
        interpret=default_interpret(interpret),
    )(x, x, x, g)
    # caller-facing layout is dwT [CO, 9C] either way; the gt variant's
    # [9C, CO] is a one-off [576, 128]-ish XLA transpose per step (and
    # cancels against the VJP's .T below)
    return (dw.T if gt else dw), db


def _conv_vjp_bwd(interpret, res, g):
    x, w = res
    c, co = x.shape[2], w.shape[-1]
    # dx: DCE'd by XLA when unused (conv1: the image is not differentiated)
    dx = _conv_call(g, _flip_transpose(w), jnp.zeros((c,), g.dtype),
                    x.dtype, interpret)
    dwt, db = conv3x3_t_wgrad(x, g, interpret)
    dw = dwt.T.reshape(3, 3, c, co).astype(w.dtype)
    return dx, dw, db[:, 0].astype(w.dtype)


conv3x3_t.defvjp(_conv_vjp_fwd, _conv_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def conv3x3_t_stats(x, w, bias, interpret=None):
    """conv3x3_t that also returns (sum [CO,1], sumsq [CO,1]) of the
    rounded output in f32 — BN batch statistics fused into the conv's
    output pass. The stats outputs' cotangents are IGNORED: the consumer
    accounts for the statistics' dependence on y inside its own custom
    VJP (same contract as pallas_conv.conv3x3_stats)."""
    return _conv_call(x, w, bias, x.dtype, interpret, stats=True)


def _conv_stats_vjp_fwd(x, w, bias, interpret):
    return _conv_call(x, w, bias, x.dtype, interpret, stats=True), (x, w)


def _conv_stats_vjp_bwd(interpret, res, cts):
    return _conv_vjp_bwd(interpret, res, cts[0])


conv3x3_t_stats.defvjp(_conv_stats_vjp_fwd, _conv_stats_vjp_bwd)


def conv3x3_t_reference(x, w, bias):
    """Equality contract: NCHW->NHWC transpose, the exact lax.conv the
    NHWC plan uses (pallas_conv.conv3x3_reference), transpose back."""
    from tpu_sandbox.ops.pallas_conv import conv3x3_reference

    y = conv3x3_reference(x.transpose(0, 1, 3, 2), w, bias)
    return y.transpose(0, 1, 3, 2)
