"""Pallas TPU kernel: fused softmax-cross-entropy from logits.

The hot ops of the parity experiment (convs, the 18M-wide matmul) belong to
XLA — hand-scheduling them would fight the compiler (pallas_guide.md: let
XLA fuse). The loss is the one op where a fused kernel is cleanly separable:
one VMEM pass computes max, log-sum-exp, and the label logit gather per row
— no [N, C] softmax materialization in HBM.

Forward runs as a Pallas kernel (grid over row blocks, classes padded to
the 128-lane tile; padding uses a large-negative filler so exp() underflows
to 0). Backward is the closed form softmax(logits) - onehot(labels),
expressed in jnp and left to XLA (it fuses into surrounding backprop).

Falls back to interpret mode off-TPU automatically, so the same call path
is tested on CPU and compiled on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tpu_sandbox.ops.pallas_common import (
    LANE as _LANE,
    NEG as _NEG,
    default_interpret,
    round_up as _round_up,
)

_BLOCK_N = 128


def _ce_kernel(logits_ref, labels_ref, out_ref):
    logits = logits_ref[:].astype(jnp.float32)  # [BN, Cp]
    m = jnp.max(logits, axis=-1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(logits - m), axis=-1, keepdims=True)) + m
    labels = labels_ref[:]  # [BN, 1]
    cols = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    picked = jnp.sum(
        jnp.where(cols == labels, logits, 0.0), axis=-1, keepdims=True
    )
    out_ref[:] = lse - picked


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def pallas_cross_entropy(
    logits: jnp.ndarray, labels: jnp.ndarray, interpret: bool | None = None
) -> jnp.ndarray:
    """Mean softmax cross-entropy; logits [N, C], labels [N] int. Matches
    ops.losses.cross_entropy_loss numerically (tested)."""
    return _forward(logits, labels, interpret)


def _block_rows(cp: int) -> int | None:
    """Rows per grid step, sized so the kernel's [rows, cp] f32 view stays
    within scoped VMEM (~4 MB budget of the 16 MB/core): at a 32k vocab
    that is 32 rows, small vocabs keep the full 128. Caught by a chipless
    v5e AOT compile — the fixed 128-row block OOMed VMEM at [16384, 32768].
    Returns None when even 8 rows exceed the budget (vocab > 128k) — the
    caller then falls back to the jnp loss, which is numerically the same."""
    budget = 4 * 1024 * 1024
    rows = (budget // (cp * 4) // 8) * 8
    return min(_BLOCK_N, rows) if rows >= 8 else None


def _forward(logits, labels, interpret):
    n, c = logits.shape
    interpret = default_interpret(interpret)
    cp = _round_up(c, _LANE)
    block_n = _block_rows(cp)
    if block_n is None:  # vocab too wide for one VMEM row-block
        # plain optax directly — NOT losses.cross_entropy_loss, whose
        # LM-vocab dispatch would re-enter this function forever
        import optax

        return optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels).mean()
    np_ = _round_up(n, block_n)
    # pad in the INPUT dtype — the f32 promotion happens inside the kernel
    # per block, so no [N, C] f32 copy ever lands in HBM
    logits_p = jnp.pad(
        logits, ((0, np_ - n), (0, cp - c)),
        constant_values=jnp.asarray(_NEG, logits.dtype),
    )
    # padded rows: give them label 0 and a 0-logit at class 0 so their loss
    # is finite garbage; they are sliced off below
    logits_p = logits_p.at[n:, 0].set(0.0)
    labels_p = jnp.pad(labels.astype(jnp.int32), (0, np_ - n))[:, None]

    grid = (np_ // block_n,)
    per_row = pl.pallas_call(
        _ce_kernel,
        out_shape=jax.ShapeDtypeStruct((np_, 1), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, cp), lambda i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
        interpret=interpret,
    )(logits_p, labels_p)
    return jnp.mean(per_row[:n, 0])


def _fwd(logits, labels, interpret):
    return _forward(logits, labels, interpret), (logits, labels)


def _bwd(interpret, res, g):
    logits, labels = res
    n = logits.shape[0]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    onehot = jax.nn.one_hot(labels, logits.shape[1], dtype=jnp.float32)
    dlogits = (g / n) * (probs - onehot)
    return dlogits.astype(logits.dtype), None


pallas_cross_entropy.defvjp(_fwd, _bwd)
