"""Training engine: jit'd train step + epoch loop with reference log parity.

Single-device parity target is the reference train() (mnist_onegpu.py:34-84):
CE loss, plain SGD(lr=1e-4), loss print every 100 steps in the exact format
``Epoch [e/E], Step [s/S], Loss: L``, and a final
``Training complete in: <timedelta>`` wall-clock line.

TPU-first differences:
- The whole update (forward, loss, backward, SGD apply, BN stats update) is
  ONE jit'd pure function with donated state — XLA fuses and schedules it;
  there is no zero_grad/backward/step choreography.
- The 28x28 -> HxW upsample happens INSIDE the step, on device
  (``jax.image.resize``, bilinear like torchvision's default Resize). The
  reference resizes per-image on the host with PIL (mnist_onegpu.py:53),
  which would starve a TPU: feeding 3000x3000 fp32 frames is 180 MB/step
  of host->device traffic vs 4 KB/step for raw 28x28.
- Optional bf16 compute (model dtype) keeps the MXU fed; the loss/params
  stay fp32.
"""

from __future__ import annotations

import contextlib
import os
import signal
import time
from dataclasses import dataclass
from datetime import timedelta
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tpu_sandbox.obs import get_recorder
from tpu_sandbox.ops.losses import cross_entropy_loss
from tpu_sandbox.train.state import TrainState
from tpu_sandbox.utils.metrics import MetricsWriter

#: Exit code the supervisor treats as "preempted: saved, restart for free".
#: Canonical home is runtime/supervisor.py; mirrored here so the training
#: layer does not import the process-management layer.
PREEMPTED_EXIT_CODE = 75

#: KV key a preempted rank raises so every peer stops at the same boundary
#: (must match supervisor.PREEMPT_KEY; the supervisor clears it between
#: generations).
PREEMPT_KEY = "preempt/requested"

#: Env vars a supervisor/host-agent sets on every rank it spawns. Mirrored
#: from runtime/{supervisor,host_agent}.py (same no-process-layer-import
#: rule as PREEMPTED_EXIT_CODE above).
ENV_GENERATION = "TPU_SANDBOX_GENERATION"
ENV_AGENT_ID = "TPU_SANDBOX_AGENT_ID"
ENV_JOB_ID = "TPU_SANDBOX_JOB_ID"


@dataclass(frozen=True)
class ElasticEnv:
    """The elastic identity a rank inherits from whoever spawned it:
    which relaunch generation this process belongs to (stamps checkpoints
    and KV claims), which host agent owns it (``None`` outside the
    cross-host agent topology — e.g. under the single-host Supervisor),
    and which job's KV namespace it coordinates in (empty string = the
    default job, bare key schema; see ``runtime.kvstore.for_job``)."""

    generation: str
    agent_id: int | None
    job_id: str = ""

    @classmethod
    def from_env(cls, environ=None) -> "ElasticEnv":
        env = os.environ if environ is None else environ
        raw = env.get(ENV_AGENT_ID, "")
        return cls(
            generation=env.get(ENV_GENERATION, "1"),
            agent_id=int(raw) if raw else None,
            job_id=env.get(ENV_JOB_ID, ""),
        )


def resize_on_device(images, image_size):
    """[N,h,w,C] -> [N,H,W,C] bilinear, channel-layout safe: a size-1
    channel is squeezed around the resize so no [N,H,W,1] intermediate is
    laid out with the degenerate dim on the 128-wide lane axis (XLA:TPU
    pads the minor dim to 128 — measured 8-128x HBM inflation on big
    spatial tensors). Resize never mixes channels, so this is exact."""
    n, _, _, c = images.shape
    if c == 1:
        out = jax.image.resize(
            images[..., 0], (n, *image_size), method="bilinear"
        )
        return out[..., None]
    return jax.image.resize(images, (n, *image_size, c), method="bilinear")


def prepare_inputs(model, images, image_size):
    """The model-plan-aware input stage: models exposing
    ``fused_input_stage`` (ConvNetS2DT) consume the raw small batch
    directly — resize + space-to-depth in two small contractions, no
    full-size [N,H,W] intermediate — and their ``__call__`` detects the
    pre-s2d shape. Every other model gets the plain on-device resize.
    Single home: the trainer and both parallel engines route through
    here."""
    stage = getattr(model, "fused_input_stage", None)
    if stage is not None:
        return stage(images, image_size)
    return resize_on_device(images, image_size)


def make_train_step(
    model,
    tx: optax.GradientTransformation,
    *,
    image_size: tuple[int, int] | None = None,
    accum_steps: int = 1,
    donate: bool = True,
    remat: bool = False,
) -> Callable:
    """Build the jit'd (state, images, labels) -> (state, loss) step.

    ``image_size``: if set, inputs [N,h,w,C] are bilinearly resized to
    [N,H,W,C] on device before the forward pass.

    ``accum_steps``: gradient accumulation — the batch is split into
    ``accum_steps`` microbatches scanned sequentially; gradients are
    averaged and ONE optimizer update is applied. This is the
    single-device counterpart of the reference's OOM workaround (its DDP
    splits effective batch 10 across 2 GPUs; accumulation trains the same
    effective batch on one device with 1/k the activation memory, at k
    sequential passes). BN statistics update per microbatch, sequentially —
    the same semantics k torch forward passes would produce. The resize
    also happens per microbatch, so the full-size image batch never
    materializes at once.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    def loss_fn(params, batch_stats, images, labels):
        if image_size is not None:
            images = prepare_inputs(model, images, image_size)
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
        logits, mutated = model.apply(
            variables, images, train=True, mutable=["batch_stats"]
        )
        return cross_entropy_loss(logits, labels), mutated.get("batch_stats", {})

    # ``remat``: recompute the whole forward during backward instead of
    # saving activations (jax.checkpoint over the loss). The capacity
    # lever for the reference's OOM experiment — on the 3000² ConvNet the
    # dominant saved residual is conv1's [N,750,750,256] output (~300 MB/
    # image); remat trades it for one extra forward pass of (cheap, at
    # these MFUs) FLOPs. BN batch-stats semantics are unchanged: the aux
    # stats output is part of the checkpointed function.
    if remat:
        loss_fn = jax.checkpoint(loss_fn)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    @partial(jax.jit, donate_argnums=(0,) if donate else ())
    def train_step(state: TrainState, images: jax.Array, labels: jax.Array):
        if accum_steps == 1:
            (loss, new_stats), grads = grad_fn(
                state.params, state.batch_stats, images, labels
            )
        else:
            n = images.shape[0]
            if n % accum_steps:
                raise ValueError(
                    f"batch {n} not divisible by accum_steps {accum_steps}"
                )
            micro = n // accum_steps
            m_images = images.reshape(accum_steps, micro, *images.shape[1:])
            m_labels = labels.reshape(accum_steps, micro, *labels.shape[1:])

            def body(carry, mb):
                grads_acc, loss_acc, stats = carry
                (loss, stats), grads = grad_fn(
                    state.params, stats, mb[0], mb[1]
                )
                grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
                return (grads_acc, loss_acc + loss, stats), None

            zeros = jax.tree.map(jnp.zeros_like, state.params)
            (grads, loss, new_stats), _ = jax.lax.scan(
                body, (zeros, jnp.zeros((), jnp.float32), state.batch_stats),
                (m_images, m_labels),
            )
            grads = jax.tree.map(lambda g: g / accum_steps, grads)
            loss = loss / accum_steps
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return (
            state.replace(
                step=state.step + 1,
                params=new_params,
                batch_stats=new_stats,
                opt_state=new_opt,
            ),
            loss,
        )

    return train_step


def make_eval_step(model, *, image_size: tuple[int, int] | None = None) -> Callable:
    """Jit'd (state, images, labels) -> (correct_count, loss_sum)."""

    @jax.jit
    def eval_step(state: TrainState, images: jax.Array, labels: jax.Array):
        if image_size is not None:
            images = prepare_inputs(model, images, image_size)
        logits = model.apply(state.variables(), images, train=False)
        loss = cross_entropy_loss(logits, labels)
        correct = jnp.sum(jnp.argmax(logits, -1) == labels)
        return correct, loss

    return eval_step


class Trainer:
    """Epoch loop with the reference's logging contract."""

    def __init__(
        self,
        train_step: Callable,
        *,
        log_every: int = 100,
        log_rank: int | None = None,
        verbose: bool = True,
        ckpt_dir: str | None = None,
        ckpt_every: int = 0,
        state_for_checkpoint: Callable | None = None,
    ):
        """``ckpt_every`` > 0 (with ``ckpt_dir``) saves every N optimizer
        steps — the crash-recovery companion of the watchdog subsystem (the
        reference trains fire-and-forget; a dead run loses everything).
        ``state_for_checkpoint`` maps the live (possibly engine-sharded)
        state to the layout to save, e.g. DataParallel.unshard_state."""
        self.train_step = train_step
        self.log_every = log_every
        self.log_rank = log_rank  # None: single-device format; int: DDP format
        self.verbose = verbose
        self.ckpt_dir = ckpt_dir
        self.ckpt_every = ckpt_every if ckpt_dir else 0
        self.state_for_checkpoint = state_for_checkpoint or (lambda s: s)
        self._saver = None
        self.losses: list[float] = []

    def _maybe_checkpoint(self, state, opt_step: int) -> None:
        """``opt_step`` is a host-side counter (each train_step increments
        state.step by one) — reading state.step here would sync the device
        every step and kill host/device overlap."""
        if not self.ckpt_every or opt_step % self.ckpt_every:
            return
        if self._saver is None:
            from tpu_sandbox.train.checkpoint import AsyncSaver

            self._saver = AsyncSaver(self.ckpt_dir)
        if self._saver.save(self.state_for_checkpoint(state), opt_step):
            if self.verbose:
                print(f"checkpoint saved at step {opt_step}")
        elif self.verbose:
            print(
                f"checkpoint SKIPPED at step {opt_step}: {self.ckpt_dir} "
                "already holds a later step (stale dir from a previous run? "
                "pass --resume or a fresh --ckpt-dir)"
            )

    def fit(self, state: TrainState, loader, epochs: int, *,
            set_epoch: bool = False, prefetch: bool = False,
            metrics_path: str | None = None):
        """Run ``epochs`` epochs. ``set_epoch=False`` reproduces the
        reference quirk of never reshuffling the sharded data
        (no ``sampler.set_epoch``, SURVEY §2.1 C14).

        ``prefetch=True`` wraps the loader in a
        :class:`~tpu_sandbox.data.loader.PrefetchLoader` (double-buffered
        background batch assembly) unless it already is one — same batches
        in the same order, assembled while the previous step runs.

        ``metrics_path`` writes a JSONL metrics record per log event; the
        writer's lifetime is the fit call (context-managed, so the fd
        closes on every exit path, raising included)."""
        loader = _maybe_prefetch(loader, prefetch)
        start = time.monotonic()
        total_step = len(loader)
        opt_step = int(jax.numpy.ravel(state.step)[0])  # resume-safe seed
        try:
            with (MetricsWriter(metrics_path) if metrics_path
                  else contextlib.nullcontext()) as mw:
                state = self._run_epochs(state, loader, epochs, set_epoch,
                                         total_step, opt_step, mw=mw)
        finally:
            if self._saver is not None:
                # drain in-flight async writes even when the loop raised —
                # an abandoned background save is an orphaned tmp dir, i.e.
                # a lost crash-recovery checkpoint
                self._saver.close()
                self._saver = None
        jax.block_until_ready(state)
        self.elapsed = timedelta(seconds=time.monotonic() - start)
        if self.verbose:
            print("Training complete in: " + str(self.elapsed))
        return state

    def _run_epochs(self, state, loader, epochs, set_epoch, total_step,
                    opt_step, mw=None):
        for epoch in range(epochs):
            if set_epoch:
                loader.set_epoch(epoch)
            for i, (images, labels) in enumerate(loader):
                t_step = time.monotonic()
                state, loss = self.train_step(state, images, labels)
                get_recorder().complete("train:step", t_step,
                                        args={"step": opt_step + 1})
                opt_step += 1
                self._maybe_checkpoint(state, opt_step)
                if (i + 1) % self.log_every == 0:
                    # DP steps return per-rank losses; log rank 0's, which is
                    # what the reference prints (mnist_distributed.py:104-106).
                    # In multi-controller runs the loss array spans processes;
                    # read this process's addressable shard instead.
                    if (
                        hasattr(loss, "is_fully_addressable")
                        and not loss.is_fully_addressable
                    ):
                        loss_host = loss.addressable_shards[0].data
                    else:
                        loss_host = loss
                    loss_val = float(jax.numpy.ravel(loss_host)[0])
                    self.losses.append(loss_val)
                    if mw is not None:
                        mw.write(opt_step, loss=loss_val, epoch=epoch + 1)
                    if self.verbose:
                        if self.log_rank is not None:
                            print(
                                "Rank [{}], Epoch [{}/{}], Step [{}/{}], Loss: {:.4f}".format(
                                    self.log_rank, epoch + 1, epochs, i + 1,
                                    total_step, loss_val,
                                )
                            )
                        else:
                            print(
                                "Epoch [{}/{}], Step [{}/{}], Loss: {:.4f}".format(
                                    epoch + 1, epochs, i + 1, total_step, loss_val
                                )
                            )
        return state


def _maybe_prefetch(loader, prefetch: bool):
    """Wrap ``loader`` for background prefetch when asked (idempotent)."""
    if not prefetch:
        return loader
    from tpu_sandbox.data.loader import PrefetchLoader

    if isinstance(loader, PrefetchLoader):
        return loader
    return PrefetchLoader(loader)


# -- train -> serve handoff -------------------------------------------------


def publish_checkpoint(kv, params, *, export_dir, step: int,
                       fleet: str = "", extra: dict | None = None,
                       compress: bool = False) -> int:
    """Seal ``params`` as a one-rank export and register it in the deploy
    model registry; returns the allocated version number. This is the
    trainer's side of the zero-downtime handoff: the export either seals
    completely (manifest written last) or raises — a torn artifact is
    never registered, and the DeployController re-verifies checksums
    before any replica is told to load it. Imports stay lazy so the plain
    training path never pulls in the deploy plane."""
    from tpu_sandbox.deploy.registry import publish_version
    from tpu_sandbox.train.checkpoint import export_params

    step_dir = export_params(export_dir, params, int(step), extra=extra,
                             compress=compress)
    return publish_version(kv, step_dir, fleet=fleet, step=int(step),
                           extra=extra)


# -- elastic / resumable training -----------------------------------------

class Preempted(RuntimeError):
    """Raised by ``train_resumable`` after a SIGTERM-initiated checkpoint:
    state is saved, the process should exit with ``exit_code`` so the
    supervisor restarts it without charging the restart budget."""

    exit_code = PREEMPTED_EXIT_CODE

    def __init__(self, step: int):
        super().__init__(
            f"preempted at optimizer step {step}; checkpoint saved"
        )
        self.step = step


class AbortOnAnomaly(RuntimeError):
    """``max_bad_steps`` consecutive non-finite losses: the run is
    diverging, not glitching — restarting would replay the same batches
    into the same blowup, so fail for real (charges the restart budget)."""


class PreemptionHandler:
    """SIGTERM → finish the in-flight step, checkpoint, exit preempted.

    The handler itself only flips a flag (a signal handler that touched
    the KV client could re-enter its request lock mid-call and deadlock);
    all real work happens at the next step boundary via :meth:`sync`,
    which also *propagates* the preemption through the KV store — in a
    multi-controller job the save must happen at the same boundary on
    every rank, and peers that never received the signal learn about it
    from the ``preempt/requested`` key.
    """

    def __init__(self, kv=None, key: str = PREEMPT_KEY):
        self.kv = kv
        self.key = key
        self._flag = False
        self._announced = False
        self._prev = None

    def install(self) -> "PreemptionHandler":
        try:
            self._prev = signal.signal(signal.SIGTERM, self._on_signal)
        except ValueError:
            self._prev = None  # not the main thread (tests); KV still works
        return self

    def uninstall(self) -> None:
        if self._prev is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev)
            except ValueError:
                pass
            self._prev = None

    def _on_signal(self, signum, frame) -> None:
        self._flag = True  # flag only — see class docstring

    def preempt_now(self) -> None:
        """Programmatic preemption (tests)."""
        self._flag = True

    def requested(self) -> bool:
        """True once this rank should stop: locally signaled or a peer
        announced through the store. Call at step boundaries only."""
        if self._flag:
            if self.kv is not None and not self._announced:
                try:
                    self.kv.set(self.key, b"1")
                except Exception:
                    pass  # store gone: still honor the local signal
                self._announced = True
            return True
        if self.kv is not None:
            try:
                if self.kv.try_get(self.key) is not None:
                    self._flag = True
                    return True
            except Exception:
                pass
        return False


def _loss_is_finite(loss) -> bool:
    """Finite check that works for scalars, per-rank loss vectors, and
    multi-controller global arrays (where the on-device reduction yields a
    replicated scalar, so every process reaches the same verdict)."""
    if isinstance(loss, jax.Array) and not loss.is_fully_addressable:
        return int((~jnp.isfinite(loss)).sum()) == 0
    return bool(np.isfinite(np.asarray(loss)).all())


def _host_loss(loss) -> float:
    if hasattr(loss, "is_fully_addressable") and not loss.is_fully_addressable:
        loss = loss.addressable_shards[0].data
    return float(np.ravel(np.asarray(loss))[0])


@dataclass
class ResumableReport:
    resumed_step: int | None  # optimizer step restored from, None = fresh
    start_epoch: int
    start_offset: int
    steps_applied: int  # optimizer updates this call actually performed
    skipped_nonfinite: int
    final_step: int
    losses: list[float]


def build_elastic_checkpoint(
    directory,
    *,
    dp,
    template,
    rank: int,
    world_size: int,
    sharded: bool | None = None,
    kv=None,
    injector=None,
    verify_interval: float = 0.0,
    commit_timeout: float = 60.0,
    generation: int | str | None = None,
    keep: int = 3,
    verbose: bool = True,
    compress: bool = False,
):
    """Build the (save_fn, restore_fn, verifier) triple ``train_resumable``
    consumes, picking the checkpoint backend for an elastic run.

    ``sharded=None`` auto-selects: ZeRO mode (``dp.zero``) *requires* the
    sharded backend — the rank-0-only ``HostCheckpoint`` would silently
    drop every other rank's optimizer shard — and plain DP defaults to it
    too unless explicitly disabled. ``sharded=False`` keeps the PR-1 npz
    path (single rank-0 writer, no manifests).

    - save: each rank hands its host-local view + placement spec to
      :class:`ShardedCheckpoint`; rank 0 seals with the manifest after the
      two-phase commit. ``injector.maybe_fire_commit`` is wired into the
      commit window so ``kill_during_commit`` faults land at the exact
      nastiest instants.
    - restore: reassemble + checksum-verify; at unchanged world size every
      rank gets its own BN-stats replica back bitwise, at a changed world
      size per-replica leaves fold to replica 0 and ZeRO optimizer shards
      are re-sliced for the new world (the cross-shard reshard).
    - verifier: a rank-0 :class:`CheckpointVerifier` when
      ``verify_interval`` > 0 (caller starts/stops it around training).
    """
    from tpu_sandbox.train.checkpoint import (
        CheckpointVerifier,
        HostCheckpoint,
        ShardedCheckpoint,
        fold_per_replica,
    )

    if sharded is None:
        sharded = True
    # Engines with step-persistent sync state (the compressed-gradient
    # error-feedback residual) extend the restore template here: leaves a
    # template does not name are never restored, so this must run before
    # either backend captures it.
    if hasattr(dp, "checkpoint_template"):
        template = dp.checkpoint_template(template)
    if dp.zero and not sharded:
        raise ValueError(
            "ZeRO optimizer-state sharding needs the sharded checkpoint "
            "backend: HostCheckpoint is rank-0-only and would lose every "
            "other rank's optimizer shard"
        )

    if not sharded:
        hc = HostCheckpoint(directory, keep=keep)

        def restore_fn():
            res = hc.restore(template)
            if res is None:
                return None
            host_state, meta = res
            return dp.shard_state(host_state), meta

        def save_fn(dstate, step, epoch, offset):
            if rank == 0:
                host = jax.tree.map(
                    lambda h, t: np.asarray(h).reshape(np.shape(t)),
                    dstate.host_view(), template,
                )
                hc.save(host, step, epoch=epoch, offset=offset)

        return save_fn, restore_fn, None

    sc = ShardedCheckpoint(
        directory, rank=rank, world_size=world_size, kv=kv, keep=keep,
        commit_timeout=commit_timeout, generation=generation,
        verbose=verbose, compress=compress,
    )

    def save_fn(dstate, step, epoch, offset):
        hook = None
        if injector is not None:
            def hook(phase, _step=step):
                injector.maybe_fire_commit(_step)
        sc.save(
            dstate.host_view(), dp.checkpoint_spec(dstate), step,
            epoch=epoch, offset=offset, commit_hook=hook,
        )

    def restore_fn():
        # Partial fast path: at unchanged world size each rank reads only
        # rank 0's shard and its own (2 files + 2 hash passes instead of
        # world_size) and places its blocks directly, skipping the global
        # reassembly buffer. Valid only when every process owns exactly
        # its own mesh slot; anything surprising — world changed, missing
        # shard, checksum mismatch — falls back to the full restore below,
        # which reshards across worlds and can quarantine a rotten step
        # and walk back to an older sealed one.
        if (hasattr(dp, "shard_state_local")
                and jax.process_count() == world_size
                and jax.local_device_count() == 1):
            try:
                res = sc.restore_partial(template)
            except Exception as e:
                if verbose:
                    print(f"[elastic] partial restore unavailable ({e}); "
                          "falling back to full restore", flush=True)
            else:
                if res is None:
                    return None
                local_state, meta = res
                return dp.shard_state_local(local_state, template), meta
        res = sc.restore(template)
        if res is None:
            return None
        host_state, meta = res
        if int(meta.get("world_size", world_size)) == world_size:
            # same world: place every rank's own BN replica back bitwise
            return dp.shard_state(host_state, stats_expanded=True), meta
        folded = fold_per_replica(host_state, template)
        return dp.shard_state(folded), meta

    verifier = None
    if verify_interval > 0 and rank == 0:
        verifier = CheckpointVerifier(sc, interval=verify_interval)
    return save_fn, restore_fn, verifier


def train_resumable(
    step_fn: Callable,
    state: TrainState,
    loader,
    epochs: int,
    *,
    save_fn: Callable[[TrainState, int, int, int], None] | None = None,
    restore_fn: Callable[[], tuple[TrainState, dict] | None] | None = None,
    ckpt_every: int = 0,
    preemption: PreemptionHandler | None = None,
    agree_fn: Callable[[bool], bool] | None = None,
    injector=None,
    max_bad_steps: int = 3,
    log_every: int = 100,
    log_rank: int | None = None,
    verbose: bool = True,
    set_epoch: bool = False,
    prefetch: bool = False,
) -> tuple[TrainState, ResumableReport]:
    """The crash-safe epoch loop: checkpoint every ``ckpt_every`` optimizer
    steps *with data-order state*, resume exactly where the stream stood,
    survive preemption, and refuse to train on garbage.

    - **Exact data order.** Each checkpoint records (epoch, batch offset);
      resume re-seeds the loader's deterministic per-epoch order and skips
      exactly the consumed batches — no batch replayed, none skipped. With
      ``save_fn=None`` the loop still runs (plain training with guards).
    - **Preemption.** ``preemption.requested()`` is polled every boundary;
      when set the in-flight step has already finished, so the loop saves
      and raises :class:`Preempted` — the caller exits with
      ``PREEMPTED_EXIT_CODE`` and the supervisor restarts for free.
      In a multi-controller job pass ``agree_fn`` (an OR-reduction across
      ranks, e.g. a tiny psum): the KV flag alone is racy — a peer can
      read its boundary a hair before the signaled rank announces, walk
      into the next step's collective, and block there forever. The
      collective vote forces every rank to the same verdict at the same
      boundary, so the whole world saves and exits 75 together.
    - **Anomaly guard.** A non-finite loss discards that update (the
      previous state is kept — ``step_fn`` must therefore NOT donate its
      input state; build engines with ``donate=False`` for elastic runs)
      and counts against ``max_bad_steps`` consecutive anomalies, after
      which :class:`AbortOnAnomaly` ends the run as a real failure. The
      per-step finite check syncs the loss to host, trading a little
      step-overlap for the guarantee — the resilience tax.
    - **Fault injection.** ``injector.maybe_fire(opt_step)`` runs after
      every applied update, so test faults land at exact, reproducible
      optimizer steps.

    ``restore_fn() -> (state, meta) | None`` and
    ``save_fn(state, step, epoch, offset)`` keep this loop agnostic of the
    checkpoint backend (orbax single-process, HostCheckpoint
    multi-controller) and of engine sharding.

    ``prefetch=True`` wraps the loader in a background
    :class:`~tpu_sandbox.data.loader.PrefetchLoader`. The prefetcher's
    determinism contract (same batches, same order, delegated
    ``set_epoch``) keeps the (epoch, offset) checkpoint metadata exact, so
    resume parity is unchanged — tested in tests/test_overlap.py.
    """
    loader = _maybe_prefetch(loader, prefetch)
    steps_per_epoch = len(loader)
    resumed_step = None
    start_epoch, start_offset = 0, 0
    if restore_fn is not None:
        res = restore_fn()
        if res is not None:
            state, meta = res
            resumed_step = int(meta.get("step", 0))
            # sidecar is authoritative; derive from the step count when it
            # is missing/corrupt (possible after a kill mid-sidecar-write)
            start_epoch = int(meta.get("epoch", resumed_step // steps_per_epoch))
            start_offset = int(
                meta.get("offset", resumed_step % steps_per_epoch)
            )
            if start_offset >= steps_per_epoch:
                start_epoch += 1
                start_offset = 0
    opt_step = resumed_step if resumed_step is not None else 0
    report = ResumableReport(
        resumed_step=resumed_step, start_epoch=start_epoch,
        start_offset=start_offset, steps_applied=0, skipped_nonfinite=0,
        final_step=opt_step, losses=[],
    )
    consecutive_bad = 0

    def checkpoint(epoch: int, offset: int) -> None:
        if save_fn is not None:
            save_fn(state, opt_step, epoch, offset)

    for epoch in range(start_epoch, epochs):
        if set_epoch:
            loader.set_epoch(epoch)
        for i, (images, labels) in enumerate(loader):
            if epoch == start_epoch and i < start_offset:
                continue  # consumed before the checkpoint: replay nothing
            new_state, loss = step_fn(state, images, labels)
            if _loss_is_finite(loss):
                state = new_state
                opt_step += 1
                report.steps_applied += 1
                consecutive_bad = 0
                applied = True
            else:
                report.skipped_nonfinite += 1
                consecutive_bad += 1
                applied = False
                if verbose:
                    print(
                        f"non-finite loss at epoch {epoch + 1} batch "
                        f"{i + 1}; update skipped "
                        f"({consecutive_bad}/{max_bad_steps} consecutive)"
                    )
                if consecutive_bad >= max_bad_steps:
                    raise AbortOnAnomaly(
                        f"{consecutive_bad} consecutive non-finite losses "
                        f"around optimizer step {opt_step}; aborting"
                    )
            saved_here = False
            if applied and ckpt_every and opt_step % ckpt_every == 0:
                checkpoint(epoch, i + 1)
                saved_here = True
            if injector is not None and applied:
                injector.maybe_fire(opt_step)
            if preemption is not None or agree_fn is not None:
                want = preemption is not None and preemption.requested()
                stop = agree_fn(want) if agree_fn is not None else want
                if stop:
                    if preemption is not None:
                        # a rank outvoted here (peer was signaled, we were
                        # not) must still exit with the preempted code
                        preemption.preempt_now()
                    if not saved_here:
                        checkpoint(epoch, i + 1)
                    report.final_step = opt_step
                    raise Preempted(opt_step)
            if applied and (i + 1) % log_every == 0:
                loss_val = _host_loss(loss)
                report.losses.append(loss_val)
                if verbose:
                    prefix = (
                        f"Rank [{log_rank}], " if log_rank is not None else ""
                    )
                    print(
                        "{}Epoch [{}/{}], Step [{}/{}], Loss: {:.4f}".format(
                            prefix, epoch + 1, epochs, i + 1,
                            steps_per_epoch, loss_val,
                        )
                    )
        start_offset = 0  # only the resumed epoch starts mid-stream
    report.final_step = opt_step
    return state, report
