"""Checkpoint / resume.

The reference has none (no torch.save/load anywhere — SURVEY §5
"checkpoint/resume: absent"); a framework needs it, and on TPU the
idiomatic tool is Orbax (async-capable, sharding-aware: a sharded
TrainState round-trips with its NamedShardings under the same mesh).

API: ``save(dir, state, step)`` / ``restore(dir, template, step=None)`` /
``latest_step(dir)``. The template provides structure, dtypes, and (if its
leaves are sharded) target shardings.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import tempfile
import threading
import time
import zipfile
from pathlib import Path

import jax
import numpy as np
import orbax.checkpoint as ocp

from tpu_sandbox.runtime.staging import stream_load_npz
from tpu_sandbox.train.state import TrainState


#: Parameter-layout generation stamped into every checkpoint directory.
#: "hcw" = the canonical (h, c, w) fc row order (models/convnet.py,
#: round 4). Checkpoints written before the stamp existed hold fc rows
#: in (h, w, c) order — same shapes, silently permuted values — so
#: restore refuses them loudly instead of resuming into garbage logits.
_LAYOUT = "fc-row-order=hcw"
_LAYOUT_FILE = "LAYOUT"


def _has_steps(directory: Path) -> bool:
    """Any pre-existing checkpoint content present? Conservative on
    purpose (ADVICE r04): pattern-matching numeric step names would let a
    future non-default orbax ``step_name_format`` (prefixed/padded step
    dirs) make a pre-canonical checkpoint directory look empty and slip
    a permuted-row fc kernel past the layout guard. Any child DIRECTORY
    counts as content (orbax steps are always directories, whatever the
    step_name_format); plain files (.gitkeep and friends) don't trip the
    guard."""
    return directory.is_dir() and any(
        p.is_dir() for p in directory.iterdir()
    )


def _layout_error(directory: Path, found: str) -> ValueError:
    return ValueError(
        f"checkpoint layout mismatch under {directory}: expected "
        f"'{_LAYOUT}', found '{found}'. The directory contains "
        "subdirectories but no layout stamp — either pre-canonical "
        "checkpoints (saved before the (h, c, w) fc row order: same "
        "shapes, silently permuted rows — restoring would corrupt the "
        "model; re-save from the original code or re-permute fc/kernel "
        "rows (h,w,c)->(h,c,w)) or unrecognized subdirectories this "
        "guard conservatively refuses to stamp over (point `directory` "
        "at a dedicated checkpoint dir). One benign cause: a run "
        "interrupted during its FIRST save leaves only "
        "'*.orbax-checkpoint-tmp-*' debris directories behind — if that "
        "is all you see here, just delete them and re-run; no fc "
        "re-permutation is involved."
    )


def _stamp_layout(directory: Path) -> None:
    f = directory / _LAYOUT_FILE
    if f.exists():
        _check_layout(directory)
    elif _has_steps(directory):
        # refusing to stamp an unstamped directory that already holds
        # steps: stamping would launder its pre-canonical checkpoints
        # past the very guard the stamp implements
        raise _layout_error(directory, "<missing, with existing steps>")
    else:
        directory.mkdir(parents=True, exist_ok=True)
        f.write_text(_LAYOUT + "\n")


def _check_layout(directory: Path) -> None:
    f = directory / _LAYOUT_FILE
    if not f.exists():
        if _has_steps(directory):
            raise _layout_error(directory, "<missing>")
        return  # empty/absent dir: let orbax report not-found clearly
    found = f.read_text().strip()
    if found != _LAYOUT:
        raise _layout_error(directory, found)


def _manager(directory: str | os.PathLike, create: bool = True) -> ocp.CheckpointManager:
    return ocp.CheckpointManager(
        Path(directory).absolute(),
        options=ocp.CheckpointManagerOptions(create=create, max_to_keep=3),
    )


def save(directory: str | os.PathLike, state: TrainState, step: int | None = None) -> int:
    """Write a checkpoint; returns the step it was saved under."""
    step = int(state.step) if step is None else step
    _stamp_layout(Path(directory).absolute())
    with _manager(directory) as mgr:
        mgr.save(step, args=ocp.args.StandardSave(state))
        mgr.wait_until_finished()
    return step


def latest_step(directory: str | os.PathLike) -> int | None:
    """Newest step orbax knows about. Hardened: a directory whose listing
    orbax cannot parse (stray junk dropped next to step dirs by a killed
    worker) degrades to a manual scan of numeric child dirs instead of
    crashing the restore path."""
    try:
        with _manager(directory, create=False) as mgr:
            return mgr.latest_step()
    except Exception:
        steps = _numeric_steps(directory)
        return max(steps) if steps else None


def _numeric_steps(directory: str | os.PathLike) -> list[int]:
    d = Path(directory)
    if not d.is_dir():
        return []
    return sorted(
        int(p.name) for p in d.iterdir() if p.is_dir() and p.name.isdigit()
    )


def quarantine_step(directory: str | os.PathLike, step: int) -> Path | None:
    """Move a broken step directory into ``<directory>.quarantine/`` (next
    to, never inside, the checkpoint dir — orbax must not rediscover it)
    so restore can fall back to an older step. Concurrent quarantiners
    (every rank restores at startup) race benignly: first rename wins,
    the rest see ENOENT and move on. Returns the new location, or None
    when someone else already moved it."""
    src = Path(directory).absolute() / str(step)
    qdir = src.parent.with_name(src.parent.name + ".quarantine")
    qdir.mkdir(parents=True, exist_ok=True)
    dst = qdir / src.name
    n = 0
    while dst.exists():  # same step quarantined twice across restarts
        n += 1
        dst = qdir / f"{src.name}.{n}"
    try:
        os.replace(src, dst)
    except OSError:
        return None
    print(f"checkpoint step {step} is broken; quarantined to {dst}",
          flush=True)
    return dst


# -- data-order sidecars ---------------------------------------------------
#
# Resume must replay no batch and skip none: alongside each checkpoint the
# trainer records where the data stream stood (epoch, batch offset within
# the epoch, optimizer step). Plain *files* in the checkpoint dir — orbax
# step discovery and the layout guard both only look at directories.

def save_data_state(
    directory: str | os.PathLike, step: int, *, epoch: int, offset: int,
    extra: dict | None = None,
) -> Path:
    d = Path(directory).absolute()
    d.mkdir(parents=True, exist_ok=True)
    payload = {"step": int(step), "epoch": int(epoch), "offset": int(offset)}
    payload.update(extra or {})
    final = d / f"data_state-{int(step)}.json"
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".json.tmp")
    with os.fdopen(fd, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, final)  # atomic: a kill mid-save never leaves half a file
    return final


def load_data_state(
    directory: str | os.PathLike, step: int
) -> dict | None:
    f = Path(directory).absolute() / f"data_state-{int(step)}.json"
    if not f.exists():
        return None
    try:
        return json.loads(f.read_text())
    except (OSError, json.JSONDecodeError):
        return None  # sidecar corrupt: caller derives order from the step


class AsyncSaver:
    """Keep one manager open and save WITHOUT blocking the training loop —
    orbax writes in the background while subsequent steps run. ``close()``
    (or exiting the context) waits for outstanding writes."""

    def __init__(self, directory: str | os.PathLike, max_to_keep: int = 3):
        _stamp_layout(Path(directory).absolute())
        self._mgr = ocp.CheckpointManager(
            Path(directory).absolute(),
            options=ocp.CheckpointManagerOptions(
                create=True, max_to_keep=max_to_keep,
                enable_async_checkpointing=True,
            ),
        )

    def save(self, state: TrainState, step: int) -> bool:
        """Returns False when orbax declined the save (e.g. the directory
        already holds a step >= ``step`` from an earlier run) — callers
        must not report success in that case."""
        return bool(self._mgr.save(step, args=ocp.args.StandardSave(state)))

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- HostCheckpoint: coordination-free save/restore for elastic runs -------

def _flatten_with_paths(tree) -> tuple[list[tuple[str, object]], object]:
    leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    def pstr(path):
        return "/".join(
            str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
            for p in path
        )
    return [(pstr(path), leaf) for path, leaf in leaves], treedef


def _to_savable(arr: np.ndarray) -> tuple[np.ndarray, str | None]:
    """npz can't hold the ml_dtypes (bfloat16/fp8: numpy kind 'V'); store
    them widened to float32 and remember the original dtype name. Exact:
    every bf16/fp8 value is representable in fp32."""
    if arr.dtype.kind == "V":
        return arr.astype(np.float32), arr.dtype.name
    return arr, None


def _from_savable(arr: np.ndarray, dtype_name: str | None) -> np.ndarray:
    if dtype_name is None:
        return arr
    import ml_dtypes

    return arr.astype(np.dtype(getattr(ml_dtypes, dtype_name)))


class HostCheckpoint:
    """Single-writer numpy checkpointing for multi-controller runs.

    Orbax's multi-controller save is a *collective* (global barriers at
    commit) — exactly what an elastic job cannot rely on, because ranks
    die mid-save and a barrier with a dead peer never completes. This
    class sidesteps the whole problem: rank 0 writes its host-local view
    of the state (params/opt are replicated, so rank 0's copy is the
    model; BN stats are rank 0's replica, the same single-device layout
    ``DataParallel.unshard_state`` checkpoints) as one ``step-<n>.npz``
    with an atomic rename, and every rank restores by reading that file —
    no cross-process coordination anywhere on the save/restore path.

    Restore verifies content integrity BEFORE parsing: every save writes a
    SHA-256 sidecar (``step-<n>.npz.sha256``) and restore re-hashes the
    npz against it first — a scribbled-but-still-valid zipfile (bitrot,
    fault injection, a partial copy with plausible contents) is caught
    here, where "does the zip parse" cannot see it. Files failing either
    check are renamed to ``*.corrupt`` (sidecar moved along with them —
    they are evidence) and the next older step is used. Files without a
    sidecar (pre-integrity checkpoints) still restore on load success.
    """

    def __init__(self, directory: str | os.PathLike, *, keep: int = 3):
        self.directory = Path(directory).absolute()
        self.keep = keep

    def _path(self, step: int) -> Path:
        return self.directory / f"step-{int(step):08d}.npz"

    def _sidecar(self, step: int) -> Path:
        return self.directory / f"step-{int(step):08d}.npz.sha256"

    def steps(self) -> list[int]:
        if not self.directory.is_dir():
            return []
        out = []
        for p in self.directory.glob("step-*.npz"):
            try:
                out.append(int(p.stem.split("-", 1)[1]))
            except ValueError:
                continue
        return sorted(out)

    def latest_step(self) -> int | None:
        s = self.steps()
        return s[-1] if s else None

    def save(self, local_state, step: int, *, epoch: int, offset: int,
             extra: dict | None = None) -> Path:
        """``local_state``: a fully host-addressable view (see
        ``TrainState.host_view``). Atomic: concurrent readers only ever
        see complete files."""
        self.directory.mkdir(parents=True, exist_ok=True)
        leaves, _ = _flatten_with_paths(local_state)
        arrays: dict[str, np.ndarray] = {}
        dtypes: dict[str, str] = {}
        for path, leaf in leaves:
            arr, orig = _to_savable(np.asarray(leaf))
            arrays[f"leaf:{path}"] = arr
            if orig is not None:
                dtypes[path] = orig
        meta = {"step": int(step), "epoch": int(epoch),
                "offset": int(offset), "dtypes": dtypes}
        meta.update(extra or {})
        arrays["__meta__"] = np.array(json.dumps(meta))
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".npz.tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **arrays)
            os.replace(tmp, self._path(step))
        except BaseException:
            Path(tmp).unlink(missing_ok=True)
            raise
        # Integrity sidecar, written AFTER the npz lands: a crash between
        # the two renames leaves a checkpoint without a sidecar (restorable,
        # just unverified — same posture as a pre-integrity file), never a
        # sidecar pointing at bytes that don't exist yet.
        digest = _sha256_file(self._path(step))
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".sha256.tmp")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(f"{digest}  {self._path(step).name}\n")
            os.replace(tmp, self._sidecar(step))
        except BaseException:
            Path(tmp).unlink(missing_ok=True)
            raise
        self._prune()
        return self._path(step)

    def _prune(self) -> None:
        for s in self.steps()[: -self.keep]:
            try:
                self._path(s).unlink()
            except OSError:
                pass
            self._sidecar(s).unlink(missing_ok=True)
            sidecar = self.directory / f"data_state-{s}.json"
            sidecar.unlink(missing_ok=True)

    def _load(self, step: int, template):
        problem = verify_npz_sidecar(self._path(step))
        if problem is not None:
            raise ValueError(problem)
        # chunk-streamed staging: each member lands directly in its
        # preallocated array instead of np.load's whole-member copies
        z = stream_load_npz(self._path(step))
        meta = json.loads(str(z["__meta__"]))
        leaves, treedef = _flatten_with_paths(template)
        restored = []
        for path, leaf in leaves:
            key = f"leaf:{path}"
            if key not in z:
                raise KeyError(f"checkpoint misses leaf {path!r}")
            arr = _from_savable(z[key], meta["dtypes"].get(path))
            want = np.shape(leaf)
            if tuple(arr.shape) != tuple(want):
                raise ValueError(
                    f"leaf {path!r}: checkpoint shape {arr.shape} != "
                    f"template shape {want}"
                )
            restored.append(arr)
        return jax.tree_util.tree_unflatten(treedef, restored), meta

    def restore(self, template, step: int | None = None):
        """-> (state, meta) from the newest *valid* step; ``None`` when the
        directory holds nothing restorable (fresh start). Broken files are
        renamed aside, never deleted — they are evidence."""
        if step is not None:
            return self._load(step, template)
        for s in reversed(self.steps()):
            try:
                return self._load(s, template)
            except Exception as e:
                bad = self._path(s)
                try:
                    os.replace(bad, bad.with_suffix(".npz.corrupt"))
                    print(
                        f"host checkpoint step {s} unreadable ({e!r}); "
                        f"quarantined to {bad.with_suffix('.npz.corrupt')}",
                        flush=True,
                    )
                except OSError:
                    pass  # concurrent restorer won the rename race
                else:
                    side = self._sidecar(s)
                    if side.exists():
                        try:  # keep the evidence pair together
                            os.replace(side, Path(str(side) + ".corrupt"))
                        except OSError:
                            pass
        return None


def verify_npz_sidecar(path: Path | str) -> str | None:
    """Re-hash ``path`` against its ``.sha256`` sidecar.

    Returns a human-readable problem description on mismatch (or on an
    unparseable sidecar), ``None`` when the hash matches or no sidecar
    exists — pre-integrity checkpoints stay restorable, their validity
    judged only by whether they parse. Shared by HostCheckpoint restore
    and ``tools/verify_ckpt.py``.
    """
    path = Path(path)
    side = Path(str(path) + ".sha256")
    if not side.exists():
        return None
    try:
        recorded = side.read_text().split()[0]
    except (OSError, IndexError):
        return f"sidecar {side.name} unreadable or empty"
    if len(recorded) != 64 or not all(c in "0123456789abcdef"
                                      for c in recorded.lower()):
        return f"sidecar {side.name} does not contain a sha256 digest"
    actual = _sha256_file(path)
    if actual != recorded:
        return (f"{path.name}: sha256 mismatch — sidecar records "
                f"{recorded[:12]}…, file hashes to {actual[:12]}…")
    return None


# -- ShardedCheckpoint: per-rank shards + manifest + two-phase commit ------
#
# HostCheckpoint's rank-0-only npz silently drops every non-replicated leaf
# that is not rank 0's (under ZeRO-1 that is (N-1)/N of the optimizer
# state), has no content integrity beyond "the zipfile parses", and its
# notion of "latest" is whatever file sorts last. This layer fixes all
# three:
#
#   <dir>/step-00000008/shard-00000.npz   rank 0's leaves
#   <dir>/step-00000008/shard-00001.npz   rank 1's leaves
#   <dir>/step-00000008/MANIFEST.json     the seal (written LAST, atomically)
#   <dir>.quarantine/step-.../            torn/corrupt steps, moved aside
#
# Commit is two-phase over the KV store and timeout-bounded (never a
# barrier — the orbax deadlock in ROADMAP is exactly a collective commit
# wedging on a dead rank):
#   phase 1: every rank writes its shard (tmp+rename), hashes it, and
#            claims ``ckpt/g<gen>/<step>/shard_done/<rank>`` with the hash.
#   phase 2: rank 0 publishes MANIFEST.json (shard list + SHA-256s + data-
#            order meta) only once all claims landed — or gives up at the
#            deadline, leaving the step unsealed.
# A kill at ANY instant therefore leaves either a sealed step (manifest
# present, every referenced shard complete) or a torn one (no manifest),
# and torn steps are quarantined, never restored from and never pruned
# into. Claims are generation-scoped and TTL'd so a restarted generation
# cannot match its predecessor's claims.

MANIFEST_NAME = "MANIFEST.json"
MANIFEST_FORMAT = "tpu-sandbox-sharded-ckpt-v1"

#: Leaf placement kinds recorded in the manifest's spec:
#:   "rep"    — replicated; stored once, in rank 0's shard.
#:   "shard0" — sharded on dim 0; each rank stores its block, restore
#:              concatenates blocks in rank order.
SPEC_KINDS = ("rep", "shard0")


def _sha256_file(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _npz_raw_bytes(path: Path) -> int | None:
    """Uncompressed payload size of an npz without inflating it: sum each
    member's .npy header (shape x dtype). None if any header is unreadable
    — callers record sizes opportunistically, never fail a commit on it."""
    try:
        total = 0
        with zipfile.ZipFile(path) as z:
            for name in z.namelist():
                with z.open(name) as f:
                    version = np.lib.format.read_magic(f)
                    shape, _, dtype = np.lib.format._read_array_header(
                        f, version
                    )
                    total += int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        return total
    except Exception:
        return None


def _step_dir_name(step: int) -> str:
    return f"step-{int(step):08d}"


def _parse_step_dir(p: Path) -> int | None:
    if not p.is_dir() or not p.name.startswith("step-"):
        return None
    tail = p.name.split("-", 1)[1]
    return int(tail) if tail.isdigit() else None


def verify_step_dir(step_dir: str | os.PathLike) -> list[str]:
    """Integrity report for one sharded step directory: ``[]`` means sealed
    and every shard re-hashes to its manifest entry. Problem strings are
    prefixed ``torn:`` (commit never completed — expected after a kill in
    the commit window) or ``corrupt:`` (sealed but the bytes changed —
    bitrot, scribbles, truncation). Module-level so tools/verify_ckpt.py
    and the in-process verifier share one notion of 'valid'."""
    sd = Path(step_dir)
    mf = sd / MANIFEST_NAME
    if not mf.exists():
        return ["torn: no manifest (commit never completed)"]
    try:
        manifest = json.loads(mf.read_text())
    except (OSError, json.JSONDecodeError) as e:
        return [f"corrupt: manifest unreadable ({e})"]
    if manifest.get("format") != MANIFEST_FORMAT:
        return [f"corrupt: unknown manifest format {manifest.get('format')!r}"]
    problems = []
    for sh in manifest.get("shards", []):
        f = sd / sh["file"]
        if not f.exists():
            problems.append(f"corrupt: shard {sh['rank']} missing ({sh['file']})")
            continue
        size = f.stat().st_size
        if size != sh["bytes"]:
            problems.append(
                f"corrupt: shard {sh['rank']} is {size} bytes, "
                f"manifest says {sh['bytes']}"
            )
            continue
        digest = _sha256_file(f)
        if digest != sh["sha256"]:
            problems.append(
                f"corrupt: shard {sh['rank']} sha256 {digest[:12]}... != "
                f"manifest {sh['sha256'][:12]}..."
            )
    return problems


class ShardedCheckpoint:
    """Every rank persists its own leaves; rank 0 seals the step.

    ``local_tree`` passed to :meth:`save` is this rank's host view of the
    state (``TrainState.host_view``: full value for replicated leaves, this
    rank's block for sharded ones); ``spec_tree`` mirrors it with "rep" /
    "shard0" kinds (``DataParallel.checkpoint_spec``). Restore reassembles
    (concatenating shard0 blocks in rank order — which also *reshards*
    across a world-size change, because the reassembled array is the full
    global value and placement happens downstream), verifying every shard's
    SHA-256 against the manifest before a single byte is parsed.

    ``kv=None`` degrades phase 1 to filesystem polling (rank 0 waits for
    all shard files and hashes them itself) — same commit guarantee on a
    shared local filesystem, used by single-process tests.
    """

    def __init__(
        self,
        directory: str | os.PathLike,
        *,
        rank: int,
        world_size: int,
        kv=None,
        keep: int = 3,
        commit_timeout: float = 60.0,
        poll: float = 0.02,
        generation: int | str | None = None,
        verbose: bool = True,
        compress: bool = False,
    ):
        """``compress=True`` writes shard files with zlib-deflated npz
        (``np.savez_compressed``). Restore is format-agnostic (``np.load``
        inflates transparently, so mixed-compression histories restore
        fine), and the SHA-256 in each claim/manifest is still over the
        bytes ON DISK — integrity verification never decompresses. The
        manifest records both on-disk and raw sizes per shard."""
        if not 0 <= rank < world_size:
            raise ValueError(f"rank {rank} outside world of {world_size}")
        self.directory = Path(directory).absolute()
        self.rank = rank
        self.world_size = world_size
        self.kv = kv
        self.keep = keep
        self.commit_timeout = commit_timeout
        self.poll = poll
        self.generation = str(generation) if generation is not None else "0"
        self.verbose = verbose
        self.compress = compress

    # -- paths / keys ------------------------------------------------------

    def step_dir(self, step: int) -> Path:
        return self.directory / _step_dir_name(step)

    def _shard_name(self, rank: int) -> str:
        return f"shard-{rank:05d}.npz"

    def _claim_key(self, step: int, rank: int) -> str:
        # generation-scoped: a relaunched generation re-reaching this step
        # must gather FRESH claims, not its dead predecessor's
        return f"ckpt/g{self.generation}/{int(step)}/shard_done/{rank}"

    def _log(self, msg: str) -> None:
        if self.verbose:
            print(f"[sharded-ckpt r{self.rank}] {msg}", flush=True)

    # -- step discovery ----------------------------------------------------

    def steps_on_disk(self) -> list[int]:
        if not self.directory.is_dir():
            return []
        out = []
        for p in self.directory.iterdir():
            s = _parse_step_dir(p)
            if s is not None:
                out.append(s)
        return sorted(out)

    def sealed_steps(self) -> list[int]:
        return [
            s for s in self.steps_on_disk()
            if (self.step_dir(s) / MANIFEST_NAME).exists()
        ]

    def latest_sealed_step(self) -> int | None:
        s = self.sealed_steps()
        return s[-1] if s else None

    # -- save (two-phase commit) -------------------------------------------

    def save(
        self,
        local_tree,
        spec_tree,
        step: int,
        *,
        epoch: int,
        offset: int,
        extra: dict | None = None,
        commit_hook=None,
    ) -> bool:
        """Phase 1 on every rank, phase 2 (the seal) on rank 0 only.

        Returns True when this rank's part of the commit completed (for
        rank 0: the manifest is sealed; for others: shard written and
        claimed — they cannot observe the seal and do not wait for it, or
        a dead rank 0 would wedge them). ``commit_hook(phase)`` is the
        fault-injection window: called with "claimed" after this rank's
        phase-1 claim, and on rank 0 with "sealing" after all claims landed
        but before the manifest rename — the worst possible kill instants.
        """
        sd = self.step_dir(step)
        sd.mkdir(parents=True, exist_ok=True)
        leaves, _ = _flatten_with_paths(local_tree)
        kinds = dict(_flatten_with_paths(spec_tree)[0])
        arrays: dict[str, np.ndarray] = {}
        dtypes: dict[str, str] = {}
        for path, leaf in leaves:
            kind = kinds[path]
            if kind not in SPEC_KINDS:
                raise ValueError(f"leaf {path!r}: unknown spec kind {kind!r}")
            if kind == "rep" and self.rank != 0:
                continue  # replicated leaves are stored once, by rank 0
            arr, orig = _to_savable(np.asarray(leaf))
            arrays[f"leaf:{path}"] = arr
            if orig is not None:
                dtypes[path] = orig
        arrays["__meta__"] = np.array(json.dumps(
            {"rank": self.rank, "step": int(step), "dtypes": dtypes}
        ))
        final = sd / self._shard_name(self.rank)
        fd, tmp = tempfile.mkstemp(dir=sd, suffix=".npz.tmp")
        saver = np.savez_compressed if self.compress else np.savez
        try:
            with os.fdopen(fd, "wb") as f:
                saver(f, **arrays)
            os.replace(tmp, final)
        except BaseException:
            Path(tmp).unlink(missing_ok=True)
            raise
        claim = {
            "rank": self.rank,
            "file": final.name,
            "sha256": _sha256_file(final),
            "bytes": final.stat().st_size,
            "raw_bytes": int(sum(a.nbytes for a in arrays.values())),
            "compressed": bool(self.compress),
        }
        if self.kv is not None:
            # TTL'd: a claim that outlives its commit window by far is
            # stale state on a long-lived server, never a commit input
            self.kv.set_ttl(
                self._claim_key(step, self.rank), json.dumps(claim),
                ttl=max(4 * self.commit_timeout, 60.0),
            )
        if self.rank != 0:
            if commit_hook is not None:
                commit_hook("claimed")
            return True
        if commit_hook is not None:
            commit_hook("claimed")
        shards = self._await_claims(step, own_claim=claim)
        if shards is None:
            self._log(
                f"step {step}: commit deadline ({self.commit_timeout}s) "
                "passed with shard claims missing; leaving the step "
                "UNSEALED (previous sealed step remains the restore point)"
            )
            return False
        if commit_hook is not None:
            commit_hook("sealing")
        manifest = {
            "format": MANIFEST_FORMAT,
            "step": int(step),
            "epoch": int(epoch),
            "offset": int(offset),
            "world_size": self.world_size,
            "shards": shards,
            "spec": {p: k for p, k in kinds.items()},
        }
        manifest.update(extra or {})
        mfd, mtmp = tempfile.mkstemp(dir=sd, suffix=".json.tmp")
        try:
            with os.fdopen(mfd, "w") as f:
                json.dump(manifest, f, indent=1)
            os.replace(mtmp, sd / MANIFEST_NAME)  # THE seal
        except BaseException:
            Path(mtmp).unlink(missing_ok=True)
            raise
        if self.kv is not None:
            try:
                self.kv.delete_prefix(f"ckpt/g{self.generation}/{int(step)}/")
            except Exception:
                pass  # TTL reaps them anyway
        self._prune()
        return True

    def _await_claims(self, step: int, own_claim: dict) -> list[dict] | None:
        """Rank 0's phase-2 wait: all ranks' claims, or None at the
        deadline. KV-less mode polls the filesystem and hashes the shard
        files itself once they are all renamed into place."""
        deadline = time.monotonic() + self.commit_timeout
        claims: dict[int, dict] = {0: own_claim}
        while True:
            for r in range(1, self.world_size):
                if r in claims:
                    continue
                if self.kv is not None:
                    raw = self.kv.try_get(self._claim_key(step, r))
                    if raw is not None:
                        claims[r] = json.loads(raw)
                else:
                    f = self.step_dir(step) / self._shard_name(r)
                    if f.exists() and not f.suffix == ".tmp":
                        claims[r] = {
                            "rank": r,
                            "file": f.name,
                            "sha256": _sha256_file(f),
                            "bytes": f.stat().st_size,
                            "raw_bytes": _npz_raw_bytes(f),
                            "compressed": bool(self.compress),
                        }
            if len(claims) == self.world_size:
                return [claims[r] for r in range(self.world_size)]
            if time.monotonic() >= deadline:
                return None
            time.sleep(self.poll)

    # -- restore -----------------------------------------------------------

    def restore(self, template, step: int | None = None):
        """-> (tree, meta) from the newest step that is sealed AND passes
        checksum verification; ``None`` when nothing restorable exists.
        Torn and corrupt steps are quarantined (moved aside as evidence)
        and the next older step is tried — the same fall-through contract
        as the corrupt-npz path, now checksum-backed. An explicit ``step``
        is strict: fail loud, quarantine nothing."""
        if step is not None:
            problems = verify_step_dir(self.step_dir(step))
            if problems:
                raise ValueError(
                    f"step {step} failed verification: {problems}"
                )
            return self._load(step, template)
        for s in reversed(self.steps_on_disk()):
            problems = verify_step_dir(self.step_dir(s))
            if problems:
                self._quarantine(s, "; ".join(problems))
                continue
            try:
                return self._load(s, template)
            except Exception as e:  # shapes/leaves wrong despite good hashes
                self._quarantine(s, repr(e))
        return None

    def _load(self, step: int, template):
        sd = self.step_dir(step)
        manifest = json.loads((sd / MANIFEST_NAME).read_text())
        spec: dict = manifest["spec"]
        shard_data: list[dict] = []
        shard_dtypes: list[dict] = []
        for sh in sorted(manifest["shards"], key=lambda s: s["rank"]):
            # chunk-streamed staging (the deploy swap path stages every
            # shard through here): no whole-file copy, no z[k].copy()
            z = stream_load_npz(sd / sh["file"])
            meta = json.loads(str(z["__meta__"]))
            shard_data.append(
                {k[len("leaf:"):]: z[k] for k in z
                 if k.startswith("leaf:")}
            )
            shard_dtypes.append(meta.get("dtypes", {}))
        leaves, treedef = _flatten_with_paths(template)
        restored = []
        for path, tleaf in leaves:
            kind = spec.get(path)
            if kind is None:
                raise KeyError(f"manifest misses leaf {path!r}")
            want = tuple(np.shape(tleaf))
            if kind == "rep":
                if path not in shard_data[0]:
                    raise KeyError(f"rank-0 shard misses leaf {path!r}")
                arr = _from_savable(
                    shard_data[0][path], shard_dtypes[0].get(path)
                )
                if tuple(arr.shape) != want:
                    raise ValueError(
                        f"leaf {path!r}: checkpoint shape {arr.shape} != "
                        f"template shape {want}"
                    )
            else:  # shard0: concatenate rank blocks -> the full global value
                blocks = []
                for r, data in enumerate(shard_data):
                    if path not in data:
                        raise KeyError(f"rank-{r} shard misses leaf {path!r}")
                    blocks.append(
                        _from_savable(data[path], shard_dtypes[r].get(path))
                    )
                arr = np.concatenate(blocks, axis=0)
                # exact match: a ZeRO'd optimizer leaf (global shape is
                # world-independent). (W, *want): a per-replica leaf (BN
                # stats) — kept expanded; fold_per_replica picks a replica
                # when the caller cannot place all of them.
                if tuple(arr.shape) != want and tuple(arr.shape[1:]) != want:
                    raise ValueError(
                        f"leaf {path!r}: reassembled shape {arr.shape} "
                        f"matches neither template {want} nor (world, *{want})"
                    )
            restored.append(arr)
        meta = {k: manifest[k] for k in ("step", "epoch", "offset",
                                         "world_size")}
        for k, v in manifest.items():
            if k not in ("format", "shards", "spec", *meta):
                meta[k] = v
        return jax.tree_util.tree_unflatten(treedef, restored), meta

    def restore_partial(self, template, step: int | None = None):
        """-> (tree, meta) reading only THIS RANK's slice of the step: rank
        0's shard (replicated leaves) plus this rank's own shard (sharded
        leaves hold the rank's saved block, not the reassembled global
        value) — two files and two hash passes instead of ``world_size``,
        the restart fast path when placement did not change. Requires the
        manifest's ``world_size`` to equal this instance's (``ValueError``
        otherwise — a changed world needs :meth:`restore`'s full
        reassembly, which is what reshards). Strict like explicit-step
        :meth:`restore`: any problem raises, nothing is quarantined and no
        older step is tried. ``step=None`` picks the latest sealed step
        (``None`` when there is none). Checksums are verified for exactly
        the shards read."""
        if step is None:
            step = self.latest_sealed_step()
            if step is None:
                return None
        sd = self.step_dir(step)
        mf = sd / MANIFEST_NAME
        if not mf.exists():
            raise ValueError(f"step {step} is not sealed (no manifest)")
        manifest = json.loads(mf.read_text())
        if manifest.get("format") != MANIFEST_FORMAT:
            raise ValueError(
                f"unknown manifest format {manifest.get('format')!r}"
            )
        if int(manifest["world_size"]) != self.world_size:
            raise ValueError(
                f"partial restore needs an unchanged world size: step "
                f"{step} was written by {manifest['world_size']} ranks, "
                f"this rank is {self.rank} of {self.world_size} — use "
                "restore(), whose full reassembly is what reshards"
            )
        by_rank = {sh["rank"]: sh for sh in manifest["shards"]}
        shard_data: dict[int, dict] = {}
        shard_dtypes: dict[int, dict] = {}
        for r in sorted({0, self.rank}):
            sh = by_rank.get(r)
            if sh is None:
                raise ValueError(f"manifest misses shard for rank {r}")
            f = sd / sh["file"]
            if not f.exists():
                raise ValueError(f"shard {r} missing ({sh['file']})")
            size = f.stat().st_size
            if size != sh["bytes"]:
                raise ValueError(
                    f"shard {r} is {size} bytes, manifest says {sh['bytes']}"
                )
            digest = _sha256_file(f)
            if digest != sh["sha256"]:
                raise ValueError(
                    f"shard {r} sha256 {digest[:12]}... != manifest "
                    f"{sh['sha256'][:12]}..."
                )
            z = stream_load_npz(f)
            meta = json.loads(str(z["__meta__"]))
            shard_data[r] = {
                k[len("leaf:"):]: z[k] for k in z
                if k.startswith("leaf:")
            }
            shard_dtypes[r] = meta.get("dtypes", {})
        spec: dict = manifest["spec"]
        leaves, treedef = _flatten_with_paths(template)
        restored = []
        for path, tleaf in leaves:
            kind = spec.get(path)
            if kind is None:
                raise KeyError(f"manifest misses leaf {path!r}")
            src = 0 if kind == "rep" else self.rank
            data = shard_data[src]
            if path not in data:
                raise KeyError(f"rank-{src} shard misses leaf {path!r}")
            arr = _from_savable(data[path], shard_dtypes[src].get(path))
            if kind == "rep" and tuple(arr.shape) != tuple(np.shape(tleaf)):
                raise ValueError(
                    f"leaf {path!r}: checkpoint shape {arr.shape} != "
                    f"template shape {tuple(np.shape(tleaf))}"
                )
            restored.append(arr)
        meta = {k: manifest[k] for k in ("step", "epoch", "offset",
                                         "world_size")}
        for k, v in manifest.items():
            if k not in ("format", "shards", "spec", *meta):
                meta[k] = v
        return jax.tree_util.tree_unflatten(treedef, restored), meta

    # -- quarantine / prune ------------------------------------------------

    def _quarantine(self, step: int, reason: str) -> Path | None:
        """Move a broken step directory to ``<dir>.quarantine/``. Every
        rank restores concurrently; first rename wins, losers see ENOENT
        and move on (same benign race as ``quarantine_step``)."""
        src = self.step_dir(step)
        qdir = self.directory.with_name(self.directory.name + ".quarantine")
        qdir.mkdir(parents=True, exist_ok=True)
        dst = qdir / src.name
        n = 0
        while dst.exists():
            n += 1
            dst = qdir / f"{src.name}.{n}"
        try:
            os.replace(src, dst)
        except OSError:
            return None
        self._log(f"step {step} quarantined to {dst}: {reason}")
        return dst

    def _prune(self) -> None:
        """Keep the newest ``keep`` SEALED steps. Older sealed steps are
        deleted; older torn steps are quarantined, never deleted — a torn
        step is evidence of a crash, and pruning must never be the thing
        that destroys the last copy of anything."""
        sealed = self.sealed_steps()
        if len(sealed) <= self.keep:
            cutoff = sealed[0] if sealed else None
        else:
            cutoff = sealed[-self.keep]
        if cutoff is None:
            return
        for s in self.steps_on_disk():
            if s >= cutoff:
                continue
            if s in sealed:
                shutil.rmtree(self.step_dir(s), ignore_errors=True)
            else:
                self._quarantine(s, "torn step older than the keep window")


def fold_per_replica(tree, template, index: int = 0):
    """Collapse restored per-replica leaves (shape ``(world, *t.shape)``)
    to one replica so the tree matches the unsharded template — the
    fallback when the world size changed across the restart and the saved
    replicas cannot be placed one-per-rank."""
    return jax.tree.map(
        lambda x, t: x[index] if np.shape(x) != np.shape(t) else x,
        tree, template,
    )


class CheckpointVerifier:
    """Background bitrot hunter: periodically re-hashes every *sealed* step
    against its manifest and quarantines any that no longer verify, so a
    silently-rotted step is pulled out of the fallback chain before it is
    the only step left (ROADMAP: "caught before the last good step is
    pruned"). Runs on rank 0 only — verification is read-mostly, and one
    quarantiner avoids rename storms."""

    def __init__(self, ckpt: ShardedCheckpoint, interval: float = 60.0):
        self.ckpt = ckpt
        self.interval = interval
        self.scans = 0
        self.corrupt_found: list[int] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def scan_once(self) -> list[int]:
        """One synchronous sweep; returns the steps quarantined. Public so
        tests (and the CLI) get determinism without thread timing."""
        bad = []
        for s in self.ckpt.sealed_steps():
            problems = verify_step_dir(self.ckpt.step_dir(s))
            if problems:
                self.ckpt._quarantine(
                    s, "verifier: " + "; ".join(problems)
                )
                bad.append(s)
        self.scans += 1
        self.corrupt_found.extend(bad)
        return bad

    def start(self) -> "CheckpointVerifier":
        if self._thread is not None:
            return self

        def run():
            while not self._stop.wait(self.interval):
                try:
                    self.scan_once()
                except Exception:
                    pass  # a transient FS error must not kill the thread

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


# -- single-rank export: the train->serve weight handoff ----------------------
#
# Serving replicas hold one host-addressable params tree; the deploy
# registry hands them sealed artifacts in the same ShardedCheckpoint
# format training already trusts (manifest + SHA-256 per shard, seal
# written last). Exports are always world_size=1 — the trainer collapses
# its sharded state to a host view first — so a replica restore is two
# files and one hash pass, with no cross-process commit to wait on.

def export_params(
    directory: str | os.PathLike, params, step: int, *,
    extra: dict | None = None, compress: bool = False,
) -> Path:
    """Seal ``params`` as a one-rank sharded step under ``directory`` and
    return the sealed step dir. Raises when the commit did not complete —
    an unsealed export must never be registered for promotion. Exports are
    never pruned here; lifecycle (GC of superseded versions) belongs to
    the deploy registry audit, which knows which versions are still
    rollback targets."""
    spec = jax.tree.map(lambda _: "rep", params)
    ck = ShardedCheckpoint(
        directory, rank=0, world_size=1, kv=None,
        keep=1_000_000_000, verbose=False, compress=compress,
    )
    ok = ck.save(params, spec, int(step), epoch=0, offset=0,
                 extra=dict(extra or {}, exported=True))
    if not ok:
        raise RuntimeError(
            f"export of step {step} under {directory} did not seal"
        )
    return ck.step_dir(int(step))


def load_exported_params(step_dir: str | os.PathLike, template):
    """Restore a sealed single-rank export (strict: checksum-verified,
    fail-loud) into ``template``'s structure. Returns the params tree."""
    sd = Path(step_dir).absolute()
    step = _parse_step_dir(sd)
    if step is None:
        raise ValueError(f"{step_dir} is not a step-XXXXXXXX directory")
    ck = ShardedCheckpoint(
        sd.parent, rank=0, world_size=1, kv=None, verbose=False,
    )
    tree, _meta = ck.restore(template, step=step)
    return tree


def restore(
    directory: str | os.PathLike, template: TrainState, step: int | None = None
) -> TrainState:
    """Restore into the template's structure (and shardings, if sharded).

    Hardened for the elastic world, where the supervisor routinely kills
    workers mid-save: when no explicit ``step`` is requested, a step that
    fails to load (partially written, corrupted) is *quarantined* (moved to
    ``<directory>.quarantine/``) and the next older step is tried, so the
    job restores the latest **valid** checkpoint instead of crash-looping
    on a broken one. An explicit ``step`` keeps strict fail-loud behavior.
    """
    if not Path(directory).is_dir():
        raise FileNotFoundError(f"no checkpoint directory at {directory}")
    _check_layout(Path(directory).absolute())
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
    if step is not None:
        with _manager(directory, create=False) as mgr:
            return mgr.restore(step, args=ocp.args.StandardRestore(abstract))
    last_err: Exception | None = None
    tried: set[int] = set()
    # bounded by the number of steps on disk; each attempt re-opens the
    # manager so quarantined dirs are really gone from its step listing
    while True:
        with _manager(directory, create=False) as mgr:
            cur = mgr.latest_step()
            if cur is None:
                if last_err is not None:
                    raise FileNotFoundError(
                        f"no *valid* checkpoints under {directory} "
                        f"(all steps quarantined; last error: {last_err!r})"
                    )
                raise FileNotFoundError(f"no checkpoints under {directory}")
            if cur in tried:
                # quarantine could not remove it (permissions?) — fail loud
                # instead of spinning on the same broken step
                raise last_err  # type: ignore[misc]
            try:
                return mgr.restore(
                    cur, args=ocp.args.StandardRestore(abstract)
                )
            except Exception as e:  # corrupt/partial step: quarantine, retry
                last_err = e
                tried.add(cur)
        quarantine_step(directory, cur)  # a lost race still unblocks retry
