"""Checkpoint / resume.

The reference has none (no torch.save/load anywhere — SURVEY §5
"checkpoint/resume: absent"); a framework needs it, and on TPU the
idiomatic tool is Orbax (async-capable, sharding-aware: a sharded
TrainState round-trips with its NamedShardings under the same mesh).

API: ``save(dir, state, step)`` / ``restore(dir, template, step=None)`` /
``latest_step(dir)``. The template provides structure, dtypes, and (if its
leaves are sharded) target shardings.
"""

from __future__ import annotations

import os
from pathlib import Path

import jax
import orbax.checkpoint as ocp

from tpu_sandbox.train.state import TrainState


#: Parameter-layout generation stamped into every checkpoint directory.
#: "hcw" = the canonical (h, c, w) fc row order (models/convnet.py,
#: round 4). Checkpoints written before the stamp existed hold fc rows
#: in (h, w, c) order — same shapes, silently permuted values — so
#: restore refuses them loudly instead of resuming into garbage logits.
_LAYOUT = "fc-row-order=hcw"
_LAYOUT_FILE = "LAYOUT"


def _has_steps(directory: Path) -> bool:
    """Any pre-existing checkpoint content present? Conservative on
    purpose (ADVICE r04): pattern-matching numeric step names would let a
    future non-default orbax ``step_name_format`` (prefixed/padded step
    dirs) make a pre-canonical checkpoint directory look empty and slip
    a permuted-row fc kernel past the layout guard. Any child DIRECTORY
    counts as content (orbax steps are always directories, whatever the
    step_name_format); plain files (.gitkeep and friends) don't trip the
    guard."""
    return directory.is_dir() and any(
        p.is_dir() for p in directory.iterdir()
    )


def _layout_error(directory: Path, found: str) -> ValueError:
    return ValueError(
        f"checkpoint layout mismatch under {directory}: expected "
        f"'{_LAYOUT}', found '{found}'. The directory contains "
        "subdirectories but no layout stamp — either pre-canonical "
        "checkpoints (saved before the (h, c, w) fc row order: same "
        "shapes, silently permuted rows — restoring would corrupt the "
        "model; re-save from the original code or re-permute fc/kernel "
        "rows (h,w,c)->(h,c,w)) or unrecognized subdirectories this "
        "guard conservatively refuses to stamp over (point `directory` "
        "at a dedicated checkpoint dir)."
    )


def _stamp_layout(directory: Path) -> None:
    f = directory / _LAYOUT_FILE
    if f.exists():
        _check_layout(directory)
    elif _has_steps(directory):
        # refusing to stamp an unstamped directory that already holds
        # steps: stamping would launder its pre-canonical checkpoints
        # past the very guard the stamp implements
        raise _layout_error(directory, "<missing, with existing steps>")
    else:
        directory.mkdir(parents=True, exist_ok=True)
        f.write_text(_LAYOUT + "\n")


def _check_layout(directory: Path) -> None:
    f = directory / _LAYOUT_FILE
    if not f.exists():
        if _has_steps(directory):
            raise _layout_error(directory, "<missing>")
        return  # empty/absent dir: let orbax report not-found clearly
    found = f.read_text().strip()
    if found != _LAYOUT:
        raise _layout_error(directory, found)


def _manager(directory: str | os.PathLike, create: bool = True) -> ocp.CheckpointManager:
    return ocp.CheckpointManager(
        Path(directory).absolute(),
        options=ocp.CheckpointManagerOptions(create=create, max_to_keep=3),
    )


def save(directory: str | os.PathLike, state: TrainState, step: int | None = None) -> int:
    """Write a checkpoint; returns the step it was saved under."""
    step = int(state.step) if step is None else step
    _stamp_layout(Path(directory).absolute())
    with _manager(directory) as mgr:
        mgr.save(step, args=ocp.args.StandardSave(state))
        mgr.wait_until_finished()
    return step


def latest_step(directory: str | os.PathLike) -> int | None:
    with _manager(directory, create=False) as mgr:
        return mgr.latest_step()


class AsyncSaver:
    """Keep one manager open and save WITHOUT blocking the training loop —
    orbax writes in the background while subsequent steps run. ``close()``
    (or exiting the context) waits for outstanding writes."""

    def __init__(self, directory: str | os.PathLike, max_to_keep: int = 3):
        _stamp_layout(Path(directory).absolute())
        self._mgr = ocp.CheckpointManager(
            Path(directory).absolute(),
            options=ocp.CheckpointManagerOptions(
                create=True, max_to_keep=max_to_keep,
                enable_async_checkpointing=True,
            ),
        )

    def save(self, state: TrainState, step: int) -> bool:
        """Returns False when orbax declined the save (e.g. the directory
        already holds a step >= ``step`` from an earlier run) — callers
        must not report success in that case."""
        return bool(self._mgr.save(step, args=ocp.args.StandardSave(state)))

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def restore(
    directory: str | os.PathLike, template: TrainState, step: int | None = None
) -> TrainState:
    """Restore into the template's structure (and shardings, if sharded)."""
    _check_layout(Path(directory).absolute())
    with _manager(directory, create=False) as mgr:
        if step is None:
            step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        return mgr.restore(step, args=ocp.args.StandardRestore(abstract))
