"""Checkpoint / resume.

The reference has none (no torch.save/load anywhere — SURVEY §5
"checkpoint/resume: absent"); a framework needs it, and on TPU the
idiomatic tool is Orbax (async-capable, sharding-aware: a sharded
TrainState round-trips with its NamedShardings under the same mesh).

API: ``save(dir, state, step)`` / ``restore(dir, template, step=None)`` /
``latest_step(dir)``. The template provides structure, dtypes, and (if its
leaves are sharded) target shardings.
"""

from __future__ import annotations

import json
import os
import tempfile
from pathlib import Path

import jax
import numpy as np
import orbax.checkpoint as ocp

from tpu_sandbox.train.state import TrainState


#: Parameter-layout generation stamped into every checkpoint directory.
#: "hcw" = the canonical (h, c, w) fc row order (models/convnet.py,
#: round 4). Checkpoints written before the stamp existed hold fc rows
#: in (h, w, c) order — same shapes, silently permuted values — so
#: restore refuses them loudly instead of resuming into garbage logits.
_LAYOUT = "fc-row-order=hcw"
_LAYOUT_FILE = "LAYOUT"


def _has_steps(directory: Path) -> bool:
    """Any pre-existing checkpoint content present? Conservative on
    purpose (ADVICE r04): pattern-matching numeric step names would let a
    future non-default orbax ``step_name_format`` (prefixed/padded step
    dirs) make a pre-canonical checkpoint directory look empty and slip
    a permuted-row fc kernel past the layout guard. Any child DIRECTORY
    counts as content (orbax steps are always directories, whatever the
    step_name_format); plain files (.gitkeep and friends) don't trip the
    guard."""
    return directory.is_dir() and any(
        p.is_dir() for p in directory.iterdir()
    )


def _layout_error(directory: Path, found: str) -> ValueError:
    return ValueError(
        f"checkpoint layout mismatch under {directory}: expected "
        f"'{_LAYOUT}', found '{found}'. The directory contains "
        "subdirectories but no layout stamp — either pre-canonical "
        "checkpoints (saved before the (h, c, w) fc row order: same "
        "shapes, silently permuted rows — restoring would corrupt the "
        "model; re-save from the original code or re-permute fc/kernel "
        "rows (h,w,c)->(h,c,w)) or unrecognized subdirectories this "
        "guard conservatively refuses to stamp over (point `directory` "
        "at a dedicated checkpoint dir)."
    )


def _stamp_layout(directory: Path) -> None:
    f = directory / _LAYOUT_FILE
    if f.exists():
        _check_layout(directory)
    elif _has_steps(directory):
        # refusing to stamp an unstamped directory that already holds
        # steps: stamping would launder its pre-canonical checkpoints
        # past the very guard the stamp implements
        raise _layout_error(directory, "<missing, with existing steps>")
    else:
        directory.mkdir(parents=True, exist_ok=True)
        f.write_text(_LAYOUT + "\n")


def _check_layout(directory: Path) -> None:
    f = directory / _LAYOUT_FILE
    if not f.exists():
        if _has_steps(directory):
            raise _layout_error(directory, "<missing>")
        return  # empty/absent dir: let orbax report not-found clearly
    found = f.read_text().strip()
    if found != _LAYOUT:
        raise _layout_error(directory, found)


def _manager(directory: str | os.PathLike, create: bool = True) -> ocp.CheckpointManager:
    return ocp.CheckpointManager(
        Path(directory).absolute(),
        options=ocp.CheckpointManagerOptions(create=create, max_to_keep=3),
    )


def save(directory: str | os.PathLike, state: TrainState, step: int | None = None) -> int:
    """Write a checkpoint; returns the step it was saved under."""
    step = int(state.step) if step is None else step
    _stamp_layout(Path(directory).absolute())
    with _manager(directory) as mgr:
        mgr.save(step, args=ocp.args.StandardSave(state))
        mgr.wait_until_finished()
    return step


def latest_step(directory: str | os.PathLike) -> int | None:
    """Newest step orbax knows about. Hardened: a directory whose listing
    orbax cannot parse (stray junk dropped next to step dirs by a killed
    worker) degrades to a manual scan of numeric child dirs instead of
    crashing the restore path."""
    try:
        with _manager(directory, create=False) as mgr:
            return mgr.latest_step()
    except Exception:
        steps = _numeric_steps(directory)
        return max(steps) if steps else None


def _numeric_steps(directory: str | os.PathLike) -> list[int]:
    d = Path(directory)
    if not d.is_dir():
        return []
    return sorted(
        int(p.name) for p in d.iterdir() if p.is_dir() and p.name.isdigit()
    )


def quarantine_step(directory: str | os.PathLike, step: int) -> Path | None:
    """Move a broken step directory into ``<directory>.quarantine/`` (next
    to, never inside, the checkpoint dir — orbax must not rediscover it)
    so restore can fall back to an older step. Concurrent quarantiners
    (every rank restores at startup) race benignly: first rename wins,
    the rest see ENOENT and move on. Returns the new location, or None
    when someone else already moved it."""
    src = Path(directory).absolute() / str(step)
    qdir = src.parent.with_name(src.parent.name + ".quarantine")
    qdir.mkdir(parents=True, exist_ok=True)
    dst = qdir / src.name
    n = 0
    while dst.exists():  # same step quarantined twice across restarts
        n += 1
        dst = qdir / f"{src.name}.{n}"
    try:
        os.replace(src, dst)
    except OSError:
        return None
    print(f"checkpoint step {step} is broken; quarantined to {dst}",
          flush=True)
    return dst


# -- data-order sidecars ---------------------------------------------------
#
# Resume must replay no batch and skip none: alongside each checkpoint the
# trainer records where the data stream stood (epoch, batch offset within
# the epoch, optimizer step). Plain *files* in the checkpoint dir — orbax
# step discovery and the layout guard both only look at directories.

def save_data_state(
    directory: str | os.PathLike, step: int, *, epoch: int, offset: int,
    extra: dict | None = None,
) -> Path:
    d = Path(directory).absolute()
    d.mkdir(parents=True, exist_ok=True)
    payload = {"step": int(step), "epoch": int(epoch), "offset": int(offset)}
    payload.update(extra or {})
    final = d / f"data_state-{int(step)}.json"
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".json.tmp")
    with os.fdopen(fd, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, final)  # atomic: a kill mid-save never leaves half a file
    return final


def load_data_state(
    directory: str | os.PathLike, step: int
) -> dict | None:
    f = Path(directory).absolute() / f"data_state-{int(step)}.json"
    if not f.exists():
        return None
    try:
        return json.loads(f.read_text())
    except (OSError, json.JSONDecodeError):
        return None  # sidecar corrupt: caller derives order from the step


class AsyncSaver:
    """Keep one manager open and save WITHOUT blocking the training loop —
    orbax writes in the background while subsequent steps run. ``close()``
    (or exiting the context) waits for outstanding writes."""

    def __init__(self, directory: str | os.PathLike, max_to_keep: int = 3):
        _stamp_layout(Path(directory).absolute())
        self._mgr = ocp.CheckpointManager(
            Path(directory).absolute(),
            options=ocp.CheckpointManagerOptions(
                create=True, max_to_keep=max_to_keep,
                enable_async_checkpointing=True,
            ),
        )

    def save(self, state: TrainState, step: int) -> bool:
        """Returns False when orbax declined the save (e.g. the directory
        already holds a step >= ``step`` from an earlier run) — callers
        must not report success in that case."""
        return bool(self._mgr.save(step, args=ocp.args.StandardSave(state)))

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- HostCheckpoint: coordination-free save/restore for elastic runs -------

def _flatten_with_paths(tree) -> tuple[list[tuple[str, object]], object]:
    leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    def pstr(path):
        return "/".join(
            str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
            for p in path
        )
    return [(pstr(path), leaf) for path, leaf in leaves], treedef


def _to_savable(arr: np.ndarray) -> tuple[np.ndarray, str | None]:
    """npz can't hold the ml_dtypes (bfloat16/fp8: numpy kind 'V'); store
    them widened to float32 and remember the original dtype name. Exact:
    every bf16/fp8 value is representable in fp32."""
    if arr.dtype.kind == "V":
        return arr.astype(np.float32), arr.dtype.name
    return arr, None


def _from_savable(arr: np.ndarray, dtype_name: str | None) -> np.ndarray:
    if dtype_name is None:
        return arr
    import ml_dtypes

    return arr.astype(np.dtype(getattr(ml_dtypes, dtype_name)))


class HostCheckpoint:
    """Single-writer numpy checkpointing for multi-controller runs.

    Orbax's multi-controller save is a *collective* (global barriers at
    commit) — exactly what an elastic job cannot rely on, because ranks
    die mid-save and a barrier with a dead peer never completes. This
    class sidesteps the whole problem: rank 0 writes its host-local view
    of the state (params/opt are replicated, so rank 0's copy is the
    model; BN stats are rank 0's replica, the same single-device layout
    ``DataParallel.unshard_state`` checkpoints) as one ``step-<n>.npz``
    with an atomic rename, and every rank restores by reading that file —
    no cross-process coordination anywhere on the save/restore path.

    Restore validates the newest file by actually loading it; a truncated
    or scribbled file (a worker killed mid-write can't produce one —
    that's the tmp+rename — but fault injection and disk trouble can) is
    renamed to ``*.corrupt`` and the next older step is used.
    """

    def __init__(self, directory: str | os.PathLike, *, keep: int = 3):
        self.directory = Path(directory).absolute()
        self.keep = keep

    def _path(self, step: int) -> Path:
        return self.directory / f"step-{int(step):08d}.npz"

    def steps(self) -> list[int]:
        if not self.directory.is_dir():
            return []
        out = []
        for p in self.directory.glob("step-*.npz"):
            try:
                out.append(int(p.stem.split("-", 1)[1]))
            except ValueError:
                continue
        return sorted(out)

    def latest_step(self) -> int | None:
        s = self.steps()
        return s[-1] if s else None

    def save(self, local_state, step: int, *, epoch: int, offset: int,
             extra: dict | None = None) -> Path:
        """``local_state``: a fully host-addressable view (see
        ``TrainState.host_view``). Atomic: concurrent readers only ever
        see complete files."""
        self.directory.mkdir(parents=True, exist_ok=True)
        leaves, _ = _flatten_with_paths(local_state)
        arrays: dict[str, np.ndarray] = {}
        dtypes: dict[str, str] = {}
        for path, leaf in leaves:
            arr, orig = _to_savable(np.asarray(leaf))
            arrays[f"leaf:{path}"] = arr
            if orig is not None:
                dtypes[path] = orig
        meta = {"step": int(step), "epoch": int(epoch),
                "offset": int(offset), "dtypes": dtypes}
        meta.update(extra or {})
        arrays["__meta__"] = np.array(json.dumps(meta))
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".npz.tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **arrays)
            os.replace(tmp, self._path(step))
        except BaseException:
            Path(tmp).unlink(missing_ok=True)
            raise
        self._prune()
        return self._path(step)

    def _prune(self) -> None:
        for s in self.steps()[: -self.keep]:
            try:
                self._path(s).unlink()
            except OSError:
                pass
            sidecar = self.directory / f"data_state-{s}.json"
            sidecar.unlink(missing_ok=True)

    def _load(self, step: int, template):
        with np.load(self._path(step), allow_pickle=False) as z:
            meta = json.loads(str(z["__meta__"]))
            leaves, treedef = _flatten_with_paths(template)
            restored = []
            for path, leaf in leaves:
                key = f"leaf:{path}"
                if key not in z:
                    raise KeyError(f"checkpoint misses leaf {path!r}")
                arr = _from_savable(z[key], meta["dtypes"].get(path))
                want = np.shape(leaf)
                if tuple(arr.shape) != tuple(want):
                    raise ValueError(
                        f"leaf {path!r}: checkpoint shape {arr.shape} != "
                        f"template shape {want}"
                    )
                restored.append(arr)
        return jax.tree_util.tree_unflatten(treedef, restored), meta

    def restore(self, template, step: int | None = None):
        """-> (state, meta) from the newest *valid* step; ``None`` when the
        directory holds nothing restorable (fresh start). Broken files are
        renamed aside, never deleted — they are evidence."""
        if step is not None:
            return self._load(step, template)
        for s in reversed(self.steps()):
            try:
                return self._load(s, template)
            except Exception as e:
                bad = self._path(s)
                try:
                    os.replace(bad, bad.with_suffix(".npz.corrupt"))
                    print(
                        f"host checkpoint step {s} unreadable ({e!r}); "
                        f"quarantined to {bad.with_suffix('.npz.corrupt')}",
                        flush=True,
                    )
                except OSError:
                    pass  # concurrent restorer won the rename race
        return None


def restore(
    directory: str | os.PathLike, template: TrainState, step: int | None = None
) -> TrainState:
    """Restore into the template's structure (and shardings, if sharded).

    Hardened for the elastic world, where the supervisor routinely kills
    workers mid-save: when no explicit ``step`` is requested, a step that
    fails to load (partially written, corrupted) is *quarantined* (moved to
    ``<directory>.quarantine/``) and the next older step is tried, so the
    job restores the latest **valid** checkpoint instead of crash-looping
    on a broken one. An explicit ``step`` keeps strict fail-loud behavior.
    """
    if not Path(directory).is_dir():
        raise FileNotFoundError(f"no checkpoint directory at {directory}")
    _check_layout(Path(directory).absolute())
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
    if step is not None:
        with _manager(directory, create=False) as mgr:
            return mgr.restore(step, args=ocp.args.StandardRestore(abstract))
    last_err: Exception | None = None
    tried: set[int] = set()
    # bounded by the number of steps on disk; each attempt re-opens the
    # manager so quarantined dirs are really gone from its step listing
    while True:
        with _manager(directory, create=False) as mgr:
            cur = mgr.latest_step()
            if cur is None:
                if last_err is not None:
                    raise FileNotFoundError(
                        f"no *valid* checkpoints under {directory} "
                        f"(all steps quarantined; last error: {last_err!r})"
                    )
                raise FileNotFoundError(f"no checkpoints under {directory}")
            if cur in tried:
                # quarantine could not remove it (permissions?) — fail loud
                # instead of spinning on the same broken step
                raise last_err  # type: ignore[misc]
            try:
                return mgr.restore(
                    cur, args=ocp.args.StandardRestore(abstract)
                )
            except Exception as e:  # corrupt/partial step: quarantine, retry
                last_err = e
                tried.add(cur)
        quarantine_step(directory, cur)  # a lost race still unblocks retry
