"""Checkpoint / resume.

The reference has none (no torch.save/load anywhere — SURVEY §5
"checkpoint/resume: absent"); a framework needs it, and on TPU the
idiomatic tool is Orbax (async-capable, sharding-aware: a sharded
TrainState round-trips with its NamedShardings under the same mesh).

API: ``save(dir, state, step)`` / ``restore(dir, template, step=None)`` /
``latest_step(dir)``. The template provides structure, dtypes, and (if its
leaves are sharded) target shardings.
"""

from __future__ import annotations

import os
from pathlib import Path

import jax
import orbax.checkpoint as ocp

from tpu_sandbox.train.state import TrainState


def _manager(directory: str | os.PathLike, create: bool = True) -> ocp.CheckpointManager:
    return ocp.CheckpointManager(
        Path(directory).absolute(),
        options=ocp.CheckpointManagerOptions(create=create, max_to_keep=3),
    )


def save(directory: str | os.PathLike, state: TrainState, step: int | None = None) -> int:
    """Write a checkpoint; returns the step it was saved under."""
    step = int(state.step) if step is None else step
    with _manager(directory) as mgr:
        mgr.save(step, args=ocp.args.StandardSave(state))
        mgr.wait_until_finished()
    return step


def latest_step(directory: str | os.PathLike) -> int | None:
    with _manager(directory, create=False) as mgr:
        return mgr.latest_step()


class AsyncSaver:
    """Keep one manager open and save WITHOUT blocking the training loop —
    orbax writes in the background while subsequent steps run. ``close()``
    (or exiting the context) waits for outstanding writes."""

    def __init__(self, directory: str | os.PathLike, max_to_keep: int = 3):
        self._mgr = ocp.CheckpointManager(
            Path(directory).absolute(),
            options=ocp.CheckpointManagerOptions(
                create=True, max_to_keep=max_to_keep,
                enable_async_checkpointing=True,
            ),
        )

    def save(self, state: TrainState, step: int) -> bool:
        """Returns False when orbax declined the save (e.g. the directory
        already holds a step >= ``step`` from an earlier run) — callers
        must not report success in that case."""
        return bool(self._mgr.save(step, args=ocp.args.StandardSave(state)))

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def restore(
    directory: str | os.PathLike, template: TrainState, step: int | None = None
) -> TrainState:
    """Restore into the template's structure (and shardings, if sharded)."""
    with _manager(directory, create=False) as mgr:
        if step is None:
            step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        return mgr.restore(step, args=ocp.args.StandardRestore(abstract))
