from tpu_sandbox.train.state import TrainState  # noqa: F401
from tpu_sandbox.train.trainer import (  # noqa: F401
    Trainer,
    make_train_step,
    prepare_inputs,
    resize_on_device,
)
