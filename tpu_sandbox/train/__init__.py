from tpu_sandbox.train.state import TrainState  # noqa: F401
from tpu_sandbox.train.trainer import (  # noqa: F401
    PREEMPTED_EXIT_CODE,
    AbortOnAnomaly,
    ElasticEnv,
    Preempted,
    PreemptionHandler,
    ResumableReport,
    Trainer,
    build_elastic_checkpoint,
    make_train_step,
    prepare_inputs,
    resize_on_device,
    train_resumable,
)
