from tpu_sandbox.train.state import TrainState  # noqa: F401
from tpu_sandbox.train.trainer import Trainer, make_train_step  # noqa: F401
