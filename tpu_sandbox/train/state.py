"""Train state: params + BN batch stats + optimizer state, as one pytree.

The reference's equivalents are scattered across mutable objects (the torch
module's parameters/buffers and the SGD optimizer's state, reference
mnist_onegpu.py:36-49); here they are one immutable pytree so the whole
update is a pure function XLA can fuse, donate, and shard.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
import optax
from flax import struct


@struct.dataclass
class TrainState:
    # grad_residual is the error-feedback buffer of the compressed gradient
    # sync (parallel/collectives.py::CompressedAllReduce): a param-shaped
    # fp32 pytree per rank, or None (an empty pytree node, so states built
    # before/without compression keep their leaf structure bit-for-bit).
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any
    grad_residual: Any = None

    @classmethod
    def create(cls, model, rng, sample_input, tx: optax.GradientTransformation):
        """Init by tracing (gives the reference's LazyLinear sizing without
        its CPU dummy-forward dance, mnist_onegpu.py:39)."""
        try:
            variables = model.init(rng, sample_input, train=False)
        except TypeError:  # model without a train-mode switch (e.g. the LM)
            variables = model.init(rng, sample_input)
        params = variables["params"]
        return cls(
            step=jax.numpy.zeros((), jax.numpy.int32),
            params=params,
            batch_stats=variables.get("batch_stats", {}),
            opt_state=tx.init(params),
        )

    def variables(self) -> dict:
        v = {"params": self.params}
        if self.batch_stats:
            v["batch_stats"] = self.batch_stats
        return v

    def host_view(self) -> "TrainState":
        """This process's host-local numpy copy of every leaf.

        For fully-addressable arrays that is the whole value; for
        multi-controller global arrays it is the first *addressable* shard
        — the full value for replicated leaves (params, opt state under
        plain DP), this process's block for sharded ones (its BN-stats
        replica). This is what elastic workers checkpoint: it needs no
        collective, so it still works while peer ranks are dead.
        """

        def to_host(leaf):
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                return np.asarray(leaf.addressable_shards[0].data)
            return np.asarray(leaf)

        return jax.tree.map(to_host, self)
