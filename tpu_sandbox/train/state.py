"""Train state: params + BN batch stats + optimizer state, as one pytree.

The reference's equivalents are scattered across mutable objects (the torch
module's parameters/buffers and the SGD optimizer's state, reference
mnist_onegpu.py:36-49); here they are one immutable pytree so the whole
update is a pure function XLA can fuse, donate, and shard.
"""

from __future__ import annotations

from typing import Any

import jax
import optax
from flax import struct


@struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any

    @classmethod
    def create(cls, model, rng, sample_input, tx: optax.GradientTransformation):
        """Init by tracing (gives the reference's LazyLinear sizing without
        its CPU dummy-forward dance, mnist_onegpu.py:39)."""
        try:
            variables = model.init(rng, sample_input, train=False)
        except TypeError:  # model without a train-mode switch (e.g. the LM)
            variables = model.init(rng, sample_input)
        params = variables["params"]
        return cls(
            step=jax.numpy.zeros((), jax.numpy.int32),
            params=params,
            batch_stats=variables.get("batch_stats", {}),
            opt_state=tx.init(params),
        )

    def variables(self) -> dict:
        v = {"params": self.params}
        if self.batch_stats:
            v["batch_stats"] = self.batch_stats
        return v
