"""tpu_sandbox — a TPU-native distributed-training sandbox.

A brand-new framework with the capabilities of the reference
``torch-distributed-sandbox`` (see SURVEY.md), designed TPU-first:

- runtime/   process bootstrap + rendezvous (jax.distributed) and device meshes
- parallel/  collectives, data/tensor/pipeline/sequence/expert parallelism
- models/    Flax model zoo (ConvNet parity model + transformer for the wider
             parallelism demos)
- ops/       custom ops (Pallas kernels) and numerics helpers
- data/      MNIST IDX reader, synthetic data, distributed samplers
- train/     single-device and distributed trainers
- utils/     logging, timing, config

The reference is a 4-script torch.distributed sandbox; everything heavyweight
there lives in PyTorch's C++/CUDA. Here the compute path is JAX/XLA (jit /
shard_map over an explicit `jax.sharding.Mesh`), and the runtime glue the
reference borrowed from PyTorch (rendezvous, samplers, data loading) is
implemented in-tree.
"""

__version__ = "0.1.0"

from tpu_sandbox import runtime  # noqa: F401
from tpu_sandbox.runtime import bootstrap, mesh  # noqa: F401
