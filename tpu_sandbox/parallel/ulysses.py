"""Ulysses-style sequence parallelism: all-to-all head/sequence swap.

The second of the two classic sequence-parallel attention schemes (the
ring is in parallel/ring_attention.py; SURVEY §2.2 lists both as absent
from the reference). Instead of rotating K/V blocks around a ring, each
device trades its sequence shard for a head shard with ONE all-to-all:

    [B, S/n, H, D]  --all_to_all(seq<->head)-->  [B, S, H/n, D]
    full-sequence attention on the local head subset (no masks to patch:
    every query sees the whole sequence)
    [B, S, H/n, D]  --all_to_all(head<->seq)-->  [B, S/n, H, D]

Tradeoffs vs the ring (why the framework ships both):
- Ulysses moves activations twice per attention with all-to-all (O(S·H·D/n)
  per device) regardless of sequence length; the ring moves K/V n-1 times
  but overlaps each hop with compute.
- Ulysses needs H % n == 0 (head-count bound on parallelism); the ring
  scales to any n that divides S.
- On TPU both map to native ICI collectives: AllToAll vs neighbor
  ppermute. For very long S with few heads use the ring; for many-head
  models the single all-to-all is usually cheaper.

Must run inside shard_map with ``axis_name`` bound, like ring_attention;
same ``attn(q, k, v)`` signature so models.transformer can inject either.
"""

from __future__ import annotations

from functools import partial

import jax.numpy as jnp

from tpu_sandbox.utils.compat import axis_size, shard_map
from jax import lax
from jax.sharding import Mesh

from tpu_sandbox.ops.attention import causal_attention


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    *,
    causal: bool = True,
    impl: str = "flash",
) -> jnp.ndarray:
    """q,k,v: local shards [B, S/n, H, D] (inside shard_map) -> same shape.

    ``impl`` picks the local attention after the head swap: ``"flash"``
    (default) streams K/V blocks through VMEM with the Pallas kernel —
    O(block²) memory, which is the whole point of sequence parallelism —
    while ``"dense"`` materializes the full [S, S] score matrix (kept for
    exact-parity tests only; VERDICT r01 weak #6 flagged dense-by-default
    as contradicting SP's purpose).
    """
    n = axis_size(axis_name)
    h = q.shape[2]
    if h % n:
        raise ValueError(
            f"ulysses needs heads % ranks == 0, got H={h}, n={n} "
            "(use ring attention for head-starved models)"
        )

    def seq_to_heads(x):  # [B, S/n, H, D] -> [B, S, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):  # [B, S, H/n, D] -> [B, S/n, H, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    if impl == "flash":
        from tpu_sandbox.ops.pallas_attention import flash_attention

        local_attn = partial(flash_attention, causal=causal)
    elif impl == "dense":
        local_attn = partial(causal_attention, causal=causal)
    else:
        raise ValueError(f"impl must be 'flash' or 'dense', got {impl!r}")

    out = local_attn(seq_to_heads(q), seq_to_heads(k), seq_to_heads(v))
    return heads_to_seq(out.astype(q.dtype))


def make_ulysses_attention(mesh: Mesh, axis: str, *, causal: bool = True,
                           impl: str = "flash"):
    """Standalone jit'd Ulysses attention over global [B, S, H, D] arrays
    sharded on dim 1 (mirror of make_ring_attention, tested against it)."""
    import jax
    from jax.sharding import PartitionSpec as P

    if axis not in mesh.axis_names:
        raise ValueError(f"axis {axis!r} not in mesh axes {mesh.axis_names}")
    fn = shard_map(
        partial(ulysses_attention, axis_name=axis, causal=causal, impl=impl),
        mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis),
        check_vma=False,  # pallas_call outputs carry no vma annotation
    )
    return jax.jit(fn)
