"""Sequence-parallel training: the full step under shard_map over
('data', 'sp') with ring attention.

Long sequences shard over 'sp': every device holds S/n_sp tokens of each
sequence (and B/n_dp sequences). Embeddings, LayerNorms and MLPs are
token-local so they need no communication; attention is the only op that
mixes positions and runs as a ring (parallel/ring_attention.py) over ICI
neighbors. Gradients psum over BOTH axes — data-parallel and
sequence-parallel reduce into the same mean because every token contributes
equally to the global-mean LM loss.

The reference has nothing like this (no sequence dimension at all, SURVEY
§5 "long-context: absent entirely"); it is the capability that makes the
framework long-context-ready, and it composes with the DP engine's design:
params replicated, batch (and here sequence) sharded, one jit'd step.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax

from tpu_sandbox.utils.compat import shard_map
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_sandbox.ops.losses import cross_entropy_loss
from tpu_sandbox.parallel.ring_attention import ring_attention
from tpu_sandbox.train.state import TrainState


class SeqParallel:
    """Train-step factory for TransformerLM over a ('data','sp') mesh."""

    def __init__(
        self,
        model_ctor: Callable[[Callable | None], "flax.linen.Module"],  # noqa: F821
        tx: optax.GradientTransformation,
        mesh: Mesh,
        *,
        data_axis: str = "data",
        seq_axis: str = "sp",
        attn: str = "ring",
        donate: bool = True,
    ):
        for ax in (data_axis, seq_axis):
            if ax not in mesh.axis_names:
                raise ValueError(f"axis {ax!r} not in mesh axes {mesh.axis_names}")
        self.mesh = mesh
        self.data_axis, self.seq_axis = data_axis, seq_axis
        self.tx = tx
        # the model used INSIDE shard_map: attention mixes positions across
        # the 'sp' shards — either K/V ring rotation or the Ulysses
        # all-to-all head/sequence swap (see parallel/ulysses.py for the
        # tradeoff between the two)
        if attn == "ring":
            sp_attn = partial(ring_attention, axis_name=seq_axis)
        elif attn == "ulysses":
            from tpu_sandbox.parallel.ulysses import ulysses_attention

            sp_attn = partial(ulysses_attention, axis_name=seq_axis)
        elif attn == "flash_ring":
            from tpu_sandbox.parallel.flash_ring import flash_ring_attention

            def sp_attn(q, k, v):
                return flash_ring_attention(q, k, v, seq_axis)
        else:
            raise ValueError(
                f"attn must be 'ring', 'ulysses' or 'flash_ring', got {attn!r}"
            )
        self.sp_model = model_ctor(sp_attn)
        # the same architecture with local attention (for init / eval)
        self.local_model = model_ctor(None)
        self._build(donate)

    def init_state(self, rng, sample_tokens) -> TrainState:
        """Init with the local-attention twin (identical params pytree)."""
        return TrainState.create(self.local_model, rng, sample_tokens, self.tx)

    def shard_state(self, state: TrainState) -> TrainState:
        return jax.device_put(state, NamedSharding(self.mesh, P()))

    def shard_batch(self, tokens, targets):
        """tokens/targets [B, S] -> sharded (tokens, targets, positions).

        Targets are the NEXT token (shift done on the host before sharding,
        so causality across shard boundaries is already correct).
        """
        b, s = tokens.shape
        positions = np.broadcast_to(np.arange(s, dtype=np.int32), (b, s))
        sh = NamedSharding(self.mesh, P(self.data_axis, self.seq_axis))
        return tuple(
            jax.device_put(jnp.asarray(x), sh) for x in (tokens, targets, positions)
        )

    def _build(self, donate: bool) -> None:
        model, tx = self.sp_model, self.tx
        daxis, saxis = self.data_axis, self.seq_axis

        def loss_fn(params, tokens, targets, positions):
            logits = model.apply({"params": params}, tokens, positions)
            return cross_entropy_loss(
                logits.reshape(-1, logits.shape[-1]), targets.reshape(-1)
            )

        def body(state: TrainState, tokens, targets, positions):
            loss, grads = jax.value_and_grad(loss_fn)(
                state.params, tokens, targets, positions
            )
            # mean over all shards: every token weighs equally (equal shard
            # sizes), so pmean over both axes == global-batch mean grad
            grads = lax.pmean(lax.pmean(grads, daxis), saxis)
            loss = lax.pmean(lax.pmean(loss, daxis), saxis)
            updates, new_opt = tx.update(grads, state.opt_state, state.params)
            return (
                state.replace(
                    step=state.step + 1,
                    params=optax.apply_updates(state.params, updates),
                    opt_state=new_opt,
                ),
                loss,
            )

        batch_spec = P(daxis, saxis)
        state_spec = TrainState(step=P(), params=P(), batch_stats=P(), opt_state=P())
        smapped = shard_map(
            body,
            mesh=self.mesh,
            in_specs=(state_spec, batch_spec, batch_spec, batch_spec),
            out_specs=(state_spec, P()),
            check_vma=False,  # replicated-out params: pmean'd grads guarantee it
        )
        self._jitted = jax.jit(smapped, donate_argnums=(0,) if donate else ())

    def train_step(self, state: TrainState, tokens, targets, positions):
        return self._jitted(state, tokens, targets, positions)

    def eval_logits(self, state: TrainState, tokens) -> jax.Array:
        """Single-stream (local attention) logits for parity checks."""
        return self.local_model.apply({"params": state.params}, tokens)
