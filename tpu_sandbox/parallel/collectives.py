"""Raw collectives over a device mesh — the TPU-native L3 layer.

Capability parity with the reference's collective surface
(``dist.all_reduce(SUM)`` at allreduce_toy.py:31, ``dist.barrier()`` at
allreduce_toy.py:33, implicit DDP param broadcast at mnist_distributed.py:67,
``dist.new_group`` at allreduce_toy.py:27 / mnist_distributed.py:100),
re-expressed the XLA way: a :class:`CollectiveGroup` binds a mesh axis once
(fixing the reference's group-per-step leak), and each collective is a jit'd
``shard_map`` whose body is a ``lax`` collective. XLA compiles these into
ICI/DCN ring or torus collectives — there is no user-level communicator
management, which is the point.

Data model: a "per-rank value" is an array whose leading dimension is the
group size, sharded over the group axis — rank i's tensor is row i. This is
the single-controller analogue of torch's one-tensor-per-process model; it
works identically on 8 virtual CPU devices, one real chip, or a pod slice.
"""

from __future__ import annotations

import dataclasses
from functools import cached_property, partial

import jax

from tpu_sandbox.utils.compat import shard_map
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class CollectiveGroup:
    """A set of devices that communicate — created once, reused every step.

    The reference creates a fresh ``dist.new_group`` every iteration
    (allreduce_toy.py:26-27); communicator setup is never free, so here the
    group (mesh axis binding + compiled collectives) is built once and every
    call reuses the jit cache.
    """

    def __init__(self, mesh: Mesh, axis: str | None = None):
        if axis is None:
            if len(mesh.axis_names) != 1:
                raise ValueError(
                    f"mesh has axes {mesh.axis_names}; pass axis= explicitly"
                )
            axis = mesh.axis_names[0]
        if axis not in mesh.axis_names:
            raise ValueError(f"axis {axis!r} not in mesh axes {mesh.axis_names}")
        self.mesh = mesh
        self.axis = axis
        self.size = mesh.shape[axis]

    # -- sharding helpers ---------------------------------------------------

    @cached_property
    def ranked_sharding(self) -> NamedSharding:
        """Leading dim = rank over the group axis."""
        return NamedSharding(self.mesh, P(self.axis))

    def put(self, values) -> jax.Array:
        """Place a host array of per-rank values (leading dim == group size)."""
        values = jnp.asarray(values)
        if values.shape[0] % self.size:
            raise ValueError(
                f"leading dim {values.shape[0]} not divisible by group size {self.size}"
            )
        return jax.device_put(values, self.ranked_sharding)

    def _smap(self, f, out_specs, check_vma: bool = True):
        # check_vma=False where the body provably replicates its output
        # (all_gather/broadcast) but jax's varying-mesh-axes analysis can't
        # statically see it.
        return jax.jit(
            shard_map(
                f,
                mesh=self.mesh,
                in_specs=P(self.axis),
                out_specs=out_specs,
                check_vma=check_vma,
            )
        )

    # -- collectives --------------------------------------------------------

    @cached_property
    def _all_reduce_fns(self):
        def make(reducer):
            return self._smap(partial(reducer, axis_name=self.axis), P(self.axis))

        return {
            "sum": make(lax.psum),
            "mean": make(lax.pmean),
            "max": make(lax.pmax),
            "min": make(lax.pmin),
        }

    def all_reduce(self, values, op: str = "sum") -> jax.Array:
        """Elementwise reduce across ranks; every rank sees the result.

        Parity: ``dist.all_reduce(tensor, ReduceOp.SUM)`` (allreduce_toy.py:31)
        and the dead commented-out AVG loss reduce (mnist_distributed.py:102).
        """
        if op not in self._all_reduce_fns:
            raise ValueError(f"op {op!r} not in {sorted(self._all_reduce_fns)}")
        return self._all_reduce_fns[op](self.put(values))

    @cached_property
    def _all_gather_fn(self):
        return self._smap(
            lambda x: lax.all_gather(x, self.axis, axis=0, tiled=True),
            P(),
            check_vma=False,
        )

    def all_gather(self, values) -> jax.Array:
        """Every rank receives the concatenation of all ranks' rows."""
        return self._all_gather_fn(self.put(values))

    @cached_property
    def _reduce_scatter_fn(self):
        return self._smap(
            lambda x: lax.psum_scatter(x, self.axis, scatter_dimension=1, tiled=True),
            P(self.axis),
        )

    def reduce_scatter(self, values) -> jax.Array:
        """Each rank contributes a full payload (its row); the rows are
        summed and rank i keeps the i-th 1/size slice of the sum.

        ``values``: shape ``(size, m)`` with ``m % size == 0``; returns
        shape ``(size, m // size)`` where row i is slice i of the sum.
        """
        values = jnp.asarray(values)
        if values.ndim != 2 or values.shape[1] % self.size:
            raise ValueError(
                f"reduce_scatter wants shape (size, m) with m % {self.size} == 0, "
                f"got {values.shape}"
            )
        return self._reduce_scatter_fn(self.put(values))

    @cached_property
    def _broadcast_fn(self):
        def body(x, root):
            full = lax.all_gather(x, self.axis, axis=0, tiled=True)
            return lax.dynamic_index_in_dim(full, root, axis=0, keepdims=False)

        return jax.jit(
            shard_map(
                body,
                mesh=self.mesh,
                in_specs=(P(self.axis), P()),
                out_specs=P(),
                check_vma=False,
            )
        )

    def broadcast(self, values, root: int = 0) -> jax.Array:
        """All ranks receive rank ``root``'s row — DDP's initial param sync
        (mnist_distributed.py:67) as an explicit collective."""
        return self._broadcast_fn(self.put(values), jnp.asarray(root))

    def _shift_fn(self, offset: int):
        cache = self.__dict__.setdefault("_shift_cache", {})
        if offset not in cache:
            perm = [(i, (i + offset) % self.size) for i in range(self.size)]
            cache[offset] = self._smap(
                lambda x: lax.ppermute(x, self.axis, perm), P(self.axis)
            )
        return cache[offset]

    def shift(self, values, offset: int = 1) -> jax.Array:
        """Ring permute: rank i's row moves to rank (i+offset) % size.

        The primitive under ring attention / pipeline p2p — no torch analogue
        in the reference (it has no send/recv), included because rings are
        how TPU ICI wants to move data."""
        return self._shift_fn(offset)(self.put(values))

    @cached_property
    def _all_to_all_fn(self):
        # local block is [1, size, ...]; drop the sharded leading dim, trade
        # sub-row j to rank j, restack what arrived, restore the leading dim
        return self._smap(
            lambda x: lax.all_to_all(
                x[0], self.axis, split_axis=0, concat_axis=0, tiled=True
            )[None],
            P(self.axis),
        )

    def all_to_all(self, values) -> jax.Array:
        """Transpose rows across ranks: rank i sends chunk j of its row-block
        to rank j. ``values``: shape ``(size, size, ...)`` — rank i holds
        block ``values[i]`` whose j-th sub-row goes to rank j; returns the
        same shape with ``out[j, i] = values[i, j]``.

        The primitive under expert dispatch (MoE) and Ulysses-style
        sequence parallelism; maps to one XLA AllToAll on the ICI fabric.
        No torch analogue in the reference (SURVEY §2.2 "EP: no all_to_all").
        """
        values = jnp.asarray(values)
        if values.ndim < 2 or values.shape[0] != self.size or (
            values.shape[1] != self.size
        ):
            raise ValueError(
                f"all_to_all wants shape (size, size, ...), got {values.shape}"
            )
        return self._all_to_all_fn(self.put(values))

    def compressed_all_reduce(self, values, policy) -> jax.Array:
        """Mean across ranks under a :class:`CompressedAllReduce` policy
        (stateless surface — no error-feedback residual is carried here;
        the engines thread that through :class:`TrainState`)."""
        policy = as_compress_policy(policy)
        cache = self.__dict__.setdefault("_compress_cache", {})
        if policy not in cache:
            def body(x):
                mean, _ = policy.pmean(x[0], self.axis, self.size, None)
                return mean[None]

            cache[policy] = self._smap(body, P(self.axis), check_vma=False)
        return cache[policy](self.put(values))

    @cached_property
    def _barrier_fn(self):
        return self._smap(lambda x: lax.psum(x, self.axis), P())

    def barrier(self) -> None:
        """Block the host until every device in the group has participated.

        Parity: ``dist.barrier()`` (allreduce_toy.py:33). A psum of a unit
        token; host-blocks on the result.
        """
        token = self.put(jnp.ones((self.size,), jnp.int32))
        self._barrier_fn(token).block_until_ready()

    # -- microbenchmark -----------------------------------------------------

    def allreduce_bandwidth(self, nbytes: int = 1 << 26, iters: int = 10) -> dict:
        """All-reduce bus bandwidth — the north-star metric BASELINE.md names.

        Returns algorithm bandwidth (payload/time) and bus bandwidth
        (algbw * 2*(n-1)/n — the standard ring-allreduce accounting, which
        is what NCCL reports for the reference's fabric).

        Timing is fetch-synced and differential (see
        utils/profiling.py::measure_per_step): each iteration's input is the
        previous iteration's output (mean keeps values stable), so no
        iteration can be elided, and the only trusted sync — a device->host
        scalar fetch — ends each timed run. ``block_until_ready`` is NOT
        used: on async-dispatch platforms (the axon TPU tunnel) it returns
        before the device executes, which is how r01 published an unreal
        headline number.
        """
        from tpu_sandbox.utils.profiling import measure_per_step

        n = self.size
        elems = max(nbytes // 4, n)
        elems -= elems % n
        x = self.put(jnp.ones((n, elems // n), jnp.float32))
        fn = self._all_reduce_fns["mean"]

        # k collectives chained INSIDE one program per timed call: a
        # Python-level launch loop dispatches k separate multi-device
        # programs back-to-back, and XLA:CPU's per-launch participant
        # rendezvous can deadlock when the host has fewer cores than
        # devices (~30% of runs on a 1-core/8-device box). In-program
        # collectives are cooperative — the same shape as a train step —
        # and fori_loop keeps every iteration data-dependent, so no
        # iteration can be elided.
        @partial(jax.jit, static_argnums=0)
        def run_k(k, v):
            return jax.lax.fori_loop(0, k, lambda _, o: fn(o), v)

        def run(k):
            return run_k(k, x)

        timing = measure_per_step(run, iters)
        if timing["sec_per_step"] <= 0:
            # tiny payloads + timing noise can turn the differential
            # negative; amortize over more iterations before giving up
            timing = measure_per_step(run, iters * 8)
        dt = timing["sec_per_step"]
        ok = dt > 0
        algbw = elems * 4 / dt if ok else 0.0
        busbw = algbw * (2 * (n - 1) / n)
        result = {
            "bytes": elems * 4,
            "seconds": dt,
            "algbw_GBps": algbw / 1e9,
            "busbw_GBps": busbw / 1e9,
            "timing_method": timing["timing_method"],
        }
        if not ok:
            result["degraded"] = (
                f"non-positive differential ({dt:.3e}s) even at "
                f"{iters * 8} iters; no bandwidth published"
            )
        return result


# -- compressed gradient synchronization ------------------------------------
#
# At pod scale the gradient all-reduce is DCN-bandwidth-bound while the chip
# idles (EQuARX, arxiv 2506.17615). These helpers shrink the wire payload:
# a bf16 cast (2x) or an int8 block-scaled two-shot exchange (~4x), with an
# optional error-feedback residual so quantization error is re-injected into
# the next step's gradient instead of lost.

_COMPRESS_MODES = ("none", "bf16", "int8")


def _quantize_int8_blocks(v):
    """Symmetric per-block int8: ``v`` is fp32 ``[..., block]``; returns
    ``(q int8, scale fp32 [..., 1])`` with scale = blockwise absmax / 127
    (guarded so an all-zero block dequantizes to exact zeros)."""
    s = jnp.max(jnp.abs(v), axis=-1, keepdims=True) / 127.0
    safe = jnp.where(s > 0, s, 1.0)
    q = jnp.clip(jnp.round(v / safe), -127, 127).astype(jnp.int8)
    return q, s


def int8_block_pmean(value, residual, axis_name, size: int, block: int):
    """Block-quantized mean over ``axis_name`` for one array, inside
    ``shard_map``. Returns ``(mean, new_residual)``.

    Two-shot exchange so accumulation happens in fp32 master precision,
    never int8:

    1. flatten + residual, pad to ``size * chunk`` (chunk block-aligned),
       quantize ``[size, nb, block]`` and ``all_to_all`` — the quantized
       spelling of reduce-scatter: rank j receives every rank's chunk j;
    2. dequantize, accumulate the mean in fp32, REquantize the owned chunk
       and ``all_gather`` it back — the second shot.

    Error feedback (``residual`` not None): the returned residual carries
    rank-local shot-1 error plus ``size *`` shot-2 error injected only at
    this rank's own chunk, so summing residuals across ranks next step
    re-injects exactly what this step's mean dropped — the compression
    telescopes instead of biasing the trajectory.
    """
    shape, dtype = value.shape, value.dtype
    flat = value.astype(jnp.float32).reshape(-1)
    n = flat.size
    if residual is not None:
        flat = flat + residual.reshape(-1).astype(jnp.float32)
    chunk = -(-n // (size * block)) * block
    pad = size * chunk - n
    v = jnp.pad(flat, (0, pad)).reshape(size, chunk // block, block)
    q, s = _quantize_int8_blocks(v)
    qx = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0, tiled=True)
    sx = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0, tiled=True)
    red = jnp.sum(qx.astype(jnp.float32) * sx, axis=0) / size  # [nb, block]
    q2, s2 = _quantize_int8_blocks(red)
    q2g = lax.all_gather(q2, axis_name, axis=0, tiled=True)
    s2g = lax.all_gather(s2, axis_name, axis=0, tiled=True)
    mean = (
        (q2g.astype(jnp.float32) * s2g).reshape(-1)[:n]
        .reshape(shape).astype(dtype)
    )
    if residual is None:
        return mean, None
    err1 = v - q.astype(jnp.float32) * s
    err2 = red - q2.astype(jnp.float32) * s2
    rows = lax.broadcasted_iota(jnp.int32, (size, 1, 1), 0)
    inj = jnp.where(rows == lax.axis_index(axis_name), err2[None] * size, 0.0)
    new_res = (
        (err1 + inj).reshape(-1)[: n + pad][:n]
        .reshape(shape).astype(residual.dtype)
    )
    return mean, new_res


@dataclasses.dataclass(frozen=True)
class CompressedAllReduce:
    """Gradient-sync compression policy, shared by the parallel engines.

    ``mode``:
      - ``"none"``: plain fp32 ``lax.pmean`` — byte-for-byte today's path;
      - ``"bf16"``: cast to bf16, pmean, cast back (2x payload reduction,
        no state);
      - ``"int8"``: :func:`int8_block_pmean` (~4x payload reduction;
        pair with ``error_feedback`` for fp32-tracking convergence).

    ``block``: int8 scale granularity; one fp32 scale per ``block`` elements
    (overhead ``4 / block`` bytes/element on the wire). Chunks are sized to
    the group axis so every rank owns an aligned slice in shot 2.

    ``error_feedback``: only meaningful for int8 — the engine must then
    carry a param-shaped residual pytree across steps
    (:attr:`needs_residual`).
    """

    mode: str = "none"
    block: int = 256
    error_feedback: bool = True

    def __post_init__(self):
        if self.mode not in _COMPRESS_MODES:
            raise ValueError(
                f"grad_compress mode {self.mode!r} not in {_COMPRESS_MODES}"
            )
        if self.block < 1:
            raise ValueError(f"block must be positive, got {self.block}")

    @property
    def needs_residual(self) -> bool:
        return self.mode == "int8" and self.error_feedback

    def block_for(self, n: int, size: int) -> int:
        """Per-leaf int8 block size over the sync axis (``size`` is the
        mesh's slowest — DCN at pod scale — axis, the one the exchange
        crosses). ``block`` is the ceiling; leaves whose per-rank chunk is
        smaller than one block shrink it by halving (floor 8), because
        :func:`int8_block_pmean` pads each rank's chunk to a block multiple
        and a 16-element bias padded to 256 would ship 16x its payload in
        alignment zeros. Leaves at or above one block per rank keep the
        configured granularity (and its ``4 / block`` scale overhead)."""
        per_rank = -(-int(n) // size)
        b = self.block
        while b > 8 and b > per_rank:
            b //= 2
        return b

    def pmean(self, value, axis_name, size: int, residual=None):
        """Compressed mean of one array across ``axis_name`` (inside
        ``shard_map``). Returns ``(mean, new_residual)``."""
        if self.mode == "none":
            return lax.pmean(value, axis_name), residual
        if self.mode == "bf16":
            return (
                lax.pmean(value.astype(jnp.bfloat16), axis_name)
                .astype(value.dtype),
                residual,
            )
        if not self.error_feedback:
            residual = None
        return int8_block_pmean(
            value, residual, axis_name, size, self.block_for(value.size, size)
        )

    def pmean_tree(self, grads, axis_name, size: int, residuals=None):
        """:meth:`pmean` over a pytree. ``residuals`` is None (no error
        feedback) or a pytree matching ``grads``; returns
        ``(means, new_residuals)`` with ``new_residuals is None`` iff
        no residual was threaded in."""
        if self.mode != "int8" or not self.error_feedback:
            residuals = None
        leaves, treedef = jax.tree.flatten(grads)
        if residuals is None:
            res_leaves = [None] * len(leaves)
        else:
            res_leaves = treedef.flatten_up_to(residuals)
        pairs = [
            self.pmean(g, axis_name, size, r)
            for g, r in zip(leaves, res_leaves)
        ]
        means = treedef.unflatten([m for m, _ in pairs])
        if residuals is None:
            return means, None
        return means, treedef.unflatten([r for _, r in pairs])

    def wire_bytes(self, leaf_sizes, size: int) -> dict:
        """Analytic per-participant bytes contributed to the fabric per
        step for gradients of the given element counts — the chipless
        counterpart of the HLO-derived number in
        ``tools/hlo_traffic.collective_bytes``.

        Returns ``{"total", "payload", "overhead"}``: ``payload`` is the
        gradient elements themselves at the compressed width (4n fp32 /
        2n bf16 / n int8 — the headline 2x / 4x), ``overhead`` is what
        int8 adds on top (fp32 block scales, ``4 / block`` per element,
        plus block/axis-alignment padding on both shots), so the all-in
        ``total`` never hides it. fp32/bf16 count the all-reduce operand;
        int8 counts both shots' operands (all_to_all + re-quantized
        all_gather). Block sizes follow :meth:`block_for` per leaf, the
        same rule the on-wire path uses, so this stays the HLO's mirror."""
        payload = total = 0
        for n in leaf_sizes:
            n = int(n)
            if self.mode == "none":
                payload += 4 * n
                total += 4 * n
            elif self.mode == "bf16":
                payload += 2 * n
                total += 2 * n
            else:
                block = self.block_for(n, size)
                chunk = -(-n // (size * block)) * block
                nb = chunk // block
                # shot 1 (q + scales) + shot 2 (q2 + scales); payload is
                # the unpadded elements crossing once per shot pair
                payload += n + -(-n // size)
                total += size * chunk + size * nb * 4
                total += chunk + nb * 4
        return {"total": total, "payload": payload,
                "overhead": total - payload}


def as_compress_policy(policy) -> CompressedAllReduce:
    """Coerce a CLI string / None / policy object to a policy."""
    if isinstance(policy, CompressedAllReduce):
        return policy
    return CompressedAllReduce(mode=str(policy) if policy else "none")


def world_group(mesh: Mesh | None = None, axis: str = "data") -> CollectiveGroup:
    """The default all-devices group (the reference's implicit WORLD)."""
    if mesh is None:
        from tpu_sandbox.runtime.mesh import make_mesh

        mesh = make_mesh({axis: -1})
    return CollectiveGroup(mesh, axis)


def sub_groups(mesh: Mesh, axis: str) -> CollectiveGroup:
    """Collectives over one axis of a multi-axis mesh: every slice along the
    other axes forms an independent group — the once-created analogue of
    ``dist.new_group(range(args.gpus))`` (mnist_distributed.py:100)."""
    return CollectiveGroup(mesh, axis)
