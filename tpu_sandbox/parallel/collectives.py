"""Raw collectives over a device mesh — the TPU-native L3 layer.

Capability parity with the reference's collective surface
(``dist.all_reduce(SUM)`` at allreduce_toy.py:31, ``dist.barrier()`` at
allreduce_toy.py:33, implicit DDP param broadcast at mnist_distributed.py:67,
``dist.new_group`` at allreduce_toy.py:27 / mnist_distributed.py:100),
re-expressed the XLA way: a :class:`CollectiveGroup` binds a mesh axis once
(fixing the reference's group-per-step leak), and each collective is a jit'd
``shard_map`` whose body is a ``lax`` collective. XLA compiles these into
ICI/DCN ring or torus collectives — there is no user-level communicator
management, which is the point.

Data model: a "per-rank value" is an array whose leading dimension is the
group size, sharded over the group axis — rank i's tensor is row i. This is
the single-controller analogue of torch's one-tensor-per-process model; it
works identically on 8 virtual CPU devices, one real chip, or a pod slice.
"""

from __future__ import annotations

from functools import cached_property, partial

import jax

from tpu_sandbox.utils.compat import shard_map
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class CollectiveGroup:
    """A set of devices that communicate — created once, reused every step.

    The reference creates a fresh ``dist.new_group`` every iteration
    (allreduce_toy.py:26-27); communicator setup is never free, so here the
    group (mesh axis binding + compiled collectives) is built once and every
    call reuses the jit cache.
    """

    def __init__(self, mesh: Mesh, axis: str | None = None):
        if axis is None:
            if len(mesh.axis_names) != 1:
                raise ValueError(
                    f"mesh has axes {mesh.axis_names}; pass axis= explicitly"
                )
            axis = mesh.axis_names[0]
        if axis not in mesh.axis_names:
            raise ValueError(f"axis {axis!r} not in mesh axes {mesh.axis_names}")
        self.mesh = mesh
        self.axis = axis
        self.size = mesh.shape[axis]

    # -- sharding helpers ---------------------------------------------------

    @cached_property
    def ranked_sharding(self) -> NamedSharding:
        """Leading dim = rank over the group axis."""
        return NamedSharding(self.mesh, P(self.axis))

    def put(self, values) -> jax.Array:
        """Place a host array of per-rank values (leading dim == group size)."""
        values = jnp.asarray(values)
        if values.shape[0] % self.size:
            raise ValueError(
                f"leading dim {values.shape[0]} not divisible by group size {self.size}"
            )
        return jax.device_put(values, self.ranked_sharding)

    def _smap(self, f, out_specs, check_vma: bool = True):
        # check_vma=False where the body provably replicates its output
        # (all_gather/broadcast) but jax's varying-mesh-axes analysis can't
        # statically see it.
        return jax.jit(
            shard_map(
                f,
                mesh=self.mesh,
                in_specs=P(self.axis),
                out_specs=out_specs,
                check_vma=check_vma,
            )
        )

    # -- collectives --------------------------------------------------------

    @cached_property
    def _all_reduce_fns(self):
        def make(reducer):
            return self._smap(partial(reducer, axis_name=self.axis), P(self.axis))

        return {
            "sum": make(lax.psum),
            "mean": make(lax.pmean),
            "max": make(lax.pmax),
            "min": make(lax.pmin),
        }

    def all_reduce(self, values, op: str = "sum") -> jax.Array:
        """Elementwise reduce across ranks; every rank sees the result.

        Parity: ``dist.all_reduce(tensor, ReduceOp.SUM)`` (allreduce_toy.py:31)
        and the dead commented-out AVG loss reduce (mnist_distributed.py:102).
        """
        if op not in self._all_reduce_fns:
            raise ValueError(f"op {op!r} not in {sorted(self._all_reduce_fns)}")
        return self._all_reduce_fns[op](self.put(values))

    @cached_property
    def _all_gather_fn(self):
        return self._smap(
            lambda x: lax.all_gather(x, self.axis, axis=0, tiled=True),
            P(),
            check_vma=False,
        )

    def all_gather(self, values) -> jax.Array:
        """Every rank receives the concatenation of all ranks' rows."""
        return self._all_gather_fn(self.put(values))

    @cached_property
    def _reduce_scatter_fn(self):
        return self._smap(
            lambda x: lax.psum_scatter(x, self.axis, scatter_dimension=1, tiled=True),
            P(self.axis),
        )

    def reduce_scatter(self, values) -> jax.Array:
        """Each rank contributes a full payload (its row); the rows are
        summed and rank i keeps the i-th 1/size slice of the sum.

        ``values``: shape ``(size, m)`` with ``m % size == 0``; returns
        shape ``(size, m // size)`` where row i is slice i of the sum.
        """
        values = jnp.asarray(values)
        if values.ndim != 2 or values.shape[1] % self.size:
            raise ValueError(
                f"reduce_scatter wants shape (size, m) with m % {self.size} == 0, "
                f"got {values.shape}"
            )
        return self._reduce_scatter_fn(self.put(values))

    @cached_property
    def _broadcast_fn(self):
        def body(x, root):
            full = lax.all_gather(x, self.axis, axis=0, tiled=True)
            return lax.dynamic_index_in_dim(full, root, axis=0, keepdims=False)

        return jax.jit(
            shard_map(
                body,
                mesh=self.mesh,
                in_specs=(P(self.axis), P()),
                out_specs=P(),
                check_vma=False,
            )
        )

    def broadcast(self, values, root: int = 0) -> jax.Array:
        """All ranks receive rank ``root``'s row — DDP's initial param sync
        (mnist_distributed.py:67) as an explicit collective."""
        return self._broadcast_fn(self.put(values), jnp.asarray(root))

    def _shift_fn(self, offset: int):
        cache = self.__dict__.setdefault("_shift_cache", {})
        if offset not in cache:
            perm = [(i, (i + offset) % self.size) for i in range(self.size)]
            cache[offset] = self._smap(
                lambda x: lax.ppermute(x, self.axis, perm), P(self.axis)
            )
        return cache[offset]

    def shift(self, values, offset: int = 1) -> jax.Array:
        """Ring permute: rank i's row moves to rank (i+offset) % size.

        The primitive under ring attention / pipeline p2p — no torch analogue
        in the reference (it has no send/recv), included because rings are
        how TPU ICI wants to move data."""
        return self._shift_fn(offset)(self.put(values))

    @cached_property
    def _all_to_all_fn(self):
        # local block is [1, size, ...]; drop the sharded leading dim, trade
        # sub-row j to rank j, restack what arrived, restore the leading dim
        return self._smap(
            lambda x: lax.all_to_all(
                x[0], self.axis, split_axis=0, concat_axis=0, tiled=True
            )[None],
            P(self.axis),
        )

    def all_to_all(self, values) -> jax.Array:
        """Transpose rows across ranks: rank i sends chunk j of its row-block
        to rank j. ``values``: shape ``(size, size, ...)`` — rank i holds
        block ``values[i]`` whose j-th sub-row goes to rank j; returns the
        same shape with ``out[j, i] = values[i, j]``.

        The primitive under expert dispatch (MoE) and Ulysses-style
        sequence parallelism; maps to one XLA AllToAll on the ICI fabric.
        No torch analogue in the reference (SURVEY §2.2 "EP: no all_to_all").
        """
        values = jnp.asarray(values)
        if values.ndim < 2 or values.shape[0] != self.size or (
            values.shape[1] != self.size
        ):
            raise ValueError(
                f"all_to_all wants shape (size, size, ...), got {values.shape}"
            )
        return self._all_to_all_fn(self.put(values))

    @cached_property
    def _barrier_fn(self):
        return self._smap(lambda x: lax.psum(x, self.axis), P())

    def barrier(self) -> None:
        """Block the host until every device in the group has participated.

        Parity: ``dist.barrier()`` (allreduce_toy.py:33). A psum of a unit
        token; host-blocks on the result.
        """
        token = self.put(jnp.ones((self.size,), jnp.int32))
        self._barrier_fn(token).block_until_ready()

    # -- microbenchmark -----------------------------------------------------

    def allreduce_bandwidth(self, nbytes: int = 1 << 26, iters: int = 10) -> dict:
        """All-reduce bus bandwidth — the north-star metric BASELINE.md names.

        Returns algorithm bandwidth (payload/time) and bus bandwidth
        (algbw * 2*(n-1)/n — the standard ring-allreduce accounting, which
        is what NCCL reports for the reference's fabric).

        Timing is fetch-synced and differential (see
        utils/profiling.py::measure_per_step): each iteration's input is the
        previous iteration's output (mean keeps values stable), so no
        iteration can be elided, and the only trusted sync — a device->host
        scalar fetch — ends each timed run. ``block_until_ready`` is NOT
        used: on async-dispatch platforms (the axon TPU tunnel) it returns
        before the device executes, which is how r01 published an unreal
        headline number.
        """
        from tpu_sandbox.utils.profiling import measure_per_step

        n = self.size
        elems = max(nbytes // 4, n)
        elems -= elems % n
        x = self.put(jnp.ones((n, elems // n), jnp.float32))
        fn = self._all_reduce_fns["mean"]

        def run(k):
            out = x
            for _ in range(k):
                out = fn(out)
            return out

        timing = measure_per_step(run, iters)
        if timing["sec_per_step"] <= 0:
            # tiny payloads + timing noise can turn the differential
            # negative; amortize over more iterations before giving up
            timing = measure_per_step(run, iters * 8)
        dt = timing["sec_per_step"]
        ok = dt > 0
        algbw = elems * 4 / dt if ok else 0.0
        busbw = algbw * (2 * (n - 1) / n)
        result = {
            "bytes": elems * 4,
            "seconds": dt,
            "algbw_GBps": algbw / 1e9,
            "busbw_GBps": busbw / 1e9,
            "timing_method": timing["timing_method"],
        }
        if not ok:
            result["degraded"] = (
                f"non-positive differential ({dt:.3e}s) even at "
                f"{iters * 8} iters; no bandwidth published"
            )
        return result


def world_group(mesh: Mesh | None = None, axis: str = "data") -> CollectiveGroup:
    """The default all-devices group (the reference's implicit WORLD)."""
    if mesh is None:
        from tpu_sandbox.runtime.mesh import make_mesh

        mesh = make_mesh({axis: -1})
    return CollectiveGroup(mesh, axis)


def sub_groups(mesh: Mesh, axis: str) -> CollectiveGroup:
    """Collectives over one axis of a multi-axis mesh: every slice along the
    other axes forms an independent group — the once-created analogue of
    ``dist.new_group(range(args.gpus))`` (mnist_distributed.py:100)."""
    return CollectiveGroup(mesh, axis)
