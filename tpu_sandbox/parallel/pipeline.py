"""Pipeline parallelism: GPipe-style microbatched stages over a 'pipe' axis.

The reference has no pipeline dimension (SURVEY §2.2 "PP: ABSENT — no stage
split, no send/recv"); this adds it TPU-style. There are no point-to-point
sends on a TPU mesh — the pipeline is an SPMD program under ``shard_map``
where every stage runs the same code each tick and activations move to the
next stage with ``lax.ppermute`` over neighbor ICI links:

- the transformer's homogeneous blocks are STACKED: their params carry a
  leading [n_layers] dim, reshaped to [n_stages, layers_per_stage, ...] and
  sharded on 'pipe' — each device materializes only its own stage's layers
  (the model-memory win pipeline parallelism exists for);
- embedding (pre) and head (post) params are replicated; only stage 0's
  pre output enters the pipe and only the last stage's block output is
  real — ``where`` masks select them, and the same masks route gradients
  correctly (pre grads live on stage 0 only, made global with a psum);
- a batch is split into M microbatches; the loop runs M + S - 1 ticks with
  the classic (S-1)/(M+S-1) bubble; the tick loop is a ``lax.scan`` so the
  whole pipeline is one differentiable compiled program — backward runs the
  reverse pipeline automatically.

Composes with data parallelism over a ('data', 'pipe') mesh: batch sharded
on 'data', grads pmean'd on 'data'.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_sandbox.models.transformer import Block, TransformerConfig, TransformerLM
from tpu_sandbox.ops.losses import cross_entropy_loss
from tpu_sandbox.train.state import TrainState


def split_transformer_params(params: dict, n_stages: int):
    """TransformerLM params -> (pre, stacked blocks [L,...], post).

    Blocks are stacked leaf-wise into a leading layer dim; the engine
    reshapes that to [n_stages, layers_per_stage, ...] and shards it.
    """
    block_keys = sorted(
        (k for k in params if k.startswith("block")), key=lambda k: int(k[5:])
    )
    if len(block_keys) % n_stages:
        raise ValueError(
            f"{len(block_keys)} layers not divisible into {n_stages} stages"
        )
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *[params[k] for k in block_keys])
    pre = {k: params[k] for k in ("tok_emb", "pos_emb")}
    post = {k: params[k] for k in ("ln_f", "lm_head")}
    return pre, stacked, post


def merge_transformer_params(pre: dict, stacked, post: dict) -> dict:
    """Inverse of split_transformer_params (for checkpoints/eval parity)."""
    n_layers = jax.tree.leaves(stacked)[0].shape[0]
    out = dict(pre)
    for i in range(n_layers):
        out[f"block{i}"] = jax.tree.map(lambda x: x[i], stacked)
    out.update(post)
    return out


class PipelineParallel:
    """Pipelined TransformerLM training over a ('data', 'pipe') mesh."""

    def __init__(
        self,
        config: TransformerConfig,
        tx: optax.GradientTransformation,
        mesh: Mesh,
        *,
        microbatches: int,
        data_axis: str = "data",
        pipe_axis: str = "pipe",
        donate: bool = True,
    ):
        for ax in (data_axis, pipe_axis):
            if ax not in mesh.axis_names:
                raise ValueError(f"axis {ax!r} not in mesh axes {mesh.axis_names}")
        self.config = config
        self.tx = tx
        self.mesh = mesh
        self.microbatches = microbatches
        self.data_axis, self.pipe_axis = data_axis, pipe_axis
        self.n_stages = mesh.shape[pipe_axis]
        if config.n_layers % self.n_stages:
            raise ValueError(
                f"{config.n_layers} layers not divisible by {self.n_stages} stages"
            )
        self.block = Block(config)
        self.model = TransformerLM(config)  # init / parity twin
        self._build(donate)

    # -- state --------------------------------------------------------------

    def init_state(self, rng, sample_tokens) -> TrainState:
        state = TrainState.create(self.model, rng, sample_tokens, self.tx)
        pre, stacked, post = split_transformer_params(state.params, self.n_stages)
        lps = self.config.n_layers // self.n_stages
        stacked = jax.tree.map(
            lambda x: x.reshape(self.n_stages, lps, *x.shape[1:]), stacked
        )
        params = {"pre": pre, "stages": stacked, "post": post}
        return state.replace(params=params, opt_state=self.tx.init(params))

    def _param_specs(self, params):
        return {
            "pre": jax.tree.map(lambda _: P(), params["pre"]),
            "stages": jax.tree.map(lambda _: P(self.pipe_axis), params["stages"]),
            "post": jax.tree.map(lambda _: P(), params["post"]),
        }

    def _state_specs(self, state: TrainState) -> TrainState:
        # optimizer states (sgd/adam moments) embed param-shaped leaves whose
        # paths contain the params subtree names: 'stages' leaves shard on
        # 'pipe', everything else replicates
        def opt_leaf_spec(path, _leaf):
            keys = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
            return P(self.pipe_axis) if "stages" in keys else P()

        return TrainState(
            step=P(),
            params=self._param_specs(state.params),
            batch_stats=jax.tree.map(lambda _: P(), state.batch_stats),
            opt_state=jax.tree_util.tree_map_with_path(opt_leaf_spec, state.opt_state),
        )

    def shard_state(self, state: TrainState) -> TrainState:
        specs = self._state_specs(state)
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)), state, specs
        )

    def shard_batch(self, tokens, targets):
        sh = NamedSharding(self.mesh, P(self.data_axis))
        return (
            jax.device_put(jnp.asarray(tokens), sh),
            jax.device_put(jnp.asarray(targets), sh),
        )

    # -- the pipeline -------------------------------------------------------

    def _stage_apply(self, stage_params, h):
        """Apply this stage's layers_per_stage blocks sequentially."""

        def one(hh, layer_params):
            return self.block.apply({"params": layer_params}, hh), None

        out, _ = lax.scan(one, h, stage_params)
        return out

    def _build(self, donate: bool) -> None:
        cfg, n_stages, M = self.config, self.n_stages, self.microbatches
        daxis, paxis = self.data_axis, self.pipe_axis
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def embed(pre, tokens, positions):
            tok = pre["tok_emb"]["embedding"][tokens]
            pos = pre["pos_emb"]["embedding"][positions]
            return (tok + pos).astype(cfg.dtype)

        def head(post, h):
            mean = h.mean(-1, keepdims=True)
            var = h.var(-1, keepdims=True)
            ln = post["ln_f"]
            hn = (h - mean) / jnp.sqrt(var + 1e-6) * ln["scale"] + ln["bias"]
            return (
                hn.astype(cfg.dtype) @ post["lm_head"]["kernel"]
                + post["lm_head"]["bias"]
            ).astype(jnp.float32)

        def pipe_forward(params, tokens):
            idx = lax.axis_index(paxis)
            b, s = tokens.shape
            mb = b // M
            positions = jnp.broadcast_to(jnp.arange(s), (b, s))
            h = embed(params["pre"], tokens, positions)  # [b, S, D]
            h_mb = h.reshape(M, mb, s, cfg.d_model)
            # local stage shard is [1, layers_per_stage, ...]: drop the
            # sharded singleton, keep the per-stage layer stack for scan
            my_stage = jax.tree.map(lambda x: x[0], params["stages"])

            outputs0 = jnp.zeros_like(h_mb)
            state0 = jnp.zeros_like(h_mb[0])

            def tick(carry, t):
                outputs, buf = carry
                feed = lax.dynamic_index_in_dim(
                    h_mb, jnp.clip(t, 0, M - 1), 0, keepdims=False
                )
                inp = jnp.where(idx == 0, feed, buf)
                out = self._stage_apply(my_stage, inp)
                widx = jnp.clip(t - (n_stages - 1), 0, M - 1)
                valid = t >= (n_stages - 1)
                cur = lax.dynamic_index_in_dim(outputs, widx, 0, keepdims=False)
                outputs = lax.dynamic_update_index_in_dim(
                    outputs, jnp.where(valid, out, cur), widx, 0
                )
                buf = lax.ppermute(out, paxis, perm)
                return (outputs, buf), None

            (outputs, _), _ = lax.scan(
                tick, (outputs0, state0), jnp.arange(M + n_stages - 1)
            )
            # outputs are only real on the last stage; callers mask by idx.
            # (Broadcasting them with a psum before the loss would make every
            # stage backprop a full copy of the loss — psum's transpose SUMS
            # the cotangents, inflating grads by n_stages.)
            h_out = outputs.reshape(b, s, cfg.d_model)
            return head(params["post"], h_out), idx

        def body(state: TrainState, tokens, targets):
            def loss_fn(params):
                logits, idx = pipe_forward(params, tokens)
                ce = cross_entropy_loss(
                    logits.reshape(-1, logits.shape[-1]), targets.reshape(-1)
                )
                # the loss is real on the last stage only; masking (rather
                # than broadcasting) keeps exactly one backprop path alive
                return jnp.where(idx == n_stages - 1, ce, 0.0)

            loss, grads = jax.value_and_grad(loss_fn)(state.params)
            # pre grads are nonzero only on stage 0 (the input where-mask),
            # post grads only on the last stage (the loss mask); psum makes
            # both global+replicated. stage grads stay local: no 'pipe' comm.
            grads = {
                "pre": lax.psum(grads["pre"], paxis),
                "stages": grads["stages"],
                "post": lax.psum(grads["post"], paxis),
            }
            grads = lax.pmean(grads, daxis)
            loss = lax.pmean(lax.psum(loss, paxis), daxis)
            updates, new_opt = self.tx.update(grads, state.opt_state, state.params)
            return (
                state.replace(
                    step=state.step + 1,
                    params=optax.apply_updates(state.params, updates),
                    opt_state=new_opt,
                ),
                loss,
            )

        self._pipe_forward = pipe_forward
        self._body = body
        self._jitted = None
        self._donate = donate

    def _compile_for(self, state: TrainState) -> Callable:
        specs = self._state_specs(state)
        smapped = jax.shard_map(
            self._body,
            mesh=self.mesh,
            in_specs=(specs, P(self.data_axis), P(self.data_axis)),
            out_specs=(specs, P()),
            check_vma=False,
        )
        return jax.jit(smapped, donate_argnums=(0,) if self._donate else ())

    def train_step(self, state: TrainState, tokens, targets):
        if self._jitted is None:
            self._jitted = self._compile_for(state)
        return self._jitted(state, tokens, targets)

    # -- parity helpers ------------------------------------------------------

    def merged_params(self, state: TrainState) -> dict:
        stacked = jax.tree.map(
            lambda x: np.asarray(x).reshape(-1, *x.shape[2:]), state.params["stages"]
        )
        return merge_transformer_params(
            jax.tree.map(np.asarray, state.params["pre"]),
            stacked,
            jax.tree.map(np.asarray, state.params["post"]),
        )
