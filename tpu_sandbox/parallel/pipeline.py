"""Pipeline parallelism: microbatched stages over a 'pipe' axis, SPMD-style.

The reference has no pipeline dimension (SURVEY §2.2 "PP: ABSENT — no stage
split, no send/recv"); this adds it TPU-style. There are no point-to-point
sends on a TPU mesh — the pipeline is an SPMD program under ``shard_map``
where every stage runs the same code each tick and activations move to the
next stage with ``lax.ppermute`` over neighbor ICI links:

- the transformer's homogeneous blocks are STACKED: their params carry a
  leading [n_layers] dim, reshaped to [n_stages, layers_per_stage, ...] and
  sharded on 'pipe' — each device materializes only its own stage's layers
  (the model-memory win pipeline parallelism exists for);
- a batch is split into M microbatches; the tick loop is a ``lax.scan``,
  and the whole pipeline is one differentiable compiled program — backward
  runs the reverse pipeline automatically.

Schedules — GPipe and circular (interleaved) are ONE implementation,
parameterized by ``circular_chunks`` (v):

- v=1 is GPipe: each device holds n_layers/S consecutive blocks; M + S - 1
  ticks, bubble (S-1)/(M+S-1).
- v>1 is the circular schedule (Megatron's interleaved stages, praxis's
  circular pipeline): each device holds v NON-consecutive layer chunks
  (global layer order = chunk-major round-robin: chunk c of device i holds
  layers [c·S·L + i·L .. +L)), and a microbatch rings around the devices v
  times. Unit u = t - idx at tick t decodes to (chunk c, microbatch m);
  the ring automatically delivers chunk c+1 of a microbatch to device 0
  exactly when its schedule slot arrives. M·v + S - 1 ticks for M·v units
  of work per device: the bubble shrinks to (S-1)/(M·v + S - 1) — ~v×
  smaller at equal M. Cost: v× as many (smaller) ppermute hops; needs
  M % S == 0.

  Bubble fraction at S=4 stages (``bubble_fraction()``):

      M      4      8      16
      v=1  0.429  0.273  0.158
      v=2  0.273  0.158  0.086
      v=4  0.158  0.086  0.045

Work is gated to the stage that owns it (VERDICT r01 weak #3 fixed — the
first version embedded/headed the full batch on EVERY stage and carried a
[M, mb, S, D] outputs buffer):

- the embedding runs per tick on one microbatch, under ``lax.cond(idx==0)``;
- the head + loss run per tick on the microbatch EXITING the last stage,
  under ``lax.cond(idx==n_stages-1)`` — logits for the full batch are never
  materialized; the scan carries only (loss_sum, ring buffer);
- per-stage FLOPs therefore no longer scale with n_stages, and the loss
  mask keeps exactly one backprop path alive (broadcasting the outputs
  with a psum before the loss would make every stage backprop a full copy,
  inflating grads by n_stages through psum's summing transpose).

Memory schedule: ``remat=True`` (default) wraps each tick in
``jax.checkpoint``, so backward saves only the scan carry per tick —
(M+S-1) x [mb, seq, d_model] — and recomputes block internals, the same
activation-memory class as a 1F1B schedule (which bounds in-flight
microbatches to S) and far below naive GPipe autodiff (every block's
internals for all M microbatches). Bubble fraction is (S-1)/(M+S-1) either
way; 1F1B's advantage over GPipe is memory, not bubble, and remat delivers
that here without a hand-scheduled backward.

Composes with data parallelism over a ('data', 'pipe') mesh (batch sharded
on 'data', grads pmean'd on 'data'), and with tensor parallelism over a
('data', 'model', 'pipe') mesh: pass ``model_axis='model'`` and the stage
blocks run Megatron-style — qkv/up kernels column-sharded (heads / d_ff),
out/down kernels row-sharded, ONE psum per residual branch, bias added
after the psum.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax

from tpu_sandbox.utils.compat import shard_map
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_sandbox.models.transformer import Block, TransformerConfig, TransformerLM
from tpu_sandbox.ops.attention import causal_attention
from tpu_sandbox.ops.losses import cross_entropy_loss
from tpu_sandbox.parallel.pjit_engine import _path_str
from tpu_sandbox.train.state import TrainState


def split_transformer_params(params: dict, n_stages: int):
    """TransformerLM params -> (pre, stacked blocks [L,...], post).

    Blocks are stacked leaf-wise into a leading layer dim; the engine
    reshapes that to [n_stages, layers_per_stage, ...] and shards it.
    """
    block_keys = sorted(
        (k for k in params if k.startswith("block")), key=lambda k: int(k[5:])
    )
    if len(block_keys) % n_stages:
        raise ValueError(
            f"{len(block_keys)} layers not divisible into {n_stages} stages"
        )
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *[params[k] for k in block_keys])
    pre = {k: params[k] for k in ("tok_emb", "pos_emb")}
    post = {k: params[k] for k in ("ln_f", "lm_head")}
    return pre, stacked, post


def merge_transformer_params(pre: dict, stacked, post: dict) -> dict:
    """Inverse of split_transformer_params (for checkpoints/eval parity)."""
    n_layers = jax.tree.leaves(stacked)[0].shape[0]
    out = dict(pre)
    for i in range(n_layers):
        out[f"block{i}"] = jax.tree.map(lambda x: x[i], stacked)
    out.update(post)
    return out


def _layernorm(x, p):
    """flax.linen.LayerNorm(dtype=fp32) semantics (eps 1e-6)."""
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    return (xf - mean) / jnp.sqrt(var + 1e-6) * p["scale"] + p["bias"]


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _tp_region_input(x, axis_name):
    """Megatron's 'f' operator: identity forward, psum backward.

    The input to a column-parallel matmul is consumed by every model rank's
    weight shard; each rank's backward produces only its shard's partial
    cotangent, so the cotangent must be all-reduced over the model axis
    here (the conjugate of the explicit psum after the row-parallel matmul,
    whose transpose is the identity). Without it, everything upstream —
    layernorms, earlier blocks, embeddings — trains on 1/m of its gradient.
    """
    return x


def _tp_region_input_fwd(x, axis_name):
    return x, None


def _tp_region_input_bwd(axis_name, _, g):
    return (lax.psum(g, axis_name),)


_tp_region_input.defvjp(_tp_region_input_fwd, _tp_region_input_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _tp_region_output(x, axis_name):
    """Megatron's 'g' operator: psum forward, identity backward.

    The conjugate of ``_tp_region_input``. Spelled as a custom_vjp (not a
    bare ``lax.psum``) so the backward is the identity BY CONSTRUCTION:
    shard_map's own transpose of psum is another psum (each rank's output
    is consumed by every rank's downstream replica), which here would
    multiply the row-parallel kernel gradients by the model-axis size."""
    return lax.psum(x, axis_name)


def _tp_region_output_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _tp_region_output_bwd(axis_name, _, g):
    return (g,)


_tp_region_output.defvjp(_tp_region_output_fwd, _tp_region_output_bwd)


class PipelineParallel:
    """Pipelined TransformerLM training over a ('data', 'pipe') mesh —
    optionally ('data', 'model', 'pipe') with tensor-parallel stages."""

    def __init__(
        self,
        config: TransformerConfig,
        tx: optax.GradientTransformation,
        mesh: Mesh,
        *,
        microbatches: int,
        data_axis: str = "data",
        pipe_axis: str = "pipe",
        model_axis: str | None = None,
        seq_axis: str | None = None,
        seq_attn: str = "ring",
        circular_chunks: int = 1,
        remat: bool = True,
        donate: bool = True,
        attention_fn: Callable | None = None,
    ):
        axes = (data_axis, pipe_axis) + ((model_axis,) if model_axis else ()) \
            + ((seq_axis,) if seq_axis else ())
        for ax in axes:
            if ax not in mesh.axis_names:
                raise ValueError(f"axis {ax!r} not in mesh axes {mesh.axis_names}")
        self.config = config
        self.tx = tx
        self.mesh = mesh
        self.microbatches = microbatches
        self.data_axis, self.pipe_axis = data_axis, pipe_axis
        self.model_axis = model_axis
        self.remat = remat
        self.n_stages = mesh.shape[pipe_axis]
        self.circular_chunks = v = circular_chunks
        if v < 1:
            raise ValueError(f"circular_chunks must be >= 1, got {v}")
        if config.n_layers % (self.n_stages * v):
            raise ValueError(
                f"{config.n_layers} layers not divisible into "
                f"{self.n_stages} stages x {v} chunks"
            )
        if v > 1 and microbatches % self.n_stages:
            raise ValueError(
                f"the circular schedule needs microbatches ({microbatches}) "
                f"divisible by n_stages ({self.n_stages})"
            )
        if model_axis:
            m = mesh.shape[model_axis]
            if config.n_heads % m or config.d_ff % m:
                raise ValueError(
                    f"tensor-parallel stages shard heads and d_ff: n_heads="
                    f"{config.n_heads} and d_ff={config.d_ff} must divide by "
                    f"{model_axis}={m}"
                )
        # sequence parallelism INSIDE the pipeline stages: activations ride
        # the pipe as [mb, S/sp, D] slices and attention mixes positions
        # across the 'sp' ring (ring_attention locates its shard itself via
        # lax.axis_index, so it drops in as the per-block attention_fn;
        # causality uses global positions). Embedding offsets positions per
        # shard; the loss/grads add a pmean over 'sp' (equal shards ⇒ mean
        # of local means is the global mean). Composes with model_axis:
        # dp x tp x pp x sp on one mesh.
        self.seq_axis = seq_axis
        if seq_axis:
            if attention_fn is not None:
                raise ValueError(
                    "seq_axis owns attention: pass seq_attn='ring'|"
                    "'flash_ring' instead of attention_fn"
                )
            if seq_attn == "ring":
                from tpu_sandbox.parallel.ring_attention import ring_attention

                attention_fn = functools.partial(
                    ring_attention, axis_name=seq_axis
                )
            elif seq_attn == "flash_ring":
                from tpu_sandbox.parallel.flash_ring import (
                    flash_ring_attention,
                )

                def attention_fn(q, k, v):
                    return flash_ring_attention(q, k, v, seq_axis)
            else:
                raise ValueError(
                    f"seq_attn must be 'ring' or 'flash_ring', got {seq_attn!r}"
                )
        # attention_fn is injected through to every stage block (and the
        # init/parity twin) exactly as models.transformer.TransformerLM:89
        # accepts it — flash (O(S) memory) instead of the dense [S,S]
        # causal_attention at the sequence lengths the SP schemes target.
        # Params are attention_fn-independent, so checkpoints interchange.
        self.attention_fn = attention_fn
        self.block = Block(config, attention_fn)
        # init / parity twin: ring attention only exists inside the
        # shard_map (axis must be bound), so the twin stays dense there —
        # params are attention_fn-independent either way
        self.model = TransformerLM(config, None if seq_axis else attention_fn)
        self._build(donate)

    def bubble_fraction(self) -> float:
        """Idle fraction of the pipeline schedule:
        (S-1) / (M·v + S - 1)."""
        ticks = self.microbatches * self.circular_chunks + self.n_stages - 1
        return (self.n_stages - 1) / ticks

    # -- state --------------------------------------------------------------

    def init_state(self, rng, sample_tokens) -> TrainState:
        state = TrainState.create(self.model, rng, sample_tokens, self.tx)
        pre, stacked, post = split_transformer_params(state.params, self.n_stages)
        v, n = self.circular_chunks, self.n_stages
        lps = self.config.n_layers // (n * v)
        # global layer order is chunk-major round-robin ([v, n, lps]);
        # swap to [n, v, lps] so the sharded 'pipe' dim leads
        stacked = jax.tree.map(
            lambda x: x.reshape(v, n, lps, *x.shape[1:]).swapaxes(0, 1),
            stacked,
        )
        params = {"pre": pre, "stages": stacked, "post": post}
        return state.replace(params=params, opt_state=self.tx.init(params))

    def _stage_leaf_spec(self, path: str, ndim: int) -> P:
        """'pipe' on the stacked leading dim; with tensor-parallel stages,
        'model' on the Megatron dim of each kernel/bias. Kernel dims are
        indexed from the END — the leading [stage, chunk, layer] stack is
        layout-dependent (chunk dim only exists conceptually; the leaves
        are [S, v, L, ...])."""
        spec = [self.pipe_axis] + [None] * (ndim - 1)
        m = self.model_axis
        if m:
            if "qkv/kernel" in path:
                spec[ndim - 2] = m  # [..., d_model, 3, H, hd] -> heads
            elif "qkv/bias" in path:
                spec[ndim - 2] = m  # [..., 3, H, hd]
            elif "out/kernel" in path:
                spec[ndim - 3] = m  # [..., H, hd, d_model] -> row-parallel
            elif "up/kernel" in path:
                spec[ndim - 1] = m  # [..., d_model, d_ff] -> columns
            elif "up/bias" in path:
                spec[ndim - 1] = m  # [..., d_ff]
            elif "down/kernel" in path:
                spec[ndim - 2] = m  # [..., d_ff, d_model] -> row-parallel
            # out/bias, down/bias, layernorms: replicated over 'model'
        while spec and spec[-1] is None:
            spec.pop()
        return P(*spec)

    def _param_specs(self, params):
        def stage_spec(path, leaf):
            return self._stage_leaf_spec(_path_str(path), jnp.ndim(leaf))

        return {
            "pre": jax.tree.map(lambda _: P(), params["pre"]),
            "stages": jax.tree_util.tree_map_with_path(
                stage_spec, params["stages"]
            ),
            "post": jax.tree.map(lambda _: P(), params["post"]),
        }

    def _state_specs(self, state: TrainState) -> TrainState:
        # optimizer states (sgd/adam moments) embed param-shaped leaves whose
        # paths contain the params subtree names: 'stages' leaves shard like
        # their params, everything else replicates
        def opt_leaf_spec(path, leaf):
            path_s = _path_str(path)
            if "stages" in path_s.split("/"):
                return self._stage_leaf_spec(path_s, jnp.ndim(leaf))
            return P()

        return TrainState(
            step=P(),
            params=self._param_specs(state.params),
            batch_stats=jax.tree.map(lambda _: P(), state.batch_stats),
            opt_state=jax.tree_util.tree_map_with_path(opt_leaf_spec, state.opt_state),
        )

    def shard_state(self, state: TrainState) -> TrainState:
        specs = self._state_specs(state)
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)), state, specs
        )

    def shard_batch(self, tokens, targets):
        b, s = jnp.shape(tokens)
        if self.seq_axis:
            n_sp = self.mesh.shape[self.seq_axis]
            if s % n_sp:
                raise ValueError(
                    f"sequence length {s} not divisible by the "
                    f"{self.seq_axis}={n_sp} shards"
                )
        sh = NamedSharding(
            self.mesh, P(self.data_axis, self.seq_axis)
            if self.seq_axis else P(self.data_axis)
        )
        return (
            jax.device_put(jnp.asarray(tokens), sh),
            jax.device_put(jnp.asarray(targets), sh),
        )

    # -- stage compute ------------------------------------------------------

    def _stage_apply(self, stage_params, h):
        """Apply this stage's layers_per_stage blocks sequentially."""
        if self.model_axis is None:

            def one(hh, layer_params):
                return self.block.apply({"params": layer_params}, hh), None

        else:
            one = self._tp_block_step

        out, _ = lax.scan(one, h, stage_params)
        return out

    def _tp_block_step(self, h, p):
        """One transformer block with Megatron tensor parallelism over
        ``model_axis`` — manual math (flax modules can't psum between the
        row-parallel matmul and its bias), numerically matching Block.apply:
        LayerNorm fp32/eps 1e-6, gelu, residuals, cfg.dtype matmuls.

        Local shards: qkv kernel holds H/m heads, up kernel d_ff/m columns
        (biases likewise local); out/down kernels hold the matching rows and
        their partial products psum once per residual branch, bias (full,
        replicated) added after the psum so it is counted exactly once.
        """
        cfg, m_ax = self.config, self.model_axis
        dt = cfg.dtype

        a = p["attn"]
        hn = _tp_region_input(_layernorm(h, p["ln1"]).astype(dt), m_ax)
        qkv = (
            jnp.einsum("bsd,dthk->bsthk", hn, a["qkv"]["kernel"].astype(dt))
            + a["qkv"]["bias"].astype(dt)
        )
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        # heads are already local shards (H/m); any [B,S,H,D] attention_fn
        # works per-head unchanged — flash here keeps TP stages O(S) memory
        # instead of causal_attention's dense [S,S] score materialization
        attn_fn = self.attention_fn or causal_attention
        attn = attn_fn(q, k, v)  # local heads only
        partial = jnp.einsum(
            "bshk,hkd->bsd", attn, a["out"]["kernel"].astype(dt)
        )
        attn_out = _tp_region_output(partial, m_ax) + a["out"]["bias"].astype(dt)
        h = h + attn_out

        mlp = p["mlp"]
        hn = _tp_region_input(_layernorm(h, p["ln2"]).astype(dt), m_ax)
        up = hn @ mlp["up"]["kernel"].astype(dt) + mlp["up"]["bias"].astype(dt)
        partial = jax.nn.gelu(up) @ mlp["down"]["kernel"].astype(dt)
        h = h + _tp_region_output(partial, m_ax) + mlp["down"]["bias"].astype(dt)
        return h, None

    # -- the pipeline -------------------------------------------------------

    def _build(self, donate: bool) -> None:
        cfg, n_stages, M = self.config, self.n_stages, self.microbatches
        daxis, paxis = self.data_axis, self.pipe_axis
        saxis = self.seq_axis
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def embed(pre, tokens):
            # sequence-sharded: local slice covers global positions
            # [sp_idx*s_local, ...) — pos_emb must see the global index
            base = lax.axis_index(saxis) * tokens.shape[1] if saxis else 0
            positions = jnp.broadcast_to(
                base + jnp.arange(tokens.shape[1]), tokens.shape
            )
            tok = pre["tok_emb"]["embedding"][tokens]
            pos = pre["pos_emb"]["embedding"][positions]
            return (tok + pos).astype(cfg.dtype)

        def head_loss(post, h, targets):
            """ln_f + lm_head + CE for ONE microbatch -> mean loss.

            Logits stay in compute dtype: cross_entropy_loss upcasts on
            its plain path (bit-identical) and the fused Pallas CE
            upcasts per row-block in VMEM — no [tokens, vocab] fp32
            materialization per microbatch (cf. TransformerConfig
            .fp32_logits)."""
            hn = _layernorm(h, post["ln_f"]).astype(cfg.dtype)
            logits = (
                hn @ post["lm_head"]["kernel"].astype(cfg.dtype)
                + post["lm_head"]["bias"].astype(cfg.dtype)
            )
            return cross_entropy_loss(
                logits.reshape(-1, logits.shape[-1]), targets.reshape(-1)
            )

        v = self.circular_chunks

        def body(state: TrainState, tokens, targets):
            idx = lax.axis_index(paxis)
            b, s = tokens.shape
            if b % M:
                raise ValueError(f"local batch {b} not divisible by {M} microbatches")
            mb = b // M
            tokens_mb = tokens.reshape(M, mb, s)
            targets_mb = targets.reshape(M, mb, s)

            def loss_fn(params):
                # local shard: [1, v, lps, ...] -> chunk stack [v, lps, ...]
                my_chunks = jax.tree.map(lambda x: x[0], params["stages"])

                def tick(carry, t):
                    loss_sum, buf = carry
                    # schedule decode: unit u = t - idx; groups of n_stages
                    # microbatches run chunk c before the next group enters
                    # (v=1 degenerates to GPipe: c == 0, m == u)
                    u = t - idx
                    active = jnp.logical_and(u >= 0, u < M * v)
                    uc = jnp.clip(u, 0, M * v - 1)
                    r = uc % (n_stages * v)
                    c = r // n_stages
                    m = (uc // (n_stages * v)) * n_stages + r % n_stages
                    toks = lax.dynamic_index_in_dim(
                        tokens_mb, m, 0, keepdims=False
                    )
                    # embed is (stage 0, chunk 0)'s job; elsewhere the ring
                    # buffer feeds
                    h_in = lax.cond(
                        jnp.logical_and(idx == 0, c == 0),
                        lambda: embed(params["pre"], toks),
                        lambda: buf,
                    )
                    stage = jax.tree.map(
                        lambda x: lax.dynamic_index_in_dim(
                            x, c, 0, keepdims=False
                        ),
                        my_chunks,
                    )
                    out = self._stage_apply(stage, h_in)
                    tgt = lax.dynamic_index_in_dim(
                        targets_mb, m, 0, keepdims=False
                    )
                    # head + loss are (last stage, last chunk)'s job, on
                    # active units only; the cond mask keeps exactly one
                    # backprop path alive (a psum broadcast here would
                    # inflate grads by n_stages via its summing transpose)
                    mb_loss = lax.cond(
                        jnp.logical_and(
                            jnp.logical_and(idx == n_stages - 1, c == v - 1),
                            active,
                        ),
                        lambda: head_loss(params["post"], out, tgt) / M,
                        lambda: jnp.float32(0.0),
                    )
                    buf = lax.ppermute(out, paxis, perm)
                    return (loss_sum + mb_loss, buf), None

                if self.remat:
                    tick = jax.checkpoint(tick)
                zero = jnp.zeros((mb, s, cfg.d_model), cfg.dtype)
                (loss_sum, _), _ = lax.scan(
                    tick, (jnp.float32(0.0), zero),
                    jnp.arange(M * v + n_stages - 1),
                )
                return loss_sum

            loss, grads = jax.value_and_grad(loss_fn)(state.params)
            # pre grads are nonzero only on stage 0 (the embed cond), post
            # grads only on the last stage (the loss cond); psum makes both
            # global+replicated. stage grads stay local: no 'pipe' comm.
            grads = {
                "pre": lax.psum(grads["pre"], paxis),
                "stages": grads["stages"],
                "post": lax.psum(grads["post"], paxis),
            }
            grads = lax.pmean(grads, daxis)
            loss = lax.pmean(lax.psum(loss, paxis), daxis)
            if saxis:
                # each sp shard's CE is the mean over ITS positions and its
                # param grads are the partials of that local mean (attention
                # cross-terms already routed by the ring's VJP): with equal
                # shards, the global mean is the mean of local means
                grads = lax.pmean(grads, saxis)
                loss = lax.pmean(loss, saxis)
            updates, new_opt = self.tx.update(grads, state.opt_state, state.params)
            return (
                state.replace(
                    step=state.step + 1,
                    params=optax.apply_updates(state.params, updates),
                    opt_state=new_opt,
                ),
                loss,
            )

        self._body = body
        self._jitted = None
        self._donate = donate

    def _compile_for(self, state: TrainState) -> Callable:
        specs = self._state_specs(state)
        bspec = (P(self.data_axis, self.seq_axis) if self.seq_axis
                 else P(self.data_axis))
        smapped = shard_map(
            self._body,
            mesh=self.mesh,
            in_specs=(specs, bspec, bspec),
            out_specs=(specs, P()),
            check_vma=False,
        )
        return jax.jit(smapped, donate_argnums=(0,) if self._donate else ())

    def train_step(self, state: TrainState, tokens, targets):
        if self._jitted is None:
            self._jitted = self._compile_for(state)
        return self._jitted(state, tokens, targets)

    def lower_step(self, state: TrainState, tokens, targets):
        """AOT-lower the pipelined step without executing it — same hook
        as ``DataParallel.lower_step`` so the HLO analysis tools (traffic,
        schedule, graftlint pass 2) can treat every engine uniformly."""
        if self._jitted is None:
            self._jitted = self._compile_for(state)
        return self._jitted.lower(state, tokens, targets)

    # -- parity helpers ------------------------------------------------------

    def merged_params(self, state: TrainState) -> dict:
        # [n, v, lps, ...] -> chunk-major [v, n, lps, ...] -> flat [L, ...]
        stacked = jax.tree.map(
            lambda x: np.asarray(x).swapaxes(0, 1).reshape(-1, *x.shape[3:]),
            state.params["stages"],
        )
        return merge_transformer_params(
            jax.tree.map(np.asarray, state.params["pre"]),
            stacked,
            jax.tree.map(np.asarray, state.params["post"]),
        )
