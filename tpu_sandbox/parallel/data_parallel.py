"""Data parallelism — the DDP-equivalent engine, built the XLA way.

Reference counterpart: ``nn.parallel.DistributedDataParallel(model)``
(mnist_distributed.py:67), whose C++ reducer broadcasts params once and then
fires bucketed async NCCL all-reduces per gradient bucket during backward.

TPU-native design (SURVEY §1 "TPU mapping", §7 step 6):
- The whole per-rank training body becomes ONE jit'd ``shard_map`` over a
  ``Mesh`` axis: the global batch is sharded on that axis, params are
  replicated, and gradients are ``lax.pmean``'d. XLA's latency-hiding
  scheduler overlaps the grad all-reduce with remaining backprop — the
  hand-rolled bucketing DDP does in C++ falls out of the compiler.
- DDP's initial param broadcast (rank 0 -> all) is a *sharding*: params are
  placed replicated on the mesh; there is nothing to broadcast at step time.
- BatchNorm statistics stay **per-replica** (DDP does not sync BN buffers;
  loss-curve parity requires matching that — SURVEY §7 hard-part 5). Each
  batch-stats leaf carries a leading mesh-axis dimension and is sharded on
  it, so rank i's stats live on device i exactly as they would in torch.
- The per-step loss is rank-local, like DDP's (the reference prints rank 0's
  loss; its cross-rank AVG all_reduce is dead code at mnist_distributed.py:102).
  ``average_loss=True`` enables the pmean that dead code intended.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax

from tpu_sandbox.utils.compat import shard_map
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_sandbox.ops.losses import cross_entropy_loss
from tpu_sandbox.parallel.collectives import CompressedAllReduce
from tpu_sandbox.train.state import TrainState


class DataParallel:
    """Data-parallel train-step factory over one mesh axis.

    Usage::

        dp = DataParallel(model, tx, mesh)          # mesh axis 'data'
        state = dp.shard_state(state)               # replicate params, split BN
        state, loss = dp.train_step(state, images, labels)   # global batch
    """

    def __init__(
        self,
        model,
        tx: optax.GradientTransformation,
        mesh: Mesh,
        axis: str = "data",
        *,
        image_size: tuple[int, int] | None = None,
        average_loss: bool = False,
        zero: bool = False,
        donate: bool = True,
        grad_compress: str | CompressedAllReduce = "none",
        error_feedback: bool = True,
        overlap_grad_sync: bool = False,
        bucket_mb: float = 25.0,
    ):
        """``zero=True`` is ZeRO-1 (optimizer-state sharding): optimizer
        state lives sharded over the data axis (dim 0, leaves whose leading
        dim divides the axis size; others stay replicated), each rank
        updates only its parameter block, and the updated blocks are
        all-gathered. Same math as plain DP — the update is elementwise per
        parameter — with the optimizer memory (e.g. Adam's two moments)
        divided by the axis size. This is the TPU spelling of DeepSpeed/
        FSDP's optimizer-state sharding: the reduce/scatter/gather
        choreography is just shardings + XLA collectives.

        Contract: the transform must be ELEMENTWISE per parameter (sgd,
        momentum, adam/adamw, ...). Transforms that couple parameters —
        e.g. ``optax.clip_by_global_norm`` (a norm over ALL grads) — would
        silently compute per-block norms; transforms whose state does not
        mirror param shapes (e.g. adafactor's factored moments) are
        rejected by a structural check at shard time.

        ``grad_compress`` compresses the gradient sync's wire payload:
        ``'none'`` (bitwise-identical to the uncompressed path), ``'bf16'``
        (cast-pmean-cast, 2x), or ``'int8'`` (block-scaled two-shot
        exchange, ~4x — see collectives.CompressedAllReduce). With int8,
        ``error_feedback=True`` carries a param-shaped fp32 residual in
        ``TrainState.grad_residual`` (one per rank, sharded like BN stats)
        so quantization error is re-injected next step; it checkpoints as a
        per-rank shard so elastic resume is bitwise. Under ``zero`` the
        compressed mean replaces BOTH the psum_scatter and pmean branches:
        wire compression is kept, but the scatter-only half-volume trick is
        traded away (each rank slices its block from the full compressed
        mean).

        ``overlap_grad_sync`` buckets the gradient sync (DDP's reducer):
        grads are grouped into ``bucket_mb``-targeted flat buffers
        (parallel/buckets.py) and each bucket is one independent collective,
        giving XLA's latency-hiding scheduler the freedom to start a
        bucket's all-reduce while later backward dots still run. Composes
        with every ``grad_compress`` mode (buckets quantize as units, with
        per-bucket error-feedback residuals that still checkpoint
        leaf-shaped) and with ``zero`` (full bucketed mean, then each rank
        slices its block — same trade as compression). Off by default:
        overlap off + ``grad_compress='none'`` is byte-for-byte the
        monolithic path."""
        if axis not in mesh.axis_names:
            raise ValueError(f"axis {axis!r} not in mesh axes {mesh.axis_names}")
        self.model = model
        self.tx = tx
        self.mesh = mesh
        self.axis = axis
        self.size = mesh.shape[axis]
        self.image_size = image_size
        self.average_loss = average_loss
        self.zero = zero
        if isinstance(grad_compress, CompressedAllReduce):
            self.compress = grad_compress
        else:
            self.compress = CompressedAllReduce(
                mode=str(grad_compress) if grad_compress else "none",
                error_feedback=error_feedback,
            )
        if bucket_mb <= 0:
            raise ValueError(f"bucket_mb must be positive, got {bucket_mb}")
        self.overlap = bool(overlap_grad_sync)
        self.bucket_bytes = int(bucket_mb * 2 ** 20)
        self._build(donate)

    def _dim0_sharded(self, leaf) -> bool:
        """ZeRO placement rule for one array: shard dim 0 iff it divides
        the axis size (conv kernels with dim0=5 stay replicated; the fat
        fc/Dense kernels and 1-D scales shard)."""
        return (
            hasattr(leaf, "ndim") and leaf.ndim >= 1
            and leaf.shape[0] >= self.size and leaf.shape[0] % self.size == 0
        )

    # -- state placement ----------------------------------------------------

    def _specs(self, state: TrainState) -> TrainState:
        """PartitionSpecs mirroring the state pytree: everything replicated
        except batch-stats, which shard their (added) leading axis — and,
        under ZeRO-1, eligible optimizer-state leaves, which shard dim 0."""
        if self.zero:
            # structural guard for the elementwise contract: every sharded
            # opt leaf must mirror some param's shape, else the blockwise
            # tx.update would see mismatched operands (e.g. adafactor's
            # factored moments) — fail loudly here instead
            param_shapes = {
                jnp.shape(p) for p in jax.tree.leaves(state.params)
            }
            bad = [
                jnp.shape(x) for x in jax.tree.leaves(state.opt_state)
                if self._dim0_sharded(x) and jnp.shape(x) not in param_shapes
            ]
            if bad:
                raise ValueError(
                    "zero=True needs an elementwise optimizer whose state "
                    f"mirrors param shapes; found opt-state leaves {bad} "
                    "matching no parameter (e.g. factored moments)"
                )
            opt_specs = jax.tree.map(
                lambda x: P(self.axis) if self._dim0_sharded(x) else P(),
                state.opt_state,
            )
        else:
            opt_specs = jax.tree.map(lambda _: P(), state.opt_state)
        return TrainState(
            step=P(),
            params=jax.tree.map(lambda _: P(), state.params),
            batch_stats=jax.tree.map(lambda _: P(self.axis), state.batch_stats),
            opt_state=opt_specs,
            # error-feedback residuals are rank-local like BN stats: one
            # param-shaped copy per rank behind a leading mesh-axis dim
            grad_residual=jax.tree.map(
                lambda _: P(self.axis), state.grad_residual
            ),
        )

    def shard_state(
        self, state: TrainState, *, stats_expanded: bool = False
    ) -> TrainState:
        """Place a single-device state on the mesh: params/opt replicated
        (DDP's param broadcast), BN stats expanded to one copy per rank.

        ``stats_expanded=True``: the batch-stats leaves already carry the
        leading per-replica axis of size ``self.size`` (a sharded-checkpoint
        restore at unchanged world size hands back every rank's own replica)
        and are placed as-is instead of broadcast from one copy — the exact
        per-replica resume. Under ZeRO the optimizer-state leaves are
        re-sliced here whatever world size wrote them, because the input is
        always the full reassembled value: this IS the cross-shard reshard.

        Works in multi-controller (multi-process) runs too: every process
        must hold the same host values (same seed -> same init, exactly the
        reference's implicit contract), and each process materializes only
        its addressable shards via ``make_array_from_callback``.
        """
        if self.compress.needs_residual and state.grad_residual is None:
            # first placement of a compression-naive state: start the
            # error-feedback residual at zero (its mathematical identity)
            state = state.replace(
                grad_residual=jax.tree.map(
                    lambda p: (
                        np.zeros((self.size, *np.shape(p)), np.float32)
                        if stats_expanded
                        else np.zeros(np.shape(p), np.float32)
                    ),
                    state.params,
                )
            )
        if stats_expanded:
            expanded = state
        else:
            expanded = state.replace(
                batch_stats=jax.tree.map(
                    lambda x: jnp.broadcast_to(x[None], (self.size, *x.shape)),
                    state.batch_stats,
                ),
                grad_residual=jax.tree.map(
                    lambda x: jnp.broadcast_to(x[None], (self.size, *x.shape)),
                    state.grad_residual,
                ),
            )
        specs = self._specs(expanded)
        if jax.process_count() == 1:
            return jax.tree.map(
                lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
                expanded,
                specs,
            )

        def put(x, s):
            import numpy as np

            host = np.asarray(x)
            return jax.make_array_from_callback(
                host.shape, NamedSharding(self.mesh, s),
                lambda idx: host[idx],
            )

        return jax.tree.map(put, expanded, specs)

    def shard_state_local(
        self, local_state: TrainState, template: TrainState
    ) -> TrainState:
        """Place a partial restore (``ShardedCheckpoint.restore_partial``)
        directly on the mesh: replicated leaves arrive at global shape
        (rank 0's shard), dim0-sharded leaves arrive as THIS RANK's block
        and are placed verbatim — no cross-rank reads, no world-sized host
        reassembly buffer.

        Multi-controller only, one addressable device per process: under
        that layout a process's single addressable shard of a ``P(axis)``
        leaf is exactly its own rank's block, so the block from
        ``restore_partial`` can be handed to ``make_array_from_callback``
        as-is. Any other device layout must go through the full
        ``restore`` + ``shard_state`` path.

        ``template`` is the unsharded host template the restore used
        (``checkpoint_template`` output): it supplies the tree structure
        and the global shapes the specs are derived from, so placement
        here and ``checkpoint_spec`` at save time share one eligibility
        rule and can never disagree.
        """
        if jax.process_count() != self.size or jax.local_device_count() != 1:
            raise ValueError(
                "shard_state_local needs one process per mesh slot "
                f"(process_count={jax.process_count()}, "
                f"local_device_count={jax.local_device_count()}, "
                f"world={self.size}); use restore + shard_state instead"
            )
        # global-shape view for spec derivation: per-replica leaves grow
        # the leading mesh axis; params/opt leaves are already global in
        # the template (abstract shapes suffice — nothing is materialized)
        expanded = template.replace(
            batch_stats=jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(
                    (self.size, *np.shape(x)), np.asarray(x).dtype),
                template.batch_stats,
            ),
            grad_residual=jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(
                    (self.size, *np.shape(x)), np.float32),
                template.grad_residual,
            ),
        )
        specs = self._specs(expanded)

        def put(local, s, ref):
            host = np.asarray(local)
            gshape = tuple(ref.shape) if hasattr(ref, "shape") else ()
            sharding = NamedSharding(self.mesh, s)
            if s == P():
                if host.shape != gshape:
                    raise ValueError(
                        f"replicated leaf shape {host.shape} != template "
                        f"{gshape}"
                    )
                return jax.make_array_from_callback(
                    gshape, sharding, lambda idx: host[idx])
            block = (gshape[0] // self.size, *gshape[1:])
            if host.shape != block:
                raise ValueError(
                    f"local block shape {host.shape} != expected {block} "
                    f"for global {gshape} over world {self.size}"
                )
            # the callback is asked only for this process's own shard,
            # which IS the restored block
            return jax.make_array_from_callback(
                gshape, sharding, lambda idx: host)

        return jax.tree.map(put, local_state, specs, expanded)

    def unshard_state(self, state: TrainState, rank: int = 0) -> TrainState:
        """Single-device view: params as-is, rank ``rank``'s BN stats.

        The error-feedback residual is dropped: it is a per-rank sync
        buffer whose single-rank slice means nothing to a resumed run
        (re-placement restarts it at zero). Exact residual resume is the
        sharded elastic checkpoint's job, which saves every rank's copy."""
        return state.replace(
            batch_stats=jax.tree.map(lambda x: x[rank], state.batch_stats),
            grad_residual=None,
        )

    def checkpoint_template(self, template: TrainState) -> TrainState:
        """Host-side restore template with the error-feedback residual slot
        attached (zeros, param-shaped). Checkpoint backends restore only
        leaves the template names, so a template built before the first
        step (residual still None) would silently drop every rank's saved
        residual on resume — attach the slot up front instead."""
        if not self.compress.needs_residual or template.grad_residual is not None:
            return template
        return template.replace(
            grad_residual=jax.tree.map(
                lambda p: np.zeros(np.shape(p), np.float32), template.params
            )
        )

    def checkpoint_spec(self, state: TrainState) -> TrainState:
        """Per-leaf placement kinds for the sharded checkpoint layer,
        derived from the same specs that placed the state: ``"shard0"``
        for leaves sharded on the data axis (BN-stats replicas; under
        ZeRO-1 the eligible optimizer-state blocks), ``"rep"`` for
        everything replicated. ``state`` is the SHARDED state (expanded
        BN stats) — global shapes feed the same ZeRO eligibility rule
        that placed the leaves, so save and placement can never disagree."""
        return jax.tree.map(
            lambda s: "shard0" if s == P(self.axis) else "rep",
            self._specs(state),
            is_leaf=lambda x: isinstance(x, P),
        )

    def shard_batch(self, images, labels):
        """Place a global batch sharded over the data axis. Device i receives
        the slice DistributedSampler would have given rank i (see
        ShardedBatchLoader, which lays the global batch out that way)."""
        sh = NamedSharding(self.mesh, P(self.axis))
        return jax.device_put(jnp.asarray(images), sh), jax.device_put(
            jnp.asarray(labels), sh
        )

    # -- the engine ---------------------------------------------------------

    def _build(self, donate: bool) -> None:
        model, tx, axis = self.model, self.tx, self.axis
        image_size, average_loss = self.image_size, self.average_loss
        zero, size, dim0_sharded = self.zero, self.size, self._dim0_sharded
        compress = self.compress
        overlap, bucket_bytes = self.overlap, self.bucket_bytes

        def loss_fn(params, batch_stats, images, labels):
            variables = {"params": params}
            if batch_stats:
                variables["batch_stats"] = batch_stats
            logits, mutated = model.apply(
                variables, images, train=True, mutable=["batch_stats"]
            )
            return (
                cross_entropy_loss(logits, labels),
                mutated.get("batch_stats", {}),
            )

        def shard_body(state: TrainState, images, labels):
            # Per-rank block: images [B/size, ...]; BN stats [1, ...] -> local.
            local_stats = jax.tree.map(lambda x: x[0], state.batch_stats)
            if image_size is not None:
                from tpu_sandbox.train import prepare_inputs
                images = prepare_inputs(model, images, image_size)
            (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params, local_stats, images, labels
            )
            new_residual = state.grad_residual
            if overlap or compress.mode != "none":
                # Sync happens ONCE here for every leaf; the branches below
                # then consume already-mean'd grads. (Under ZeRO this
                # supersedes the psum_scatter half-volume trick — the wire
                # carries the bucketed/compressed payload instead.)
                local_res = (
                    jax.tree.map(lambda x: x[0], state.grad_residual)
                    if compress.needs_residual
                    else None
                )
                if overlap:
                    from tpu_sandbox.parallel.buckets import sync_buckets

                    grads, new_res = sync_buckets(
                        grads, axis, size, compress, residuals=local_res,
                        bucket_bytes=bucket_bytes,
                    )
                else:
                    grads, new_res = compress.pmean_tree(
                        grads, axis, size, local_res
                    )
                if compress.needs_residual:
                    new_residual = jax.tree.map(lambda x: x[None], new_res)
            if zero:
                # ZeRO-1: reduce-SCATTER each eligible gradient (every rank
                # receives only its dim-0 block of the mean — the collective
                # the ZeRO paper prescribes, ~half an all-reduce's volume),
                # update that block against the pre-sharded optimizer state
                # from in_specs, and all-gather the updated blocks.
                # Elementwise optimizers make the math identical to the
                # replicated update.
                idx = lax.axis_index(axis)
                sharded = jax.tree.map(dim0_sharded, state.params)

                def blk(x):
                    n = x.shape[0] // size
                    return lax.dynamic_slice_in_dim(x, idx * n, n, 0)

                params_blk = jax.tree.map(
                    lambda p, s: blk(p) if s else p, state.params, sharded
                )
                if overlap or compress.mode != "none":
                    # already mean'd by the bucketed/compressed sync above —
                    # each rank just slices its own block
                    grads_blk = jax.tree.map(
                        lambda g, s: blk(g) if s else g, grads, sharded
                    )
                else:
                    grads_blk = jax.tree.map(
                        lambda g, s: (
                            lax.psum_scatter(g, axis, scatter_dimension=0,
                                             tiled=True) / size
                            if s else lax.pmean(g, axis)
                        ),
                        grads, sharded,
                    )
                updates, new_opt = tx.update(
                    grads_blk, state.opt_state, params_blk
                )
                new_blk = optax.apply_updates(params_blk, updates)
                new_params = jax.tree.map(
                    lambda p, s: (
                        lax.all_gather(p, axis, axis=0, tiled=True) if s else p
                    ),
                    new_blk, sharded,
                )
            else:
                if not overlap and compress.mode == "none":
                    # THE data-parallel step: mean grads across ranks.
                    grads = lax.pmean(grads, axis)
                updates, new_opt = tx.update(
                    grads, state.opt_state, state.params
                )
                new_params = optax.apply_updates(state.params, updates)
            if average_loss:
                loss = lax.pmean(loss, axis)  # the reference's dead AVG reduce
            new_state = state.replace(
                step=state.step + 1,
                params=new_params,
                batch_stats=jax.tree.map(lambda x: x[None], new_stats),
                opt_state=new_opt,
                grad_residual=new_residual,
            )
            return new_state, loss[None]

        # Specs are structural: build them from a state *template* lazily on
        # first call (they depend on the pytree structure, not values).
        self._jitted: Callable | None = None
        self._donate = donate
        self._shard_body = shard_body

    def _compile_for(self, state: TrainState) -> Callable:
        specs = self._specs(state)
        smapped = shard_map(
            self._shard_body,
            mesh=self.mesh,
            in_specs=(specs, P(self.axis), P(self.axis)),
            out_specs=(specs, P(self.axis)),
            check_vma=False,  # params are replicated by construction (pmean'd
            # grads + replicated inputs); the static analysis can't see it
        )
        return jax.jit(smapped, donate_argnums=(0,) if self._donate else ())

    def train_step(self, state: TrainState, images, labels):
        """(sharded state, global batch) -> (sharded state, per-rank losses).

        The returned loss has shape [size]; element i is rank i's local loss
        (DDP parity — print element 0 to match the reference's logs).
        """
        if self._jitted is None:
            self._jitted = self._compile_for(state)
        return self._jitted(state, images, labels)

    def lower_step(self, state: TrainState, images, labels):
        """AOT-lower the train step without executing it — the hook the
        collective-traffic accounting uses (``.compile().as_text()`` keeps
        the cross-replica collectives with inline operand shapes)."""
        if self._jitted is None:
            self._jitted = self._compile_for(state)
        return self._jitted.lower(state, images, labels)
