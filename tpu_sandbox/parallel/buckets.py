"""Bucketed gradient synchronization — DDP's reducer discipline, XLA-side.

PyTorch DDP's C++ reducer groups gradients into ~25MB buckets and launches
one async NCCL all-reduce per bucket as soon as the bucket's grads are
produced, hiding comm under the rest of backward. The XLA analogue is
structural, not imperative: emit one independent collective per bucket over
a flat buffer, and the latency-hiding scheduler is free to hoist each
``all-reduce-start`` to right after the last contributing cotangent and sink
the matching ``all-reduce-done`` below later backward dots. A single
monolithic sync (or one collective per leaf, which the all-reduce combiner
may refuse to merge across dtypes) gives the scheduler strictly less
freedom; size-targeted flat buckets are the shape it wants
(``tools/hlo_schedule.py`` is the receipt).

Composition: each bucket goes through one :class:`CompressedAllReduce`
exchange — ``none`` stays a plain ``lax.pmean`` of the flat buffer
(elementwise, so bitwise-equal to the per-leaf spelling), bf16/int8
quantize per bucket with per-bucket error-feedback residuals. Residuals
stay LEAF-shaped in ``TrainState`` (checkpoint layout unchanged from the
monolithic path); they are concatenated into the bucket buffer on entry and
split back on exit, so quantization block boundaries are genuinely
per-bucket while elastic resume remains bitwise.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from tpu_sandbox.parallel.collectives import as_compress_policy

# PyTorch DDP's bucket_cap_mb default — the same trade: big enough that
# per-collective latency amortizes, small enough that the first bucket is
# ready well before backward finishes.
DEFAULT_BUCKET_MB = 25.0


def plan_buckets(nbytes, bucket_bytes, keys=None):
    """Greedily group CONSECUTIVE leaves into size-targeted buckets.

    ``nbytes``: per-leaf byte sizes in flatten order. ``bucket_bytes``: the
    target; a bucket closes when adding the next leaf would push it past
    the target (a single over-target leaf still gets its own bucket).
    ``keys``: optional per-leaf grouping keys (dtypes) — a key change
    forces a bucket boundary so flat buffers never mix dtypes.

    Returns a list of ``(start, stop)`` index spans covering every leaf
    exactly once, in order. Leaf ORDER inside the flattened pytree is taken
    as given; :func:`sync_buckets` applies DDP's reverse-autograd heuristic
    by ISSUING the buckets in reversed span order instead of reordering
    leaves (flatten order ~ forward order, so backward produces the last
    spans' cotangents first).
    """
    nbytes = [int(b) for b in nbytes]
    bucket_bytes = int(bucket_bytes)
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
    if keys is not None and len(keys) != len(nbytes):
        raise ValueError(
            f"keys length {len(keys)} != nbytes length {len(nbytes)}"
        )
    spans = []
    start, acc = 0, 0
    for i, b in enumerate(nbytes):
        boundary = i > start and (
            acc + b > bucket_bytes or (keys is not None and keys[i] != keys[start])
        )
        if boundary:
            spans.append((start, i))
            start, acc = i, 0
        acc += b
    if start < len(nbytes):
        spans.append((start, len(nbytes)))
    return spans


def sync_buckets(grads, axis_name, size, compress=None, residuals=None,
                 bucket_bytes=int(DEFAULT_BUCKET_MB * 2 ** 20)):
    """Mean ``grads`` across ``axis_name`` (inside ``shard_map``) as one
    independent collective per size-targeted bucket.

    ``compress``: a :class:`CompressedAllReduce` (or mode string / None);
    each bucket's flat buffer goes through one ``compress.pmean`` exchange.
    ``residuals``: leaf-shaped error-feedback pytree matching ``grads``
    (honored only when the policy :attr:`needs_residual`, mirroring
    ``pmean_tree``); returns ``(means, new_residuals)`` with
    ``new_residuals is None`` iff no residual was threaded in.
    """
    compress = as_compress_policy(compress)
    leaves, treedef = jax.tree.flatten(grads)
    if not leaves:
        return grads, None
    use_res = compress.needs_residual and residuals is not None
    if use_res:
        res_leaves = treedef.flatten_up_to(residuals)
    else:
        res_leaves = [None] * len(leaves)
    spans = plan_buckets(
        [g.size * jnp.dtype(g.dtype).itemsize for g in leaves],
        bucket_bytes,
        keys=[jnp.dtype(g.dtype) for g in leaves],
    )
    # Exchange phase: one compress.pmean per bucket, issued in REVERSED
    # leaf order — cotangent production order (backward visits layers in
    # reverse, so the last leaves' grads are ready first), the same order
    # DDP's reducer fires its buckets. Consecutive issues are
    # dependency-chained through an optimization_barrier (identity on
    # values): each bucket's input depends on the previously issued
    # bucket's mean, so XLA's all-reduce combiner — which merges any
    # INDEPENDENT same-shaped collectives — cannot re-fuse the buckets
    # into one monolithic sync (observed on TPU compiles: without the
    # chain the combiner undoes the bucketing entirely). Backward compute
    # stays free to interleave: the chain only orders collectives against
    # each other, DDP's NCCL-stream discipline exactly.
    results = [None] * len(spans)  # per-span (mean, new_residual)
    prev_k = None
    for k in range(len(spans) - 1, -1, -1):
        start, stop = spans[k]
        group = leaves[start:stop]
        rgroup = res_leaves[start:stop]
        if len(group) == 1:
            # no reshape churn for a lone (usually over-target) leaf
            buf, rbuf = group[0], rgroup[0]
        else:
            buf = jnp.concatenate([g.reshape(-1) for g in group])
            rbuf = (
                jnp.concatenate([r.reshape(-1) for r in rgroup])
                if use_res else None
            )
        if prev_k is not None:
            buf, chained = lax.optimization_barrier(
                (buf, results[prev_k][0])
            )
            results[prev_k] = (chained, results[prev_k][1])
        results[k] = compress.pmean(buf, axis_name, size, rbuf)
        prev_k = k

    # Split phase: scatter each bucket's mean back into leaf shapes.
    out = [None] * len(leaves)
    new_res = [None] * len(leaves)
    for (start, stop), (mean, rmean) in zip(spans, results):
        group = leaves[start:stop]
        rgroup = res_leaves[start:stop]
        if len(group) == 1:
            out[start], new_res[start] = mean, rmean
            continue
        off = 0
        for j, g in enumerate(group):
            n = g.size
            out[start + j] = lax.slice_in_dim(mean, off, off + n).reshape(
                g.shape
            )
            if use_res:
                new_res[start + j] = lax.slice_in_dim(
                    rmean, off, off + n
                ).reshape(rgroup[j].shape)
            off += n
    means = treedef.unflatten(out)
    if not use_res:
        return means, None
    return means, treedef.unflatten(new_res)
