from tpu_sandbox.parallel.collectives import CollectiveGroup  # noqa: F401
from tpu_sandbox.parallel.data_parallel import DataParallel  # noqa: F401
from tpu_sandbox.parallel.pjit_engine import PjitEngine  # noqa: F401
