from tpu_sandbox.parallel.collectives import CollectiveGroup  # noqa: F401
