from tpu_sandbox.parallel.buckets import plan_buckets, sync_buckets  # noqa: F401
from tpu_sandbox.parallel.collectives import (  # noqa: F401
    CollectiveGroup,
    CompressedAllReduce,
)
from tpu_sandbox.parallel.data_parallel import DataParallel  # noqa: F401
from tpu_sandbox.parallel.expert import MoeMlp  # noqa: F401
from tpu_sandbox.parallel.pipeline import PipelineParallel  # noqa: F401
from tpu_sandbox.parallel.pjit_engine import PjitEngine, megatron_rules  # noqa: F401
from tpu_sandbox.parallel.ring_attention import make_ring_attention, ring_attention  # noqa: F401
from tpu_sandbox.parallel.seq_parallel import SeqParallel  # noqa: F401
from tpu_sandbox.parallel.ulysses import make_ulysses_attention, ulysses_attention  # noqa: F401
from tpu_sandbox.parallel.flash_ring import (  # noqa: F401
    flash_ring_attention,
    make_flash_ring_attention,
)
