"""Expert parallelism: switch-style (top-1) Mixture-of-Experts MLP.

The reference has no MoE and no all_to_all (SURVEY §2.2 "EP: ABSENT");
this module adds the capability TPU-style. The layer is written as pure
einsum dataflow — gate, capacity-bounded dispatch, per-expert FFN, combine —
with the expert dimension explicit in every tensor. Expert parallelism is
then *a sharding rule, not an engine*: shard the expert-weight leading dim
and the dispatched tensor's expert dim over an 'expert' mesh axis
(PjitEngine rule ``("w_(up|down)", P("expert", None, None))``) and XLA
inserts the all-to-alls that route tokens to their expert's device.

Routing is top-k with per-sequence capacity C = capacity_factor * S / E:
k=1 is Switch Transformer (combine weight = the router probability
itself), k>1 is GShard-style (gates = the top-k probabilities normalized
to sum to 1; capacity is granted choice-major — every token's first
choice queues before any second choice, so a 2nd choice never evicts a
1st). Overflow tokens pass through the residual (their combine weights
are zero) — the standard TPU-friendly static-shape treatment: no
data-dependent shapes, everything MXU-shaped einsums.

The router also exposes its load-balancing auxiliary loss (Switch eq. 4,
computed over first choices) via ``self.sow("aux_loss", ...)`` for
engines that want to add it; PjitEngine(task="lm") folds it into the
objective with ``aux_weight``.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpu_sandbox.models.transformer import TransformerConfig


class MoeMlp(nn.Module):
    """Drop-in MLP replacement for models.transformer.Block (mlp_cls)."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        e = cfg.n_experts
        if e <= 0:
            raise ValueError("MoeMlp needs config.n_experts > 0")
        if not 1 <= cfg.router_top_k <= e:
            raise ValueError(
                f"router_top_k must be in [1, n_experts={e}], "
                f"got {cfg.router_top_k}"
            )
        b, s, d = x.shape
        capacity = max(1, int(cfg.capacity_factor * s / e))

        # --- router (fp32 for numerics) ---
        gate_logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            x.astype(jnp.float32)
        )  # [B,S,E]
        probs = jnp.asarray(jax.nn.softmax(gate_logits, axis=-1))
        k = cfg.router_top_k
        top_vals, top_idx = jax.lax.top_k(probs, k)  # [B,S,K]
        # Switch (k=1): gate = the raw router prob; GShard (k>1): top-k
        # gates renormalized so kept tokens mix to weight ~1
        gates = top_vals if k == 1 else (
            top_vals / jnp.maximum(top_vals.sum(-1, keepdims=True), 1e-9)
        )

        onehot = jax.nn.one_hot(top_idx, e, dtype=jnp.float32)  # [B,S,K,E]
        # capacity positions, CHOICE-MAJOR: flatten [K,S] with choice as
        # the slow axis so every 1st choice queues before any 2nd choice,
        # then cumulative-count per expert (per sequence)
        oh_km = onehot.transpose(0, 2, 1, 3).reshape(b, k * s, e)
        pos_km = jnp.cumsum(oh_km, axis=1) * oh_km - 1.0  # -1 if not routed
        pos = pos_km.reshape(b, k, s, e).transpose(0, 2, 1, 3)  # [B,S,K,E]
        in_capacity = (pos >= 0) & (pos < capacity)
        pos_onehot = jax.nn.one_hot(
            jnp.where(in_capacity, pos, -1.0).astype(jnp.int32),
            capacity, dtype=jnp.float32,
        )  # [B,S,K,E,C] (all-zero row for dropped/unrouted)
        dispatch_k = onehot[..., None] * pos_onehot  # [B,S,K,E,C]
        dispatch = dispatch_k.sum(2)  # [B,S,E,C] — positions are disjoint
        combine = (dispatch_k * gates[..., None, None]).sum(2)  # [B,S,E,C]

        # load-balance aux loss (Switch eq. 4): E * sum_e f_e * P_e,
        # f_e over FIRST choices (the GShard convention for k>1)
        frac_tokens = jnp.mean(onehot[:, :, 0], axis=(0, 1))  # [E]
        frac_probs = jnp.mean(probs, axis=(0, 1))  # [E]
        self.sow("aux_loss", "load_balance", e * jnp.sum(frac_tokens * frac_probs))

        # --- dispatch -> expert FFN -> combine (dtype follows the model) ---
        xd = x.astype(cfg.dtype)
        dispatched = jnp.einsum(
            "bsec,bsd->ebcd", dispatch.astype(cfg.dtype), xd
        )  # [E,B,C,D] — expert dim leading: THE expert-parallel shard dim
        w_up = self.param(
            "w_up", nn.initializers.lecun_normal(), (e, d, cfg.d_ff)
        ).astype(cfg.dtype)
        w_down = self.param(
            "w_down", nn.initializers.lecun_normal(), (e, cfg.d_ff, d)
        ).astype(cfg.dtype)
        h = nn.gelu(jnp.einsum("ebcd,edf->ebcf", dispatched, w_up))
        out = jnp.einsum("ebcf,efd->ebcd", h, w_down)  # [E,B,C,D]
        y = jnp.einsum("bsec,ebcd->bsd", combine.astype(cfg.dtype), out)
        return y
