"""Expert parallelism: switch-style (top-1) Mixture-of-Experts MLP.

The reference has no MoE and no all_to_all (SURVEY §2.2 "EP: ABSENT");
this module adds the capability TPU-style. The layer is written as pure
einsum dataflow — gate, capacity-bounded dispatch, per-expert FFN, combine —
with the expert dimension explicit in every tensor. Expert parallelism is
then *a sharding rule, not an engine*: shard the expert-weight leading dim
and the dispatched tensor's expert dim over an 'expert' mesh axis
(PjitEngine rule ``("w_(up|down)", P("expert", None, None))``) and XLA
inserts the all-to-alls that route tokens to their expert's device.

Top-1 (Switch Transformer) routing with per-sequence capacity
C = capacity_factor * S / E: overflow tokens pass through the residual
(their combine weights are zero), the standard TPU-friendly static-shape
treatment — no data-dependent shapes, everything MXU-shaped einsums.

The router also exposes its load-balancing auxiliary loss (Switch eq. 4)
via ``self.sow("aux_loss", ...)`` for engines that want to add it.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpu_sandbox.models.transformer import TransformerConfig


class MoeMlp(nn.Module):
    """Drop-in MLP replacement for models.transformer.Block (mlp_cls)."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        e = cfg.n_experts
        if e <= 0:
            raise ValueError("MoeMlp needs config.n_experts > 0")
        b, s, d = x.shape
        capacity = max(1, int(cfg.capacity_factor * s / e))

        # --- router (fp32 for numerics) ---
        gate_logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            x.astype(jnp.float32)
        )  # [B,S,E]
        probs = jnp.asarray(jax.nn.softmax(gate_logits, axis=-1))
        expert_idx = jnp.argmax(probs, axis=-1)  # [B,S]
        gate = jnp.max(probs, axis=-1)  # [B,S]

        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # [B,S,E]
        # position of each token in its expert's queue (per sequence)
        pos = jnp.cumsum(onehot, axis=1) * onehot - 1.0  # [B,S,E], -1 if not routed
        in_capacity = (pos >= 0) & (pos < capacity)
        pos_onehot = jax.nn.one_hot(
            jnp.where(in_capacity, pos, -1), capacity, dtype=jnp.float32
        )  # [B,S,E,C] (all-zero row for dropped/unrouted)
        dispatch = onehot[..., None] * pos_onehot  # [B,S,E,C]
        combine = dispatch * gate[..., None, None]  # [B,S,E,C]

        # load-balance aux loss (Switch eq. 4): E * sum_e f_e * P_e
        frac_tokens = jnp.mean(onehot, axis=(0, 1))  # [E]
        frac_probs = jnp.mean(probs, axis=(0, 1))  # [E]
        self.sow("aux_loss", "load_balance", e * jnp.sum(frac_tokens * frac_probs))

        # --- dispatch -> expert FFN -> combine (dtype follows the model) ---
        xd = x.astype(cfg.dtype)
        dispatched = jnp.einsum(
            "bsec,bsd->ebcd", dispatch.astype(cfg.dtype), xd
        )  # [E,B,C,D] — expert dim leading: THE expert-parallel shard dim
        w_up = self.param(
            "w_up", nn.initializers.lecun_normal(), (e, d, cfg.d_ff)
        ).astype(cfg.dtype)
        w_down = self.param(
            "w_down", nn.initializers.lecun_normal(), (e, cfg.d_ff, d)
        ).astype(cfg.dtype)
        h = nn.gelu(jnp.einsum("ebcd,edf->ebcf", dispatched, w_up))
        out = jnp.einsum("ebcf,efd->ebcd", h, w_down)  # [E,B,C,D]
        y = jnp.einsum("bsec,ebcd->bsd", combine.astype(cfg.dtype), out)
        return y
