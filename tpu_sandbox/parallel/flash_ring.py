"""Flash-ring attention: the Pallas flash kernel as ring attention's
per-block compute, with a hand-written ring backward.

parallel/ring_attention.py keeps its per-arriving-block math in jnp and
lets jax autodiff the loop — correct, but the [B,H,Sq,Sk]-per-step score
blocks are XLA's to schedule. This variant runs every block pair through
ops.pallas_attention's forward kernel (MXU matmuls, VMEM-resident online
softmax, O(block) memory) and merges the per-block partials with their
logsumexps:

    lse' = logaddexp(lse, lse_b)
    o'   = o·exp(lse−lse') + o_b·exp(lse_b−lse')

Backward is the standard ring-attention backward, written explicitly
because pallas_call is opaque to autodiff: K/V (and their gradient
accumulators) make a second pass around the ring; each device adds its
block's contribution using the saved final logsumexp, and after n hops a
block's accumulated dK/dV arrives back at its owner. Residuals are
O(S/n · D) per device, and each hop's contribution runs through the Pallas
backward kernels (ops/pallas_attention._flash_bwd) — peak memory O(block)
per core; no score matrix is ever materialized, forward or backward.

Same contract as ring_attention: local shards [B, S/n, H, D] inside a
shard_map with ``axis_name`` bound; ``make_flash_ring_attention`` wraps
for standalone use. Verified against ring_attention and the single-device
reference in tests.
"""

from __future__ import annotations

from functools import partial

import jax

from tpu_sandbox.utils.compat import axis_size, shard_map
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpu_sandbox.ops.pallas_attention import (
    flash_attention_lse,
    make_flash_bwd_lse,
)
from tpu_sandbox.ops.pallas_common import NEG as _NEG
from tpu_sandbox.parallel.ring_attention import varying as _varying


def _merge(o, lse, o_b, lse_b):
    """Combine two attention partials by their logsumexps (fp32)."""
    new_lse = jnp.logaddexp(lse, lse_b)
    w_old = jnp.exp(lse - new_lse)[..., None]
    w_new = jnp.exp(lse_b - new_lse)[..., None]
    return o * w_old + o_b.astype(jnp.float32) * w_new, new_lse


def _ring_fwd_pass(q, k, v, axis_name, causal, block_q, block_k, interpret):
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    q_off = idx * s_loc
    shift = [(i, (i + 1) % n) for i in range(n)]

    o0 = _varying(jnp.zeros((b, s_loc, h, d), jnp.float32), axis_name)
    lse0 = _varying(jnp.full((b, s_loc, h), _NEG, jnp.float32), axis_name)

    def body(j, carry):
        o, lse, k_cur, v_cur = carry
        src = (idx - j) % n
        o_b, lse_b = flash_attention_lse(
            q, k_cur, v_cur, causal=causal, q_offset=q_off,
            kv_offset=src * s_loc, block_q=block_q, block_k=block_k,
            interpret=interpret,
        )
        o, lse = _merge(o, lse, o_b, lse_b)
        k_nxt = lax.ppermute(k_cur, axis_name, shift)
        v_nxt = lax.ppermute(v_cur, axis_name, shift)
        return (o, lse, k_nxt, v_nxt)

    o, lse, _, _ = lax.fori_loop(0, n, body, (o0, lse0, k, v))
    return o.astype(q.dtype), lse


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_ring_attention(
    q, k, v, axis_name: str, causal: bool = True,
    block_q: int = 128, block_k: int = 128, interpret: bool | None = None,
):
    """q,k,v: local shards [B, S/n, H, D] (inside shard_map) -> same shape."""
    out, _ = _ring_fwd_pass(q, k, v, axis_name, causal, block_q, block_k,
                            interpret)
    return out


def _fr_fwd(q, k, v, axis_name, causal, block_q, block_k, interpret):
    out, lse = _ring_fwd_pass(q, k, v, axis_name, causal, block_q, block_k,
                              interpret)
    return out, (q, k, v, out, lse)


def _fr_bwd(axis_name, causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    s_loc = q.shape[1]
    q_off = idx * s_loc
    shift = [(i, (i + 1) % n) for i in range(n)]

    # q-side padding and delta are loop-invariant: pad/compute them once,
    # per hop only the rotating K/V blocks are prepped
    partial_bwd = make_flash_bwd_lse(
        q, out, g, lse, causal=causal, q_offset=q_off,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    dq0 = _varying(jnp.zeros(q.shape, jnp.float32), axis_name)
    dkv0 = _varying(jnp.zeros(k.shape, jnp.float32), axis_name)

    def body(j, carry):
        dq, dk_acc, dv_acc, k_cur, v_cur = carry
        src = (idx - j) % n
        dq_c, dk_c, dv_c = partial_bwd(k_cur, v_cur, src * s_loc)
        dq = dq + dq_c
        dk_acc = dk_acc + dk_c
        dv_acc = dv_acc + dv_c
        # K/V and their gradient accumulators travel the ring TOGETHER, so
        # after n hops each block's accumulated dK/dV is back at its owner
        rotate = lambda x: lax.ppermute(x, axis_name, shift)  # noqa: E731
        return (dq, rotate(dk_acc), rotate(dv_acc), rotate(k_cur),
                rotate(v_cur))

    dq, dk, dv, _, _ = lax.fori_loop(
        0, n, body, (dq0, dkv0, jnp.zeros_like(dkv0), k, v)
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_ring_attention.defvjp(_fr_fwd, _fr_bwd)


def make_flash_ring_attention(
    mesh: Mesh, axis: str, *, causal: bool = True,
    block_q: int = 128, block_k: int = 128, interpret: bool | None = None,
):
    """Standalone jit'd flash-ring attention over global [B, S, H, D]
    arrays sharded on dim 1 (mirror of make_ring_attention)."""
    if axis not in mesh.axis_names:
        raise ValueError(f"axis {axis!r} not in mesh axes {mesh.axis_names}")

    # positional call: custom_vjp nondiff argnums must not arrive as kwargs
    def local(q, k, v):
        return flash_ring_attention(q, k, v, axis, causal, block_q, block_k,
                                    interpret)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis),
        check_vma=False,  # pallas_call outputs carry no vma annotation
    )
    return jax.jit(fn)
