"""Compiler-driven parallelism: rule-based sharding + jit (the pjit path).

The explicit engine (data_parallel.py) spells out its collectives with
``shard_map`` + ``lax.pmean`` — the "I am the DDP reducer" style. This module
is the complementary, fully XLA-driven style from the TPU playbook: pick a
``Mesh``, annotate parameter/batch shardings with ``PartitionSpec`` rules,
``jit`` the step, and let XLA *insert* the collectives (grad all-reduce over
the data axis, activation collectives around tensor-sharded matmuls) and
overlap them with compute.

This is how the reference's missing parallelisms become cheap mesh axes
(SURVEY §2.2: TP/PP/SP "absent, not required — mesh axis is cheap to add
later"): e.g. the 3000x3000 experiment's 18M x 10 classifier head
(mnist_onegpu.py:21-31's LazyLinear) tensor-shards with one rule,
``("fc/kernel", P("model", None))`` — an 18M-row matmul split across chips,
each holding 18M/n rows, with XLA adding the psum.

No DDP analogue exists for this file on purpose: torch needs separate
engines for DP (DistributedDataParallel) and TP (megatron-style layers);
on TPU they are the same jit with different specs.

Note BatchNorm semantics: under jit the batch axis is a *global* axis, so
BN reduces over the full global batch (SyncBN). The explicit engine keeps
per-replica BN for DDP loss-parity; this engine is the idiomatic-TPU
alternative. Pick per experiment.
"""

from __future__ import annotations

import re
from functools import partial
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_sandbox.ops.losses import cross_entropy_loss
from tpu_sandbox.parallel.collectives import (
    CompressedAllReduce,
    as_compress_policy,
)
from tpu_sandbox.train.state import TrainState

Rule = tuple[str, P]


def megatron_rules(model_axis: str = "model") -> list[Rule]:
    """The COMPLETE tensor-parallel ruleset for models.transformer (VERDICT
    r01 weak #5 flagged the partial qkv/mlp-only version): Megatron-style
    column-parallel qkv (heads) and mlp-up (d_ff), row-parallel attention
    out-projection and mlp-down, vocab-sharded token embedding and lm_head,
    d_model-sharded position embedding. Under jit, XLA inserts the psums
    after the row-parallel matmuls and the gather/psum around the sharded
    embedding lookups."""
    m = model_axis
    return [
        (r"attn/qkv/kernel", P(None, None, m, None)),
        (r"attn/qkv/bias", P(None, m, None)),
        (r"attn/out/kernel", P(m, None, None)),
        (r"mlp/up/kernel", P(None, m)),
        (r"mlp/up/bias", P(m)),
        (r"mlp/down/kernel", P(m, None)),
        (r"lm_head/kernel", P(None, m)),
        (r"lm_head/bias", P(m)),
        (r"tok_emb/embedding", P(m, None)),
        (r"pos_emb/embedding", P(None, m)),
    ]


def _match_rule(path: str, rules: Sequence[Rule]) -> P | None:
    """First rule whose regex matches the '/'-joined param path wins;
    None when no rule claims the path (callers decide the fallback)."""
    for pattern, spec in rules:
        if re.search(pattern, path):
            return spec
    return None


def spec_for_path(path: str, rules: Sequence[Rule]) -> P:
    """Rule-matched spec for a path, default replicated."""
    s = _match_rule(path, rules)
    return P() if s is None else s


def _path_str(path) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


def param_specs(params, rules: Sequence[Rule],
                fsdp_axis: str | None = None, fsdp_axis_size: int = 1):
    """Map a params pytree to PartitionSpecs via path-regex rules.

    ``fsdp_axis``: fully-sharded data parallelism (ZeRO-3) as pure specs —
    leaves no rule claims are sharded on dim 0 over that axis when it
    divides; GSPMD then all-gathers each parameter just-in-time at its use
    and reduce-scatters its gradient, deriving the FSDP choreography from
    the sharding alone."""

    def spec(path, leaf):
        # explicit rules win outright — including an explicit P() pin; FSDP
        # only claims leaves NO rule matched
        s = _match_rule(_path_str(path), rules)
        if s is not None:
            return s
        if (
            fsdp_axis is not None
            and hasattr(leaf, "ndim") and leaf.ndim >= 1
            and leaf.shape[0] >= fsdp_axis_size
            and leaf.shape[0] % fsdp_axis_size == 0
        ):
            return P(fsdp_axis)
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


def state_specs(state: TrainState, rules: Sequence[Rule],
                zero_axis: str | None = None,
                zero_axis_size: int = 1,
                fsdp_axis: str | None = None,
                fsdp_axis_size: int = 1) -> TrainState:
    """Specs for a full TrainState: params by rules; optimizer state mirrors
    the params specs leaf-for-leaf where shapes match (optax state pytrees
    contain param-shaped leaves like momenta); BN stats replicated.

    ``zero_axis``: compiler-driven ZeRO-1 — optimizer-state leaves whose
    params carry NO rule (i.e. would be replicated) are instead sharded on
    dim 0 over that axis when it divides. The SPMD partitioner then derives
    the reduce-scatter/update/all-gather choreography from the sharding
    mismatch between gradients and moments, the pjit spelling of what
    DataParallel(zero=True) writes out by hand with shard_map."""
    if fsdp_axis is not None and zero_axis is None:
        # FSDP subsumes ZeRO-1 at THIS layer too (not just in the engine
        # constructor): params sharded without their moments would quietly
        # keep 2x replicated optimizer memory per device
        zero_axis, zero_axis_size = fsdp_axis, fsdp_axis_size
    pspecs = param_specs(state.params, rules, fsdp_axis=fsdp_axis,
                         fsdp_axis_size=fsdp_axis_size)

    def opt_spec(path, leaf):
        # param-shaped moment buffers share the param's spec; scalars/counters
        # are replicated. Match by trailing path against the params tree.
        s = _match_rule(_path_str(path), rules)
        if s is not None:
            return s
        if (
            zero_axis is not None and hasattr(leaf, "ndim") and leaf.ndim >= 1
            and leaf.shape[0] >= zero_axis_size
            and leaf.shape[0] % zero_axis_size == 0
        ):
            return P(zero_axis)
        return P()

    return TrainState(
        step=P(),
        params=pspecs,
        batch_stats=jax.tree.map(lambda _: P(), state.batch_stats),
        opt_state=jax.tree_util.tree_map_with_path(opt_spec, state.opt_state),
        # this engine never threads an error-feedback residual (grad
        # compression here is stateless); mirror the (normally empty) node
        # so pytree structures line up whatever state is handed in
        grad_residual=jax.tree.map(lambda _: P(), state.grad_residual),
    )


class PjitEngine:
    """jit-with-shardings train-step factory.

    Usage::

        eng = PjitEngine(model, tx, mesh, rules=[("fc/kernel", P(None, "model"))])
        state = eng.shard_state(state)
        state, loss = eng.train_step(state, images, labels)  # global batch
    """

    def __init__(
        self,
        model,
        tx: optax.GradientTransformation,
        mesh: Mesh,
        *,
        rules: Sequence[Rule] = (),
        batch_axis: str = "data",
        input_spec: P | None = None,
        image_size: tuple[int, int] | None = None,
        task: str = "image",
        aux_weight: float = 0.01,
        zero_axis: str | None = None,
        fsdp_axis: str | None = None,
        donate: bool = True,
        grad_compress: str | CompressedAllReduce = "none",
        overlap_grad_sync: bool = False,
        bucket_mb: float = 25.0,
    ):
        if task not in ("image", "lm"):
            raise ValueError(f"task must be 'image' or 'lm', got {task!r}")
        if batch_axis not in mesh.axis_names:
            raise ValueError(
                f"batch axis {batch_axis!r} not in mesh axes {mesh.axis_names}"
            )
        self.model = model
        self.tx = tx
        self.mesh = mesh
        self.rules = list(rules)
        self.batch_axis = batch_axis
        # input_spec can additionally shard the image dims (spatial
        # partitioning — XLA inserts conv halo exchanges): e.g.
        # P('data', 'spatial') splits batch AND image height.
        self.input_spec = input_spec if input_spec is not None else P(batch_axis)
        self.image_size = image_size
        self.task = task
        # Weight on sown "aux_loss" values (MoE load-balance, Switch eq. 4,
        # parallel/expert.py:65): without it top-1 routing can collapse onto
        # one expert (VERDICT r01 weak #8). 0.01 is the Switch paper's alpha;
        # models that sow nothing are unaffected.
        self.aux_weight = aux_weight
        if fsdp_axis is not None:
            if fsdp_axis not in mesh.axis_names:
                raise ValueError(
                    f"fsdp axis {fsdp_axis!r} not in mesh axes "
                    f"{mesh.axis_names}"
                )
            if zero_axis is not None and zero_axis != fsdp_axis:
                raise ValueError(
                    f"zero_axis {zero_axis!r} conflicts with fsdp_axis "
                    f"{fsdp_axis!r}: moments must shard with their params "
                    "(omit zero_axis — FSDP subsumes ZeRO-1)"
                )
            # FSDP subsumes ZeRO-1: moments follow their (sharded) params
            zero_axis = fsdp_axis
        if zero_axis is not None and zero_axis not in mesh.axis_names:
            raise ValueError(
                f"zero axis {zero_axis!r} not in mesh axes {mesh.axis_names}"
            )
        self.zero_axis = zero_axis
        self.fsdp_axis = fsdp_axis
        self.donate = donate
        # Compressed/bucketed grad sync needs the gradients to cross
        # exactly ONE mesh axis (the batch axis) in a known place, so it is
        # spelled as an explicit shard_map wrapped around the grad
        # computation. That only composes with pure data parallelism: under
        # TP rules / FSDP / spatial input specs, XLA owns where the
        # collectives go and we cannot intercept them. zero_axis is fine
        # (the sharding mismatch is between replicated grads and sharded
        # moments, downstream of the sync). Stateless here: no
        # error-feedback residual — use DataParallel for int8 + error
        # feedback.
        self.grad_compress = as_compress_policy(grad_compress)
        if bucket_mb <= 0:
            raise ValueError(f"bucket_mb must be positive, got {bucket_mb}")
        self.overlap = bool(overlap_grad_sync)
        self.bucket_bytes = int(bucket_mb * 2 ** 20)
        if self.grad_compress.mode != "none" or self.overlap:
            what = ("grad_compress" if self.grad_compress.mode != "none"
                    else "overlap_grad_sync")
            if self.rules:
                raise ValueError(
                    f"{what} composes only with pure data parallelism; "
                    f"drop the TP rules or disable {what}"
                )
            if self.fsdp_axis is not None:
                raise ValueError(
                    f"{what} does not compose with fsdp_axis (FSDP's "
                    "reduce-scatter is compiler-inserted)"
                )
            if self.input_spec != P(self.batch_axis):
                raise ValueError(
                    f"{what} needs input_spec == P({self.batch_axis!r}) "
                    f"(batch-only sharding), got {self.input_spec}"
                )
        self._jitted: Callable | None = None

    def _state_specs(self, state: TrainState) -> TrainState:
        """Single home for spec derivation so shard_state's placement and
        the jitted step's in/out shardings can never desynchronize."""
        return state_specs(
            state, self.rules, zero_axis=self.zero_axis,
            zero_axis_size=(
                self.mesh.shape[self.zero_axis] if self.zero_axis else 1
            ),
            fsdp_axis=self.fsdp_axis,
            fsdp_axis_size=(
                self.mesh.shape[self.fsdp_axis] if self.fsdp_axis else 1
            ),
        )

    def _sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def shard_state(self, state: TrainState) -> TrainState:
        specs = self._state_specs(state)
        return jax.tree.map(
            lambda x, s: jax.device_put(x, self._sharding(s)), state, specs
        )

    def shard_batch(self, images, labels):
        return (
            jax.device_put(jnp.asarray(images), self._sharding(self.input_spec)),
            jax.device_put(jnp.asarray(labels), self._sharding(P(self.batch_axis))),
        )

    def _build(self, state: TrainState) -> Callable:
        model, tx, image_size = self.model, self.tx, self.image_size

        if self.task == "lm":
            aux_weight = self.aux_weight

            def loss_fn(params, batch_stats, tokens, targets):
                logits, sown = model.apply(
                    {"params": params}, tokens, mutable=["aux_loss"]
                )
                loss = cross_entropy_loss(
                    logits.reshape(-1, logits.shape[-1]), targets.reshape(-1)
                )
                aux = jax.tree.leaves(sown.get("aux_loss", {}))
                if aux:  # mean over layers: alpha independent of depth
                    loss = loss + aux_weight * sum(aux) / len(aux)
                return loss, batch_stats

        else:

            def loss_fn(params, batch_stats, images, labels):
                variables = {"params": params}
                if batch_stats:
                    variables["batch_stats"] = batch_stats
                logits, mutated = model.apply(
                    variables, images, train=True, mutable=["batch_stats"]
                )
                return cross_entropy_loss(logits, labels), mutated.get(
                    "batch_stats", {}
                )

        compress = self.grad_compress
        overlap, bucket_bytes = self.overlap, self.bucket_bytes
        if compress.mode != "none" or overlap:
            if jax.tree.leaves(state.batch_stats):
                raise ValueError(
                    "grad_compress/overlap_grad_sync under PjitEngine "
                    "requires a BN-free model: batch stats mutate per data "
                    "shard inside the grad shard_map and cannot be returned "
                    "replicated. Use DataParallel (per-replica BN) instead."
                )
            from jax import lax

            from tpu_sandbox.utils.compat import shard_map

            axis = self.batch_axis
            size = self.mesh.shape[axis]

            def grads_body(params, images, labels):
                (loss, _), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(params, {}, images, labels)
                if overlap:
                    from tpu_sandbox.parallel.buckets import sync_buckets

                    grads, _ = sync_buckets(
                        grads, axis, size, compress,
                        bucket_bytes=bucket_bytes,
                    )
                else:
                    grads, _ = compress.pmean_tree(grads, axis, size, None)
                return lax.pmean(loss, axis), grads

            grads_fn = shard_map(
                grads_body,
                mesh=self.mesh,
                in_specs=(P(), P(axis), P(axis)),
                out_specs=(P(), P()),
                check_vma=False,  # grads are replicated by the compressed
                # pmean; the static analysis can't see through it
            )

            def step(state: TrainState, images, labels):
                if image_size is not None and self.task == "image":
                    from tpu_sandbox.train import prepare_inputs
                    images = prepare_inputs(model, images, image_size)
                loss, grads = grads_fn(state.params, images, labels)
                updates, new_opt = tx.update(
                    grads, state.opt_state, state.params
                )
                return (
                    state.replace(
                        step=state.step + 1,
                        params=optax.apply_updates(state.params, updates),
                        opt_state=new_opt,
                    ),
                    loss,
                )

        else:

            def step(state: TrainState, images, labels):
                if image_size is not None and self.task == "image":
                    from tpu_sandbox.train import prepare_inputs
                    images = prepare_inputs(model, images, image_size)
                (loss, new_stats), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(state.params, state.batch_stats, images, labels)
                updates, new_opt = tx.update(grads, state.opt_state, state.params)
                return (
                    state.replace(
                        step=state.step + 1,
                        params=optax.apply_updates(state.params, updates),
                        batch_stats=new_stats,
                        opt_state=new_opt,
                    ),
                    loss,
                )

        specs = self._state_specs(state)
        to_sh = lambda tree: jax.tree.map(self._sharding, tree)  # noqa: E731
        return jax.jit(
            step,
            in_shardings=(
                to_sh(specs),
                self._sharding(self.input_spec),
                self._sharding(P(self.batch_axis)),
            ),
            out_shardings=(to_sh(specs), self._sharding(P())),
            donate_argnums=(0,) if self.donate else (),
        )

    def train_step(self, state: TrainState, images, labels):
        if self._jitted is None:
            self._jitted = self._build(state)
        return self._jitted(state, images, labels)

    def lower_step(self, state: TrainState, images, labels):
        """AOT-lower the train step without executing it — same hook as
        ``DataParallel.lower_step`` so the HLO analysis tools (traffic,
        schedule, graftlint pass 2) can treat every engine uniformly."""
        if self._jitted is None:
            self._jitted = self._build(state)
        return self._jitted.lower(state, images, labels)
