"""Ring attention: causal attention with the sequence sharded over a mesh
axis — the long-context workhorse.

Each device holds a contiguous S/n slice of Q, K, V. K/V blocks rotate
around the ring (``lax.ppermute``, which XLA maps onto neighbor ICI links)
while every device accumulates its queries' attention over each arriving
block with the online-softmax (flash-attention) update, fp32 statistics.
After n-1 rotations every query has attended to every key it is allowed to
see; memory per device stays O(S/n * S/n) per block instead of O(S^2).

Causality with a sharded sequence is handled by *global* positions: local
query i on shard r has global position r*(S/n)+i, and each arriving K/V
block knows which shard it came from, so masking needs no full-sequence
materialization.

The reference has no analogue (no attention, no send/recv — SURVEY §2.2);
this is capability the TPU build adds because long context is first-class
here. Verified in tests against ops.attention.causal_attention.

``ring_attention`` must run inside a shard_map with ``axis_name`` bound;
``make_ring_attention`` wraps it for standalone use.
"""

from __future__ import annotations

from functools import partial

import jax

from tpu_sandbox.utils.compat import axis_size, shard_map
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def varying(x, axis_name):
    """Mark an array device-varying over ``axis_name`` for jax's VMA typing
    (pcast on newer jax, pvary fallback). Shared by both ring variants."""
    try:
        return lax.pcast(x, axis_name, to="varying")
    except (AttributeError, TypeError):
        pass
    try:
        return lax.pvary(x, axis_name)
    except AttributeError:
        return x  # pre-VMA jax: nothing to annotate (check is off anyway)


def _online_update(carry, kv_block, q, src_index, *, local_len, causal):
    """Accumulate one arriving K/V block into the online-softmax state."""
    o, m, l, q_offset = carry
    k, v = kv_block
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.asarray(d, jnp.float32))
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        kv_pos = src_index * local_len + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= kv_pos[None, :]
        scores = jnp.where(mask[None, None], scores, -jnp.inf)

    block_max = scores.max(-1)  # [B,H,Q]
    new_m = jnp.maximum(m, block_max)
    # guard: fully-masked rows have new_m == -inf; keep math finite
    safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
    alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - safe_m, -jnp.inf))
    p = jnp.exp(scores - safe_m[..., None])  # exp(-inf)=0 handles masked
    l_new = l * alpha + p.sum(-1)
    o_new = o * alpha[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(jnp.float32)
    )
    return (o_new, new_m, l_new, q_offset)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    *,
    causal: bool = True,
) -> jnp.ndarray:
    """q,k,v: local shards [B, S/n, H, D] (inside shard_map). -> [B, S/n, H, D]."""
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    q_offset = idx * s_local

    # the accumulators are device-varying state: jax's VMA typing needs the
    # initial zeros cast as such or the fori_loop carry types mismatch
    o = varying(jnp.zeros((b, h, s_local, d), jnp.float32), axis_name)
    m = varying(jnp.full((b, h, s_local), -jnp.inf, jnp.float32), axis_name)
    l = varying(jnp.zeros((b, h, s_local), jnp.float32), axis_name)

    # neighbor ring: shift K/V to rank+1 each step, so at step j we hold the
    # block that originated at rank (idx - j) mod n
    shift = [(i, (i + 1) % n) for i in range(n)]

    def body(j, carry):
        o, m, l, k_cur, v_cur = carry
        src = (idx - j) % n
        o, m, l, _ = _online_update(
            (o, m, l, q_offset), (k_cur, v_cur), q, src,
            local_len=s_local, causal=causal,
        )
        k_nxt = lax.ppermute(k_cur, axis_name, shift)
        v_nxt = lax.ppermute(v_cur, axis_name, shift)
        return (o, m, l, k_nxt, v_nxt)

    o, m, l, _, _ = lax.fori_loop(0, n, body, (o, m, l, k, v))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [B,S/n,H,D]


def make_ring_attention(mesh: Mesh, axis: str, *, causal: bool = True):
    """Standalone jit'd ring attention over global [B, S, H, D] arrays
    sharded on dim 1."""
    if axis not in mesh.axis_names:
        raise ValueError(f"axis {axis!r} not in mesh axes {mesh.axis_names}")
    fn = shard_map(
        partial(ring_attention, axis_name=axis, causal=causal),
        mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis),
    )
    return jax.jit(fn)
