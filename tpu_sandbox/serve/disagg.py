"""Disaggregated prefill/decode prototype over the MPMD stage transport.

Splits one request's serving between two replicas the way the MPMD
pipeline splits training between stages: a PREFILL replica runs the
prompt pass, then ships exactly this request's paged-KV block rows plus
the next-token logits over a
:class:`~tpu_sandbox.mpmd.transport.Transport`; a DECODE replica with
its own (differently laid out) page buffers imports the rows at its own
freshly-allocated block ids and runs the decode loop. Block ids are
private to each cache — attention only ever gathers through the block
table — so the handoff re-homes the pages without touching their
contents, and the generated tokens are bitwise identical to a
single-replica engine serving the same request (same compiled step
geometry, same ``sample_token`` keyed by (seed, step index); held by
tests/test_mpmd.py).

This is the serving face of the tentpole: the same durable claim-once
slots that carry activations between training stages carry KV pages
between serving roles. A real deployment would put a DCN wire behind
the Transport interface; everything above it stays as written.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

from tpu_sandbox.models.transformer import TransformerConfig
from tpu_sandbox.serve.cache import CacheConfig, PagedKVCache
from tpu_sandbox.serve.decode import (
    DecodeStep,
    build_decode_step,
    init_pages,
    sample_token,
)


@dataclass
class DisaggRequest:
    rid: str
    prompt: list[int]
    max_new_tokens: int
    seed: int = 0
    temperature: float = 0.0
    top_k: int = 0
    eos_token: int | None = None


def _edge(rid: str) -> str:
    return f"kvpage/{rid}"


def _pick_token(req: DisaggRequest, logits_row: np.ndarray,
                step_index: int) -> int:
    """Greedy or replay-exact sampled — byte-for-byte the engine's
    ``_pick_token`` policy, keyed by (request seed, decode-step index)."""
    if req.temperature <= 0.0:
        return int(logits_row.argmax())
    return sample_token(logits_row, seed=req.seed, step_index=step_index,
                        temperature=req.temperature, top_k=req.top_k)


class _Replica:
    def __init__(self, params, model_cfg: TransformerConfig,
                 cache_cfg: CacheConfig, transport, *,
                 step: DecodeStep | None = None, max_batch: int = 4,
                 buckets: tuple[int, ...] = (16, 32, 64)):
        self.params = params
        self.model_cfg = model_cfg
        self.cache_cfg = cache_cfg
        self.transport = transport
        # replicas may share one compiled DecodeStep (same geometry)
        self.step = step if step is not None else build_decode_step(
            model_cfg, cache_cfg, max_batch=max_batch, buckets=buckets)
        self.cache = PagedKVCache(cache_cfg)
        self.k_pages, self.v_pages = init_pages(
            model_cfg, cache_cfg, self.step.cache_dtype)


class PrefillReplica(_Replica):
    """Runs the prompt pass and ships the request's KV rows + logits."""

    def prefill_and_ship(self, req: DisaggRequest) -> None:
        alloc = self.cache.alloc(req.prompt, 0)
        if alloc is None:
            raise RuntimeError("prefill cache out of blocks")
        plen = len(req.prompt)
        bucket = self.step.pick_bucket(plen)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :plen] = req.prompt
        dest = self.cache.dest_indices(alloc, bucket).astype(np.int32)
        next_logits, self.k_pages, self.v_pages = self.step.prefill[bucket](
            self.params, self.k_pages, self.v_pages,
            jnp.asarray(toks), jnp.asarray(dest),
            jnp.asarray(plen - 1, jnp.int32))
        alloc.length = plen
        self.cache.commit_prefix(alloc)
        # exactly this request's block rows, in block-table order — the
        # decode side re-homes them at its own ids, contents untouched.
        # The gather happens ON DEVICE: only the request's rows are ever
        # staged (pack_views stages them for host wires; DeviceTransport
        # ships the device buffers as-is), never the whole page pool.
        ids = jnp.asarray(alloc.block_ids)
        k_rows = self.k_pages[:, ids]
        v_rows = self.v_pages[:, ids]
        self.transport.put(_edge(req.rid), 0, 0,
                           [k_rows, v_rows, next_logits])
        self.cache.free(alloc)


class DecodeReplica(_Replica):
    """Imports shipped KV rows into its own page layout and decodes."""

    def __init__(self, *args, generation: int = 0, **kwargs):
        super().__init__(*args, **kwargs)
        self.generation = generation

    def decode_from_handoff(self, req: DisaggRequest, *,
                            timeout: float = 60.0) -> list[int]:
        if not self.transport.claim(_edge(req.rid), 0, 0,
                                    generation=self.generation):
            raise RuntimeError(f"request {req.rid!r} already decoded "
                               f"in generation {self.generation}")
        k_rows, v_rows, next_logits = self.transport.get(
            _edge(req.rid), 0, 0, timeout=timeout)
        plen = len(req.prompt)
        alloc = self.cache.alloc(req.prompt, 0)
        if alloc is None:
            raise RuntimeError("decode cache out of blocks")
        if len(alloc.block_ids) > alloc.n_shared:
            idx = jnp.asarray(alloc.block_ids[alloc.n_shared:])
            self.k_pages = self.k_pages.at[:, idx].set(
                jnp.asarray(k_rows[:, alloc.n_shared:],
                            self.step.cache_dtype))
            self.v_pages = self.v_pages.at[:, idx].set(
                jnp.asarray(v_rows[:, alloc.n_shared:],
                            self.step.cache_dtype))
        alloc.length = plen
        self.cache.commit_prefix(alloc)

        generated = [_pick_token(req, np.asarray(next_logits), 0)]
        tokens = list(req.prompt) + generated
        B = self.step.max_batch
        bs = self.cache_cfg.block_size
        while (len(generated) < req.max_new_tokens
               and (req.eos_token is None
                    or generated[-1] != req.eos_token)):
            # the incoming token's kv slot, grown exactly like the
            # engine's _ensure_capacity (one block at a time)
            if (alloc.length % bs == 0
                    and alloc.length // bs >= len(alloc.block_ids)):
                if not self.cache.grow(alloc):
                    raise RuntimeError("decode cache out of blocks")
            toks = np.zeros((B, 1), np.int32)
            toks[0, 0] = tokens[-1]
            lengths = np.zeros((B,), np.int32)
            lengths[0] = len(tokens)
            tables = np.zeros((B, self.cache_cfg.max_blocks_per_seq),
                              np.int32)
            tables[0] = self.cache.block_table(alloc)
            logits, self.k_pages, self.v_pages = self.step.decode(
                self.params, self.k_pages, self.v_pages,
                jnp.asarray(toks), jnp.asarray(lengths),
                jnp.asarray(tables))
            alloc.length = len(tokens)
            tok = _pick_token(req, np.asarray(logits)[0], len(generated))
            generated.append(tok)
            tokens.append(tok)
        self.cache.free(alloc)
        return generated


def serve_disaggregated(prefill: PrefillReplica, decode: DecodeReplica,
                        req: DisaggRequest, *,
                        timeout: float = 60.0) -> list[int]:
    """One request through the split path: prompt on the prefill replica,
    pages over the transport, tokens from the decode replica."""
    prefill.prefill_and_ship(req)
    return decode.decode_from_handoff(req, timeout=timeout)
