"""Serve replicas behind a KV-backed request queue — zero-loss by protocol.

The KV store (the same one the elastic runtime coordinates through) holds
the whole request plane:

    serve/req/<rid>      request body  {rid, prompt, max_new_tokens}
    serve/queue/tail     atomic entry counter (``add()``)
    serve/queue/<n>      entry n -> rid  (requeues append fresh entries)
    serve/claim/<n>      claim-once marker: first ``add()`` == 1 wins
    serve/lease/<rid>    TTL heartbeat while a replica works the request
    serve/scavenged/<n>  claim-once marker so an orphaned entry is
                         requeued exactly once
    serve/tq/<tag>/tail  targeted queue: entries the gateway routed to one
    serve/tq/<tag>/<n>   specific replica (prefix-cache affinity). Only the
                         owner claims its own targeted entries; peers
                         scavenge a dead owner's entries back to the shared
                         queue (see ``scavenge``), so routing is an
                         optimization, never a new loss case.
    serve/tclaim/<tag>/<n>  claim-once markers for targeted entries
    serve/tscav/<tag>/<n>   scavenged-once markers for targeted entries
    serve/result/<rid>   terminal verdict — a token result or an explicit
                         SHED body; idempotent for results (greedy or
                         seeded-sampled decode over bitwise-deterministic
                         steps: every execution of a request writes
                         identical bytes)
    serve/done/<rid>     claim-once verdict marker: the first publisher
                         (result or SHED) wins, so a request reaches
                         exactly one terminal verdict even when a shed
                         races a scavenged duplicate execution
    serve/load/<tag>     TTL'd per-replica load report (queue depth,
                         block-pool pressure, decode-step lag) — the
                         autoscaler's input; also carries the running
                         weight version (``ver``), which is the swap ack
                         the deploy controller advances on
    serve/pin/<rid>      weight-version pin, written by the first claimer:
                         every later execution of the rid (requeue,
                         scavenge, drain) decodes on this version, so a
                         verdict is always single-version and replays are
                         bitwise. Cleared only by a client retry, which
                         starts a fresh lifecycle.
    serve/cmd/<tag>      fault mailbox (shed_storm / stall_replica /
                         swap — the deploy controller's rolling update)
    serve/total          number of distinct requests the producer will pose

Loss cases and their answers:

- **SIGTERM (drain path)** — the replica evicts every in-flight sequence
  back to request form and appends fresh queue entries, then exits with
  ``PREEMPTED_EXIT_CODE`` so the elastic budget treats it as preemption.
- **SIGKILL (no goodbye)** — its claims stay but the leases expire;
  any peer's scavenge pass requeues claimed-unleased-unresulted entries
  (at most once per entry via ``serve/scavenged/<n>``).
- **Double execution** — a slow-but-alive claimant racing a scavenged
  duplicate wastes compute, never correctness: results are identical and
  the write is idempotent.

Replicas run as ranks of a HostAgent gang (one rank per replica), so a
killed replica process triggers the standard generation teardown and
relaunch — the elastic runtime is the autoscaler's restart loop.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
from dataclasses import dataclass
from typing import Sequence

from tpu_sandbox.obs import get_recorder
from tpu_sandbox.runtime.kvstore import KVClient
from tpu_sandbox.runtime.supervisor import ENV_KV_PORT, PREEMPTED_EXIT_CODE

K_TAIL = "serve/queue/tail"
K_TOTAL = "serve/total"


def k_req(rid: str) -> str:
    return f"serve/req/{rid}"


def k_queue(seq: int) -> str:
    return f"serve/queue/{seq}"


def k_claim(seq: int) -> str:
    return f"serve/claim/{seq}"


def k_lease(rid: str) -> str:
    return f"serve/lease/{rid}"


def k_scavenged(seq: int) -> str:
    return f"serve/scavenged/{seq}"


def k_result(rid: str) -> str:
    return f"serve/result/{rid}"


def k_done(rid: str) -> str:
    return f"serve/done/{rid}"


def k_pin(rid: str) -> str:
    return f"serve/pin/{rid}"


def k_load(tag: str) -> str:
    return f"serve/load/{tag}"


def k_cmd(tag: str) -> str:
    return f"serve/cmd/{tag}"


def k_tq_tail(tag: str) -> str:
    return f"serve/tq/{tag}/tail"


def k_tq(tag: str, seq: int) -> str:
    return f"serve/tq/{tag}/{seq}"


def k_tq_claim(tag: str, seq: int) -> str:
    return f"serve/tclaim/{tag}/{seq}"


def k_tq_scavenged(tag: str, seq: int) -> str:
    return f"serve/tscav/{tag}/{seq}"


# -- producer side -----------------------------------------------------------


def write_request(kv, rid: str, prompt: Sequence[int],
                  max_new_tokens: int, *, deadline_unix: float | None = None,
                  temperature: float = 0.0, top_k: int = 0,
                  seed: int = 0, tc: dict | None = None,
                  gw: str | None = None) -> None:
    """Write the request body without enqueueing — the gateway writes the
    body once, then targets the entry at the replica routing chose.
    ``deadline_unix`` is wall clock (``time.time()``) so it survives the
    hop between client and replica processes; replicas translate it to
    their engine clock at claim time. ``tc`` is the submit trace context
    (``TraceContext.to_wire()``); it rides the body so the claim span can
    chain to the gateway's enqueue span. ``gw`` is the routing gateway's
    HA identity; replicas count claims per gateway in their load reports
    so the chaos claim audit can show a killed gateway's in-flight work
    being finished by the fleet. The body is written exactly once per rid
    either way, so adding these keys never perturbs the idempotent-
    verdict contract."""
    body = {"rid": rid, "prompt": list(map(int, prompt)),
            "max_new_tokens": int(max_new_tokens)}
    if deadline_unix is not None:
        body["deadline_unix"] = float(deadline_unix)
    if temperature > 0.0:
        body.update(temperature=float(temperature), top_k=int(top_k),
                    seed=int(seed))
    if tc is not None:
        body["tc"] = tc
    if gw is not None:
        body["gw"] = str(gw)
    kv.set(k_req(rid), json.dumps(body))


def submit_request(kv, rid: str, prompt: Sequence[int],
                   max_new_tokens: int, *, deadline_unix: float | None = None,
                   temperature: float = 0.0, top_k: int = 0,
                   seed: int = 0) -> None:
    """Queue one request on the shared queue (any replica may claim it)."""
    write_request(kv, rid, prompt, max_new_tokens,
                  deadline_unix=deadline_unix, temperature=temperature,
                  top_k=top_k, seed=seed)
    enqueue(kv, rid)


def enqueue(kv, rid: str) -> int:
    n = kv.add(K_TAIL) - 1
    kv.set(k_queue(n), rid)
    return n


def enqueue_to(kv, tag: str, rid: str) -> int:
    """Append an entry to one replica's targeted queue. The request body
    must already be written (``write_request``)."""
    n = kv.add(k_tq_tail(tag)) - 1
    kv.set(k_tq(tag, n), rid)
    return n


def targeted_tags(kv) -> list[str]:
    """Replica tags that have (or had) a targeted queue — scavenge scope."""
    tags = {k.split("/")[2] for k in kv.keys("serve/tq/")
            if k.count("/") >= 3}
    return sorted(tags)


def announce_total(kv, total: int) -> None:
    kv.set(K_TOTAL, str(total))


def results_done(kv) -> bool:
    total = kv.try_get(K_TOTAL)
    if total is None:
        return False
    return len(kv.keys("serve/result/")) >= int(total)


def read_result(kv, rid: str, timeout: float = 60.0) -> dict:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        raw = kv.try_get(k_result(rid))
        if raw is not None:
            return json.loads(raw)
        time.sleep(0.02)
    raise TimeoutError(f"no result for {rid} within {timeout}s")


def read_load_reports(kv) -> dict[str, dict]:
    """Current (unexpired) per-replica load reports, keyed by replica tag."""
    out = {}
    for key in kv.keys("serve/load/"):
        raw = kv.try_get(key)
        if raw is not None:
            out[key[len("serve/load/"):]] = json.loads(raw)
    return out


# -- replica side ------------------------------------------------------------


@dataclass
class ReplicaStats:
    claimed: int = 0
    completed: int = 0
    requeued: int = 0
    scavenged: int = 0
    shed: int = 0
    stalls: int = 0
    swaps: int = 0
    swap_errors: int = 0


class ReplicaWorker:
    """One replica: claims queue entries into a local engine, publishes
    results, heartbeats leases, scavenges orphans. Pure poll loop — no
    threads of its own, so it embeds cleanly in tests and in the worker
    process main below."""

    def __init__(self, kv: KVClient, engine, *, tag: str = "replica",
                 lease_ttl: float = 3.0, claim_depth: int | None = None,
                 scavenge_interval: float | None = None,
                 load_interval: float | None = None,
                 ts_flusher=None, publish_ts: bool = True,
                 swap_loader=None):
        from tpu_sandbox.obs.tsdb import TimeSeriesFlusher

        self.kv = kv
        self.engine = engine
        self.tag = tag
        # durable time-series trail, flushed on the load-report cadence;
        # the health plane's per-replica rules read it under this proc
        self.ts_flusher = ts_flusher
        if self.ts_flusher is None and publish_ts:
            self.ts_flusher = TimeSeriesFlusher(
                kv, tag.replace("/", "-") or "replica")
        self.lease_ttl = lease_ttl
        self.claim_depth = claim_depth or 2 * engine.config.max_batch
        self.scavenge_interval = scavenge_interval or lease_ttl
        self.load_interval = load_interval or lease_ttl / 2
        self._scanned = 0
        self._gw_claims: dict[str, int] = {}  # routing gateway -> claims
        self._tq_scanned = 0  # cursor into our own targeted queue
        self._tq_hole_slot = -1   # targeted slot seen tail-bumped but empty
        self._tq_hole_since = 0.0
        self._published: set[str] = set()
        self._pin_skipped: set[str] = set()
        # swap command -> params hook (tests/benches inject stub weights);
        # None falls back to the artifact path in the command
        self.swap_loader = swap_loader
        self._swap_error: dict | None = None
        self._next_scavenge = time.monotonic() + self.scavenge_interval
        self._next_load = 0.0  # publish on the first tick
        self.stats = ReplicaStats()
        self._draining = False

    # one request currently inside the local engine per rid
    def _local_load(self) -> int:
        return self.engine.active_requests + len(self.engine.waiting)

    def request_drain(self) -> None:
        self._draining = True

    def tick(self) -> bool:
        """One poll-loop iteration. Returns False when all work is done
        (or a drain was requested and completed)."""
        from tpu_sandbox.serve.engine import Request

        if self._draining:
            self.drain()
            return False
        if results_done(self.kv):
            return False
        self._poll_faults()
        # targeted entries first (the gateway routed them here for prefix
        # affinity — serving them elsewhere wastes the resident cache), then
        # top up from the shared queue
        tq_tail = int(self.kv.try_get(k_tq_tail(self.tag)) or b"0")
        while self._tq_scanned < tq_tail \
                and self._local_load() < self.claim_depth:
            n = self._tq_scanned
            rid_raw = self.kv.try_get(k_tq(self.tag, n))
            if rid_raw is None:
                # tail bumped, entry body not visible yet (the producer is
                # mid-write). We are the only claimer of this queue, so
                # skipping would strand the request forever — peers defer
                # to a live owner. Hold the cursor and retry, advancing
                # only once the hole proves permanent (producer died
                # between bump and set: no rid was ever written, so
                # nothing is lost by moving on).
                if self._tq_hole_slot != n:
                    self._tq_hole_slot = n
                    self._tq_hole_since = time.monotonic()
                elif time.monotonic() - self._tq_hole_since > self.lease_ttl:
                    self._tq_scanned += 1
                break
            self._tq_scanned += 1
            self._claim_entry(rid_raw, k_tq_claim(self.tag, n))
        tail = int(self.kv.try_get(K_TAIL) or b"0")
        while self._scanned < tail and self._local_load() < self.claim_depth:
            n = self._scanned
            self._scanned += 1
            self._claim_entry(self.kv.try_get(k_queue(n)), k_claim(n))
        if not self.engine.idle:
            self.engine.step()
        self._heartbeat()
        self._publish_new()
        self._publish_load()
        if time.monotonic() >= self._next_scavenge:
            self._next_scavenge = time.monotonic() + self.scavenge_interval
            self.scavenge()
        return True

    def run(self, poll: float = 0.005, timeout: float = 300.0) -> None:
        deadline = time.monotonic() + timeout
        while self.tick():
            if time.monotonic() > deadline:
                raise TimeoutError(f"replica {self.tag} timed out")
            if self.engine.idle:
                time.sleep(poll)

    def _claim_entry(self, rid_raw: bytes | None, claim_key: str) -> bool:
        """Lease-then-claim one queue entry into the local engine. False
        when the entry is absent (tail bumped, body not written yet —
        shared-queue scans revisit via scavenge; targeted scans hold the
        cursor and retry, since only the owner claims there), already
        resulted, or lost the claim race."""
        if rid_raw is None:
            return False
        t_claim = time.monotonic()
        rid = rid_raw.decode()
        if self.kv.try_get(k_result(rid)) is not None:
            return False
        # lease before claim: a scavenger never sees a fresh claim
        # without a heartbeat (spurious requeues would still be safe,
        # just wasted work)
        self.kv.set_ttl(k_lease(rid), self.tag, self.lease_ttl)
        if self.kv.add(claim_key) != 1:
            return False
        body = json.loads(self.kv.get(k_req(rid)))
        # per-gateway claim attribution for the HA/chaos claim audit: a
        # request stamped by a since-killed gateway showing up here is the
        # fleet finishing that gateway's in-flight work
        gw = body.get("gw")
        if gw is not None:
            self._gw_claims[gw] = self._gw_claims.get(gw, 0) + 1
        # a rid can come around again legitimately: a client that saw
        # our SHED verdict cleared it and re-enqueued. Forget that we
        # published, so the fresh execution's verdict goes out too
        # (the claim-once serve/done marker still arbitrates races).
        self._published.discard(rid)
        req = self._to_request(body)
        # version pin: the FIRST claimer of a rid stamps the weight version
        # it will decode on; every re-execution (requeue, scavenge, another
        # replica) reads the pin back and decodes on the same version, so
        # the published verdict is single-version and bitwise-replayable
        pin_raw = self.kv.try_get(k_pin(rid))
        if pin_raw is not None:
            req.ver = int(pin_raw)
        else:
            req.ver = int(self.engine.version)
            self.kv.set(k_pin(rid), str(req.ver))
        ctx = get_recorder().complete(
            "claim", t_claim, parent=body.get("tc"),
            args={"rid": rid, "replica": self.tag})
        if ctx is not None:
            req.tc = ctx.to_wire()
        self.engine.submit(req)
        self.stats.claimed += 1
        return True

    def _to_request(self, body: dict):
        """Queue-entry body -> engine Request, translating the wall-clock
        deadline into this engine's clock (monotonic clocks don't travel
        between processes, wall clock does)."""
        from tpu_sandbox.serve.engine import Request

        deadline = None
        if body.get("deadline_unix") is not None:
            deadline = self.engine.clock() \
                + (float(body["deadline_unix"]) - time.time())
        return Request(
            rid=body["rid"], prompt=body["prompt"],
            max_new_tokens=body["max_new_tokens"],
            arrival=self.engine.clock(), deadline=deadline,
            temperature=float(body.get("temperature", 0.0)),
            top_k=int(body.get("top_k", 0)),
            seed=int(body.get("seed", 0)))

    def _poll_faults(self) -> None:
        """Consume the replica fault mailbox (serve/cmd/<tag>): shed_storm
        sheds the local waiting queue, stall_replica freezes this poll
        loop long enough for leases to lapse (peers scavenge the claims)."""
        raw = self.kv.try_get(k_cmd(self.tag))
        if raw is None:
            return
        self.kv.delete(k_cmd(self.tag))
        cmd = json.loads(raw)
        action = cmd.get("action")
        if action == "shed_storm":
            self.stats.shed += self.engine.shed_waiting("fault:shed_storm")
        elif action == "stall_replica":
            self.stats.stalls += 1
            time.sleep(float(cmd.get("duration", 2 * self.lease_ttl)))
        elif action == "swap":
            self._apply_swap(cmd)

    def _apply_swap(self, cmd: dict) -> None:
        """Install the commanded weight version between decode steps.
        Verify-before-touch: a manifest that fails its checksums leaves the
        engine exactly as it was, with the error in the load report (the
        controller reads it and rolls back). Idempotent — the controller
        re-sends until the load report acks the version, so a replica
        killed mid-swap just swaps again after respawn."""
        ver = int(cmd.get("ver", 0))
        if ver == self.engine.version:
            return  # already there: a re-sent command, not an error
        step_dir = cmd.get("step_dir")
        if step_dir:
            from tpu_sandbox.train.checkpoint import verify_step_dir

            problems = verify_step_dir(step_dir)
            if problems:
                self._swap_error = {"ver": ver, "error": "verify",
                                    "problems": [str(p) for p in problems][:4]}
                self.stats.swap_errors += 1
                return
        params, loaded = None, False
        if self.swap_loader is not None:
            params = self.swap_loader(cmd)
            loaded = params is not None
        elif step_dir:
            from tpu_sandbox.deploy.registry import load_step_params

            try:
                params = load_step_params(step_dir, self.engine.params)
                loaded = True
            except Exception as exc:  # torn mid-read, shape mismatch, ...
                self._swap_error = {"ver": ver, "error": "load",
                                    "problems": [str(exc)[:200]]}
                self.stats.swap_errors += 1
                return
        elif self.engine.has_version(ver):
            # no artifact and no hook: a rollback to weights this process
            # still holds (None is valid params for stub engines)
            params = self.engine._params_by_ver[ver]
            loaded = True
        if not loaded:
            self._swap_error = {"ver": ver, "error": "no_params"}
            self.stats.swap_errors += 1
            return
        flushed = self.engine.swap_params(params, ver)
        self._swap_error = None
        self.stats.swaps += 1
        get_recorder().instant(
            "swap", args={"replica": self.tag, "ver": ver,
                          "prefix_flushed": flushed})

    def drain(self) -> int:
        """Requeue everything in flight; the SIGTERM path. Finished-but-
        unpublished verdicts go out first so nothing computed is lost.
        Targeted entries we never even claimed are handed back too —
        claimed first (so the scavenger can't requeue them a second time),
        then re-enqueued on the shared queue for any peer."""
        self._publish_new()
        requests = self.engine.drain_to_requests()
        for req in requests:
            if req.rid in self._published or \
                    self.kv.try_get(k_result(req.rid)) is not None:
                continue
            enqueue(self.kv, req.rid)
            self.kv.delete(k_lease(req.rid))
            self.stats.requeued += 1
        tq_tail = int(self.kv.try_get(k_tq_tail(self.tag)) or b"0")
        for n in range(tq_tail):
            if self.kv.try_get(k_tq_claim(self.tag, n)) is not None:
                continue  # claimed: drained above or already resulted
            rid_raw = self.kv.try_get(k_tq(self.tag, n))
            if rid_raw is None:
                continue
            rid = rid_raw.decode()
            if self.kv.try_get(k_result(rid)) is not None:
                continue
            if self.kv.add(k_tq_claim(self.tag, n)) != 1:
                continue  # a scavenger beat us to it
            # mark moved-to-shared so a later scavenger (seeing a claimed,
            # leaseless, unresulted entry) doesn't requeue it a second time
            self.kv.add(k_tq_scavenged(self.tag, n))
            enqueue(self.kv, rid)
            self.stats.requeued += 1
        return self.stats.requeued

    def scavenge(self) -> int:
        """Requeue claimed entries whose worker went silent (no lease, no
        result). Each entry is requeued at most once, by one scavenger.

        Targeted queues are covered too: only the owner scans its own
        queue, so a dead replica's routed entries would otherwise sit
        unclaimed forever. An unclaimed targeted entry is rescued once the
        owner's TTL'd load report is gone (dead or wedged past the TTL); a
        claimed-and-leaseless one is rescued exactly like a shared entry.
        Rescues land on the SHARED queue — the owner is presumed dead, any
        peer may serve. A spurious rescue (owner merely slow) wastes
        compute, never correctness: verdicts stay claim-once."""
        n_rescued = 0
        tail = int(self.kv.try_get(K_TAIL) or b"0")
        for n in range(tail):
            if self.kv.try_get(k_claim(n)) is None:
                continue
            rid_raw = self.kv.try_get(k_queue(n))
            if rid_raw is None:
                continue
            rid = rid_raw.decode()
            if self.kv.try_get(k_result(rid)) is not None:
                continue
            if self.kv.try_get(k_lease(rid)) is not None:
                continue  # someone is alive and working it
            if self.kv.add(k_scavenged(n)) != 1:
                continue  # another scavenger took this entry
            # exactly one scavenger reaches here per entry, so these
            # instants appear once on the merged timeline per rescue
            get_recorder().instant("lease:expired",
                                   args={"rid": rid, "entry": n})
            enqueue(self.kv, rid)
            get_recorder().instant(
                "scavenge:requeue",
                args={"rid": rid, "entry": n, "by": self.tag})
            n_rescued += 1
        for tag in targeted_tags(self.kv):
            owner_alive = tag == self.tag \
                or self.kv.try_get(k_load(tag)) is not None
            tq_tail = int(self.kv.try_get(k_tq_tail(tag)) or b"0")
            for n in range(tq_tail):
                rid_raw = self.kv.try_get(k_tq(tag, n))
                if rid_raw is None:
                    continue
                rid = rid_raw.decode()
                if self.kv.try_get(k_result(rid)) is not None:
                    continue
                if self.kv.try_get(k_lease(rid)) is not None:
                    continue
                claimed = self.kv.try_get(k_tq_claim(tag, n)) is not None
                if not claimed and owner_alive:
                    continue  # owner will claim it in its own time
                if tag == self.tag and not claimed:
                    continue  # our own backlog: tick claims it, not scavenge
                if self.kv.add(k_tq_scavenged(tag, n)) != 1:
                    continue
                get_recorder().instant(
                    "lease:expired",
                    args={"rid": rid, "entry": n, "owner": tag})
                # claim the original too, so a resurrected owner does not
                # re-execute it (racy owners only waste compute; verdict
                # publication stays claim-once either way)
                self.kv.add(k_tq_claim(tag, n))
                enqueue(self.kv, rid)
                get_recorder().instant(
                    "scavenge:requeue",
                    args={"rid": rid, "entry": n, "owner": tag,
                          "by": self.tag})
                n_rescued += 1
        self.stats.scavenged += n_rescued
        return n_rescued

    def _heartbeat(self) -> None:
        for slot in self.engine.slots:
            if slot is not None:
                self.kv.set_ttl(k_lease(slot.request.rid), self.tag,
                                self.lease_ttl)
        for req in self.engine.waiting:
            self.kv.set_ttl(k_lease(req.rid), self.tag, self.lease_ttl)

    def _publish_new(self) -> None:
        # tokens are bitwise identical across executions of a rid; the
        # ttft_s timing metadata is execution-specific, which is fine —
        # the claim-once serve/done marker means exactly one body lands,
        # and timing is observability, not an answer
        for rid, res in self.engine.results.items():
            if rid in self._published:
                continue
            # pin fence: an execution that somehow ran on a different
            # version than the rid's pin (pin written by a racing claimer
            # after our claim) must not publish — let the lease lapse and
            # the scavenger replay it on the pinned version
            pin_raw = self.kv.try_get(k_pin(rid))
            if pin_raw is not None and int(pin_raw) != int(
                    getattr(res, "ver", 0)):
                if rid not in self._pin_skipped:
                    self._pin_skipped.add(rid)
                    get_recorder().instant(
                        "verdict:pin_mismatch",
                        args={"rid": rid, "ran": getattr(res, "ver", 0),
                              "pin": int(pin_raw)})
                continue
            # the publish SPAN and verdict INSTANT are trace-only; the
            # verdict BODY below is untouched, so bitwise-identical
            # republication still holds
            t_pub = time.monotonic()
            self._publish_verdict(rid, {
                "rid": rid, "verdict": "ok", "tokens": res.tokens,
                "preemptions": res.preemptions, "replica": self.tag,
                "ver": int(getattr(res, "ver", 0)),
                "ttft_s": round(res.ttft, 6)})
            pub_ctx = get_recorder().complete(
                "publish", t_pub, parent=getattr(res, "tc", None),
                args={"rid": rid})
            get_recorder().instant(
                "verdict", parent=pub_ctx,
                args={"rid": rid, "verdict": "ok"})
            self.stats.completed += 1
        for rid, rec in self.engine.shed.items():
            if rid in self._published:
                continue
            t_pub = time.monotonic()
            self._publish_verdict(rid, {
                "rid": rid, "verdict": "SHED", "reason": rec.reason,
                "preemptions": rec.preemptions, "replica": self.tag})
            pub_ctx = get_recorder().complete(
                "publish", t_pub, parent=getattr(rec, "tc", None),
                args={"rid": rid})
            get_recorder().instant(
                "verdict", parent=pub_ctx,
                args={"rid": rid, "verdict": "SHED"})
            self.stats.shed += 1

    def _publish_verdict(self, rid: str, body: dict) -> None:
        """Exactly-one-verdict: the first publisher claims serve/done/<rid>
        and writes the result slot; a loser (a shed racing a scavenged
        duplicate's result, or vice versa) leaves the winner's verdict
        alone. Result bodies are identical across executions, so which ok
        writer wins is unobservable."""
        if self.kv.add(k_done(rid)) == 1:
            self.kv.set(k_result(rid), json.dumps(body))
        self.kv.delete(k_lease(rid))
        self._published.add(rid)

    def _publish_load(self) -> None:
        now = time.monotonic()
        if now < self._next_load:
            return
        self._next_load = now + self.load_interval
        report = dict(self.engine.load_report(), tag=self.tag,
                      wall=time.time())
        if self._gw_claims:
            report["gw_claims"] = dict(sorted(self._gw_claims.items()))
        if self._swap_error is not None:
            report["swap_error"] = self._swap_error
        self.kv.set_ttl(k_load(self.tag), json.dumps(report),
                        max(3 * self.load_interval, self.lease_ttl))
        if self.ts_flusher is not None:
            self.ts_flusher.flush()


# -- worker process main -----------------------------------------------------


def _build_engine(cfg: dict):
    import jax
    import jax.numpy as jnp

    from tpu_sandbox.models.transformer import (TransformerConfig,
                                                TransformerLM)
    from tpu_sandbox.serve.cache import CacheConfig
    from tpu_sandbox.serve.engine import ContinuousEngine, ServeConfig

    mcfg = TransformerConfig(**{**dict(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_len=128), **cfg.get("model", {})},
        dtype=jnp.float32)
    params = TransformerLM(mcfg).init(
        jax.random.key(cfg.get("param_seed", 0)),
        jnp.zeros((1, 8), jnp.int32))["params"]
    scfg = ServeConfig(
        model=mcfg,
        cache=CacheConfig(**cfg.get("cache", {})),
        max_batch=cfg.get("max_batch", 4),
        buckets=tuple(cfg.get("buckets", (16, 32))),
        max_waiting=cfg.get("max_waiting", 0),
    )
    return ContinuousEngine(params, scfg)


def main(argv: Sequence[str] | None = None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    p = argparse.ArgumentParser()
    p.add_argument("--config", required=True,
                   help="JSON: model/cache/max_batch/buckets/param_seed/"
                        "lease-ttl overrides")
    p.add_argument("--tag", default=None)
    p.add_argument("--fleet", default=os.environ.get(
        "TPU_SANDBOX_FLEET", ""),
        help="tenant fleet this replica serves: its whole request plane "
             "lives under fleet/<name>/ so several model fleets share one "
             "store behind one gateway")
    args = p.parse_args(argv)
    cfg = json.loads(args.config)

    port = int(os.environ[ENV_KV_PORT])
    tag = args.tag or (
        f"replica-a{os.environ.get('TPU_SANDBOX_AGENT_ID', '?')}"
        f"-g{os.environ.get('TPU_SANDBOX_GENERATION', '?')}")
    kv = KVClient(port=port)
    if args.fleet:
        from tpu_sandbox.gateway.fleet import fleet_kv

        kv = fleet_kv(kv, args.fleet)
    worker = ReplicaWorker(
        kv, _build_engine(cfg), tag=tag,
        lease_ttl=float(cfg.get("lease_ttl", 3.0)))

    def on_term(signum, frame):
        worker.request_drain()

    signal.signal(signal.SIGTERM, on_term)
    try:
        worker.run(timeout=float(cfg.get("timeout", 300.0)))
    finally:
        kv.close()
    if worker._draining:
        print(f"[{tag}] drained: requeued {worker.stats.requeued} "
              f"in-flight request(s)", flush=True)
        return PREEMPTED_EXIT_CODE
    print(f"[{tag}] done: {worker.stats.__dict__}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
