"""Load-signal autoscaler: replica load reports in, gang resizes out.

The control loop closes ROADMAP item 1's autoscaling gap: the elastic
runtime restarts replicas that die, but nothing *added or removed* them
from load signals. This does, by composing three existing pieces:

- **signal** — replicas publish TTL'd load reports (``serve/load/<tag>``,
  see replica.py): queue depth, block-pool pressure, decode-step lag.
  The autoscaler averages queue depth across live reports; expired
  reports (dead or stalled replicas) drop out via TTL, shrinking the
  denominator instead of poisoning the average.
- **actuator** — each replica is a one-host :class:`JobSpec` submitted to
  the ``ClusterScheduler`` (``<prefix>-rep-<k>``). Scaling up submits a
  new job at serve priority, which preempts lower-priority training when
  the pool is full (the serve/train colocation story); scaling down
  cancels the newest replica job, whose SIGTERM drain requeues every
  in-flight request — zero tokens lost. Existing replicas are never
  disturbed by a scale event.
- **leadership** — any number of autoscaler candidates may run; a
  ``LeaseElection`` on ``serve/autoscale/leader`` picks one actor, and a
  successor recovers the current replica set from the durable job queue
  (no autoscaler-local state matters).

Hysteresis: a scale decision needs the signal to point the same way for
``hysteresis_ticks`` consecutive leader ticks, and ``cooldown_s`` must
have passed since the last action — load spikes shorter than that ride
on shedding and the bounded queue instead of churning the pool.

Every action appends a ``serve/autoscale/events/<n>`` record; together
with the scheduler's ``job_events`` stamps the full scale timeline is
reconstructable from the store alone.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass

from tpu_sandbox.obs import get_registry
from tpu_sandbox.obs.health import active_subjects
from tpu_sandbox.runtime.election import LeaseElection
from tpu_sandbox.runtime.scheduler import (TERMINAL_STATES, JobSpec,
                                           cancel_job, list_jobs, submit_job)
from tpu_sandbox.serve.replica import read_load_reports

K_EVENT_TAIL = "serve/autoscale/tail"
K_JOB_IDX = "serve/autoscale/idx"


def k_event(n: int) -> str:
    return f"serve/autoscale/events/{n}"


def autoscale_events(kv) -> list[dict]:
    """Every autoscale decision, in order — the bench/test timeline."""
    out = []
    for n in range(int(kv.try_get(K_EVENT_TAIL) or b"0")):
        raw = kv.try_get(k_event(n))
        if raw is not None:
            out.append(json.loads(raw))
    return out


@dataclass(frozen=True)
class AutoscaleConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    # mean engine queue depth per live replica that triggers a resize
    scale_up_depth: float = 4.0
    scale_down_depth: float = 0.5
    hysteresis_ticks: int = 3
    cooldown_s: float = 2.0
    # the replica gang's tenancy in the shared pool: high priority so a
    # load spike preempts low-priority training, returned on scale-down
    priority: int = 10
    tenant: str = "serve"
    share: float = 1.0
    job_prefix: str = "serve"
    admission_timeout: float = 120.0
    # scale-up pre-warming: when set, every replica spawns with
    # JAX_COMPILATION_CACHE_DIR pointed here, so the first replica's
    # XLA compiles persist and later scale-ups deserialize executables
    # instead of recompiling — the difference between a scale-up that
    # serves in milliseconds and one that stalls behind a cold compile.
    # Each scale_up event records whether the new replica will find the
    # cache warm (entries present) or cold.
    compile_cache_dir: str = ""


class ReplicaAutoscaler:
    """Leader-elected control loop sizing the serve replica gang.

    ``replica_argv`` is the JobSpec agent command template for one replica
    host (same ``{agent_id}``/``{kv_port}``/... placeholders as any other
    cluster job). Call :meth:`tick` on a cadence; it is a no-op on
    non-leaders and between hysteresis windows.
    """

    def __init__(self, kv, replica_argv: list[str], *,
                 cfg: AutoscaleConfig = AutoscaleConfig(),
                 member_id: str = "autoscaler-0",
                 election_ttl: float = 3.0):
        self.kv = kv
        self.replica_argv = list(replica_argv)
        self.cfg = cfg
        self.election = LeaseElection(kv, member_id, ttl=election_ttl,
                                      prefix="serve/autoscale/leader")
        self._up_streak = 0
        self._down_streak = 0
        self._last_action = 0.0

    # -- observability -------------------------------------------------------

    def replica_jobs(self) -> list[dict]:
        """Live (queued or running) replica jobs, oldest first — recovered
        from the durable job queue, so a fresh leader sees the same gang."""
        prefix = f"{self.cfg.job_prefix}-rep-"
        return [j for j in list_jobs(self.kv)
                if j["job_id"].startswith(prefix)
                and j["state"] not in TERMINAL_STATES]

    def load_signal(self) -> tuple[float, int]:
        """(mean queue depth over live reports, number of live reports)."""
        reports = read_load_reports(self.kv)
        if not reports:
            return 0.0, 0
        depths = [r.get("queue_depth", 0) for r in reports.values()]
        return sum(depths) / len(depths), len(reports)

    # -- control loop --------------------------------------------------------

    def tick(self) -> dict | None:
        """One control iteration; returns the event dict when an action was
        taken, else None."""
        if not self.election.step(candidate=True):
            self._up_streak = self._down_streak = 0
            return None
        jobs = self.replica_jobs()
        n = len(jobs)
        if n < self.cfg.min_replicas:
            # bootstrap / repair: the floor needs no hysteresis
            return self._scale_up(n, depth=0.0, reason="min_replicas")
        if active_subjects(self.kv, "autoscale_oscillation"):
            # the health plane caught us flapping: freeze load-driven
            # scaling (floor repair above still runs) until the alert's
            # TTL expires — the loop backs off its own oscillation
            self._up_streak = self._down_streak = 0
            get_registry().counter("autoscale.backoff").inc()
            return None
        depth, n_reports = self.load_signal()
        if depth >= self.cfg.scale_up_depth:
            self._up_streak += 1
            self._down_streak = 0
        elif depth <= self.cfg.scale_down_depth:
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = self._down_streak = 0
        if time.monotonic() - self._last_action < self.cfg.cooldown_s:
            return None
        if self._up_streak >= self.cfg.hysteresis_ticks \
                and n < self.cfg.max_replicas:
            return self._scale_up(n, depth=depth, reason="queue_depth")
        if self._down_streak >= self.cfg.hysteresis_ticks \
                and n > self.cfg.min_replicas:
            return self._scale_down(jobs, depth=depth)
        return None

    def compile_cache_state(self) -> str:
        """'warm' when the shared compile-cache dir has entries a new
        replica can deserialize, 'cold' when it is empty/absent,
        'disabled' when no cache dir is configured."""
        d = self.cfg.compile_cache_dir
        if not d:
            return "disabled"
        try:
            with os.scandir(d) as it:
                return "warm" if any(True for _ in it) else "cold"
        except OSError:
            return "cold"

    def _scale_up(self, n: int, *, depth: float, reason: str) -> dict:
        idx = self.kv.add(K_JOB_IDX)  # never reuse an id, even post-sweep
        job_id = f"{self.cfg.job_prefix}-rep-{idx}"
        env = {}
        cache_state = self.compile_cache_state()
        if self.cfg.compile_cache_dir:
            os.makedirs(self.cfg.compile_cache_dir, exist_ok=True)
            env["JAX_COMPILATION_CACHE_DIR"] = self.cfg.compile_cache_dir
        submit_job(self.kv, JobSpec(
            job_id=job_id, hosts=1, world_size=1,
            agent_argv=self.replica_argv, priority=self.cfg.priority,
            admission_timeout=self.cfg.admission_timeout,
            tenant=self.cfg.tenant, share=self.cfg.share, env=env))
        return self._record("scale_up", job_id, n, n + 1, depth, reason,
                            compile_cache=cache_state)

    def _scale_down(self, jobs: list[dict], *, depth: float) -> dict:
        victim = jobs[-1]["job_id"]  # newest replica drains and requeues
        cancel_job(self.kv, victim)
        return self._record("scale_down", victim, len(jobs), len(jobs) - 1,
                            depth, "queue_depth")

    def _record(self, action: str, job_id: str, n_before: int, n_after: int,
                depth: float, reason: str, **extra) -> dict:
        self._up_streak = self._down_streak = 0
        self._last_action = time.monotonic()
        event = {"action": action, "job_id": job_id, "n_before": n_before,
                 "n_after": n_after, "queue_depth": round(depth, 3),
                 "reason": reason, "wall": time.time(), **extra}
        n = self.kv.add(K_EVENT_TAIL) - 1
        self.kv.set(k_event(n), json.dumps(event))
        get_registry().counter("autoscale.events",
                               labels={"action": action}).inc()
        get_registry().gauge("autoscale.replicas").set(n_after)
        return event
