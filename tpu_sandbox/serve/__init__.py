"""Inference serving stack: AOT decode, paged KV cache, continuous batching.

Layers (bottom up):

- ``cache``   — host-side paged KV-cache allocator: fixed-size blocks, per-
  sequence block tables, free-list reuse, refcounted prefix sharing.
- ``decode``  — AOT-compiled static-shape prefill (bucketed lengths) and
  single-token decode step for ``models/transformer.py``, both donating the
  device page buffers; replay-exact seeded sampling (``sample_token``).
- ``engine``  — continuous-batching engine: admits/evicts sequences at
  decode-step granularity, preempts-to-requeue under block pressure, plus a
  static-batch baseline for the bench comparison. SLO guardrails live here:
  per-request deadlines, a bounded admission queue with shed-on-overload,
  and the load-report backpressure signals.
- ``replica`` — replica processes behind the KV-backed request queue:
  claim-once queue entries, TTL leases, idempotent results, claim-once
  terminal verdicts (result or SHED), SIGTERM drain back to the queue,
  orphan scavenging, TTL'd load reports. Replicas run as ranks of a
  HostAgent gang so the elastic runtime relaunches them.
- ``client``  — producer-side SLO machinery: deadline submit, retry-on-shed
  with jittered backoff, straggler hedging over the idempotent verdicts.
- ``autoscale`` — leader-elected control loop sizing the replica gang from
  the load reports through the cluster scheduler (serve/train colocation).
"""

from tpu_sandbox.serve.cache import CacheConfig, PagedKVCache
from tpu_sandbox.serve.engine import (
    ContinuousEngine,
    Request,
    RequestResult,
    ServeConfig,
    ShedRecord,
    StaticEngine,
    live_engines,
)

__all__ = [
    "CacheConfig",
    "PagedKVCache",
    "ContinuousEngine",
    "Request",
    "RequestResult",
    "ServeConfig",
    "ShedRecord",
    "StaticEngine",
    "live_engines",
]
