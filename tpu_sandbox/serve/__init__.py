"""Inference serving stack: AOT decode, paged KV cache, continuous batching.

Layers (bottom up):

- ``cache``   — host-side paged KV-cache allocator: fixed-size blocks, per-
  sequence block tables, free-list reuse, refcounted prefix sharing.
- ``decode``  — AOT-compiled static-shape prefill (bucketed lengths) and
  single-token decode step for ``models/transformer.py``, both donating the
  device page buffers.
- ``engine``  — continuous-batching engine: admits/evicts sequences at
  decode-step granularity, preempts-to-requeue under block pressure, plus a
  static-batch baseline for the bench comparison.
- ``replica`` — replica processes behind the KV-backed request queue:
  claim-once queue entries, TTL leases, idempotent results, SIGTERM drain
  back to the queue, orphan scavenging. Replicas run as ranks of a
  HostAgent gang so the elastic runtime relaunches them.
"""

from tpu_sandbox.serve.cache import CacheConfig, PagedKVCache
from tpu_sandbox.serve.engine import (
    ContinuousEngine,
    Request,
    RequestResult,
    ServeConfig,
    StaticEngine,
    live_engines,
)

__all__ = [
    "CacheConfig",
    "PagedKVCache",
    "ContinuousEngine",
    "Request",
    "RequestResult",
    "ServeConfig",
    "StaticEngine",
    "live_engines",
]
