"""Continuous-batching serve engine (plus the static-batch baseline).

``ContinuousEngine`` owns the compiled steps, the page buffers, and the
paged allocator, and advances the whole replica one decode step at a time:

- **admit at step granularity** — every step first fills free decode slots
  from the waiting queue (prefill runs per request, one compiled bucket);
- **evict at step granularity** — sequences that finish release their
  blocks immediately, and the freed slots/blocks are available to the very
  next admit, no batch barrier;
- **preempt-to-requeue** — when a sequence crosses a block boundary and no
  block can be allocated, the newest-admitted sequence is evicted and its
  request goes back to the waiting queue intact (greedy decode + bitwise
  steps make the replay identical).

``StaticEngine`` is the control: admit a full batch, decode until *all* of
it finishes, then admit the next batch. Same compiled steps, same
allocator — the bench compares scheduling policy only.

Decoding is greedy argmax over fp32 logits by default — deterministic,
which is what makes requeue/replay and the replica zero-loss story exact
rather than probabilistic. Sampled decode (``temperature``/``top_k`` on the
request) keeps the same guarantee: the sampler key is derived from the
request seed folded with the decode-step index, so a replayed request
re-draws identical tokens (see ``serve/decode.py:sample_token``).

SLO guardrails live here too:

- requests may carry an absolute **deadline** (engine clock); waiting or
  active requests past their deadline are **shed** — removed from the
  system with an explicit :class:`ShedRecord` instead of silently rotting
  in the queue;
- ``ServeConfig.max_waiting`` bounds the admission queue — on overload
  ``submit`` first sheds oldest-past-deadline waiters, then sheds the
  incoming request if the queue is still full (the caller learns from the
  ``False`` return and the shed record);
- ``load_report()`` exposes the backpressure signals (queue depth,
  block-pool pressure, decode-step lag) replicas publish to the KV store.
"""

from __future__ import annotations

import time
import weakref
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

import jax.numpy as jnp
import numpy as np

from tpu_sandbox.models.transformer import TransformerConfig
from tpu_sandbox.obs import get_recorder, get_registry
from tpu_sandbox.serve.cache import CacheConfig, PagedKVCache, SeqAlloc
from tpu_sandbox.serve.decode import (DecodeStep, build_decode_step,
                                      init_pages, sample_token)

# engines with a live decode loop / replica thread, for the conftest leak
# fixture (mirrors kvstore.live_servers())
_LIVE_ENGINES: "weakref.WeakSet" = weakref.WeakSet()


def live_engines() -> list:
    return [e for e in _LIVE_ENGINES if e.active_requests or e.waiting]


@dataclass(frozen=True)
class ServeConfig:
    model: TransformerConfig = field(default_factory=TransformerConfig)
    cache: CacheConfig = field(default_factory=CacheConfig)
    max_batch: int = 4
    buckets: tuple[int, ...] = (16, 32, 64)
    cache_dtype: Any = jnp.float32
    eos_token: int | None = None  # None -> run to max_new_tokens
    max_waiting: int = 0          # admission-queue bound; 0 = unbounded


@dataclass
class Request:
    rid: str
    prompt: list[int]
    max_new_tokens: int
    arrival: float = 0.0  # engine clock time the request became visible
    preemptions: int = 0  # times evicted-to-requeue so far
    deadline: float | None = None  # engine clock; past it -> shed, not served
    temperature: float = 0.0       # 0 -> greedy argmax
    top_k: int = 0                 # 0 -> full vocab
    seed: int = 0                  # sampler key; folded with the step index
    ver: int | None = None         # pinned weight version; None -> pin to the
                                   # engine's current version at admit
    tc: dict | None = None         # trace context (wire form); never affects
                                   # tokens, only the flight recorder


@dataclass
class RequestResult:
    rid: str
    tokens: list[int]             # generated tokens only
    ttft: float                   # first-token latency (s, engine clock)
    itl: list[float]              # inter-token latencies (s)
    finished_at: float = 0.0
    preemptions: int = 0
    ver: int = 0                  # weight version every token was decoded on
    tc: dict | None = None        # decode span context; parents the verdict


@dataclass
class ShedRecord:
    """Terminal verdict for a request the engine refused or gave up on.
    A shed request never also produces a RequestResult."""
    rid: str
    reason: str       # "queue_full" | "deadline" | explicit shed reason
    shed_at: float
    preemptions: int = 0
    tc: dict | None = None  # shed-instant context; parents the verdict


@dataclass
class _Slot:
    request: Request
    alloc: SeqAlloc
    tokens: list[int]             # prompt + generated
    generated: list[int] = field(default_factory=list)
    first_token_at: float | None = None
    last_token_at: float | None = None
    itl: list[float] = field(default_factory=list)
    preemptions: int = 0
    ver: int = 0                      # weight version this slot decodes on
    logprob_sum: float = 0.0          # sum of chosen-token logprobs
    tc: dict | None = None            # admit span context
    admitted_mono: float | None = None  # real monotonic time of admission
                                        # (the engine clock may be a fake)


#: "this version is not resident" — distinct from None, which is a valid
#: params value for stub-step engines that never touch weights
_MISSING = object()


def _token_logprob(logits_row: np.ndarray, token: int) -> float:
    """Logprob of ``token`` under fp32 ``logits_row`` (stable logsumexp).
    Fed into the ``engine.logprob`` series the canary analysis compares —
    a weight regression shows up as the model scoring its own chosen
    tokens lower, with no reference labels needed."""
    row = np.asarray(logits_row, np.float64)
    m = float(row.max())
    return float(row[int(token)] - m - np.log(np.exp(row - m).sum()))


class _EngineBase:
    def __init__(self, params, config: ServeConfig,
                 step: DecodeStep | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 version: int = 0, loader: Callable | None = None):
        self.config = config
        # weights are versioned: requests pin the version they started on
        # and decode on it to the last token, even across a swap (grouped
        # decode below). The boot version is retained forever — it is the
        # rollback target when nothing was ever promoted.
        self.version = int(version)
        self._boot_version = int(version)
        self._params_by_ver: dict[int, Any] = {int(version): params}
        self.loader = loader  # optional: ver -> params | None, for pinned
                              # versions this process never held (post-respawn)
        self.step_fns = step or build_decode_step(
            config.model, config.cache, max_batch=config.max_batch,
            buckets=config.buckets, cache_dtype=config.cache_dtype)
        self.cache = PagedKVCache(config.cache)
        self.k_pages, self.v_pages = init_pages(
            config.model, config.cache, config.cache_dtype)
        self.clock = clock
        self.waiting: deque[Request] = deque()
        self.slots: list[_Slot | None] = [None] * config.max_batch
        self.results: dict[str, RequestResult] = {}
        self.shed: dict[str, ShedRecord] = {}
        self.steps = 0
        self.last_step_at: float | None = None
        _LIVE_ENGINES.add(self)

    # -- public --------------------------------------------------------------

    @property
    def params(self):
        """The *current* version's weights (the long-standing single-version
        API; versioned access goes through ``_params_for``)."""
        return self._params_by_ver[self.version]

    @params.setter
    def params(self, value) -> None:
        self._params_by_ver[self.version] = value

    @property
    def active_requests(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def swap_params(self, params, version: int) -> int:
        """Install ``params`` as weight ``version`` and make it current,
        between decode steps. Resident paged-KV state is NOT drained: live
        slots keep decoding on the version they pinned at admit (grouped
        decode), only the prefix cache is flushed — its K/V was computed
        under other weights. Returns the number of cache entries flushed."""
        t_swap = time.monotonic()
        self._params_by_ver[int(version)] = params
        self.version = int(version)
        flushed = self.cache.flush_prefix_cache()
        self._gc_params()
        get_registry().counter("engine.swap").inc()
        # process-level span (no request parent): the critpath analyzer
        # overlaps it against resident requests' gaps — a swap stalls
        # every request on this engine, and that stall should be blamed
        # on the swap, not on "queue_wait"
        get_recorder().complete("swap:pause", t_swap,
                                args={"ver": int(version),
                                      "flushed": int(flushed)})
        return flushed

    def has_version(self, ver: int) -> bool:
        return int(ver) in self._params_by_ver

    def _params_for(self, ver: int):
        """Weights for ``ver``, or the ``_MISSING`` sentinel (None is a
        valid params value — stub engines run weightless)."""
        ver = int(ver)
        if ver in self._params_by_ver:
            return self._params_by_ver[ver]
        if self.loader is not None:
            params = self.loader(ver)
            if params is not None:
                self._params_by_ver[ver] = params
                return params
        return _MISSING

    def _gc_params(self) -> None:
        keep = {self.version, self._boot_version}
        keep.update(s.ver for s in self.slots if s is not None)
        keep.update(int(r.ver) for r in self.waiting if r.ver is not None)
        for ver in [v for v in self._params_by_ver if v not in keep]:
            del self._params_by_ver[ver]

    def submit(self, request: Request) -> bool:
        """Admit ``request`` to the waiting queue. Returns False when the
        request was shed instead (bounded queue full even after expired
        waiters were swept) — a ShedRecord is written either way, so every
        submitted request reaches exactly one terminal verdict."""
        if self.cache.blocks_needed(request.prompt, request.max_new_tokens) \
                > self.config.cache.max_blocks_per_seq:
            raise ValueError(f"request {request.rid} exceeds max context")
        limit = self.config.max_waiting
        if limit and len(self.waiting) >= limit:
            # shed-on-overload: oldest-past-deadline first, then the arrival
            self.shed_expired()
            if len(self.waiting) >= limit:
                self._record_shed(request, "queue_full")
                return False
        self.waiting.append(request)
        return True

    @property
    def idle(self) -> bool:
        return not self.waiting and self.active_requests == 0

    def run_until_idle(self, max_steps: int = 100_000) -> None:
        for _ in range(max_steps):
            if self.idle:
                return
            self.step()
        raise RuntimeError("serve engine failed to drain")

    def drain_to_requests(self) -> list[Request]:
        """Evict everything in flight back to request form (original prompt,
        arrival preserved) — the replica's SIGTERM path."""
        out = []
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            self.cache.free(slot.alloc, cache_prefix=False)
            out.append(slot.request)
            self.slots[i] = None
        out.extend(self.waiting)
        self.waiting.clear()
        return out

    # -- SLO guardrails ------------------------------------------------------

    def _record_shed(self, request: Request, reason: str,
                     preemptions: int | None = None,
                     tc: dict | None = None) -> None:
        # the shed instant is the trace's terminal node for this request;
        # its context rides the ShedRecord so the replica's verdict
        # instant stays chained
        ctx = get_recorder().instant(
            f"shed:{reason}", parent=tc if tc is not None else request.tc,
            args={"rid": request.rid})
        get_registry().counter("engine.shed", labels={"reason": reason}).inc()
        self.shed[request.rid] = ShedRecord(
            rid=request.rid, reason=reason, shed_at=self.clock(),
            preemptions=request.preemptions if preemptions is None
            else preemptions,
            tc=None if ctx is None else ctx.to_wire())

    def shed_expired(self) -> int:
        """Shed every waiting or active request whose deadline has passed,
        oldest (queue head / earliest-admitted slot) first. Runs at submit
        overload and at the top of every step, so a request past its
        deadline can never be admitted or produce a late result."""
        now = self.clock()
        n = 0
        keep: deque[Request] = deque()
        while self.waiting:
            req = self.waiting.popleft()
            if req.deadline is not None and now > req.deadline:
                self._record_shed(req, "deadline")
                n += 1
            else:
                keep.append(req)
        self.waiting = keep
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            dl = slot.request.deadline
            if dl is not None and now > dl:
                self.cache.free(slot.alloc, cache_prefix=False)
                self.slots[i] = None
                self._record_shed(slot.request, "deadline",
                                  preemptions=slot.preemptions)
                n += 1
        return n

    def shed_waiting(self, reason: str) -> int:
        """Shed the entire waiting queue (the ``shed_storm`` fault)."""
        n = len(self.waiting)
        while self.waiting:
            self._record_shed(self.waiting.popleft(), reason)
        return n

    def load_report(self) -> dict:
        """Backpressure signals a replica publishes to the KV store.

        ``prefix_digest`` rides along so the gateway can route by prefix
        affinity from the load reports alone — no extra KV round trips per
        request (see ``cache.PagedKVCache.resident_prefix_digest``)."""
        now = self.clock()
        cache = self.cache
        rec_stats = get_recorder().stats()
        return {
            "queue_depth": len(self.waiting),
            "active": self.active_requests,
            "ver": self.version,  # the swap ack the deploy controller reads
            "max_batch": self.config.max_batch,
            "free_block_frac": cache.free_blocks / cache.config.num_blocks,
            "steps": self.steps,
            "step_age": None if self.last_step_at is None
            else now - self.last_step_at,
            "shed": len(self.shed),
            "done": len(self.results),
            "prefix_digest": cache.resident_prefix_digest(),
            "recorder": rec_stats,
            # a silently-dropping recorder must be visible at the top
            # level of every load report, not buried in a nested dict
            "dropped_events": rec_stats["dropped"],
        }

    # -- shared mechanics ----------------------------------------------------

    def _admit_from_waiting(self) -> bool:
        """Admit (or resolve) the queue head. True = the head was consumed
        (admitted, or shed because its pinned version is gone); False = the
        head is blocked on capacity and the loop should stop."""
        req = self.waiting[0]
        ver = self.version if req.ver is None else int(req.ver)
        if self._params_for(ver) is _MISSING:
            # the pinned weights no longer exist in this process (respawn
            # after a swap, no loader): an explicit shed verdict, so the
            # client restarts a fresh single-version lifecycle — never a
            # silent decode on different weights than the pin
            self.waiting.popleft()
            self._record_shed(req, "stale_version")
            return True
        if not self._try_admit(req):
            return False
        self.waiting.popleft()
        return True

    def _try_admit(self, request: Request) -> bool:
        free = [i for i, s in enumerate(self.slots) if s is None]
        if not free:
            return False
        # reserve the prompt's blocks only; decode grows the allocation one
        # block at a time, so block pressure shows up as preempt-to-requeue
        # rather than refused admission
        alloc = self.cache.alloc(request.prompt, 0)
        if alloc is None:
            return False
        self._prefill(request, alloc, free[0])
        return True

    def _prefill(self, request: Request, alloc: SeqAlloc, slot_idx: int):
        cfg = self.config
        t_admit = time.monotonic()
        ver = self.version if request.ver is None else int(request.ver)
        request.ver = ver  # pin sticks to the request: preempt-to-requeue
                           # and drain replay on these weights, swap or not
        params = self._params_for(ver)
        if params is _MISSING:
            raise KeyError(
                f"request {request.rid} pinned to version {ver} but no such "
                f"params are resident (admit through the queue, which sheds "
                f"stale pins, or provide a loader)")
        plen = len(request.prompt)
        bucket = self.step_fns.pick_bucket(plen)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :plen] = request.prompt
        dest = self.cache.dest_indices(alloc, bucket).astype(np.int32)
        t_prefill = time.monotonic()
        next_logits, self.k_pages, self.v_pages = self.step_fns.prefill[bucket](
            params, self.k_pages, self.v_pages,
            jnp.asarray(toks), jnp.asarray(dest),
            jnp.asarray(plen - 1, jnp.int32))
        alloc.length = plen
        self.cache.commit_prefix(alloc)
        slot = _Slot(request=request, alloc=alloc, tokens=list(request.prompt),
                     preemptions=request.preemptions, ver=ver)
        # the admit span covers admission bookkeeping plus the prefill
        # compute; the prefill child span carves the compute out so the
        # critpath analyzer can tell "slow admission" from "big prompt".
        # The decode span that follows is emitted retrospectively at
        # retire time, anchored here
        ctx = get_recorder().complete("admit", t_admit, parent=request.tc,
                                      args={"rid": request.rid})
        get_recorder().complete("prefill", t_prefill, parent=ctx,
                                args={"rid": request.rid, "plen": plen})
        slot.tc = None if ctx is None else ctx.to_wire()
        slot.admitted_mono = time.monotonic()
        self.slots[slot_idx] = slot
        row = np.asarray(next_logits).reshape(-1)
        token = self._pick_token(slot, row)
        slot.logprob_sum += _token_logprob(row, token)
        self._emit_token(slot, token)
        if self._finished(slot):
            self._retire(slot_idx)

    def _pick_token(self, slot: _Slot, logits_row: np.ndarray) -> int:
        """Greedy argmax, or sampled via a key derived from (request seed,
        decode-step index). The step index is ``len(slot.generated)`` — on
        requeue the request replays from its original prompt, so every
        re-draw folds the same index into the same key and the sampled
        trajectory is bitwise identical to the unfaulted run."""
        req = slot.request
        if req.temperature <= 0.0:
            return int(logits_row.argmax())
        return sample_token(logits_row, seed=req.seed,
                            step_index=len(slot.generated),
                            temperature=req.temperature, top_k=req.top_k)

    def _emit_token(self, slot: _Slot, token: int) -> None:
        now = self.clock()
        if slot.first_token_at is None:
            slot.first_token_at = now
        elif slot.last_token_at is not None:
            slot.itl.append(now - slot.last_token_at)
        slot.last_token_at = now
        slot.generated.append(token)
        slot.tokens.append(token)

    def _finished(self, slot: _Slot) -> bool:
        if len(slot.generated) >= slot.request.max_new_tokens:
            return True
        eos = self.config.eos_token
        return eos is not None and slot.generated and slot.generated[-1] == eos

    def _retire(self, i: int) -> None:
        slot = self.slots[i]
        self.slots[i] = None
        self.cache.free(slot.alloc)
        req = slot.request
        ctx = get_recorder().complete(
            "decode",
            slot.admitted_mono if slot.admitted_mono is not None
            else time.monotonic(),
            parent=slot.tc,
            args={"rid": req.rid, "tokens": len(slot.generated)})
        tc = None if ctx is None else ctx.to_wire()
        if req.deadline is not None and self.clock() > req.deadline:
            # finished, but past the promise: the verdict is SHED, never a
            # late result
            self._record_shed(req, "deadline", preemptions=slot.preemptions,
                              tc=tc)
            return
        get_registry().counter("engine.done").inc()
        get_registry().histogram("engine.ttft").observe(
            slot.first_token_at - req.arrival)
        get_registry().histogram("engine.logprob").observe(
            slot.logprob_sum / max(1, len(slot.generated)))
        self.results[req.rid] = RequestResult(
            rid=req.rid, tokens=list(slot.generated),
            ttft=slot.first_token_at - req.arrival,
            itl=list(slot.itl), finished_at=self.clock(),
            preemptions=slot.preemptions, ver=slot.ver, tc=tc)

    def _preempt(self, i: int) -> None:
        """Evict slot i back to the waiting queue (front: it has seniority)."""
        slot = self.slots[i]
        self.slots[i] = None
        self.cache.free(slot.alloc, cache_prefix=False)
        req = slot.request
        req.preemptions = slot.preemptions + 1
        self.waiting.appendleft(req)

    def _ensure_capacity(self, i: int) -> bool:
        """Grow slot i's allocation for its next token; on block pressure
        preempt the newest other slot and retry. False = slot i itself must
        be preempted (nothing left to evict)."""
        slot = self.slots[i]
        need_block = slot.alloc.length % self.config.cache.block_size == 0 \
            and slot.alloc.length // self.config.cache.block_size \
            >= len(slot.alloc.block_ids)
        if not need_block:
            return True
        while not self.cache.grow(slot.alloc):
            victims = [j for j, s in enumerate(self.slots)
                       if s is not None and j != i]
            if not victims:
                return False
            self._preempt(max(victims, key=lambda j: self.slots[j].alloc.seq_id))
        return True

    def _decode_active(self) -> None:
        """One compiled decode step over every occupied slot. Around a
        weight swap the batch can hold slots pinned to different versions:
        one decode call runs per resident version, with the other
        versions' rows zeroed out (length 0 masks their reads, table 0
        scatters their writes to the null block — exactly the treatment
        empty slots already get), so every sequence decodes every token on
        the weights it pinned at admit, never a blend."""
        B = self.config.max_batch
        cfg = self.config.cache
        # resolve capacity for every slot first: growing one slot may
        # preempt another that was already swept, so the batch is built
        # only from the survivors
        for i in range(B):
            if self.slots[i] is not None and not self._ensure_capacity(i):
                self._preempt(i)
        by_ver: dict[int, list[int]] = {}
        for i, slot in enumerate(self.slots):
            if slot is not None:
                by_ver.setdefault(slot.ver, []).append(i)
        if not by_ver:
            return
        rows: dict[int, np.ndarray] = {}
        for ver in sorted(by_ver):
            members = by_ver[ver]
            tokens = np.zeros((B, 1), np.int32)
            lengths = np.zeros((B,), np.int32)
            tables = np.zeros((B, cfg.max_blocks_per_seq), np.int32)
            for i in members:
                slot = self.slots[i]
                tokens[i, 0] = slot.tokens[-1]
                lengths[i] = len(slot.tokens)
                tables[i] = self.cache.block_table(slot.alloc)
            logits, self.k_pages, self.v_pages = self.step_fns.decode(
                self._params_by_ver[ver], self.k_pages, self.v_pages,
                jnp.asarray(tokens), jnp.asarray(lengths),
                jnp.asarray(tables))
            logits = np.asarray(logits)
            for i in members:
                rows[i] = logits[i]
        self.steps += 1
        self.last_step_at = self.clock()
        for i in sorted(rows):
            slot = self.slots[i]
            slot.alloc.length = len(slot.tokens)
            token = self._pick_token(slot, rows[i])
            slot.logprob_sum += _token_logprob(rows[i], token)
            self._emit_token(slot, token)
            if self._finished(slot):
                self._retire(i)


class ContinuousEngine(_EngineBase):
    """Admit/evict at decode-step granularity — freed slots refill before
    the next step, nothing waits for a batch to finish."""

    def step(self) -> None:
        self.shed_expired()
        while self.waiting:
            if not self._admit_from_waiting():
                break
        self._decode_active()


class StaticEngine(_EngineBase):
    """Batch-barrier control: fill the batch once, then decode until every
    member finishes before admitting again."""

    def step(self) -> None:
        self.shed_expired()
        if self.active_requests == 0:
            while self.waiting:
                if not self._admit_from_waiting():
                    break
        self._decode_active()
