"""Paged KV-cache allocator: blocks, block tables, free lists, prefix reuse.

The device side is two flat page arrays per model —

    k_pages, v_pages : [n_layers, num_blocks, block_size, n_heads, head_dim]

— owned and donated through the compiled prefill/decode steps (see
``serve/decode.py``). Everything in this module is *host* bookkeeping:
which blocks belong to which sequence, which are free, and which hold a
shared prompt prefix.

Design points:

- **Block 0 is the null block.** It is never allocated. Bucket-padding
  positions in prefill and empty decode slots scatter their K/V there, and
  block-table padding gathers from it; reads are masked by sequence length
  so its garbage never reaches the softmax.
- **Prefix sharing.** Every *full* block of a prompt is keyed by a chain
  hash (hash of all tokens up to and including the block). A new sequence
  whose prompt starts with an already-cached chain reuses those blocks
  (refcount bump) and its prefill skips the stores for the shared span.
  Cached blocks carry one extra cache reference so they survive their
  owning sequence; under pressure the allocator drops unreferenced cache
  entries (free-list reuse on eviction).
- **Recompute on eviction.** When a sequence is preempted its blocks are
  freed and the request is requeued with its original prompt; decoding is
  greedy and the step functions are bitwise deterministic, so the replay
  regenerates the identical continuation.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Sequence

import numpy as np


@dataclass(frozen=True)
class CacheConfig:
    num_blocks: int = 64          # includes the reserved null block 0
    block_size: int = 8           # positions per block
    max_blocks_per_seq: int = 8   # block-table width == max context / block_size

    @property
    def max_context(self) -> int:
        return self.max_blocks_per_seq * self.block_size


def _chain_hashes(tokens: Sequence[int], block_size: int) -> list[bytes]:
    """One digest per *full* block, each covering the prompt up to and
    including that block (so a hit implies the whole prefix matches)."""
    out = []
    h = hashlib.sha256()
    for start in range(0, len(tokens) - len(tokens) % block_size, block_size):
        h.update(np.asarray(tokens[start:start + block_size], np.int32).tobytes())
        out.append(h.digest())
    return out


#: bytes of each chain hash that travel in load-report digests — enough that
#: an accidental collision is a mis-routed request (a hint gone wrong, never
#: a correctness problem: the replica's allocator rehashes the full prompt)
DIGEST_BYTES = 8


def chain_digest(tokens: Sequence[int], block_size: int) -> list[str]:
    """Truncated-hex chain hashes of ``tokens``' full blocks — the compact
    form both sides of prefix-cache-aware routing speak: replicas advertise
    their resident set in this form (``PagedKVCache.resident_prefix_digest``)
    and the gateway computes a request's chain in it."""
    return [h[:DIGEST_BYTES].hex()
            for h in _chain_hashes(tokens, block_size)]


@dataclass
class SeqAlloc:
    """Host-side allocation record for one live sequence."""

    seq_id: int
    block_ids: list[int]           # owned/shared blocks, in position order
    n_shared: int                  # leading block_ids reused from the prefix cache
    prompt_hashes: list[bytes]     # chain hashes of the prompt's full blocks
    length: int = 0                # tokens currently stored


class PagedKVCache:
    """Block allocator + prefix cache. Pure host state (numpy ints only)."""

    def __init__(self, config: CacheConfig):
        if config.num_blocks < 2:
            raise ValueError("need at least one allocatable block beyond null")
        self.config = config
        self._free: list[int] = list(range(config.num_blocks - 1, 0, -1))
        self._refs: dict[int, int] = {}
        # chain hash -> block id, insertion-ordered for FIFO cache eviction
        self._prefix: dict[bytes, int] = {}
        self._seqs: dict[int, SeqAlloc] = {}
        self._next_seq = 0
        self.stats = {"prefix_hits": 0, "prefix_blocks_reused": 0,
                      "evicted_cache_blocks": 0}

    # -- introspection -------------------------------------------------------

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def resident_prefix_digest(self, top_k: int = 24) -> list[str]:
        """Truncated-hex digest of the resident prefix-cache entries, newest
        last — what a replica advertises in its load report so the gateway
        can route by prefix affinity without extra KV round trips.

        Bounded: at most ``top_k`` entries of ``2 * DIGEST_BYTES`` hex chars
        each. ``_prefix`` is insertion-ordered and eviction is FIFO, so the
        *newest* ``top_k`` entries are exactly the ones that will survive
        block pressure longest — evicted entries drop out of the digest the
        moment they drop out of the cache (no stale advertisements).
        """
        entries = list(self._prefix)[-top_k:]
        return [h[:DIGEST_BYTES].hex() for h in entries]

    def blocks_needed(self, prompt: Sequence[int], max_new: int) -> int:
        total = len(prompt) + max_new
        return -(-total // self.config.block_size)

    def can_admit(self, prompt: Sequence[int], max_new: int) -> bool:
        need = self.blocks_needed(prompt, max_new)
        shared = self._count_shared(prompt)
        return need - shared <= len(self._free) + self._reclaimable()

    # -- allocation ----------------------------------------------------------

    def alloc(self, prompt: Sequence[int], max_new: int) -> SeqAlloc | None:
        """Reserve blocks for prompt + max_new tokens. Returns None when the
        free list (plus droppable cache blocks) can't cover it."""
        cfg = self.config
        need = self.blocks_needed(prompt, max_new)
        if need > cfg.max_blocks_per_seq:
            raise ValueError(
                f"sequence needs {need} blocks > max_blocks_per_seq "
                f"{cfg.max_blocks_per_seq}")
        hashes = _chain_hashes(prompt, cfg.block_size)
        shared: list[int] = []
        for hh in hashes:
            bid = self._prefix.get(hh)
            if bid is None:
                break
            shared.append(bid)
        # blocks we are about to pin as shared are not reclaimable fuel
        if need - len(shared) > len(self._free) + self._reclaimable(
                exclude=set(shared)):
            return None
        for bid in shared:
            self._refs[bid] += 1
        fresh = [self._take_free() for _ in range(need - len(shared))]
        if shared:
            self.stats["prefix_hits"] += 1
            self.stats["prefix_blocks_reused"] += len(shared)
        alloc = SeqAlloc(
            seq_id=self._next_seq,
            block_ids=shared + fresh,
            n_shared=len(shared),
            prompt_hashes=hashes,
            length=0,
        )
        self._next_seq += 1
        self._seqs[alloc.seq_id] = alloc
        return alloc

    def commit_prefix(self, alloc: SeqAlloc) -> None:
        """Publish the sequence's full prompt blocks into the prefix cache.
        Call *after* prefill has stored their K/V; idempotent."""
        self._register_prefix(alloc)

    def free(self, alloc: SeqAlloc, *, cache_prefix: bool = True) -> None:
        """Release a sequence. Its full prompt blocks stay in the prefix
        cache (one cache ref keeps them off the free list) unless
        ``cache_prefix`` is False or they were never registered."""
        if self._seqs.pop(alloc.seq_id, None) is None:
            return
        if cache_prefix:
            self._register_prefix(alloc)
        for bid in alloc.block_ids:
            self._decref(bid)

    def grow(self, alloc: SeqAlloc) -> bool:
        """Append one block when decode crosses a block boundary. True on
        success; False means block pressure (caller preempts-to-requeue)."""
        if len(alloc.block_ids) >= self.config.max_blocks_per_seq:
            return False
        try:
            alloc.block_ids.append(self._take_free())
        except MemoryError:
            return False
        return True

    def flush_prefix_cache(self) -> int:
        """Drop every prefix-cache entry (the cache's own reference); blocks
        still pinned by live sequences survive via their remaining refs.
        Called on a weight swap: cached K/V was computed under the old
        weights and must never serve a request pinned to the new version.
        Returns the number of entries flushed."""
        n = 0
        for hh, bid in list(self._prefix.items()):
            del self._prefix[hh]
            self._decref(bid)
            n += 1
        return n

    # -- device-facing views -------------------------------------------------

    def block_table(self, alloc: SeqAlloc) -> np.ndarray:
        """Fixed-width [max_blocks_per_seq] int32 row, null-block padded."""
        cfg = self.config
        row = np.zeros(cfg.max_blocks_per_seq, np.int32)
        row[: len(alloc.block_ids)] = alloc.block_ids
        return row

    def dest_indices(self, alloc: SeqAlloc, bucket_len: int) -> np.ndarray:
        """Flat page indices [bucket_len] for storing prefill K/V.

        Position p of the prompt lands at flat slot
        ``block_ids[p // bs] * bs + p % bs``. Positions inside *shared*
        prefix blocks and bucket padding are redirected to the null block
        (flat slots [0, bs)) so prefill never rewrites shared content.
        """
        cfg = self.config
        bs = cfg.block_size
        idx = np.zeros(bucket_len, np.int64)
        for p in range(min(bucket_len, len(alloc.block_ids) * bs)):
            b = p // bs
            if b < alloc.n_shared:
                continue  # shared prefix: leave pointed at null block
            idx[p] = alloc.block_ids[b] * bs + p % bs
        return idx

    # -- internals -----------------------------------------------------------

    def _count_shared(self, prompt: Sequence[int]) -> int:
        n = 0
        for hh in _chain_hashes(prompt, self.config.block_size):
            if hh not in self._prefix:
                break
            n += 1
        return n

    def _reclaimable(self, exclude: set[int] | None = None) -> int:
        exclude = exclude or set()
        return sum(1 for bid in self._prefix.values()
                   if self._refs[bid] == 1 and bid not in exclude)

    def _take_free(self) -> int:
        if not self._free:
            self._evict_cache_block()
        if not self._free:
            raise MemoryError("paged KV cache exhausted")
        bid = self._free.pop()
        self._refs[bid] = 1
        return bid

    def _evict_cache_block(self) -> None:
        # FIFO over cache entries; only entries nobody else references can
        # be dropped. Longest chains first would be smarter; FIFO is enough.
        for hh, bid in list(self._prefix.items()):
            if self._refs[bid] == 1:
                del self._prefix[hh]
                self._decref(bid)
                self.stats["evicted_cache_blocks"] += 1
                return

    def _register_prefix(self, alloc: SeqAlloc) -> None:
        n_full = len(alloc.prompt_hashes)
        for i in range(n_full):
            hh = alloc.prompt_hashes[i]
            if hh in self._prefix:
                continue
            if i > 0 and alloc.prompt_hashes[i - 1] not in self._prefix:
                break  # never cache a chain with a missing link
            bid = alloc.block_ids[i]
            self._prefix[hh] = bid
            self._refs[bid] += 1  # the cache's own reference

    def _decref(self, bid: int) -> None:
        self._refs[bid] -= 1
        if self._refs[bid] == 0:
            del self._refs[bid]
            self._free.append(bid)
