"""Client-side SLO machinery: deadlines, retry-on-shed, straggler hedging.

``ServeClient`` wraps the producer half of the replica protocol
(serve/replica.py) with the three things a caller under an SLO needs:

- **deadlines** — every submit stamps a wall-clock deadline into the
  request body; replicas shed rather than serve past it, and the explicit
  SHED verdict in the result slot means the client never hangs on a
  request the system gave up on;
- **retry with jittered backoff** — a SHED verdict (or a request that lost
  its lease and was never rescued) is retried up to ``max_retries`` times
  with a fresh deadline, pacing the polls with the same jittered
  exponential backoff the KV client uses (``kvstore._backoff_delays``);
- **hedging** — if a request has no verdict and no live lease after
  ``hedge_after`` seconds, the client appends a duplicate queue entry so
  another replica races the straggler. Safe by construction: verdict
  publication is claim-once (serve/done/<rid>) and result bodies are
  bitwise identical across executions (greedy or seeded-sampled decode),
  so a hedge can only waste compute, never change an answer.

Direct-to-KV traffic honors the canary split too: when the deploy
controller has live traffic shares up (``deploy/shares/<fleet>``), each
submit draws a weight version by share and writes the ``serve/pin``
before enqueueing, so the first claimer decodes on the drawn version.
Without this, KV-path requests would all land on whatever version their
claimer happens to run — diluting the canary's share of traffic and the
baseline-vs-canary comparison both. A retry redraws (it is a new
lifecycle; the pin was cleared with the verdict).
"""

from __future__ import annotations

import json
import random
import time
from dataclasses import dataclass, field

from tpu_sandbox.runtime.kvstore import _backoff_delays
from tpu_sandbox.serve.replica import (enqueue, k_done, k_lease, k_pin,
                                       k_req, k_result, submit_request)


@dataclass
class ClientStats:
    submitted: int = 0
    completed: int = 0
    shed: int = 0
    retries: int = 0
    hedges: int = 0
    #: gateway-path only: connections moved to another gateway after a
    #: connect failure / mid-frame EOF / hello timeout
    failovers: int = 0


class RetriesExhausted(RuntimeError):
    """The retry budget burned out on terminal sheds. Typed — a caller
    under an SLO must distinguish "the system refused after every retry"
    from a verdict dict it might forget to check — and carries the
    evidence: the last shed reason and the per-attempt timeline."""

    def __init__(self, rid: str, verdict: dict, attempts: list[dict]):
        self.rid = rid
        self.verdict = verdict
        self.last_reason = verdict.get("reason", "")
        self.attempts = attempts
        super().__init__(
            f"retries exhausted for {rid}: last shed reason "
            f"{self.last_reason!r} after {len(attempts)} attempt(s)")


@dataclass
class _Pending:
    prompt: list[int]
    max_new_tokens: int
    deadline_s: float | None
    temperature: float
    top_k: int
    seed: int
    submitted_at: float = 0.0
    retries_left: int = 0
    hedged: bool = False
    # one entry per submit/retry: {submitted_at, shed_reason?, resolved_at?}
    attempts: list = field(default_factory=list)


class ServeClient:
    """One producer's view of the serve plane. Not thread-safe; make one
    per producer thread (they share the KV store, not this object)."""

    def __init__(self, kv, *, deadline_s: float | None = None,
                 max_retries: int = 2, hedge_after: float | None = None,
                 backoff_base: float = 0.02, backoff_cap: float = 0.5,
                 share_seed: int = 0):
        self.kv = kv
        self.deadline_s = deadline_s
        self.max_retries = max_retries
        self.hedge_after = hedge_after
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self.stats = ClientStats()
        self._pending: dict[str, _Pending] = {}
        # canary-share draws: seeded so a test or replay submits the same
        # version sequence; the deploy keys live at the store ROOT while
        # a fleet view's serve keys live under fleet/<name>/
        self._share_rng = random.Random(share_seed)
        prefix = getattr(kv, "prefix", "") or ""
        if prefix.startswith("fleet/") and prefix.endswith("/"):
            self._deploy_kv = kv.raw
            self._fleet = prefix[len("fleet/"):-1]
        else:
            self._deploy_kv = kv
            self._fleet = ""

    def _pin_share(self, rid: str) -> int | None:
        """Honor the deploy controller's live canary split: draw a weight
        version by share and pin the rid to it BEFORE the enqueue, so the
        first claimer decodes on the drawn version. No live shares (the
        overwhelmingly common case) costs one try_get. Never a
        correctness gate — a pin the fleet cannot serve yet just decodes
        on the pinned version once a replica acks it."""
        from tpu_sandbox.deploy.registry import read_shares
        from tpu_sandbox.gateway.routing import pick_by_share

        shares = read_shares(self._deploy_kv, self._fleet)
        if not shares:
            return None
        ver = pick_by_share(shares, self._share_rng.random())
        if ver is not None:
            self.kv.set(k_pin(rid), str(int(ver)))
        return ver

    def submit(self, rid: str, prompt, max_new_tokens: int, *,
               deadline_s: float | None = None, temperature: float = 0.0,
               top_k: int = 0, seed: int = 0) -> None:
        d = self.deadline_s if deadline_s is None else deadline_s
        p = _Pending(prompt=list(map(int, prompt)),
                     max_new_tokens=int(max_new_tokens), deadline_s=d,
                     temperature=temperature, top_k=top_k, seed=seed,
                     submitted_at=time.time(),
                     retries_left=self.max_retries)
        p.attempts.append({"submitted_at": p.submitted_at})
        self._pin_share(rid)
        submit_request(
            self.kv, rid, p.prompt, p.max_new_tokens,
            deadline_unix=None if d is None else p.submitted_at + d,
            temperature=temperature, top_k=top_k, seed=seed)
        self._pending[rid] = p
        self.stats.submitted += 1

    def result(self, rid: str, timeout: float = 60.0) -> dict:
        """Block until ``rid`` has a terminal verdict, retrying sheds and
        hedging stragglers along the way. Returns the "ok" verdict body
        (tokens and metadata). A shed that outlives the retry budget
        raises :class:`RetriesExhausted` — except for rids this client
        never submitted (no budget to speak of), whose SHED verdict is
        returned as data."""
        p = self._pending.get(rid)
        deadline = time.monotonic() + timeout
        while True:
            for delay in _backoff_delays(max(deadline - time.monotonic(), 0),
                                         base=self.backoff_base,
                                         cap=self.backoff_cap):
                raw = self.kv.try_get(k_result(rid))
                if raw is not None:
                    verdict = json.loads(raw)
                    if verdict.get("verdict", "ok") != "SHED":
                        self._pending.pop(rid, None)
                        self.stats.completed += 1
                        return verdict
                    if p is None:
                        self.stats.shed += 1
                        return verdict
                    if p.retries_left <= 0:
                        self._pending.pop(rid, None)
                        self.stats.shed += 1
                        if p.attempts:
                            p.attempts[-1].update(
                                shed_reason=verdict.get("reason", ""),
                                resolved_at=time.time())
                        raise RetriesExhausted(rid, verdict, p.attempts)
                    self._retry(rid, p, verdict)
                    break
                if p is not None:
                    self._maybe_hedge(rid, p)
                time.sleep(delay)
            else:
                raise TimeoutError(f"no verdict for {rid} within {timeout}s")

    def _retry(self, rid: str, p: _Pending,
               verdict: dict | None = None) -> None:
        """Re-enqueue a shed request with a fresh deadline. The old verdict
        and its claim marker are cleared first so the replay can publish —
        by the time the client sees a SHED it is terminal, nobody else
        writes that slot again. The weight-version pin goes too: a retry
        is a new lifecycle and pins whatever its claimer then runs."""
        p.retries_left -= 1
        if p.attempts:
            p.attempts[-1].update(
                shed_reason="" if verdict is None
                else verdict.get("reason", ""),
                resolved_at=time.time())
        p.submitted_at = time.time()
        p.attempts.append({"submitted_at": p.submitted_at})
        p.hedged = False
        self.kv.delete(k_result(rid))
        self.kv.delete(k_done(rid))
        self.kv.delete(k_pin(rid))
        self._pin_share(rid)  # a retry redraws the canary split
        submit_request(
            self.kv, rid, p.prompt, p.max_new_tokens,
            deadline_unix=None if p.deadline_s is None
            else p.submitted_at + p.deadline_s,
            temperature=p.temperature, top_k=p.top_k, seed=p.seed)
        self.stats.retries += 1

    def _maybe_hedge(self, rid: str, p: _Pending) -> None:
        if p.hedged or self.hedge_after is None:
            return
        if time.time() - p.submitted_at < self.hedge_after:
            return
        if self.kv.try_get(k_lease(rid)) is not None:
            return  # someone is demonstrably working on it
        # no verdict, no lease: append a duplicate entry; claim-once is per
        # entry so a second replica can race the (possibly dead) first
        enqueue(self.kv, rid)
        p.hedged = True
        self.stats.hedges += 1
