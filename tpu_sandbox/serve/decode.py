"""AOT-compiled static-shape prefill and single-token decode steps.

Both steps run the *unmodified* ``TransformerLM`` — prefill taps per-layer
K/V through the model's ``kv_cache`` sow collection, decode injects an
``attention_fn`` that reads/writes the paged cache — so serving exercises
exactly the weights and math the training stack produced.

Bitwise discipline (the parity tests in tests/test_serve.py hold these):

- Decode computes single-query attention with the query axis padded to 2:
  at q=1 XLA:CPU switches to a matvec kernel whose output-contraction
  accumulation order differs from the full forward's gemm by ~1 ulp; at
  q>=2 the gemm kernel is used and row outputs are bitwise identical
  regardless of row count.
- The softmax *sum* reduce is grouping-stable only between equal (or
  vector-aligned) k-axis lengths: reducing 17 real weights over a k=17
  axis and over a zero-tailed k=32 axis rounds differently (~1 ulp) once
  the length exceeds the unrolled-reduce threshold (16 on XLA:CPU). So
  the bitwise reference for a decode step at context length n is the
  one-shot forward evaluated at the cache's ``max_context`` padding —
  the same k-axis length decode reduces over. While n <= 16 the
  exact-length one-shot matches too, and power-of-two bucket lengths are
  mutually bitwise (prefill at bucket 8 == forward at 32, etc.).
- All other per-position ops (Dense, LayerNorm, embeds, the score
  einsum's d-contraction, the length-masked max) are row-independent or
  exactly associative and bitwise at any slice.

Static shapes everywhere: prefill is compiled once per bucket length,
decode once per (max_batch, page geometry). The page buffers are donated
through both steps — the AOT receipt in tools/aot_serve.py shows XLA
aliasing them input->output.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from tpu_sandbox.models.transformer import TransformerConfig, TransformerLM
from tpu_sandbox.serve.cache import CacheConfig


def sample_token(logits_row: np.ndarray, *, seed: int, step_index: int,
                 temperature: float, top_k: int = 0) -> int:
    """Replay-exact temperature/top-k sampling over one row of fp32 logits.

    The draw is keyed by ``fold_in(key(seed), step_index)``, where
    ``step_index`` is the request's decode-step index (number of tokens
    generated so far). A request that is preempt-requeued or replayed after
    replica death re-runs from its original prompt, recomputes bitwise
    identical logits (see module docstring), folds the same indices into
    the same key, and therefore re-draws the same tokens — sampling keeps
    the same zero-loss guarantee as greedy decode.

    Gumbel-max over host fp32: ``argmax(logits/T + g)`` with Gumbel noise
    from ``jax.random`` — deterministic given the key, no CDF rounding.
    """
    logits = np.asarray(logits_row, np.float32) / np.float32(temperature)
    if top_k > 0 and top_k < logits.shape[-1]:
        kth = np.sort(logits)[-top_k]
        logits = np.where(logits >= kth, logits, -np.inf)
    key = jax.random.fold_in(jax.random.key(seed), step_index)
    g = np.asarray(jax.random.gumbel(key, logits.shape, jnp.float32))
    return int((logits + g).argmax())


@dataclass(frozen=True)
class DecodeStep:
    """Compiled step functions plus the geometry they were built for."""

    model_cfg: TransformerConfig
    cache_cfg: CacheConfig
    max_batch: int
    buckets: tuple[int, ...]
    cache_dtype: Any
    # bucket length -> compiled prefill(params, k, v, tokens, dest, last)
    prefill: dict[int, Callable]
    # compiled decode(params, k, v, tokens, lengths, block_tables)
    decode: Callable

    def pick_bucket(self, prompt_len: int) -> int:
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt of {prompt_len} tokens exceeds largest prefill bucket "
            f"{self.buckets[-1]}")


def page_shapes(model_cfg: TransformerConfig, cache_cfg: CacheConfig,
                cache_dtype: Any) -> tuple[jax.ShapeDtypeStruct, ...]:
    head_dim = model_cfg.d_model // model_cfg.n_heads
    shape = (model_cfg.n_layers, cache_cfg.num_blocks, cache_cfg.block_size,
             model_cfg.n_heads, head_dim)
    s = jax.ShapeDtypeStruct(shape, cache_dtype)
    return s, s


def init_pages(model_cfg: TransformerConfig, cache_cfg: CacheConfig,
               cache_dtype: Any = jnp.float32):
    """Zeroed K and V page buffers (finite everywhere: padding scatters may
    multiply stale page content by zero weights, which must stay exact)."""
    ks, vs = page_shapes(model_cfg, cache_cfg, cache_dtype)
    return jnp.zeros(ks.shape, ks.dtype), jnp.zeros(vs.shape, vs.dtype)


def _flat(pages: jnp.ndarray) -> jnp.ndarray:
    L, nb, bs, H, D = pages.shape
    return pages.reshape(L, nb * bs, H, D)


def make_prefill_fn(model_cfg: TransformerConfig, cache_cfg: CacheConfig,
                    cache_dtype: Any = jnp.float32):
    """prefill(params, k_pages, v_pages, tokens[1, Lb], dest_idx[Lb],
    last_pos[]) -> (next_logits[vocab], k_pages, v_pages).

    ``dest_idx`` maps each bucket position to its flat page slot — null
    block (slot 0) for bucket padding and shared-prefix positions, so the
    scatter never rewrites shared content. Page buffers are donated.
    """
    model = TransformerLM(model_cfg)

    def prefill(params, k_pages, v_pages, tokens, dest_idx, last_pos):
        logits, taps = model.apply(
            {"params": params}, tokens, mutable=["kv_cache"])
        fk, fv = _flat(k_pages), _flat(v_pages)
        for i in range(model_cfg.n_layers):
            k, v = taps["kv_cache"][f"block{i}"]["attn"]["kv"]
            fk = fk.at[i, dest_idx].set(k[0].astype(cache_dtype))
            fv = fv.at[i, dest_idx].set(v[0].astype(cache_dtype))
        next_logits = jax.lax.dynamic_index_in_dim(
            logits[0], last_pos, axis=0, keepdims=False)
        return (next_logits,
                fk.reshape(k_pages.shape), fv.reshape(v_pages.shape))

    return jax.jit(prefill, donate_argnums=(1, 2))


def make_decode_fn(model_cfg: TransformerConfig, cache_cfg: CacheConfig,
                   max_batch: int, cache_dtype: Any = jnp.float32):
    """decode(params, k_pages, v_pages, tokens[B, 1], lengths[B],
    block_tables[B, max_blocks]) -> (logits[B, vocab], k_pages, v_pages).

    ``lengths[b]`` counts tokens *including* the one being fed, so its
    position is ``lengths[b] - 1`` and attention covers kv positions
    ``< lengths[b]`` (the causal row for that query). Empty slots use
    ``lengths == 0``: their writes land in the null block and their
    attention weights collapse to zeros.
    """
    bs = cache_cfg.block_size
    head_dim = model_cfg.d_model // model_cfg.n_heads
    max_ctx = cache_cfg.max_context
    scale = jnp.sqrt(jnp.asarray(head_dim, jnp.float32))

    def decode(params, k_pages, v_pages, tokens, lengths, block_tables):
        fk, fv = _flat(k_pages), _flat(v_pages)
        pos = jnp.maximum(lengths - 1, 0)                      # [B]
        dest = (jnp.take_along_axis(
            block_tables, (pos // bs)[:, None], axis=1)[:, 0] * bs
            + pos % bs)                                        # [B]
        # flat slot of every block-table position, in sequence order
        ctx_idx = (block_tables[:, :, None] * bs
                   + jnp.arange(bs)[None, None, :]).reshape(
                       tokens.shape[0], max_ctx)               # [B, max_ctx]
        kv_mask = (jnp.arange(max_ctx)[None, :] < lengths[:, None])

        layer = itertools.count()

        def attention_fn(q, k, v):
            # q/k/v: [B, 1, H, D] — the new token at position lengths-1
            nonlocal fk, fv
            i = next(layer)
            fk = fk.at[i, dest].set(k[:, 0].astype(cache_dtype))
            fv = fv.at[i, dest].set(v[:, 0].astype(cache_dtype))
            kc = fk[i][ctx_idx].astype(q.dtype)                # [B, ctx, H, D]
            vc = fv[i][ctx_idx].astype(v.dtype)
            # query padded to q=2: XLA's q=1 matvec kernel accumulates the
            # output contraction in a different order than the full
            # forward's gemm (~1 ulp); at q>=2 the gemm kernel matches
            # bitwise (see module docstring / tests/test_serve.py)
            q2 = jnp.concatenate([q, q], axis=1)               # [B, 2, H, D]
            scores = jnp.einsum("bqhd,bkhd->bhqk", q2, kc).astype(jnp.float32)
            scores = scores / scale
            scores = jnp.where(kv_mask[:, None, None, :], scores, -jnp.inf)
            w = jnp.nan_to_num(jnp.exp(scores - scores.max(-1, keepdims=True)))
            w = w / jnp.maximum(w.sum(-1, keepdims=True), 1e-30)
            out = jnp.einsum("bhqk,bkhd->bqhd", w.astype(vc.dtype), vc)
            return out[:, :1]

        model = TransformerLM(model_cfg, attention_fn=attention_fn)
        logits = model.apply({"params": params}, tokens, pos[:, None])
        return (logits[:, 0],
                fk.reshape(k_pages.shape), fv.reshape(v_pages.shape))

    return jax.jit(decode, donate_argnums=(1, 2))


def build_decode_step(model_cfg: TransformerConfig, cache_cfg: CacheConfig,
                      *, max_batch: int = 4,
                      buckets: tuple[int, ...] = (16, 32, 64),
                      cache_dtype: Any = jnp.float32) -> DecodeStep:
    """AOT-compile every step function for the given static geometry."""
    buckets = tuple(sorted(b for b in buckets if b <= cache_cfg.max_context))
    if not buckets:
        raise ValueError("no prefill bucket fits max_context")
    params_shape = jax.eval_shape(
        lambda: TransformerLM(model_cfg).init(
            jax.random.key(0),
            jnp.zeros((1, buckets[0]), jnp.int32))["params"])
    kd, vd = page_shapes(model_cfg, cache_cfg, cache_dtype)

    prefill = {}
    for b in buckets:
        fn = make_prefill_fn(model_cfg, cache_cfg, cache_dtype)
        prefill[b] = fn.lower(
            params_shape, kd, vd,
            jax.ShapeDtypeStruct((1, b), jnp.int32),
            jax.ShapeDtypeStruct((b,), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.int32),
        ).compile()

    decode = make_decode_fn(
        model_cfg, cache_cfg, max_batch, cache_dtype).lower(
        params_shape, kd, vd,
        jax.ShapeDtypeStruct((max_batch, 1), jnp.int32),
        jax.ShapeDtypeStruct((max_batch,), jnp.int32),
        jax.ShapeDtypeStruct(
            (max_batch, cache_cfg.max_blocks_per_seq), jnp.int32),
    ).compile()

    return DecodeStep(
        model_cfg=model_cfg, cache_cfg=cache_cfg, max_batch=max_batch,
        buckets=buckets, cache_dtype=cache_dtype,
        prefill=prefill, decode=decode,
    )
