"""Durable model registry: the train->serve handoff ledger.

Training tenants publish sealed weight exports here
(``trainer.publish_checkpoint`` -> :func:`publish_version`); the
leader-elected :class:`~tpu_sandbox.deploy.controller.DeployController`
watches the ledger and promotes. Everything lives in the KV store so any
successor controller — and any ops tool — reconstructs the full
deployment state from the store alone:

    deploy/ver/<fleet>             atomic version allocator (``add()``)
    deploy/models/<fleet>/<ver>    version record {ver, step_dir, step,
                                   wall, ...} — the artifact pointer; the
                                   artifact itself is a sealed
                                   ShardedCheckpoint step dir on disk
    deploy/target/<fleet>          the fleet's established version (set
                                   only at the END of a successful
                                   rollout — mid-rollout it still names
                                   the rollback target)
    deploy/ro/<fleet>/<ver>/<kind> rollout decision records + claim-once
                                   markers per phase (kind in rec/claim,
                                   verdict/vclaim, reject/rejclaim,
                                   done/doneclaim) — see controller.py
    deploy/shares/<fleet>          version-pinned canary traffic shares
                                   the gateway routes by (present only
                                   while a canary is live)
    deploy/events/<n>, deploy/tail durable decision log (autoscaler
                                   idiom: ``add`` the tail, set the slot)

``<fleet>`` is the serving fleet's name, or ``default`` for the bare
(unnamed) fleet — the registry always lives at the store ROOT, even when
the serve plane is namespaced under ``fleet/<name>/``, because one
controller watches every fleet.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path

K_EVENT_TAIL = "deploy/tail"


def fleet_label(fleet: str) -> str:
    return fleet or "default"


def k_ver_alloc(fleet: str) -> str:
    return f"deploy/ver/{fleet_label(fleet)}"


def k_model(fleet: str, seq: int) -> str:
    """Registry record for version ``seq`` (versions are a per-fleet
    monotone sequence — the param name keeps the claim-key scope
    explicit)."""
    return f"deploy/models/{fleet_label(fleet)}/{int(seq)}"


def k_target(fleet: str) -> str:
    return f"deploy/target/{fleet_label(fleet)}"


def k_ro(fleet: str, seq: int, kind: str) -> str:
    """Rollout phase record/claim for (fleet, version ``seq``)."""
    return f"deploy/ro/{fleet_label(fleet)}/{int(seq)}/{kind}"


def k_shares(fleet: str) -> str:
    return f"deploy/shares/{fleet_label(fleet)}"


def k_event(n: int) -> str:
    return f"deploy/events/{n}"


# -- publishing ---------------------------------------------------------------


def publish_version(kv, step_dir: str | os.PathLike, *, fleet: str = "",
                    step: int | None = None,
                    extra: dict | None = None) -> int:
    """Register a sealed export as the fleet's next version; returns the
    allocated version number. Publication is a pointer write — integrity
    is re-verified by the controller before any replica is told to load
    it, so a corrupt artifact burns a version number, never a replica."""
    sd = Path(step_dir).absolute()
    ver = kv.add(k_ver_alloc(fleet))
    body = {"ver": int(ver), "step_dir": str(sd),
            "step": int(step) if step is not None else None,
            "wall": time.time()}
    body.update(extra or {})
    kv.set(k_model(fleet, ver), json.dumps(body))
    append_event(kv, {"action": "published", "fleet": fleet_label(fleet),
                      "ver": int(ver), "step_dir": str(sd),
                      "wall": time.time()})
    return int(ver)


def registry_versions(kv, fleet: str = "") -> dict[int, dict]:
    """Every registered version record for ``fleet``, keyed by version."""
    prefix = f"deploy/models/{fleet_label(fleet)}/"
    out: dict[int, dict] = {}
    for key in kv.keys(prefix):
        raw = kv.try_get(key)
        if raw is None:
            continue
        try:
            body = json.loads(raw)
            out[int(body["ver"])] = body
        except (ValueError, KeyError):
            continue
    return out


def current_target(kv, fleet: str = "") -> int:
    """The fleet's established version; 0 = boot weights (nothing ever
    promoted)."""
    raw = kv.try_get(k_target(fleet))
    return 0 if raw is None else int(raw)


def read_shares(kv, fleet: str = "") -> dict[int, float] | None:
    """Live canary traffic shares {version: share}, or None when no
    canary is routing."""
    raw = kv.try_get(k_shares(fleet))
    if raw is None:
        return None
    try:
        body = json.loads(raw)
        return {int(v): float(s) for v, s in body.get("shares", {}).items()}
    except (ValueError, AttributeError):
        return None


def rollout_phase(kv, fleet: str, seq: int) -> dict:
    """One rollout's durable phase state: which records/claims exist and
    their payloads — the successor-reconstruction and ops-panel view."""
    out: dict = {"ver": int(seq)}
    for kind in ("rec", "reject", "verdict", "done"):
        raw = kv.try_get(k_ro(fleet, seq, kind))
        out[kind] = None if raw is None else json.loads(raw)
    for kind, claim in (("rec", "claim"), ("reject", "rejclaim"),
                        ("verdict", "vclaim"), ("done", "doneclaim")):
        out[f"{kind}_claimed"] = \
            kv.try_get(k_ro(fleet, seq, claim)) is not None
    return out


def append_event(kv, event: dict) -> int:
    n = kv.add(K_EVENT_TAIL) - 1
    kv.set(k_event(n), json.dumps(event))
    return n


def deploy_events(kv) -> list[dict]:
    """Every deployment decision, in order — the bench/test timeline."""
    out = []
    for n in range(int(kv.try_get(K_EVENT_TAIL) or b"0")):
        raw = kv.try_get(k_event(n))
        if raw is not None:
            out.append(json.loads(raw))
    return out


# -- weight loading (replica side) -------------------------------------------


def load_step_params(step_dir: str | os.PathLike, template):
    """Checksum-verified restore of a registered export into
    ``template``'s structure. Raises on torn/corrupt artifacts — the
    replica treats that as a failed swap, never a partial load.

    Staging is chunk-streamed (``runtime.staging.stream_load_npz`` under
    ``ShardedCheckpoint._load``): each member decompresses straight into
    its preallocated array in bounded chunks instead of ``np.load``'s
    whole-member bytes copy, so a swap's peak host memory is ~one model
    instead of two plus the largest member. The verify-before-touch
    checksum pass is unchanged — bytes on disk are hashed before any
    parse."""
    from tpu_sandbox.train.checkpoint import load_exported_params

    return load_exported_params(step_dir, template)


# -- registry audit (tools/verify_ckpt.py) ------------------------------------


def audit_registry(kv, fleet: str = "") -> dict:
    """Walk one fleet's registry and report, per version: seal status of
    its artifact, lifecycle status (current / candidate / rejected /
    rolled-back / superseded), and whether it is dangling (registered but
    the artifact is gone) or GC-able (superseded, finished, and no longer
    the rollback target). Pure read — the audit never deletes."""
    from tpu_sandbox.train.checkpoint import verify_step_dir

    target = current_target(kv, fleet)
    versions = registry_versions(kv, fleet)
    allocated = int(kv.try_get(k_ver_alloc(fleet)) or b"0")
    missing = sorted(set(range(1, allocated + 1)) - set(versions))
    rows = []
    for seq in sorted(versions):
        rec = versions[seq]
        phase = rollout_phase(kv, fleet, seq)
        sd = Path(rec.get("step_dir", ""))
        dangling = not sd.is_dir()
        problems = [] if dangling else verify_step_dir(sd)
        if seq == target:
            status = "current"
        elif phase["reject"] is not None:
            status = "rejected"
        elif phase["done"] is not None:
            outcome = (phase["done"] or {}).get("outcome")
            status = "rolled_back" if outcome == "rolled_back" \
                else "superseded"
        elif seq > target:
            status = "candidate"
        else:
            status = "superseded"
        # the previous target stays pinned as the live rollback target
        # while any rollout is still unfinished; a finished history makes
        # every non-current, non-candidate version collectable
        gc_able = status in ("superseded", "rolled_back", "rejected") \
            and seq != target
        rows.append({"ver": seq, "status": status,
                     "step_dir": str(sd), "dangling": dangling,
                     "sealed": (not dangling) and not problems,
                     "problems": problems, "gc_able": gc_able})
    return {"fleet": fleet_label(fleet), "target": target,
            "allocated": allocated, "missing_records": missing,
            "versions": rows}


def audited_fleets(kv) -> list[str]:
    """Fleet labels with any registry state — the audit's scan scope."""
    fleets = {k.split("/")[2] for k in kv.keys("deploy/models/")
              if k.count("/") >= 3}
    fleets |= {k.split("/")[2] for k in kv.keys("deploy/ver/")
               if k.count("/") >= 2}
    return sorted(fleets)
