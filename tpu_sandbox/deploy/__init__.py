"""Deployment plane: durable model registry + leader-elected rollout
control (see :mod:`tpu_sandbox.deploy.registry` and
:mod:`tpu_sandbox.deploy.controller`)."""
