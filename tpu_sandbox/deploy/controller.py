"""Leader-elected deployment controller: rolling weight swaps, canary
analysis, automatic rollback — exactly-once through controller death.

Any number of :class:`DeployController` candidates may run; a
``LeaseElection`` on ``deploy/leader/<fleet>`` picks one actor per fleet
(the autoscaler pattern). The leader's tick reconstructs the entire
rollout state from the store alone, so a successor resumes mid-rollout
with nothing but the registry keys:

1. **consider** — with no rollout active, the highest registered version
   above the fleet target is the candidate. Its artifact is re-verified
   on disk (``verify_step_dir``) FIRST: a torn or corrupt export gets a
   claim-once ``reject`` record and no replica is ever told about it.
   A clean candidate gets the claim-once ``rec`` begin record.
2. **canary** — the least-loaded live replica by fresh load report
   (queued + active work; ties and report-less fleets fall back to tag
   order; the choice is persisted in the rollout's phase records so it
   neither flaps nor changes across controller failover) receives a
   ``swap`` command through its ``serve/cmd/<tag>`` mailbox (idempotent,
   re-sent with local patience until the replica's TTL load report acks
   the new version; the replica stages the artifact chunk-streamed —
   ``runtime.staging`` via ``registry.load_step_params`` — after its
   verify-before-touch checksum pass, so a swap never doubles host
   memory mid-roll). Once acked, version-pinned traffic shares go up for
   the gateway (``deploy/shares/<fleet>``) and two
   :class:`~tpu_sandbox.obs.health.BaselineDeltaRule` instances compare
   the canary's p99 TTFT and mean chosen-token logprob in the tsdb
   against the incumbent replicas. ``regress_streak`` consecutive firing
   evaluations -> claim-once FAIL verdict (+ a ``canary_regression``
   health alert); ``canary_evals`` clean evaluations **with data on both
   sides** -> claim-once PASS.
3. **roll / rollback** — on PASS the remaining replicas swap one at a
   time (the controller advances only on the acked load report); on FAIL
   every swapped replica converges back to the previous version by the
   same one-at-a-time protocol. Either way the shares key is cleared,
   the target is (re)established, and a claim-once ``done`` record ends
   the rollout.

Every decision follows the ``raise_alert`` ordering — idempotent record
``set`` first, ``add()``-gated claim second — so a controller killed
between the two leaves state a successor completes without double-firing
(the claim gates events/counters; records may be rewritten with a fresh
wall stamp, which is informational only).

Leader-local state (canary streaks, swap-command patience stamps) resets
on failover; like the health monitor's detectors, a successor rebuilds
it within one evaluation window — which is why the acceptance bound is
rollback within 2 windows, not 1.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass

from tpu_sandbox.deploy.registry import (append_event, current_target,
                                         fleet_label, k_ro, k_shares,
                                         k_target, registry_versions,
                                         rollout_phase)
from tpu_sandbox.gateway.fleet import fleet_kv
from tpu_sandbox.obs import get_registry
from tpu_sandbox.obs.health import BaselineDeltaRule, raise_alert
from tpu_sandbox.runtime.election import LeaseElection
from tpu_sandbox.serve.replica import k_cmd, read_load_reports


@dataclass(frozen=True)
class DeployConfig:
    #: traffic share routed to the canary while under analysis
    canary_share: float = 0.25
    #: clean evaluations (both sides reporting data) needed to pass
    canary_evals: int = 3
    #: consecutive firing evaluations needed to fail (and roll back)
    regress_streak: int = 2
    #: local patience before re-sending an unacked swap command
    swap_resend_s: float = 1.0
    #: tsdb bucket width the canary rules read
    bucket_s: float = 1.0
    #: alert window for the canary_regression health alert
    window_s: float = 1.0
    alert_ttl_windows: float = 3.0
    #: canary p99 TTFT may not exceed baseline * ttft_ratio (None = off)
    ttft_ratio: float | None = 1.5
    #: canary mean logprob may not fall below baseline + logprob_delta
    #: (delta is negative: how much worse the canary may score; None = off)
    logprob_delta: float | None = -0.5


class DeployController:
    """One candidate's view of the deployment control loop. Call
    :meth:`tick` on a cadence; it is a no-op on non-leaders and returns
    the decision event dict when the leader acted this tick."""

    def __init__(self, kv, *, fleet: str = "", member_id: str = "deploy-0",
                 cfg: DeployConfig = DeployConfig(),
                 election_ttl: float = 3.0, clock=time.time):
        self.kv = kv                       # root store: registry + rollout
        self.skv = fleet_kv(kv, fleet)     # fleet view: serve protocol keys
        self.fleet = fleet
        self.cfg = cfg
        self.clock = clock
        self.election = LeaseElection(
            kv, member_id, ttl=election_ttl,
            prefix=f"deploy/leader/{fleet_label(fleet)}")
        self._clean_evals = 0
        self._regress = 0
        self._last_cmd: dict[tuple[str, int], float] = {}

    # -- control loop --------------------------------------------------------

    def tick(self):
        """One control iteration; returns the decision event dict when
        this tick decided something, else None."""
        if not self.election.step(candidate=True):
            # follower: leader-local canary state must not survive into a
            # later leadership stint with stale evidence
            self._clean_evals = self._regress = 0
            self._last_cmd.clear()
            return None
        return self._leader_tick()

    def _leader_tick(self):
        target = current_target(self.kv, self.fleet)
        versions = registry_versions(self.kv, self.fleet)
        active = self._active_rollout(versions, target)
        if active is None:
            return self._leader_consider(versions, target)
        return self._leader_advance(active, versions)

    def _active_rollout(self, versions: dict[int, dict],
                        target: int) -> dict | None:
        """The unfinished rollout, reconstructed from the store: a ``rec``
        begin record with neither a ``done`` nor a ``reject`` record. At
        most one exists by construction (consider only begins when none
        is active)."""
        for seq in sorted(versions, reverse=True):
            phase = rollout_phase(self.kv, self.fleet, seq)
            if phase["rec"] is not None and phase["done"] is None \
                    and phase["reject"] is None:
                return phase
        return None

    def _leader_consider(self, versions: dict[int, dict], target: int):
        """Pick and begin (or reject) the next candidate version."""
        for seq in sorted(versions, reverse=True):
            if seq <= target:
                break
            phase = rollout_phase(self.kv, self.fleet, seq)
            if phase["done"] is not None or phase["reject"] is not None:
                continue  # already rolled back or rejected: skip forever
            step_dir = versions[seq].get("step_dir", "")
            problems = self._verify_artifact(step_dir)
            if problems:
                # the hard gate: a torn/corrupt artifact never reaches a
                # replica — no swap command exists for a rejected version
                return self._decide(
                    seq, "reject", "rejclaim",
                    {"ver": seq, "step_dir": step_dir,
                     "problems": problems[:8], "wall": self.clock()},
                    "rejected", problems=len(problems))
            self._clean_evals = self._regress = 0
            return self._decide(
                seq, "rec", "claim",
                {"ver": seq, "step_dir": step_dir, "prev": int(target),
                 "wall": self.clock()},
                "promote_begin", prev=int(target))
        return None

    def _leader_advance(self, phase: dict, versions: dict[int, dict]):
        """Drive the active rollout one step: canary, then roll or roll
        back, then seal the outcome."""
        seq = int(phase["ver"])
        rec = phase["rec"]
        prev = int(rec.get("prev", 0))
        if not phase["rec_claimed"]:
            # predecessor died between record and claim: complete it
            # (claim-once keeps the begin event single)
            self._complete_claim(seq, "claim", "promote_begin", prev=prev)
        reports = read_load_reports(self.skv)
        tags = sorted(reports)
        if not tags:
            return None  # no live fleet to drive; reports are TTL'd
        if phase["verdict"] is None:
            return self._leader_canary(seq, rec, prev, reports, tags)
        return self._leader_converge(phase, seq, rec, prev, reports, tags)

    def _pick_canary(self, seq: int, reports: dict,
                     tags: list[str]) -> str:
        """The canary replica: least-loaded by its fresh load report
        (queued + active work), ties and report-less fleets falling back
        to tag order. Persisted in the rollout's phase records the first
        time it is chosen, so the choice neither flaps between ticks as
        load shifts nor changes under a controller failover mid-canary —
        the successor swaps (and measures) the same replica. A persisted
        canary whose report vanished (replica died) is re-chosen."""
        raw = self.kv.try_get(k_ro(self.fleet, seq, "canary"))
        if raw is not None:
            tag = json.loads(raw).get("tag", "")
            if tag in reports:
                return tag
        canary = min(tags, key=lambda t: (
            int(reports[t].get("queue_depth", 0))
            + int(reports[t].get("active", 0)), t))
        self.kv.set(k_ro(self.fleet, seq, "canary"), json.dumps(
            {"ver": seq, "tag": canary, "wall": self.clock()}))
        return canary

    def _leader_canary(self, seq: int, rec: dict, prev: int,
                       reports: dict, tags: list[str]):
        cfg = self.cfg
        canary = self._pick_canary(seq, reports, tags)
        ack = int(reports[canary].get("ver", 0))
        if ack != seq:
            err = reports[canary].get("swap_error")
            if isinstance(err, dict) and int(err.get("ver", -1)) == seq:
                # the replica tried and cannot load this artifact —
                # equivalent to a failed canary, same rollback path
                return self._fail_canary(seq, canary,
                                         [{"swap_error": err}])
            self._send_swap(canary, seq, rec.get("step_dir"))
            return None
        if len(tags) < 2:
            # nobody to baseline against: canary analysis is vacuous
            return self._decide(
                seq, "verdict", "vclaim",
                {"ver": seq, "outcome": "pass", "reason": "no_baseline",
                 "wall": self.clock()},
                "canary_pass", reason="no_baseline")
        self.kv.set(k_shares(self.fleet), json.dumps({
            "seq": seq,
            "shares": {str(seq): cfg.canary_share,
                       str(prev): round(1.0 - cfg.canary_share, 6)}}))
        rules = self._canary_rules(canary, [t for t in tags if t != canary])
        now_bucket = int(float(self.clock()) // cfg.bucket_s)
        fired = [payload for rule in rules
                 for _subject, payload in rule.evaluate(self.kv, now_bucket)]
        has_data = any(rule.has_data(self.kv) for rule in rules)
        if fired:
            self._regress += 1
            self._clean_evals = 0
        elif has_data:
            self._clean_evals += 1
            self._regress = 0
        if self._regress >= cfg.regress_streak:
            return self._fail_canary(seq, canary, fired)
        if self._clean_evals >= cfg.canary_evals:
            return self._decide(
                seq, "verdict", "vclaim",
                {"ver": seq, "outcome": "pass",
                 "clean_evals": self._clean_evals, "wall": self.clock()},
                "canary_pass")
        return None

    def _fail_canary(self, seq: int, canary: str, evidence: list[dict]):
        now = float(self.clock())
        event = self._decide(
            seq, "verdict", "vclaim",
            {"ver": seq, "outcome": "fail", "canary": canary,
             "evidence": evidence[:4], "wall": now},
            "canary_fail", canary=canary)
        if event is not None:
            window_idx = int(now // self.cfg.window_s)
            raise_alert(
                self.kv, "canary_regression", fleet_label(self.fleet),
                window_idx,
                {"rule": "canary_regression",
                 "subject": fleet_label(self.fleet), "ver": seq,
                 "canary": canary, "evidence": evidence[:4],
                 "window_idx": window_idx, "wall": now},
                active_ttl=self.cfg.alert_ttl_windows * self.cfg.window_s)
        return event

    def _leader_converge(self, phase: dict, seq: int, rec: dict, prev: int,
                         reports: dict, tags: list[str]):
        outcome = (phase["verdict"] or {}).get("outcome")
        if not phase["verdict_claimed"]:
            self._complete_claim(
                seq, "vclaim",
                "canary_pass" if outcome == "pass" else "canary_fail")
        if outcome == "pass":
            goal_ver, goal_dir = seq, rec.get("step_dir")
        else:
            goal_ver = prev
            goal_dir = (registry_versions(self.kv, self.fleet)
                        .get(prev, {}).get("step_dir")
                        if prev else None)
        behind = [t for t in tags
                  if int(reports[t].get("ver", 0)) != goal_ver]
        if behind:
            # strictly one replica in flight: advance only on its ack
            self._send_swap(behind[0], goal_ver, goal_dir)
            return None
        self.kv.delete(k_shares(self.fleet))
        if outcome == "pass":
            self.kv.set(k_target(self.fleet), str(seq))
        done_outcome = "promoted" if outcome == "pass" else "rolled_back"
        self._clean_evals = self._regress = 0
        return self._decide(
            seq, "done", "doneclaim",
            {"ver": seq, "outcome": done_outcome, "target": goal_ver,
             "replicas": len(tags), "wall": self.clock()},
            done_outcome, target=goal_ver)

    # -- mechanics -----------------------------------------------------------

    def _canary_rules(self, canary: str,
                      baseline: list[str]) -> list[BaselineDeltaRule]:
        def proc(tag: str) -> str:
            return tag.replace("/", "-")  # tsdb proc names are slash-free

        base = tuple(proc(t) for t in baseline)
        rules = []
        if self.cfg.ttft_ratio is not None:
            rules.append(BaselineDeltaRule(
                name="canary_ttft", series="engine.ttft",
                subject=proc(canary), baseline=base,
                threshold=self.cfg.ttft_ratio, mode="ratio", op=">",
                field="p99"))
        if self.cfg.logprob_delta is not None:
            rules.append(BaselineDeltaRule(
                name="canary_logprob", series="engine.logprob",
                subject=proc(canary), baseline=base,
                threshold=self.cfg.logprob_delta, mode="delta", op="<",
                field="mean"))
        return rules

    def _send_swap(self, tag: str, seq: int, step_dir) -> None:
        """Idempotent swap command with local re-send patience. The
        mailbox is delete-on-read, so a replica killed mid-swap simply
        gets the command again after respawn — exactly-once lives in the
        claim-once phase records, not in the mailbox."""
        key = (tag, int(seq))
        now = time.monotonic()
        if now - self._last_cmd.get(key, float("-inf")) \
                < self.cfg.swap_resend_s:
            return
        self._last_cmd[key] = now
        cmd = {"action": "swap", "ver": int(seq)}
        if step_dir:
            cmd["step_dir"] = str(step_dir)
        self.skv.set(k_cmd(tag), json.dumps(cmd))
        get_registry().counter("deploy.swap_sent").inc()

    def _verify_artifact(self, step_dir: str) -> list[str]:
        from tpu_sandbox.train.checkpoint import verify_step_dir

        if not step_dir:
            return ["torn: version record has no step_dir"]
        return verify_step_dir(step_dir)

    def _decide(self, seq: int, kind: str, claim: str, body: dict,
                action: str, **event_extra):
        """Record-then-claim, the raise_alert ordering: the idempotent
        record lands first, the add()-gated claim arbitrates the one-time
        event/counter. Killed between the two -> successor re-records and
        wins the claim itself; killed after -> successor's add sees >1
        and stays silent."""
        self.kv.set(k_ro(self.fleet, seq, kind), json.dumps(body))
        return self._complete_claim(seq, claim, action, **event_extra)

    def _complete_claim(self, seq: int, claim: str, action: str,
                        **event_extra):
        if self.kv.add(k_ro(self.fleet, seq, claim)) != 1:
            return None
        event = {"action": action, "fleet": fleet_label(self.fleet),
                 "ver": int(seq), "wall": float(self.clock()),
                 **event_extra}
        append_event(self.kv, event)
        get_registry().counter("deploy.events",
                               labels={"action": action}).inc()
        return event

    def resign(self) -> None:
        self.election.resign()
