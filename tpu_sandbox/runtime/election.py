"""Leader election over the native KV store: TTL'd lease + add()-wins claims.

The cross-host elastic design (runtime/host_agent.py) needs exactly one
agent driving generation lifecycle at a time, and needs that role to move
when its holder dies mid-generation. The store has no compare-and-swap, so
the election builds on the two primitives it does have:

- ``add()`` is atomic: the first caller of ``add("leader/claim/<t>", 1)``
  sees 1 and owns term ``t``; every later caller sees >1 and lost.
- ``set_ttl()`` makes keys vanish server-side: the winner parks its id in
  ``leader/lease/<t>`` with a TTL and renews it; a leader that dies simply
  stops renewing, the lease evaporates, and any observer of the vacancy
  runs a new election at a higher term.

Multi-tenancy note: the election never namespaces its own keys — isolation
comes from the *client*. Hand it a job-scoped view (``kvstore.for_job``)
and two jobs sharing one store run fully independent elections under
``job/<id>/leader/*`` without this module knowing jobs exist.

Key layout (under ``prefix``, default ``leader``):

- ``<p>/term``       — highest *established* term (plain int, set by the
                       winner after its claim succeeds)
- ``<p>/claim/<t>``  — add()-wins tiebreaker for term ``t`` (persistent)
- ``<p>/lease/<t>``  — TTL'd lease for term ``t``; value = holder id

Two deliberate subtleties:

1. A claim winner that dies *between* claiming and establishing would brick
   its term forever (the claim key persists). Candidates therefore skip
   claimed-but-unestablished terms after giving the claimant ``claim_grace``
   seconds to finish — bounded stall, no deadlock.
2. A deposed leader must notice. Renewal first re-reads ``<p>/term``; if it
   moved past the holder's own term, a newer leader established itself (we
   were presumed dead, e.g. after a partition heals) and the holder abdicates
   instead of renewing a stale lease.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from tpu_sandbox.runtime.kvstore import KVClient


@dataclass(frozen=True)
class LeaderInfo:
    term: int
    member_id: str


class LeaseElection:
    """One participant's view of the election. Call ``step()`` periodically
    (at least a few times per ``ttl``): it renews when leading, elects on a
    vacancy, and returns whether this member leads right now.
    """

    def __init__(
        self,
        kv: KVClient,
        member_id: int | str,
        *,
        ttl: float = 5.0,
        prefix: str = "leader",
        claim_grace: float | None = None,
    ):
        self.kv = kv
        self.member_id = str(member_id)
        if ttl <= 0:
            raise ValueError(f"lease ttl must be > 0, got {ttl}")
        self.ttl = ttl
        self.prefix = prefix
        # how long an unestablished claim bars its term before we move past
        # it (covers the claimant's claim->establish window; ttl is a safe
        # upper bound for two KV round-trips)
        self.claim_grace = ttl if claim_grace is None else claim_grace
        self._term = 0          # highest term this member has seen/held
        self._is_leader = False
        self._claim_seen: dict[int, float] = {}  # term -> patience deadline

    # -- key layout ---------------------------------------------------------

    def _term_key(self) -> str:
        return f"{self.prefix}/term"

    def _claim_key(self, term: int) -> str:
        return f"{self.prefix}/claim/{term}"

    def _lease_key(self, term: int) -> str:
        return f"{self.prefix}/lease/{term}"

    # -- observation --------------------------------------------------------

    @property
    def is_leader(self) -> bool:
        return self._is_leader

    @property
    def term(self) -> int:
        return self._term

    def stored_term(self) -> int:
        raw = self.kv.try_get(self._term_key())
        return 0 if raw is None else int(raw)

    def observe(self) -> LeaderInfo | None:
        """Current leader per the store, or None when the lease is vacant
        (expired, resigned, or nobody ever won)."""
        term = self.stored_term()
        if term == 0:
            return None
        raw = self.kv.try_get(self._lease_key(term))
        if raw is None:
            return None
        return LeaderInfo(term, raw.decode())

    # -- participation ------------------------------------------------------

    def step(self, *, candidate: bool = True) -> bool:
        """Renew / observe / elect, returning True iff this member leads.

        ``candidate=False`` observes and renews but never starts a new
        election — agents use it to bias the initial election toward a
        designated member without forfeiting failover.
        """
        stored = self.stored_term()
        if self._is_leader:
            if stored == self._term:
                # still the established leader: renew before the lease lapses
                self.kv.set_ttl(
                    self._lease_key(self._term), self.member_id, self.ttl
                )
                return True
            # a higher term established itself while we were silent
            self._is_leader = False
            self._term = max(self._term, stored)
        current = self.observe()
        if current is not None:
            self._term = current.term
            self._is_leader = current.member_id == self.member_id
            return self._is_leader
        if not candidate:
            return False
        return self._run_election(stored)

    def _run_election(self, established: int) -> bool:
        term = self._candidate_term(established)
        if term is None:
            return False  # an in-flight claimant still has grace to finish
        if self.kv.add(self._claim_key(term), 1) != 1:
            # lost the add() race; the winner gets claim_grace to establish
            self._claim_seen.setdefault(
                term, time.monotonic() + self.claim_grace
            )
            return False
        # Won the claim. Guard against a higher term having established
        # while we raced (then our lease would be ignored anyway): abdicate.
        now_stored = self.stored_term()
        if now_stored >= term:
            self._term = now_stored
            return False
        # Establish order matters: term first, lease second. A winner dying
        # between the two leaves term=t with no lease -> observers see a
        # vacancy at t and elect t+1; the reverse order could strand a live
        # lease nobody looks at.
        self.kv.set(self._term_key(), str(term))
        self.kv.set_ttl(self._lease_key(term), self.member_id, self.ttl)
        self._term, self._is_leader = term, True
        # hygiene: retire tiebreaker keys for terms at/below ours so the
        # claim namespace doesn't grow forever across failovers
        for k in self.kv.keys(f"{self.prefix}/claim/"):
            try:
                t = int(k.rsplit("/", 1)[1])
            except ValueError:
                continue
            if t < term:
                self.kv.delete(k)
        return True

    def _candidate_term(self, established: int) -> int | None:
        """Next electable term above ``established``: skips terms whose claim
        key exists (somebody won the tiebreak there), but only after giving
        that claimant ``claim_grace`` seconds to establish — returns None
        while still inside a claimant's grace window."""
        term = established + 1
        now = time.monotonic()
        while self.kv.try_get(self._claim_key(term)) is not None:
            deadline = self._claim_seen.setdefault(
                term, now + self.claim_grace
            )
            if now < deadline:
                return None
            term += 1
        return term

    def resign(self) -> None:
        """Voluntarily drop the lease so followers elect immediately instead
        of waiting out the TTL."""
        if self._is_leader:
            self.kv.delete(self._lease_key(self._term))
            self._is_leader = False
