"""Multi-tenant cluster scheduler: many jobs, one host pool, one KV store.

:class:`~tpu_sandbox.runtime.host_agent.AgentLauncher` runs exactly one
job on a dedicated set of hosts. This module is its promotion to a small
cluster scheduler (ROADMAP item 5): a durable job queue in the KV store,
gang scheduling of heterogeneous jobs onto a shared pool, and priority
preemption — a high-priority job arriving on a full pool SIGTERMs a
low-priority job's agents, which checkpoint through the existing
preemption vote and resume bitwise when hosts free up.

Division of labor — the scheduler deliberately knows nothing about
generations, budgets, or elections. All of that stays inside each job's
:class:`~tpu_sandbox.runtime.host_agent.HostAgent` gang, running in its
own KV namespace (``job/<id>/...``, see ``kvstore.for_job``). The
scheduler only:

- keeps the durable queue under the cluster-level ``sched/*`` prefix,
- spawns/respawns a job's agent processes as a gang (never partial),
- watches each job's namespaced ``job/done`` verdict key,
- SIGTERMs a victim gang to preempt it (indistinguishable, to the job,
  from the machines being reclaimed — the path the elastic runtime
  already proves bitwise), and re-queues it for an uncharged resume.

The robustness contract this buys: one job's host death, wedged rank, or
partition never touches a neighbor job, because nothing a job does —
election churn, budget charging, fault claims, health sweeps — can reach
outside its namespace. Scheduler death doesn't kill jobs either: agents
are spawned *without* pdeathsig, so running gangs finish (or keep
recovering) on their own, and a restarted scheduler adopts them from the
store.

KV schema (cluster level, outside every job namespace)::

    sched/seq                     admission-order counter (atomic)
    sched/jobs/<id>/spec          JobSpec JSON (durable across schedulers)
    sched/jobs/<id>/seq           this job's submission sequence number
    sched/jobs/<id>/state         queued|running|preempting|done|failed|
                                  preempted|cancelled|timeout
    sched/jobs/<id>/cancel        cancellation request flag
    sched/jobs/<id>/verdict       copy of the job's final job/done record
    sched/jobs/<id>/event/<name>  wall-clock stamps (submitted, admitted,
                                  readmitted, preempt_sent, preempted, ...)
                                  — receipts for bench --metric cluster;
                                  never compared against a local clock

plus, per job, everything ``host_agent.py`` documents — under
``job/<id>/`` instead of bare.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import time
from dataclasses import asdict, dataclass, field
from typing import Mapping

from tpu_sandbox.obs import get_recorder, get_registry
from tpu_sandbox.runtime.host_agent import (
    K_JOB_DONE,
    _agent_hb_key,
    assign_ranks,
)
from tpu_sandbox.runtime.kvstore import (
    ENV_JOB_ID,
    KVClient,
    KVServer,
    for_job,
    job_namespace,
)
from tpu_sandbox.runtime.watchdog import Watchdog

K_SEQ = "sched/seq"
JOBS_PREFIX = "sched/jobs/"
K_VTIME_PREFIX = "sched/vtime/"
#: TTL'd per-tenant queued-job counts — the health plane's evidence that
#: a starved tenant actually has work waiting (sched/queued/<tenant>)
K_QUEUED_PREFIX = "sched/queued/"
#: durable per-job preemption counters (sched/preempts/<job_id>) — the
#: cascade detector diffs these per evaluation window
K_PREEMPTS_PREFIX = "sched/preempts/"

#: states a job can be observed in; terminal ones never change again
QUEUED, RUNNING, PREEMPTING = "queued", "running", "preempting"
TERMINAL_STATES = ("done", "failed", "preempted", "cancelled", "timeout")


def k_spec(job_id: str) -> str:
    return f"sched/jobs/{job_id}/spec"


def k_state(job_id: str) -> str:
    return f"sched/jobs/{job_id}/state"


def k_seq(job_id: str) -> str:
    return f"sched/jobs/{job_id}/seq"


def k_cancel(job_id: str) -> str:
    return f"sched/jobs/{job_id}/cancel"


def k_verdict(job_id: str) -> str:
    return f"sched/jobs/{job_id}/verdict"


def k_event(job_id: str, name: str) -> str:
    return f"sched/jobs/{job_id}/event/{name}"


@dataclass
class JobSpec:
    """One queue entry, durable as JSON in the store.

    ``agent_argv`` is a command *template* for one host agent process;
    each element is ``str.format``-ed with ``agent_id``, ``kv_port``,
    ``job_id``, ``num_agents`` and ``world_size`` (e.g.
    ``["python", "train.py", "--agent-id", "{agent_id}", ...]``). The
    template, not a callable, is what makes the queue durable: a fresh
    scheduler process can respawn any job's agents from the store alone.

    ``hosts`` is the gang size — the job runs on exactly this many pool
    slots or not at all. ``world_size`` need not divide by ``hosts``
    (the leader publishes a balanced rank-assignment table). Higher
    ``priority`` wins; equal priority is FIFO by submission order. A job
    that cannot be admitted within ``admission_timeout`` seconds is
    timed out and its namespace swept clean.
    """

    job_id: str
    hosts: int
    world_size: int
    agent_argv: list[str]
    priority: int = 0
    admission_timeout: float = 120.0
    env: dict[str, str] = field(default_factory=dict)
    # weighted fair share: jobs naming a tenant are admitted (within a
    # priority band) in order of the tenant's accumulated normalized
    # service — host-seconds / share — so one tenant cannot starve the
    # pool. Untenanted jobs keep plain FIFO-by-seq semantics.
    tenant: str = ""
    share: float = 1.0
    # MPMD co-gangs: jobs naming the same cogroup are admitted
    # all-or-nothing as one "gang of gangs" — a cross-mesh pipeline's
    # per-stage HostAgent groups are useless admitted piecemeal (stage 1
    # without stage 0 just blocks on the transport until it times out).
    # Preemption makes room for the whole group's host total, and
    # backfill never slips one member of the head's own group in early.
    cogroup: str = ""

    def __post_init__(self):
        if not job_namespace(self.job_id):
            raise ValueError(
                f"cluster jobs need a real job id (got {self.job_id!r}); "
                "the bare default namespace is reserved for single-job runs"
            )
        if self.hosts < 1:
            raise ValueError(f"hosts must be >= 1, got {self.hosts}")
        if self.share <= 0:
            raise ValueError(f"share must be > 0, got {self.share}")
        assign_ranks(self.world_size, self.hosts)  # validates the gang shape
        self.format_argv(agent_id=0, kv_port=0)  # fail bad templates early

    def format_argv(self, *, agent_id: int, kv_port: int) -> list[str]:
        fields = {
            "agent_id": agent_id, "kv_port": kv_port,
            "job_id": self.job_id, "num_agents": self.hosts,
            "world_size": self.world_size,
        }
        try:
            return [a.format(**fields) for a in self.agent_argv]
        except (KeyError, IndexError, ValueError) as e:
            raise ValueError(
                f"bad agent_argv template {self.agent_argv!r}: {e} "
                f"(known placeholders: {sorted(fields)})"
            ) from e

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @classmethod
    def from_json(cls, text: str) -> "JobSpec":
        return cls(**json.loads(text))


# ---------------------------------------------------------------------------
# queue API — usable from any client against the scheduler's store
# ---------------------------------------------------------------------------


def submit_job(kv: KVClient, spec: JobSpec) -> int:
    """Enqueue ``spec``; returns its admission sequence number. The queue
    is durable: everything a (possibly future) scheduler needs to run the
    job lives in the store after this returns."""
    if kv.try_get(k_spec(spec.job_id)) is not None:
        raise ValueError(f"job id {spec.job_id!r} already exists")
    seq = kv.add(K_SEQ, 1)
    kv.set(k_spec(spec.job_id), spec.to_json())
    kv.set(k_seq(spec.job_id), str(seq))
    kv.set(k_state(spec.job_id), QUEUED)
    kv.set(k_event(spec.job_id, "submitted"), f"{time.time():.6f}")
    get_recorder().instant("job:submitted", args={"job": spec.job_id})
    return seq


def list_jobs(kv: KVClient) -> list[dict]:
    """Every job the store knows, queued order first. Each entry:
    ``{job_id, state, seq, priority, hosts, world_size, tenant, share}``."""
    out = []
    for key in kv.keys(JOBS_PREFIX):
        if not key.endswith("/spec"):
            continue
        raw = kv.try_get(key)
        if raw is None:
            continue
        spec = JobSpec.from_json(raw.decode())
        state = kv.try_get(k_state(spec.job_id))
        seq = kv.try_get(k_seq(spec.job_id))
        out.append({
            "job_id": spec.job_id,
            "state": (state or b"?").decode(),
            "seq": int(seq or 0),
            "priority": spec.priority,
            "hosts": spec.hosts,
            "world_size": spec.world_size,
            "tenant": spec.tenant,
            "share": spec.share,
            "cogroup": spec.cogroup,
        })
    return sorted(out, key=lambda j: j["seq"])


def cancel_job(kv: KVClient, job_id: str) -> None:
    """Request cancellation; the scheduler sweeps a queued job immediately
    and SIGTERMs a running job's gang (it checkpoints and exits like a
    preemption, but is not re-queued)."""
    kv.set(k_cancel(job_id), b"1")


def job_events(kv: KVClient, job_id: str) -> dict[str, float]:
    """The job's wall-clock event stamps (bench receipts). Differences
    between two stamps are meaningful — they come from the scheduler's
    clock — but never mix them with the caller's own clock."""
    out = {}
    prefix = k_event(job_id, "")
    for key in kv.keys(prefix):
        raw = kv.try_get(key)
        if raw is not None:
            out[key[len(prefix):]] = float(raw)
    return out


# ---------------------------------------------------------------------------
# the scheduler
# ---------------------------------------------------------------------------


class _RunningJob:
    """Scheduler-side handle for an admitted gang. ``procs`` is empty for
    an *adopted* job (admitted by a dead predecessor scheduler): those
    can't be respawned, only monitored via verdict + agent heartbeats."""

    def __init__(self, spec: JobSpec, seq: int, procs, *, adopted=False):
        self.spec = spec
        self.seq = seq
        self.procs: dict[int, subprocess.Popen] = procs
        self.adopted = adopted
        self.preempting = False
        self.cancelling = False
        self.respawns = 0
        self.watchdog: Watchdog | None = None
        self.kill_at = 0.0  # SIGKILL escalation deadline while preempting


class ClusterScheduler:
    """Serve the durable queue on a pool of ``pool_size`` host slots.

    Scheduling policy, smallest thing that honors the contract:

    - **Gang, all-or-nothing.** A job launches with its full ``hosts``
      gang or not at all; there is no partial admission, ever.
    - **Strict priority, FIFO within a priority, conservative backfill.**
      The head of the queue is considered first each tick. When the head
      is blocked with no room on the way (nothing preemptable, nothing
      winding down), *strictly lower-priority* jobs that fit the free
      slots may start behind it — strictly lower, so the head keeps
      preemption rights over every backfilled gang and can only be
      delayed by one preemption drain, never indefinitely. Once the head
      has burned ``backfill_guard_frac`` of its admission window,
      backfill stops: the remaining window is reserved for making room,
      not for new tenants to churn through.
    - **Preemption frees exactly what's needed.** When the head job
      outranks running work, the lowest-priority victims (newest first)
      are SIGTERMed until enough slots will free. Victims checkpoint via
      the normal preemption vote, exit with a ``preempted`` verdict, are
      NOT charged a restart, and re-enter the queue at their original
      sequence number to resume bitwise when slots return.
    - **Admission deadline.** A job still queued ``admission_timeout``
      seconds after the scheduler first saw it (deadline measured on the
      scheduler's own monotonic clock; it restarts with the scheduler)
      is timed out and its entire KV namespace swept — no leaked claims.

    ``until_idle`` serving returns when no job is queued or running; use
    ``stop()`` from another thread (or a signal) to stop a long server.
    """

    def __init__(
        self,
        pool_size: int,
        *,
        kv_server: KVServer | None = None,
        kv_port: int | None = None,
        poll: float = 0.05,
        drain_timeout: float = 60.0,
        respawn_limit: int = 16,
        preempt_kill_timeout: float = 120.0,
        adopt_timeout: float = 15.0,
        backfill_guard_frac: float = 0.5,
        extra_env: Mapping[str, str] | None = None,
        verbose: bool = True,
    ):
        if pool_size < 1:
            raise ValueError(f"pool_size must be >= 1, got {pool_size}")
        if kv_server is not None and kv_port is not None:
            raise ValueError("pass kv_server OR kv_port, not both")
        self.pool_size = pool_size
        self._kv_server = kv_server
        # kv_port = connect to a store hosted elsewhere: the deployment
        # shape where the store (and the jobs) outlive this scheduler
        # process, so a successor can adopt
        self._connect_port = kv_port
        self._owns_server = kv_server is None and kv_port is None
        self.poll = poll
        self.drain_timeout = drain_timeout
        self.respawn_limit = respawn_limit
        self.preempt_kill_timeout = preempt_kill_timeout
        self.adopt_timeout = adopt_timeout
        if not 0.0 <= backfill_guard_frac <= 1.0:
            raise ValueError(
                f"backfill_guard_frac must be in [0, 1], got "
                f"{backfill_guard_frac}")
        self.backfill_guard_frac = backfill_guard_frac
        self.extra_env = dict(extra_env or {})
        self.verbose = verbose
        self.kv: KVClient | None = None
        self._server: KVServer | None = None
        self._running: dict[str, _RunningJob] = {}
        self._queue_deadline: dict[str, float] = {}
        # tenant -> accumulated normalized service (host-seconds / share),
        # mirrored to the store under sched/vtime/<tenant> on every charge
        # tick: a successor scheduler loads the ledger in start() and
        # keeps converging to the same weighted shares instead of
        # forgetting every tenant's accumulated debt at each failover
        self._tenant_vtime: dict[str, float] = {}
        self._last_charge = time.monotonic()
        # jobs already stamped with a `starved` event — the health plane
        # may hold the alert active for many ticks; the event fires once
        self._starved_stamped: set[str] = set()
        self._stop = False

    # -- lifecycle ----------------------------------------------------------

    def _log(self, msg: str) -> None:
        if self.verbose:
            print(f"[scheduler] {msg}", flush=True)

    def start(self) -> "ClusterScheduler":
        if self.kv is None:
            if self._connect_port is not None:
                self.kv = KVClient(port=self._connect_port)
            else:
                self._server = self._kv_server or KVServer()
                self.kv = KVClient(port=self._server.port)
            self._load_vtime()
            self._adopt_orphans()
        return self

    def _load_vtime(self) -> None:
        """Restore the durable fair-share ledger a predecessor left in
        the store (sched/vtime/<tenant>)."""
        for key in self.kv.keys(K_VTIME_PREFIX):
            raw = self.kv.try_get(key)
            if raw is None:
                continue
            try:
                self._tenant_vtime[key[len(K_VTIME_PREFIX):]] = float(raw)
            except ValueError:
                continue

    def close(self) -> None:
        for job in self._running.values():
            for p in job.procs.values():
                if p.poll() is None:
                    p.kill()
                    p.wait()
        self._running.clear()
        if self.kv is not None:
            self.kv.close()
            self.kv = None
        if self._owns_server and self._server is not None:
            self._server.stop()
        self._server = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    @property
    def kv_port(self) -> int:
        if self._server is not None:
            return self._server.port
        return self._connect_port

    def stop(self) -> None:
        self._stop = True

    def submit(self, spec: JobSpec) -> int:
        return submit_job(self.start().kv, spec)

    # -- adoption -----------------------------------------------------------

    def _adopt_orphans(self) -> None:
        """A predecessor scheduler died: jobs it admitted are (possibly)
        still running — their agents survive scheduler death by design.
        Re-attach to every non-terminal admitted job so its verdict is
        reaped and its slots are accounted; a gang whose agents are gone
        (no heartbeats) gets failed and swept instead of leaking slots
        forever."""
        for entry in list_jobs(self.kv):
            if entry["state"] not in (RUNNING, PREEMPTING):
                continue
            raw = self.kv.try_get(k_spec(entry["job_id"]))
            if raw is None:
                continue
            spec = JobSpec.from_json(raw.decode())
            job = _RunningJob(spec, entry["seq"], {}, adopted=True)
            job.preempting = entry["state"] == PREEMPTING
            job.watchdog = Watchdog(
                for_job(self.kv, spec.job_id), spec.hosts,
                timeout=self.adopt_timeout, grace=self.adopt_timeout,
                key_fn=_agent_hb_key,
            )
            self._running[spec.job_id] = job
            self._log(f"adopted running job {spec.job_id!r} "
                      f"({spec.hosts} host(s), seq {job.seq})")

    # -- serving ------------------------------------------------------------

    def serve(self, *, until_idle: bool = True,
              timeout: float | None = None) -> dict[str, str]:
        """Run the scheduling loop; returns ``{job_id: final state}`` for
        every job observed. With ``until_idle`` (default) it returns once
        nothing is queued or running; otherwise it serves until
        :meth:`stop` or ``timeout``."""
        self.start()
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self._stop:
            queued = self._tick()
            if until_idle and not queued and not self._running:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(self.poll)
        return {j["job_id"]: j["state"] for j in list_jobs(self.kv)}

    def _tick(self) -> list[dict]:
        """One scheduling pass; returns the currently queued entries."""
        self._poll_cancellations()
        self._poll_running()
        self._charge_tenants()
        queued = [j for j in list_jobs(self.kv) if j["state"] == QUEUED]
        self._publish_queue_state(queued)
        self._admit_or_preempt(queued)
        return [j for j in list_jobs(self.kv) if j["state"] == QUEUED]

    def _publish_queue_state(self, queued: list[dict]) -> None:
        """Queue-shape metrics into the registry plus the durable
        per-tenant queued counts the starvation detector cross-checks;
        also surfaces an active starvation alert as a one-shot
        ``starved`` job event on every affected queued job."""
        from tpu_sandbox.obs.health import active_subjects

        reg = get_registry()
        reg.gauge("sched.queue.depth").set(len(queued))
        reg.gauge("sched.running").set(len(self._running))
        counts: dict[str, int] = {}
        for entry in queued:
            tenant = entry.get("tenant")
            if tenant:
                counts[tenant] = counts.get(tenant, 0) + 1
        ttl = max(1.0, 10 * self.poll)
        for tenant, n in counts.items():
            self.kv.set_ttl(f"{K_QUEUED_PREFIX}{tenant}", str(n), ttl)
            reg.gauge("sched.tenant.queued",
                      labels={"tenant": tenant}).set(n)
        starved = active_subjects(self.kv, "tenant_starvation")
        if not starved:
            return
        for entry in queued:
            job_id = entry["job_id"]
            if entry.get("tenant") in starved \
                    and job_id not in self._starved_stamped:
                self._starved_stamped.add(job_id)
                self._stamp_event(job_id, "starved")
                self._log(f"job {job_id!r}: tenant "
                          f"{entry['tenant']!r} flagged starved by the "
                          "health plane")

    # -- cancellation -------------------------------------------------------

    def _poll_cancellations(self) -> None:
        for key in self.kv.keys(JOBS_PREFIX):
            if not key.endswith("/cancel"):
                continue
            job_id = key[len(JOBS_PREFIX):-len("/cancel")]
            state = (self.kv.try_get(k_state(job_id)) or b"").decode()
            if state == QUEUED:
                self._log(f"job {job_id!r}: cancelled while queued")
                self._finish_job(job_id, "cancelled", verdict=None)
            elif state in (RUNNING, PREEMPTING):
                job = self._running.get(job_id)
                if job is not None and not job.cancelling:
                    job.cancelling = True
                    self._log(f"job {job_id!r}: cancelling (SIGTERM gang)")
                    self._terminate_gang(job)
            else:
                self.kv.delete(k_cancel(job_id))  # already terminal

    # -- running jobs -------------------------------------------------------

    def _poll_running(self) -> None:
        for job_id, job in list(self._running.items()):
            jkv = for_job(self.kv, job_id)
            raw = jkv.try_get(K_JOB_DONE)
            if raw is not None:
                self._reap(job, json.loads(raw))
                continue
            if job.preempting or job.cancelling:
                self._escalate_preempt(job)
                continue
            if job.adopted:
                self._check_adopted(job)
            else:
                self._respawn_dead_agents(job)

    def _reap(self, job: _RunningJob, verdict: dict) -> None:
        """A job's own leader posted the terminal verdict; drain its agent
        processes (they exit on their own once they see it) and route by
        how the job ended and why."""
        job_id = job.spec.job_id
        drain_deadline = time.monotonic() + self.drain_timeout
        for p in job.procs.values():
            while p.poll() is None and time.monotonic() < drain_deadline:
                time.sleep(self.poll)
            if p.poll() is None:
                p.kill()
                p.wait()
        del self._running[job_id]
        preempted = bool(verdict.get("preempted"))
        if job.preempting and preempted and not job.cancelling:
            # scheduler-initiated preemption: checkpointed, uncharged —
            # back into the queue at its original seq for a bitwise resume
            jkv = for_job(self.kv, job_id)
            jkv.delete(K_JOB_DONE)
            self.kv.delete(k_state(job_id))
            self.kv.set(k_state(job_id), QUEUED)
            self._stamp_event(job_id, "preempted")
            self._log(f"job {job_id!r}: preempted cleanly; re-queued "
                      f"(seq {job.seq}) for resume")
            return
        if job.cancelling:
            state = "cancelled"
        elif verdict.get("ok"):
            state = "done"
        elif preempted:
            state = "preempted"  # external preemption (not ours): terminal
        else:
            state = "failed"
        self._finish_job(job_id, state, verdict=verdict)

    def _respawn_dead_agents(self, job: _RunningJob) -> None:
        for aid, p in list(job.procs.items()):
            code = p.poll()
            if code is None:
                continue
            jkv = for_job(self.kv, job.spec.job_id)
            if jkv.try_get(K_JOB_DONE) is not None:
                return  # verdict just landed; reap on the next pass
            job.respawns += 1
            if job.respawns > self.respawn_limit:
                self._log(
                    f"job {job.spec.job_id!r}: agent {aid} died (exit "
                    f"{code}) with the respawn limit "
                    f"({self.respawn_limit}) spent; failing the job"
                )
                for q in job.procs.values():
                    if q.poll() is None:
                        q.kill()
                        q.wait()
                del self._running[job.spec.job_id]
                self._finish_job(
                    job.spec.job_id, "failed",
                    verdict={"ok": False,
                             "reason": "agent respawn limit exceeded"},
                )
                return
            self._log(
                f"job {job.spec.job_id!r}: agent {aid} died (exit {code}); "
                f"respawning [{job.respawns}/{self.respawn_limit}]"
            )
            job.procs[aid] = self._spawn_agent(job.spec, aid)

    def _check_adopted(self, job: _RunningJob) -> None:
        """Adopted gangs have no Popen handles — the only liveness signal
        is their agents' heartbeats. All-silent past the watchdog window
        means the gang died with the old scheduler: fail the job so its
        slots free instead of leaking forever."""
        health = job.watchdog.check()
        dead = [h.rank for h in health if not h.alive]
        if len(dead) == job.spec.hosts:
            self._log(
                f"adopted job {job.spec.job_id!r}: all {job.spec.hosts} "
                "agent(s) silent — gang is gone; failing the job"
            )
            del self._running[job.spec.job_id]
            self._finish_job(
                job.spec.job_id, "failed",
                verdict={"ok": False,
                         "reason": "adopted gang no longer heartbeating"},
            )

    # -- preemption ---------------------------------------------------------

    def _terminate_gang(self, job: _RunningJob) -> None:
        job.kill_at = time.monotonic() + self.preempt_kill_timeout
        for p in job.procs.values():
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass

    def _escalate_preempt(self, job: _RunningJob) -> None:
        """A preempted/cancelled gang that never posts its verdict (ranks
        wedged past every in-job escalation) is eventually SIGKILLed; the
        job goes back to the queue (preemption) or terminal (cancel), and
        its budget machinery settles the score on re-admission."""
        if not job.procs or time.monotonic() < job.kill_at:
            return
        if all(p.poll() is not None for p in job.procs.values()):
            # gang died without a verdict (e.g. SIGKILL raced the save)
            pass
        else:
            self._log(f"job {job.spec.job_id!r}: verdict never posted "
                      f"after {self.preempt_kill_timeout:.0f}s; SIGKILL")
            for p in job.procs.values():
                if p.poll() is None:
                    p.kill()
                    p.wait()
        job_id = job.spec.job_id
        del self._running[job_id]
        if job.cancelling:
            self._finish_job(job_id, "cancelled", verdict=None)
        else:
            self.kv.set(k_state(job_id), QUEUED)
            self._stamp_event(job_id, "preempt_killed")
            self._log(f"job {job_id!r}: re-queued after hard kill (its "
                      "restart budget will charge the unclean stop)")

    # -- admission ----------------------------------------------------------

    def _slots_free(self) -> int:
        used = sum(j.spec.hosts for j in self._running.values())
        return self.pool_size - used

    def _charge_tenants(self) -> None:
        """Accrue each running tenant's normalized service. Charged per
        tick so fair share reflects time actually held, not job count."""
        now = time.monotonic()
        dt, self._last_charge = now - self._last_charge, now
        for job in self._running.values():
            tenant = job.spec.tenant
            if tenant:
                vt = (self._tenant_vtime.get(tenant, 0.0)
                      + job.spec.hosts * dt / job.spec.share)
                self._tenant_vtime[tenant] = vt
                # durable ledger: a successor scheduler resumes the
                # 2:1 convergence instead of resetting every debt
                self.kv.set(f"{K_VTIME_PREFIX}{tenant}", repr(vt))
                get_registry().gauge("sched.tenant.vtime",
                                     labels={"tenant": tenant}).set(vt)

    def tenant_vtime(self, tenant: str) -> float:
        return self._tenant_vtime.get(tenant, 0.0)

    def _admit_or_preempt(self, queued: list[dict]) -> None:
        if not queued:
            return
        # priority first; within a band, tenants with the least normalized
        # service go first (untenanted jobs charge nothing and stay pure
        # FIFO among themselves); seq breaks the remaining ties
        order = sorted(queued, key=lambda j: (
            -j["priority"],
            self._tenant_vtime.get(j["tenant"], 0.0) if j["tenant"] else 0.0,
            j["seq"]))
        # expire everyone's admission deadline, not just the head's — a
        # low-priority job stuck behind a high-priority head must still
        # time out on schedule
        now = time.monotonic()
        for entry in order:
            dl = self._queue_deadline.get(entry["job_id"])
            if dl is None:
                raw = self.kv.try_get(k_spec(entry["job_id"]))
                spec_t = JobSpec.from_json(raw.decode())
                self._queue_deadline[entry["job_id"]] = (
                    now + spec_t.admission_timeout
                )
            elif now >= dl:
                self._log(f"job {entry['job_id']!r}: admission deadline "
                          "passed; timing out (namespace swept)")
                self._finish_job(entry["job_id"], "timeout", verdict=None)
        order = [e for e in order
                 if (self.kv.try_get(k_state(e["job_id"])) or b"").decode()
                 == QUEUED]
        if not order:
            return
        head = order[0]
        raw = self.kv.try_get(k_spec(head["job_id"]))
        if raw is None:
            return
        spec = JobSpec.from_json(raw.decode())
        group = self._cogroup_members(order, head, spec)
        needed = sum(s.hosts for s, _ in group)
        free = self._slots_free()
        if needed <= free:
            for member, seq in group:
                self._admit(member, seq)
            return
        # not enough room: can lower-priority running work make room?
        victims = self._pick_victims(spec, free, needed=needed)
        if victims:
            for member, _ in group:
                self._queue_deadline[member.job_id] = (
                    time.monotonic() + member.admission_timeout
                )  # give the group a fresh window while its room is made
            for victim in victims:
                victim.preempting = True
                self.kv.set(k_state(victim.spec.job_id), PREEMPTING)
                self._stamp_event(victim.spec.job_id, "preempt_sent")
                get_registry().counter("sched.preemptions").inc()
                # durable cycle count for the cascade detector
                self.kv.add(f"{K_PREEMPTS_PREFIX}{victim.spec.job_id}")
                self._log(
                    f"preempting job {victim.spec.job_id!r} (priority "
                    f"{victim.spec.priority}) to admit "
                    f"{spec.job_id!r} (priority {spec.priority})"
                )
                self._terminate_gang(victim)
            return
        self._try_backfill(order, spec, free, needed=needed)

    def _cogroup_members(self, order: list[dict], head: dict,
                         head_spec: JobSpec) -> list[tuple[JobSpec, int]]:
        """The head plus every other queued member of its cogroup, as
        ``(spec, seq)`` pairs — an MPMD pipeline's stage gangs admit
        all-or-nothing, one gang of gangs. A solo head is its own
        singleton group."""
        group = [(head_spec, head["seq"])]
        if not head_spec.cogroup:
            return group
        for entry in order[1:]:
            if entry.get("cogroup") != head_spec.cogroup:
                continue
            raw = self.kv.try_get(k_spec(entry["job_id"]))
            if raw is None:
                continue
            group.append((JobSpec.from_json(raw.decode()), entry["seq"]))
        return group

    def _try_backfill(self, order: list[dict], head_spec: JobSpec,
                      free: int, needed: int | None = None) -> None:
        """The head is blocked and no preemption can help it. Strictly
        lower-priority queued jobs that fit the free slots may start
        behind it: strictly lower keeps the head's preemption rights over
        every backfilled gang, so backfill can delay the head by at most
        one preemption drain — never indefinitely. The starvation guard
        stops backfilling once the head has consumed
        ``backfill_guard_frac`` of its admission window, reserving the
        rest of the window for room to appear rather than churn."""
        if needed is None:
            needed = head_spec.hosts
        if free < 1 or len(order) < 2:
            return
        pending = sum(
            j.spec.hosts for j in self._running.values()
            if j.preempting or j.cancelling
        )
        if free + pending >= needed:
            return  # the head's room is already on its way: don't take it
        dl = self._queue_deadline.get(head_spec.job_id)
        if dl is not None and dl - time.monotonic() <= (
                (1.0 - self.backfill_guard_frac)
                * head_spec.admission_timeout):
            return  # head too close to its deadline; stop churning
        for entry in order[1:]:
            if free < 1:
                return
            if entry["priority"] >= head_spec.priority:
                continue  # the head couldn't preempt it back out: skip
            raw = self.kv.try_get(k_spec(entry["job_id"]))
            if raw is None:
                continue
            cand = JobSpec.from_json(raw.decode())
            if head_spec.cogroup and cand.cogroup == head_spec.cogroup:
                continue  # the head's own co-gang never backfills itself
            if cand.hosts > free:
                continue
            self._stamp_event(cand.job_id, "backfilled")
            self._log(
                f"backfilling job {cand.job_id!r} (priority "
                f"{cand.priority}, {cand.hosts} host(s)) behind blocked "
                f"head {head_spec.job_id!r} (priority {head_spec.priority})"
            )
            self._admit(cand, entry["seq"])
            free = self._slots_free()

    def _pick_victims(self, spec: JobSpec, free: int,
                      needed: int | None = None) -> list[_RunningJob]:
        """Lowest priority first, newest first within a priority; only
        strictly-lower-priority jobs are preemptable, and only if the
        freed slots actually satisfy ``needed`` hosts — the head job
        alone, or its whole cogroup (never preempt for nothing). Jobs
        already winding down are counted as pending room rather than
        re-victimized."""
        if needed is None:
            needed = spec.hosts
        pending = sum(
            j.spec.hosts for j in self._running.values()
            if j.preempting or j.cancelling
        )
        if free + pending >= needed:
            return []  # enough room is already on its way
        candidates = sorted(
            (j for j in self._running.values()
             if not j.preempting and not j.cancelling
             and j.spec.priority < spec.priority),
            key=lambda j: (j.spec.priority, -j.seq),
        )
        chosen: list[_RunningJob] = []
        room = free + pending
        for j in candidates:
            if room >= needed:
                break
            chosen.append(j)
            room += j.spec.hosts
        return chosen if room >= needed else []

    def _spawn_agent(self, spec: JobSpec, aid: int) -> subprocess.Popen:
        env = dict(os.environ)
        env.update(self.extra_env)
        env.update(spec.env)
        env[ENV_JOB_ID] = spec.job_id
        return subprocess.Popen(
            spec.format_argv(agent_id=aid, kv_port=self.kv_port),
            env=env,
        )

    def _admit(self, spec: JobSpec, seq: int) -> None:
        jkv = for_job(self.kv, spec.job_id)
        jkv.delete(K_JOB_DONE)  # stale verdict from before a resume
        procs: dict[int, subprocess.Popen] = {}
        try:
            for aid in range(spec.hosts):
                procs[aid] = self._spawn_agent(spec, aid)
        except OSError as e:
            # gang or nothing: a half-spawned gang is torn down, never run
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
                    p.wait()
            self._log(f"job {spec.job_id!r}: gang spawn failed ({e})")
            self._finish_job(spec.job_id, "failed",
                            verdict={"ok": False, "reason": f"spawn: {e}"})
            return
        self._running[spec.job_id] = _RunningJob(spec, seq, procs)
        self._queue_deadline.pop(spec.job_id, None)
        self.kv.set(k_state(spec.job_id), RUNNING)
        resumed = self.kv.try_get(k_event(spec.job_id, "admitted"))
        name = "admitted" if resumed is None else "readmitted"
        get_registry().counter("sched.admissions",
                               labels={"kind": name}).inc()
        self._stamp_event(spec.job_id, name)
        self._log(
            f"job {spec.job_id!r}: {name} — gang of {spec.hosts} host(s), "
            f"world {spec.world_size}, priority {spec.priority}"
        )

    def _stamp_event(self, job_id: str, name: str) -> None:
        """One job-lifecycle stamp, twice: the durable wall-clock KV key
        (bench receipts, resume detection) and a flight-recorder instant
        (the merged timeline)."""
        self.kv.set(k_event(job_id, name), f"{time.time():.6f}")
        get_recorder().instant(f"job:{name}", args={"job": job_id})

    # -- terminal bookkeeping ----------------------------------------------

    def _finish_job(self, job_id: str, state: str,
                    verdict: dict | None) -> None:
        """Move a job to a terminal state and sweep every key it could
        have leaked: its whole ``job/<id>/`` namespace (claims, budgets,
        election, health — gone as a unit). The ``sched/jobs/<id>/*``
        entry stays as the durable record (spec, seq, events, verdict,
        terminal state) — which also makes job ids single-use. After
        this, ``kv.keys("job/<id>/")`` is empty — the clean-queue
        invariant the admission-timeout test asserts."""
        self._queue_deadline.pop(job_id, None)
        ns = job_namespace(job_id)
        if ns:
            self.kv.delete_prefix(ns)
        self.kv.delete(k_cancel(job_id))
        if verdict is not None:
            self.kv.set(k_verdict(job_id), json.dumps(verdict))
        self.kv.set(k_state(job_id), state)
        self._stamp_event(job_id, state)
        self._log(f"job {job_id!r}: {state}")
