"""Process bootstrap / rendezvous — the TPU-native runtime layer.

Capability parity with the reference's L4/L3 bootstrap glue
(reference: test_init.py:45-100, allreduce_toy.py:10-18,52-58,
mnist_distributed.py:15-23,124-125): ``find_free_port`` + MASTER_ADDR/
MASTER_PORT env vars + ``dist.init_process_group('nccl'|'gloo')`` become a
coordinator address + ``jax.distributed.initialize()``.

Key design differences from the reference (TPU-first, not a port):

- **One process per host, not per chip.** The reference forks one process per
  GPU with ``mp.spawn`` (test_init.py:116). On TPU, all local chips belong to
  one process (``jax.local_devices()``), and multi-*host* jobs run one process
  per host. The entire mp.spawn layer collapses; rank arithmetic
  (``rank = nr * gpus + gpu``, mnist_distributed.py:49) becomes
  ``jax.process_index()``.
- **Rendezvous is a coordinator service, not a TCPStore.** The reference sets
  MASTER_ADDR/MASTER_PORT and lets torch's env:// TCPStore handle the
  KV-store rendezvous. Here ``jax.distributed.initialize(coordinator_address,
  num_processes, process_id)`` does the same job over DCN. For familiarity we
  honor MASTER_ADDR/MASTER_PORT env vars when building the default
  coordinator address.
- **Backend selection is automatic.** The reference picks ``'nccl'`` iff CUDA
  is available, else ``'gloo'`` (test_init.py:84-88). JAX picks TPU/CPU the
  same way; :func:`backend_name` reports the choice with the same
  role ("which collective fabric will be used").

The reference's ``rank == -1`` "serial mode, skip init" sentinel
(test_init.py:73) is preserved: ``init(process_id=-1)`` is a no-op.
"""

from __future__ import annotations

import os
import socket
from dataclasses import dataclass

import jax

SERIAL_RANK = -1

# Module state: records what init() decided, so entry scripts and tests can
# query topology without re-deriving it.
_state: dict = {"initialized": False, "serial": False, "multiprocess": False}


def find_free_port() -> str:
    """Bind to port 0 and return the OS-assigned free port as a string.

    String (not int) return matches the reference helper, whose result feeds
    an env var (reference: test_init.py:45-53 and two duplicate copies).
    """
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("", 0))
        return str(s.getsockname()[1])


def coordinator_address(host: str | None = None, port: str | int | None = None) -> str:
    """Build the coordinator address, honoring MASTER_ADDR/MASTER_PORT.

    The reference exports MASTER_ADDR=127.0.0.1 and a fresh free port before
    every launch (mnist_distributed.py:124-125). We honor the same env vars
    so launch environments carry over, defaulting to loopback + free port.
    """
    host = host or os.environ.get("MASTER_ADDR", "127.0.0.1")
    port = port or os.environ.get("MASTER_PORT") or find_free_port()
    return f"{host}:{port}"


@dataclass
class Topology:
    """What this process can see after init."""

    process_id: int
    num_processes: int
    local_devices: int
    global_devices: int
    backend: str

    def summary(self) -> str:
        return (
            f"process {self.process_id}/{self.num_processes}: "
            f"{self.local_devices} local / {self.global_devices} global "
            f"{self.backend} device(s)"
        )


def init(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> Topology:
    """Join the distributed job (or no-op for single-process / serial runs).

    Parity with ``setup_process`` (reference: test_init.py:55-94):

    - ``process_id == -1``: serial sentinel — skip initialization entirely.
    - single process (num_processes in (None, 1)): nothing to rendezvous;
      local devices are the world.
    - multi-process: ``jax.distributed.initialize`` against the coordinator.
    """
    global _state
    if process_id == SERIAL_RANK:
        _state = {"initialized": True, "serial": True, "multiprocess": False}
        return topology()

    if _state.get("initialized"):
        return topology()

    num_processes = num_processes or int(os.environ.get("NUM_PROCESSES", "1"))
    if num_processes > 1:
        # Every process must dial the SAME coordinator: require an explicit
        # address or a shared MASTER_ADDR/MASTER_PORT environment. Falling
        # back to a locally-generated free port would give each process a
        # different address and the rendezvous could never complete.
        if coordinator is None:
            if "MASTER_PORT" not in os.environ:
                raise ValueError(
                    "multi-process init needs a shared coordinator: pass "
                    "coordinator='host:port' or export MASTER_ADDR/MASTER_PORT "
                    "identically on every process"
                )
            coordinator = coordinator_address()
        if process_id is None:
            if "PROCESS_ID" not in os.environ:
                raise ValueError(
                    "multi-process init needs process_id (or PROCESS_ID env); "
                    "defaulting it would make every process claim id 0"
                )
            process_id = int(os.environ["PROCESS_ID"])
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
        _state = {"initialized": True, "serial": False, "multiprocess": True}
    else:
        _state = {"initialized": True, "serial": False, "multiprocess": False}
    return topology()


def cleanup() -> None:
    """Tear down the process group (reference: ``cleanup``, test_init.py:96-100).

    Unlike the reference — which defines this but never calls it — the entry
    scripts here do call it.  Serial mode skips, same sentinel semantics.
    """
    global _state
    if _state.get("multiprocess"):
        jax.distributed.shutdown()
    _state = {"initialized": False, "serial": False, "multiprocess": False}


def is_initialized() -> bool:
    return bool(_state.get("initialized"))


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def backend_name() -> str:
    """The collective fabric in use — role parity with backend selection at
    reference test_init.py:84-88 ('nccl' iff CUDA else 'gloo')."""
    return jax.default_backend()


def topology() -> Topology:
    return Topology(
        process_id=jax.process_index(),
        num_processes=jax.process_count(),
        local_devices=jax.local_device_count(),
        global_devices=jax.device_count(),
        backend=backend_name(),
    )


def topology_summary() -> str:
    return topology().summary()
