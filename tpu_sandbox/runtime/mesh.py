"""Device meshes and device groups.

TPU-native replacement for the reference's process groups
(``dist.init_process_group`` + ``dist.new_group``; reference:
allreduce_toy.py:27,44, mnist_distributed.py:50,100). In the reference a
"group" is a set of ranks with a communicator; here it is a named axis of a
``jax.sharding.Mesh``, and collectives are ``lax.psum``-family ops over that
axis name, compiled by XLA into ICI/DCN collectives.

Design notes:

- Meshes/groups are created **once** and reused. The reference creates a
  fresh group every step (allreduce_toy.py:26-27 and the unused per-step
  group at mnist_distributed.py:99-100 — a deliberate quirk/leak its README
  era tolerated). Communicator setup is not free on any fabric; here group
  creation is explicit, up-front, and cheap to reuse.
- Multi-axis from day one: data/tensor/pipeline/sequence/expert parallelism
  are mesh axes, not separate subsystems.
"""

from __future__ import annotations

import math
from collections import OrderedDict
from collections.abc import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DEFAULT_AXIS = "data"


def make_mesh(
    axes: dict[str, int] | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a mesh over (all) devices.

    ``axes`` maps axis name -> size; one entry may be -1 to absorb the
    remaining devices. Default: a 1-D ``('data',)`` mesh over every device —
    the reference's world group (its only long-lived group).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axes is None:
        axes = {DEFAULT_AXIS: n}
    axes = OrderedDict(axes)

    wildcard = [k for k, v in axes.items() if v == -1]
    if len(wildcard) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if wildcard:
        fixed = math.prod(v for v in axes.values() if v != -1)
        if n % fixed:
            raise ValueError(
                f"{n} devices not divisible by the non-wildcard axes of {dict(axes)}"
            )
        axes[wildcard[0]] = n // fixed

    total = math.prod(axes.values())
    if total != n:
        raise ValueError(f"mesh {dict(axes)} needs {total} devices, have {n}")

    grid = np.asarray(devices).reshape(tuple(axes.values()))
    return Mesh(grid, tuple(axes.keys()))


def submesh(mesh: Mesh, axes: Sequence[str]) -> Mesh:
    """A mesh over a subset of axes, fixing the others at coordinate 0.

    The once-created analogue of ``dist.new_group(range(args.gpus))``
    (reference: mnist_distributed.py:100): a group spanning only the local
    dimension of the device grid.
    """
    unknown = set(axes) - set(mesh.axis_names)
    if unknown:
        raise ValueError(f"axes {sorted(unknown)} not in mesh axes {mesh.axis_names}")
    index = tuple(
        slice(None) if name in axes else 0 for name in mesh.axis_names
    )
    grid = mesh.devices[index]
    kept = tuple(name for name in mesh.axis_names if name in axes)
    return Mesh(grid.reshape(tuple(mesh.shape[a] for a in kept)), kept)


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding that replicates an array on every mesh device (the analogue of
    DDP's initial param broadcast, reference mnist_distributed.py:67)."""
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = DEFAULT_AXIS) -> NamedSharding:
    """Shard dim 0 (batch) across ``axis`` — DistributedSampler's role
    (reference: mnist_distributed.py:73-75) expressed as a sharding."""
    return NamedSharding(mesh, P(axis))
