"""Failure detection: heartbeats, a watchdog, and bounded-wait rendezvous.

The reference has none of this (SURVEY §5 "Failure detection: Absent — a
dead rank just hangs the group"): `init_process_group` has no timeout
wired, and a crashed worker leaves the others blocked in the next
collective forever. This subsystem closes that gap the way torchelastic's
health layer does, but over this framework's own native KV store
(native/src/kvstore.cpp) rather than a side service:

- ``Heartbeat``     — per-rank daemon thread stamping ``hb/{rank}`` with a
                      monotonic-ish wall timestamp every ``interval``.
- ``Watchdog``      — any process polls all ranks' stamps with the
                      non-blocking try-get; a rank whose stamp is older
                      than ``timeout`` (or never appeared after its grace
                      period) is reported dead. Fail-fast, not hang.
- ``wait_for_world``— rendezvous with a deadline: returns when all ranks
                      checked in, raises ``RendezvousTimeout`` listing the
                      missing ranks otherwise (vs the reference's silent
                      infinite block).

Deliberately collective-free: detection must keep working when the
accelerator side is wedged, so everything here is host-side TCP.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from tpu_sandbox.runtime.kvstore import KVClient


class RendezvousTimeout(RuntimeError):
    pass


class DeadRankError(RuntimeError):
    pass


def _hb_key(rank: int) -> str:
    return f"hb/{rank}"


class Heartbeat:
    """Background thread publishing this rank's liveness.

    Usage (per rank)::

        hb = Heartbeat(client, rank, interval=1.0)
        hb.start()
        ...
        hb.stop()
    """

    def __init__(
        self,
        client: KVClient,
        rank: int,
        interval: float = 1.0,
        *,
        key: str | None = None,
    ):
        self._owner = client
        self.rank = rank
        self.interval = interval
        # default key is the per-rank health-plane slot; host agents pass
        # their own (e.g. "agent_hb/<id>") to publish on a separate plane
        self.key = _hb_key(rank) if key is None else key
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._clone: KVClient | None = None

    @property
    def client(self) -> KVClient:
        """The beat connection: a dedicated clone of the owner's client (the
        owner's blocking get() would otherwise hold the shared request lock
        and starve beats into a false death verdict). Created on first use,
        closed by stop() so repeated start/stop cycles don't leak sockets."""
        if self._clone is None:
            self._clone = self._owner.clone()
        return self._clone

    def beat_once(self) -> None:
        self.client.set(self.key, repr(time.time()).encode())

    def start(self) -> "Heartbeat":
        if self._thread is not None:
            return self
        self._stop.clear()  # restartable after stop()
        self.beat_once()  # synchronous first beat: visible before start returns

        def run():
            while not self._stop.wait(self.interval):
                try:
                    self.beat_once()
                except Exception:
                    return  # store gone; the watchdog will notice our silence
        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        return self

    def stop(self, *, deregister: bool = False) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            if t.is_alive():
                # beat thread is wedged inside a native request (store not
                # answering). Closing its fd now would race OS fd reuse and
                # let the unwedged thread corrupt a foreign connection, so
                # leave the clone open and keep _thread set — start() stays
                # a no-op and the thread exits on its own once it unblocks
                # (the stop event is already set).
                return
            self._thread = None
        if deregister:
            try:
                self.client.delete(self.key)
            except Exception:
                pass
        if self._clone is not None:
            self._clone.close()
            self._clone = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


@dataclass
class RankHealth:
    rank: int
    alive: bool
    last_seen: float | None  # remote wall stamp of last beat (informational)
    age: float | None = field(default=None)  # local secs since stamp changed


class Watchdog:
    """Poll-based dead-rank detector over the heartbeat keys.

    Liveness is judged **skew-free**: the watchdog never compares the remote
    stamp against its own wall clock (cross-host clock skew would then read
    as death or mask one). Instead it remembers, per rank, when *it* last
    observed the stamp change; a rank is dead when its stamp has been frozen
    for > ``timeout`` of the watchdog's local seconds. The remote stamp is
    only an opaque change-token (plus an informational ``last_seen``).
    """

    def __init__(
        self,
        client: KVClient,
        world_size: int,
        *,
        timeout: float = 10.0,
        grace: float | None = None,
        key_fn: "Callable[[int], str]" = _hb_key,
    ):
        self.client = client
        self.world_size = world_size
        self.timeout = timeout
        # key_fn maps member index -> heartbeat key; the default watches the
        # per-rank plane, host agents watch each other via "agent_hb/<id>"
        self.key_fn = key_fn
        # ranks that never wrote at all get `grace` seconds from watchdog
        # construction before they count as dead (startup skew)
        self.grace = timeout if grace is None else grace
        self._born = time.time()
        # rank -> (last stamp bytes, local time we saw it change)
        self._observed: dict[int, tuple[bytes, float]] = {}

    def check(self) -> list[RankHealth]:
        now = time.time()
        report = []
        for rank in range(self.world_size):
            raw = self.client.try_get(self.key_fn(rank))
            if raw is None:
                alive = (now - self._born) < self.grace
                report.append(RankHealth(rank, alive, None))
                continue
            prev = self._observed.get(rank)
            if prev is None or prev[0] != raw:
                self._observed[rank] = (raw, now)
                changed_at = now
            else:
                changed_at = prev[1]
            age = now - changed_at
            report.append(
                RankHealth(rank, age < self.timeout, float(raw.decode()), age)
            )
        return report

    def dead_ranks(self) -> list[int]:
        return [h.rank for h in self.check() if not h.alive]

    def assert_all_alive(self) -> None:
        dead = self.dead_ranks()
        if dead:
            raise DeadRankError(
                f"rank(s) {dead} missed heartbeats for >{self.timeout}s "
                f"(world_size={self.world_size})"
            )

    def watch(
        self, *, poll: float = 1.0, stop: threading.Event | None = None
    ) -> threading.Thread:
        """Spawn a monitor thread that raises into a stored exception slot;
        read it via ``self.failure`` (threads can't raise across)."""
        self.failure: DeadRankError | None = None
        stop = stop or threading.Event()
        self._watch_stop = stop

        def run():
            while not stop.wait(poll):
                try:
                    self.assert_all_alive()
                except DeadRankError as e:
                    self.failure = e
                    return
        t = threading.Thread(target=run, daemon=True)
        t.start()
        return t

    def stop_watching(self) -> None:
        ev = getattr(self, "_watch_stop", None)
        if ev is not None:
            ev.set()


def wait_for_world(
    client: KVClient,
    world_size: int,
    rank: int,
    *,
    timeout: float = 60.0,
    key: str = "rendezvous",
    poll: float = 0.05,
) -> None:
    """Deadline-bounded rendezvous: every rank announces itself, then waits
    for the full world or raises ``RendezvousTimeout`` naming who's missing.

    The reference's analogue is ``dist.init_process_group`` blocking forever
    when a rank never starts (SURVEY §5); torch's fix is a timeout kwarg,
    ours is this function in front of ``bootstrap.init``.

    Generation-scoped: each call bumps this rank's join counter and waits
    for every rank's counter to reach the same generation, so re-rendezvous
    after an elastic restart genuinely waits for everyone again instead of
    being satisfied by the previous round's leftover keys.
    """
    gen = client.add(f"{key}/gen/{rank}", 1)
    deadline = time.time() + timeout
    while True:
        gens = []
        for r in range(world_size):
            raw = client.try_get(f"{key}/gen/{r}")
            gens.append(0 if raw is None else int(raw))
        if all(g >= gen for g in gens):
            return
        if time.time() >= deadline:
            missing = sorted(
                r for r, g in enumerate(gens) if g < gen
            )
            raise RendezvousTimeout(
                f"rank {rank}: only {world_size - len(missing)}/{world_size} "
                f"ranks joined generation {gen} within {timeout}s; "
                f"missing ranks: {missing}"
            )
        time.sleep(poll)
