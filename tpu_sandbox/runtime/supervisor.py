"""Elastic supervisor: close the detect→recover loop over worker processes.

The repo already *detects* failure (runtime/watchdog.py heartbeats +
bounded rendezvous) and can *resume* (train/checkpoint.py); until now
nothing acted on a failure — a crashed rank still took the whole job down,
exactly the reference's failure story (SURVEY §5). This module is the
missing actor, following the elastic-agent design torchelastic
popularized: a single supervisor process owns the KV store, spawns one
worker process per rank, and monitors two independent signals —

- **exit codes** (``Popen.poll``): crash, clean finish, or the distinct
  "preempted" code below;
- **heartbeats** (``Watchdog`` over the KV store): the wedged-not-dead
  rank that exit codes can never see (alive as a process, silent for
  ``heartbeat_timeout`` — e.g. stuck in a collective whose peer vanished).

On any failure the whole *generation* is torn down (a survivor of a dead
peer is blocked in a collective and useless) and relaunched after bounded
exponential backoff, up to ``max_restarts`` charged restarts. A generation
whose culprit ranks all exited with :data:`PREEMPTED_EXIT_CODE` — the code
the trainer's SIGTERM handler uses after finishing its in-flight step and
saving — is a *preemption*: it restarts promptly and does **not** charge
the restart budget (preemption is the dominant real-world TPU failure and
is not the job's fault). Workers re-join through ``wait_for_world``'s
generation-scoped rendezvous; the supervisor clears the per-generation
health keys so every generation starts from a clean plane.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Mapping, Sequence

from tpu_sandbox.runtime.kvstore import (
    DEFAULT_JOB,
    ENV_JOB_ID,
    KVClient,
    KVServer,
    for_job,
)
from tpu_sandbox.runtime.watchdog import Watchdog, _hb_key

#: Exit code meaning "I was preempted: state is saved, restart me for free".
#: 75 is sysexits' EX_TEMPFAIL — transient failure, retry is appropriate.
PREEMPTED_EXIT_CODE = 75

ENV_KV_PORT = "TPU_SANDBOX_KV_PORT"
ENV_GENERATION = "TPU_SANDBOX_GENERATION"

#: KV key a preempted rank sets so its peers stop at the same boundary.
PREEMPT_KEY = "preempt/requested"


def _pdeathsig_preexec():
    """preexec_fn that makes the child die (SIGKILL) when its parent does —
    the "host death kills everything on the host" contract a per-host agent
    needs (runtime/host_agent.py): SIGKILLing the agent must not orphan its
    rank processes into zombie trainers that keep heartbeating into the next
    generation. Linux PR_SET_PDEATHSIG; silently a no-op elsewhere."""
    try:
        import ctypes
        import ctypes.util

        libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6",
                           use_errno=True)
        libc.prctl(1, signal.SIGKILL, 0, 0, 0)  # 1 == PR_SET_PDEATHSIG
    except Exception:
        pass


class RankGroup:
    """Spawn/poll/stop a set of local rank processes.

    The proc-management core shared by the single-host :class:`Supervisor`
    and the per-host ``HostAgent``: owns the Popen handles, caches exit
    codes across polls, and implements the SIGTERM→wait→SIGKILL teardown
    escalation (SIGTERM gives the trainer's preemption handler a chance to
    save; SIGKILL unwedges ranks stuck in a native collective).
    """

    def __init__(
        self,
        *,
        term_timeout: float = 30.0,
        kill_on_parent_death: bool = False,
    ):
        self.term_timeout = term_timeout
        self._preexec = _pdeathsig_preexec if kill_on_parent_death else None
        self._procs: list[subprocess.Popen] = []
        self._codes: list[int | None] = []

    def spawn(
        self,
        cmds: Sequence[Sequence[str]],
        env: Mapping[str, str] | None = None,
    ) -> None:
        if self._procs and any(c is None for c in self.poll()):
            raise RuntimeError("RankGroup.spawn while previous group runs")
        self._procs = [
            subprocess.Popen(list(cmd),
                             env=None if env is None else dict(env),
                             preexec_fn=self._preexec)
            for cmd in cmds
        ]
        self._codes = [None] * len(self._procs)

    def __len__(self) -> int:
        return len(self._procs)

    def poll(self) -> list[int | None]:
        """Exit codes so far (None = still running); cached once observed."""
        for i, p in enumerate(self._procs):
            if self._codes[i] is None:
                self._codes[i] = p.poll()
        return list(self._codes)

    @property
    def running(self) -> bool:
        return any(c is None for c in self.poll())

    def terminate_all(self) -> None:
        for p in self._procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass

    def teardown(self) -> list[int | None]:
        """SIGTERM everyone, wait up to ``term_timeout``, SIGKILL stragglers;
        returns the final exit codes."""
        self.terminate_all()
        deadline = time.monotonic() + self.term_timeout
        for p in self._procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(0.1, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
        return self.poll()


class RestartBudgetExceeded(RuntimeError):
    """The job kept dying after ``max_restarts`` charged restarts (or blew
    through ``max_preemptions``); carries the full generation history."""

    def __init__(self, msg: str, result: "ElasticResult"):
        super().__init__(msg)
        self.result = result


@dataclass
class GenerationReport:
    generation: int
    outcome: str  # "ok" | "failure" | "preemption" | "wedged"
    exit_codes: list[int | None]
    culprits: list[int]  # ranks that initiated the failure (pre-teardown)
    elapsed: float


@dataclass
class ElasticResult:
    world_size: int
    generations: list[GenerationReport] = field(default_factory=list)
    restarts_charged: int = 0
    preemptions: int = 0

    @property
    def ok(self) -> bool:
        return bool(self.generations) and self.generations[-1].outcome == "ok"

    def summary(self) -> str:
        gens = ", ".join(
            f"gen{g.generation}:{g.outcome}" for g in self.generations
        )
        return (
            f"{len(self.generations)} generation(s) [{gens}]; "
            f"{self.restarts_charged} restart(s) charged, "
            f"{self.preemptions} preemption(s)"
        )


class Supervisor:
    """Spawn/monitor/relaunch one process per rank until the job finishes.

    ``command_for_generation(generation, kv_port) -> list[argv]`` builds
    the per-rank commands fresh for every generation (fresh coordinator
    ports, ``--resume`` flags, ... live in the builder, which keeps this
    class free of any training-specific knowledge). Each worker inherits
    ``TPU_SANDBOX_KV_PORT`` and ``TPU_SANDBOX_GENERATION`` in its env on
    top of ``os.environ`` and ``extra_env``.

    A SIGTERM delivered to the supervisor itself (the whole job being
    preempted) is forwarded to every worker; once the generation winds
    down it is reported as a preemption and the supervisor stops
    relaunching — the job's next incarnation resumes from the checkpoint.
    """

    def __init__(
        self,
        world_size: int,
        command_for_generation: Callable[[int, int], Sequence[Sequence[str]]],
        *,
        max_restarts: int = 3,
        max_preemptions: int = 32,
        backoff: float = 1.0,
        backoff_max: float = 30.0,
        heartbeat_timeout: float = 60.0,
        grace: float = 180.0,
        poll: float = 0.1,
        term_timeout: float = 30.0,
        extra_env: Mapping[str, str] | None = None,
        kv_server: KVServer | None = None,
        verbose: bool = True,
        job_id: str = "",
    ):
        if world_size < 1:
            raise ValueError(f"world_size must be >= 1, got {world_size}")
        self.world_size = world_size
        self.job_id = job_id
        self.command_for_generation = command_for_generation
        self.max_restarts = max_restarts
        self.max_preemptions = max_preemptions
        self.backoff = backoff
        self.backoff_max = backoff_max
        self.heartbeat_timeout = heartbeat_timeout
        self.grace = grace
        self.poll = poll
        self.term_timeout = term_timeout
        self.extra_env = dict(extra_env or {})
        self._kv_server = kv_server
        self._owns_server = kv_server is None
        self.verbose = verbose
        self._external_preempt = False
        self._group = RankGroup(term_timeout=term_timeout)

    # -- logging ----------------------------------------------------------

    def _log(self, msg: str) -> None:
        if self.verbose:
            print(f"[supervisor] {msg}", flush=True)

    # -- health-plane reset ------------------------------------------------

    def _reset_health_plane(self, kv: KVClient) -> None:
        """A new generation must not inherit the dead one's liveness or
        rendezvous state: a frozen heartbeat stamp would read as an
        immediately-dead rank, and unequal rendezvous counters (a rank that
        died before joining) would wedge ``wait_for_world`` forever. Fault
        claims are deliberately NOT cleared — a fault fires once per job,
        not once per generation. Checkpoint shard-done claims ARE cleared:
        they are per-commit-attempt state, and a relaunched generation
        re-reaching the same step must gather fresh claims, never its dead
        predecessor's (the claims are generation-scoped and TTL'd as well —
        this sweep is the belt to those braces, and keeps a long-lived
        server from accumulating dead keys across generations)."""
        for r in range(self.world_size):
            kv.delete(_hb_key(r))
            kv.delete(f"rendezvous/gen/{r}")
        kv.delete(PREEMPT_KEY)
        kv.delete_prefix("ckpt/")

    def _reset_job_plane(self, kv: KVClient) -> None:
        """Job-start sweep for an EXTERNAL long-lived KV server reused
        across supervisor runs: the previous job's fault claims would make
        this job's identical fault plan never fire, and its stale commit
        claims could alias this job's. Runs once, before generation 1 —
        within a job, fault claims persist across generations (fire-once
        semantics)."""
        kv.delete_prefix("fault/")
        kv.delete_prefix("ckpt/")

    # -- teardown ----------------------------------------------------------

    def _teardown(self, codes: list[int | None]) -> None:
        """Stop every still-running worker (SIGTERM→wait→SIGKILL via the
        :class:`RankGroup`) and fill in the final exit codes."""
        final = self._group.teardown()
        for i, c in enumerate(final):
            if codes[i] is None:
                codes[i] = c

    # -- one generation ----------------------------------------------------

    def _run_generation(self, gen: int, kv: KVClient, kv_port: int
                        ) -> GenerationReport:
        cmds = [list(c) for c in self.command_for_generation(gen, kv_port)]
        if len(cmds) != self.world_size:
            raise ValueError(
                f"command_for_generation returned {len(cmds)} commands for "
                f"world_size {self.world_size}"
            )
        env = dict(os.environ)
        env.update(self.extra_env)
        env[ENV_KV_PORT] = str(kv_port)
        env[ENV_GENERATION] = str(gen)
        env[ENV_JOB_ID] = self.job_id or DEFAULT_JOB
        start = time.monotonic()
        self._group.spawn(cmds, env)
        watchdog = Watchdog(
            kv, self.world_size,
            timeout=self.heartbeat_timeout, grace=self.grace,
        )
        codes: list[int | None] = [None] * self.world_size
        try:
            while any(c is None for c in codes):
                codes = self._group.poll()
                culprits = [
                    r for r, c in enumerate(codes) if c not in (None, 0)
                ]
                if culprits:
                    # initiator-only classification: codes produced later by
                    # our own teardown (SIGTERM/SIGKILL of blocked peers)
                    # must not turn a preemption into a charged failure
                    preempted = all(
                        codes[r] == PREEMPTED_EXIT_CODE for r in culprits
                    )
                    self._teardown(codes)
                    outcome = "preemption" if preempted else "failure"
                    return GenerationReport(
                        gen, outcome, codes, culprits,
                        time.monotonic() - start,
                    )
                wedged = [r for r in watchdog.dead_ranks() if codes[r] is None]
                if wedged:
                    self._teardown(codes)
                    return GenerationReport(
                        gen, "wedged", codes, wedged,
                        time.monotonic() - start,
                    )
                time.sleep(self.poll)
        finally:
            # belt and braces: never leak workers past a generation, even
            # when the monitor loop itself raises (e.g. KeyboardInterrupt)
            if self._group.running:
                self._teardown(codes)
        return GenerationReport(
            gen, "ok", codes, [], time.monotonic() - start
        )

    # -- the elastic loop --------------------------------------------------

    def _install_forwarder(self):
        """Forward a supervisor-level SIGTERM to the workers (whole-job
        preemption). Returns the previous handler, restored by run()."""
        def fwd(signum, frame):
            self._external_preempt = True
            self._group.terminate_all()
        try:
            return signal.signal(signal.SIGTERM, fwd)
        except ValueError:
            return None  # not the main thread (tests); skip forwarding

    def run(self) -> ElasticResult:
        result = ElasticResult(self.world_size)
        server = self._kv_server or KVServer()
        # job-scoped view: a shared external store can host several
        # supervised jobs whose health/budget/fault keys never collide
        kv = for_job(KVClient(port=server.port), self.job_id)
        self._reset_job_plane(kv)
        prev_handler = self._install_forwarder()
        gen = 0
        try:
            while True:
                gen += 1
                self._reset_health_plane(kv)
                kv.set("elastic/generation", str(gen))
                self._log(
                    f"generation {gen}: launching {self.world_size} worker(s)"
                )
                report = self._run_generation(gen, kv, server.port)
                result.generations.append(report)
                if report.outcome == "ok":
                    self._log(f"done: {result.summary()}")
                    return result
                if report.outcome == "preemption":
                    result.preemptions += 1
                    if self._external_preempt:
                        self._log(
                            "preempted from outside; state saved — exiting "
                            "without relaunch: " + result.summary()
                        )
                        return result
                    if result.preemptions > self.max_preemptions:
                        raise RestartBudgetExceeded(
                            f"more than {self.max_preemptions} preemptions; "
                            "refusing to thrash: " + result.summary(),
                            result,
                        )
                    delay = self.backoff  # prompt, no exponential ramp
                else:  # failure / wedged: charge the budget
                    result.restarts_charged += 1
                    if result.restarts_charged > self.max_restarts:
                        raise RestartBudgetExceeded(
                            f"rank(s) {report.culprits} {report.outcome} in "
                            f"generation {gen} and the restart budget "
                            f"({self.max_restarts}) is spent: "
                            + result.summary(),
                            result,
                        )
                    delay = min(
                        self.backoff * (2 ** (result.restarts_charged - 1)),
                        self.backoff_max,
                    )
                self._log(
                    f"generation {gen} {report.outcome} "
                    f"(culprit rank(s) {report.culprits}, exit codes "
                    f"{report.exit_codes}); relaunching in {delay:.1f}s "
                    f"[{result.restarts_charged}/{self.max_restarts} "
                    f"restarts charged, {result.preemptions} preemption(s)]"
                )
                time.sleep(delay)
        finally:
            if prev_handler is not None:
                try:
                    signal.signal(signal.SIGTERM, prev_handler)
                except ValueError:
                    pass
            kv.close()
            if self._owns_server:
                server.stop()


def main_argv_for_rank(base: Sequence[str], rank: int) -> list[str]:
    """Tiny helper for builders: ``base + ["--rank", str(rank)]``."""
    return [*base, "--rank", str(rank)]
