"""Cross-host elastic supervision: per-host agents + an elected leader.

The single-host Supervisor (runtime/supervisor.py) owns the KV store and
Popens every rank locally, so a real multi-host job dies with the first
*host*. This module splits that role in two:

- :class:`HostAgent` — one per host. Spawns and monitors only its LOCAL
  ranks (through the shared :class:`~tpu_sandbox.runtime.supervisor
  .RankGroup`, with PR_SET_PDEATHSIG so agent death kills its ranks like a
  machine vanishing), publishes an agent-level heartbeat
  (``agent_hb/<id>``), executes generation commands it reads from the KV
  store, and reports local outcomes. Every agent also participates in
  leader election (runtime/election.py).

- the **leader** — whichever agent currently holds the lease. It drives the
  generation lifecycle as KV commands with per-host acks under deadlines
  and charges the restart/preemption budget through central KV counters,
  so host loss, agent death, and rank death all funnel into one
  teardown→relaunch state machine. Leadership is soft state: a new leader
  reconstructs everything it needs (current generation, whether teardown
  was posted, which acks/reports landed, what was already charged) from
  the store, which is what makes leader death mid-generation survivable.

KV schema (all under the job's namespace — bare keys for the default job,
``job/<id>/``-prefixed for every other job; see kvstore.for_job)::

    elastic/generation          current generation number (int)
    gen/<n>/launch              launch command {world_size, at_gen, assign}
                                where assign is the rank-assignment table
                                {agent_id: [ranks...]} (heterogeneous hosts:
                                world_size need not divide by num_agents)
    gen/<n>/coordinator         jax.distributed port, set by rank-0's agent
    gen/<n>/ack/launch/<a>      agent <a> spawned its ranks for gen n
    gen/<n>/teardown            teardown command {reason, kind}
    gen/<n>/ack/teardown/<a>    agent <a>'s local ranks are down {exit_codes}
    gen/<n>/report/<a>          agent <a>'s local outcome
                                {outcome, exit_codes, culprits}
    agent_hb/<a>                agent liveness stamp
    agent/cmd/<a>               fault mailbox (runtime/faults.py)
    budget/restarts             charged restarts (atomic counter)
    budget/preemptions          uncharged preemptions (atomic counter)
    budget/claim/<n>            add()-wins guard: generation n charged once,
                                even across a leader failover mid-resolution
    job/done                    terminal verdict {ok, preempted, reason}

One machine stands in for N hosts by running N agent *processes*
(:class:`AgentLauncher` — it owns the KV server and respawns dead agents,
playing the cluster scheduler). Nothing in the agent itself assumes
colocation except the KV server's loopback bind (see ROADMAP follow-ups).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import time
from dataclasses import dataclass
from typing import Callable, Mapping, Sequence

from tpu_sandbox.obs import get_recorder
from tpu_sandbox.runtime.election import LeaseElection
from tpu_sandbox.runtime.faults import agent_cmd_key
from tpu_sandbox.runtime.kvstore import (
    ENV_JOB_ID,
    DEFAULT_JOB,
    KVClient,
    KVServer,
    for_job,
)
from tpu_sandbox.runtime.supervisor import (
    ENV_GENERATION,
    ENV_KV_PORT,
    PREEMPT_KEY,
    PREEMPTED_EXIT_CODE,
    RankGroup,
)
from tpu_sandbox.runtime.watchdog import Heartbeat, Watchdog, _hb_key

ENV_AGENT_ID = "TPU_SANDBOX_AGENT_ID"

K_GENERATION = "elastic/generation"
K_JOB_DONE = "job/done"
K_RESTARTS = "budget/restarts"
K_PREEMPTIONS = "budget/preemptions"


def _agent_hb_key(agent_id: int) -> str:
    return f"agent_hb/{agent_id}"


def k_launch(gen: int) -> str:
    return f"gen/{gen}/launch"


def k_coordinator(gen: int) -> str:
    return f"gen/{gen}/coordinator"


def k_launch_ack(gen: int, agent_id: int) -> str:
    return f"gen/{gen}/ack/launch/{agent_id}"


def k_teardown(gen: int) -> str:
    return f"gen/{gen}/teardown"


def k_teardown_ack(gen: int, agent_id: int) -> str:
    return f"gen/{gen}/ack/teardown/{agent_id}"


def k_report(gen: int, agent_id: int) -> str:
    return f"gen/{gen}/report/{agent_id}"


def k_charge_claim(gen: int) -> str:
    return f"budget/claim/{gen}"


def assign_ranks(world_size: int, num_agents: int) -> list[list[int]]:
    """Balanced contiguous rank blocks for heterogeneous gangs.

    ``world_size`` need not divide evenly: the first ``world % agents``
    agents take one extra rank (e.g. world 3 on 2 hosts -> [[0, 1], [2]]).
    Contiguity is load-bearing — rank 0 (the jax.distributed coordinator)
    always lands on agent 0, and checkpoint shard locality per host stays
    a contiguous slice. Every agent gets at least one rank: a host with
    nothing to run can't ack launches, so an over-provisioned gang is an
    admission-time error, not a silent idle host."""
    if num_agents < 1:
        raise ValueError(f"num_agents must be >= 1, got {num_agents}")
    if world_size < num_agents:
        raise ValueError(
            f"world_size {world_size} < {num_agents} agents: every host "
            "must own at least one rank"
        )
    base, extra = divmod(world_size, num_agents)
    blocks, start = [], 0
    for a in range(num_agents):
        n = base + (1 if a < extra else 0)
        blocks.append(list(range(start, start + n)))
        start += n
    return blocks


def ranks_for_agent(agent_id: int, num_agents: int, world_size: int
                    ) -> list[int]:
    """Contiguous rank block for one agent (see :func:`assign_ranks`)."""
    return assign_ranks(world_size, num_agents)[agent_id]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class AgentConfig:
    agent_id: int
    num_agents: int
    world_size: int
    kv_port: int
    kv_host: str = "127.0.0.1"
    heartbeat_interval: float = 0.5
    heartbeat_timeout: float = 60.0   # rank wedge detection (leader-side)
    agent_timeout: float = 10.0       # agent/host wedge detection
    grace: float = 180.0              # rank startup grace (jax import etc.)
    lease_ttl: float = 3.0
    poll: float = 0.05
    term_timeout: float = 30.0        # SIGTERM→SIGKILL escalation for ranks
    ack_timeout: float = 60.0         # teardown-ack deadline
    agent_wait: float = 120.0         # relaunch gate: wait for agents alive
    max_restarts: int = 3
    max_preemptions: int = 32
    backoff: float = 1.0
    backoff_max: float = 30.0
    verbose: bool = True
    # Multi-tenancy: which job's KV namespace this agent lives in. The
    # default job keeps the historical bare key schema; any other id puts
    # every key (election, budgets, generations, heartbeats, fault claims)
    # under job/<id>/ so concurrent jobs on one store cannot collide.
    job_id: str = ""

    @property
    def local_ranks(self) -> list[int]:
        return ranks_for_agent(self.agent_id, self.num_agents,
                               self.world_size)


class _LeaderState:
    """Leader-only soft state, rebuilt from the store on takeover. Nothing
    here is authoritative: a fresh leader starting from zero re-reads the
    generation, teardown, acks, reports, and the charge claim, and resumes
    exactly where the dead leader stopped."""

    def __init__(self):
        self.rank_watchdog: Watchdog | None = None
        self.agent_watchdog: Watchdog | None = None
        self.resolved_gen = 0         # highest gen whose outcome we handled
        self.teardown_deadline: dict[int, float] = {}
        self.next_launch_at = 0.0
        self.gate_deadline: dict[int, float] = {}


class HostAgent:
    """One host's member of the elastic job; see the module docstring.

    ``rank_commands(generation, rank, coordinator_port) -> argv`` builds
    the worker command for one LOCAL rank; each worker additionally
    inherits ``TPU_SANDBOX_KV_PORT``, ``TPU_SANDBOX_GENERATION`` and
    ``TPU_SANDBOX_AGENT_ID`` on top of ``os.environ`` and ``extra_env``.

    ``run()`` blocks until the job reaches a terminal verdict and returns
    the agent's exit code: 0 (job ok), 1 (job failed), or
    :data:`PREEMPTED_EXIT_CODE` (whole job preempted; state saved).
    """

    def __init__(
        self,
        config: AgentConfig,
        rank_commands: Callable[[int, int, int], Sequence[str]],
        *,
        extra_env: Mapping[str, str] | None = None,
    ):
        self.cfg = config
        self.rank_commands = rank_commands
        self.extra_env = dict(extra_env or {})
        self.aid = config.agent_id
        self.kv: KVClient | None = None
        self.hb: Heartbeat | None = None
        self.election: LeaseElection | None = None
        self.group = RankGroup(
            term_timeout=config.term_timeout, kill_on_parent_death=True
        )
        self._spawned_gen = 0
        self._spawned_ranks: list[int] = list(config.local_ranks)
        self._reported_gen = 0
        self._acked_teardown_gen = 0
        self._partition_until = 0.0
        self._external_preempt = False
        self._leader_state: _LeaderState | None = None
        # bias the first election toward agent 0 (deterministic tests, and
        # no thundering claim herd at job start); failover is unaffected —
        # the bias lapses once the agent has been alive for ~a lease ttl
        self._candidacy_after = time.monotonic() + (
            0.0 if self.aid == 0 else config.lease_ttl + 1.0
        )

    # -- logging ------------------------------------------------------------

    def _log(self, msg: str) -> None:
        if self.cfg.verbose:
            print(f"[agent {self.aid}] {msg}", flush=True)

    # -- the agent loop -----------------------------------------------------

    def run(self) -> int:
        cfg = self.cfg
        # All of this agent's KV traffic — election included — goes through
        # the job-scoped view, so two jobs sharing one store elect separate
        # leaders, charge separate budgets, and sweep separate namespaces.
        self.kv = for_job(KVClient(cfg.kv_host, cfg.kv_port), cfg.job_id)
        self.election = LeaseElection(
            self.kv, self.aid, ttl=cfg.lease_ttl, prefix="leader"
        )
        self.hb = Heartbeat(
            self.kv, self.aid, cfg.heartbeat_interval,
            key=_agent_hb_key(self.aid),
        ).start()
        prev_handler = self._install_forwarder()
        self._log(
            f"up: ranks {cfg.local_ranks} of world {cfg.world_size} "
            f"({cfg.num_agents} agents)"
        )
        try:
            while True:
                if self._partition_tick():
                    time.sleep(cfg.poll)
                    continue
                self._poll_fault_cmd()
                verdict = self.kv.try_get(K_JOB_DONE)
                if verdict is not None:
                    return self._finish(json.loads(verdict))
                leading = self.election.step(
                    candidate=time.monotonic() >= self._candidacy_after
                )
                if leading and self._leader_state is None:
                    self._leader_state = _LeaderState()
                    self._log(
                        f"elected leader (term {self.election.term})"
                    )
                elif not leading and self._leader_state is not None:
                    self._leader_state = None
                    self._log("deposed (a newer leader established itself)")
                self._agent_tick()
                if leading:
                    self._leader_tick()
                time.sleep(cfg.poll)
        finally:
            try:
                if self.group.running:
                    self.group.teardown()
            finally:
                if prev_handler is not None:
                    try:
                        signal.signal(signal.SIGTERM, prev_handler)
                    except ValueError:
                        pass
                self.hb.stop()
                self.kv.close()

    def _finish(self, verdict: dict) -> int:
        if self.group.running:
            self.group.teardown()
        self._log(f"job done: {verdict.get('reason', '')}".rstrip(": "))
        if verdict.get("ok"):
            return 0
        return PREEMPTED_EXIT_CODE if verdict.get("preempted") else 1

    def _install_forwarder(self):
        """A SIGTERM to the agent is the whole host being preempted:
        forward it to the local ranks (their PreemptionHandler saves and
        exits 75) and remember, so a leader among us reports the job
        preempted instead of relaunching."""
        def fwd(signum, frame):
            self._external_preempt = True
            self.group.terminate_all()
        try:
            return signal.signal(signal.SIGTERM, fwd)
        except ValueError:
            return None  # not the main thread (in-process tests)

    # -- fault mailbox ------------------------------------------------------

    def _poll_fault_cmd(self) -> None:
        raw = self.kv.try_get(agent_cmd_key(self.aid))
        if raw is None:
            return
        self.kv.delete(agent_cmd_key(self.aid))
        cmd = json.loads(raw)
        action = cmd.get("action")
        if action == "kill_agent":
            self._log("fault: kill_agent — dying uncleanly (SIGKILL self; "
                      "pdeathsig takes the local ranks with us)")
            # last words for the postmortem: instants flush to disk, so
            # the merged timeline shows the kill even though nothing of
            # this process survives the next line
            rec = get_recorder()
            rec.instant("fault:kill_agent", args={"agent": self.aid})
            rec.flush()
            os.kill(os.getpid(), signal.SIGKILL)
        elif action == "partition_host":
            dur = float(cmd.get("arg") or 5.0)
            get_recorder().instant("fault:partition_host",
                                   args={"agent": self.aid, "duration": dur})
            self._log(
                f"fault: partition_host — silent toward the KV store for "
                f"{dur:.1f}s (local ranks keep running)"
            )
            self.hb.stop()  # the beat thread must go silent too
            self._partition_until = time.monotonic() + dur
        else:
            self._log(f"ignoring unknown agent command {action!r}")

    def _partition_tick(self) -> bool:
        """True while the simulated partition holds (all KV traffic,
        including heartbeats and election, is suppressed)."""
        if not self._partition_until:
            return False
        if time.monotonic() < self._partition_until:
            self.group.poll()  # keep watching local ranks; can't report yet
            return True
        self._partition_until = 0.0
        self._log("partition healed; rejoining the control plane")
        self.hb.start()
        return False

    # -- per-agent duties (every agent, leader included) --------------------

    def _current_gen(self) -> int:
        raw = self.kv.try_get(K_GENERATION)
        return 0 if raw is None else int(raw)

    def _gen_assignment(self, gen: int) -> dict[int, list[int]]:
        """The generation's rank-assignment table, read from the launch
        record the leader published. Pre-table records (or a record that
        hasn't landed yet) fall back to the config-derived split — both
        sides compute :func:`assign_ranks` deterministically, so the
        fallback agrees with what the table would have said."""
        raw = self.kv.try_get(k_launch(gen))
        if raw is not None:
            table = json.loads(raw).get("assign")
            if table:
                return {int(a): [int(r) for r in rs]
                        for a, rs in table.items()}
        blocks = assign_ranks(self.cfg.world_size, self.cfg.num_agents)
        return dict(enumerate(blocks))

    def _agent_tick(self) -> None:
        gen = self._current_gen()
        if gen == 0:
            return
        if self.kv.try_get(k_teardown(gen)) is not None:
            self._ack_teardown(gen)
            return
        if self.kv.try_get(k_launch(gen)) is None:
            return
        if self._spawned_gen != gen:
            self._maybe_spawn(gen)
            return
        self._monitor_local(gen)

    def _ack_teardown(self, gen: int) -> None:
        if self._acked_teardown_gen == gen:
            return
        codes: list[int | None] = []
        if len(self.group) and (self.group.running
                                or self._spawned_gen == gen):
            # kill whatever local ranks exist — even ones from an OLDER
            # generation (a partition can strand us with zombies the rest
            # of the job already moved past); the ack below is the leader's
            # guarantee that this host carries nothing into the next gen
            final = self.group.teardown()
            if self._spawned_gen == gen:
                codes = final
            self._log(
                f"gen {gen}: teardown complete, local exit codes {final}"
            )
        self.kv.set(
            k_teardown_ack(gen, self.aid),
            json.dumps({"exit_codes": codes}),
        )
        self._acked_teardown_gen = gen

    def _maybe_spawn(self, gen: int) -> None:
        cfg = self.cfg
        ranks = self._gen_assignment(gen).get(self.aid, cfg.local_ranks)
        if self.kv.try_get(k_launch_ack(gen, self.aid)) is not None:
            # a previous incarnation of this agent acked this generation and
            # died; pdeathsig killed its ranks with it. Report the loss so
            # the leader tears down fast instead of waiting out a heartbeat
            # timeout on ranks that will never speak again.
            if (self._reported_gen != gen
                    and self.kv.try_get(k_report(gen, self.aid)) is None):
                self._report(gen, "failure", {}, ranks,
                             note="agent restarted; local ranks lost")
            self._reported_gen = gen
            return
        if 0 in ranks:
            # rank 0 lives here: its host picks the jax.distributed
            # coordinator port (must be free on THIS machine) and publishes
            # it for everyone
            port = _free_port()
            self.kv.set(k_coordinator(gen), str(port))
        else:
            raw = self.kv.try_get(k_coordinator(gen))
            if raw is None:
                return  # rank-0's agent hasn't published yet; retry
            port = int(raw)
        env = dict(os.environ)
        env.update(self.extra_env)
        env[ENV_KV_PORT] = str(cfg.kv_port)
        env[ENV_GENERATION] = str(gen)
        env[ENV_AGENT_ID] = str(self.aid)
        env[ENV_JOB_ID] = cfg.job_id or DEFAULT_JOB
        cmds = [
            list(self.rank_commands(gen, r, port)) for r in ranks
        ]
        self.group.spawn(cmds, env)
        self._spawned_gen = gen
        self._spawned_ranks = list(ranks)
        self._reported_gen = 0
        self.kv.set(k_launch_ack(gen, self.aid), b"1")
        self._log(f"gen {gen}: spawned local rank(s) {ranks}")

    def _monitor_local(self, gen: int) -> None:
        if self._reported_gen == gen:
            return
        codes = self.group.poll()
        ranks = self._spawned_ranks
        culprits = [r for r, c in zip(ranks, codes) if c not in (None, 0)]
        if culprits:
            # initiator-only classification (same rule as the Supervisor):
            # only pre-teardown exits decide preemption vs failure
            preempted = all(
                c == PREEMPTED_EXIT_CODE
                for r, c in zip(ranks, codes) if r in culprits
            )
            outcome = "preemption" if preempted else "failure"
            self._report(gen, outcome, dict(zip(ranks, codes)), culprits)
        elif all(c == 0 for c in codes):
            self._report(gen, "ok", dict(zip(ranks, codes)), [])

    def _report(self, gen: int, outcome: str, codes: dict, culprits: list,
                note: str = "") -> None:
        self.kv.set(
            k_report(gen, self.aid),
            json.dumps({
                "outcome": outcome, "culprits": culprits, "note": note,
                "exit_codes": {str(r): c for r, c in codes.items()},
            }),
        )
        self._reported_gen = gen
        self._log(f"gen {gen}: local outcome {outcome}"
                  + (f" (culprits {culprits})" if culprits else ""))

    # -- leader duties ------------------------------------------------------

    def _leader_tick(self) -> None:
        # Re-verify leadership: _agent_tick may have blocked in a rank
        # teardown for longer than the lease TTL, in which case a peer has
        # legitimately taken over and acting now would be a stale leader
        # mutating shared state (the classic fencing problem).
        if not self.election.step(candidate=False):
            self._leader_state = None
            self._log("deposed (a newer leader established itself)")
            return
        st = self._leader_state
        gen = self._current_gen()
        if gen == 0:
            self._reset_job_plane()
            self._advance_generation(1)
            return
        if self.kv.try_get(k_teardown(gen)) is None:
            if self.kv.try_get(k_launch(gen)) is None:
                # predecessor died between bumping the generation and
                # publishing the launch; no ranks exist yet, so publishing
                # (with a fresh health plane) is safe and unblocks everyone
                self._publish_generation(gen)
                return
            self._monitor_generation(gen, st)
        else:
            if st.resolved_gen < gen:
                self._maybe_resolve(gen, st)
            if st.resolved_gen >= gen:
                self._maybe_relaunch(gen, st)

    def _reset_job_plane(self) -> None:
        """Job-start sweep (mirrors Supervisor._reset_job_plane): stale
        fault claims or commit claims from a previous job on a long-lived
        external store must not bleed into this one."""
        self.kv.delete_prefix("fault/")
        self.kv.delete_prefix("ckpt/")

    def _reset_health_plane(self) -> None:
        for r in range(self.cfg.world_size):
            self.kv.delete(_hb_key(r))
            self.kv.delete(f"rendezvous/gen/{r}")
        self.kv.delete(PREEMPT_KEY)
        self.kv.delete_prefix("ckpt/")

    def _advance_generation(self, gen: int) -> None:
        get_recorder().instant("generation:advance", args={"gen": gen})
        self.kv.set(K_GENERATION, str(gen))
        self._publish_generation(gen)

    def _publish_generation(self, gen: int) -> None:
        get_recorder().instant("generation:publish", args={"gen": gen})
        st = self._leader_state
        self._reset_health_plane()
        self.kv.delete(k_coordinator(gen))
        blocks = assign_ranks(self.cfg.world_size, self.cfg.num_agents)
        # the rank-assignment table rides in the launch record: agents
        # spawn exactly the ranks the leader assigned them, so world sizes
        # that don't divide by the host count gang-schedule cleanly
        self.kv.set(
            k_launch(gen),
            json.dumps({
                "world_size": self.cfg.world_size, "at_gen": gen,
                "assign": {str(a): rs for a, rs in enumerate(blocks)},
            }),
        )
        st.rank_watchdog = st.agent_watchdog = None  # fresh grace per gen
        self._ensure_watchdogs(st)
        sizes = "+".join(str(len(b)) for b in blocks)
        self._log(
            f"gen {gen}: launch posted "
            f"({self.cfg.num_agents} host(s), rank split {sizes})"
        )

    def _ensure_watchdogs(self, st: _LeaderState) -> None:
        """Leadership taken over mid-generation (or mid-teardown): rebuild
        the observers. Their grace restarts, trading a little detection
        latency for never flagging a stamp the new leader hasn't watched."""
        if st.rank_watchdog is None:
            st.rank_watchdog = Watchdog(
                self.kv, self.cfg.world_size,
                timeout=self.cfg.heartbeat_timeout, grace=self.cfg.grace,
            )
        if st.agent_watchdog is None:
            st.agent_watchdog = Watchdog(
                self.kv, self.cfg.num_agents,
                timeout=self.cfg.agent_timeout,
                grace=max(self.cfg.agent_timeout, 30.0),
                key_fn=_agent_hb_key,
            )

    def _reports(self, gen: int) -> dict[int, dict]:
        out = {}
        for a in range(self.cfg.num_agents):
            raw = self.kv.try_get(k_report(gen, a))
            if raw is not None:
                out[a] = json.loads(raw)
        return out

    def _monitor_generation(self, gen: int, st: _LeaderState) -> None:
        reports = self._reports(gen)
        bad = {a: r for a, r in reports.items() if r["outcome"] != "ok"}
        if bad:
            a, r = next(iter(sorted(bad.items())))
            self._post_teardown(
                gen, kind=r["outcome"],
                reason=(f"agent {a} reported {r['outcome']} "
                        f"(culprit rank(s) {r['culprits']}"
                        + (f"; {r['note']}" if r.get("note") else "") + ")"),
            )
            return
        if len(reports) == self.cfg.num_agents:
            self._post_job_done(ok=True, reason="all ranks finished")
            return
        self._ensure_watchdogs(st)
        # Wedged RANKS: only frozen stamps count (a key that disappeared is
        # a clean deregister; a rank that dies pre-first-beat surfaces as an
        # exit code in its agent's report instead). Ranks of agents that
        # already reported are done, not wedged.
        owner = {
            r: a for a, ranks in self._gen_assignment(gen).items()
            for r in ranks
        }
        health = st.rank_watchdog.check()
        wedged = [
            h.rank for h in health
            if not h.alive and h.age is not None and owner[h.rank] not in
            reports
        ]
        if wedged:
            ages = {h.rank: round(h.age, 1) for h in health
                    if h.rank in wedged}
            self._post_teardown(
                gen, kind="wedged",
                reason=(f"rank(s) {wedged} stopped heartbeating "
                        f"(stamp ages {ages}, timeout "
                        f"{self.cfg.heartbeat_timeout}s)"),
            )
            return
        # Wedged AGENTS (host dead or partitioned): silent toward the store
        # for > agent_timeout with no final report. Their ranks may look
        # perfectly healthy — that is the case only this check can see.
        ahealth = st.agent_watchdog.check()
        silent = [h.rank for h in ahealth
                  if not h.alive and h.rank not in reports]
        if silent:
            ages = {h.rank: (round(h.age, 1) if h.age is not None else None)
                    for h in ahealth if h.rank in silent}
            self._post_teardown(
                gen, kind="wedged",
                reason=(f"agent(s) {silent} silent for "
                        f">{self.cfg.agent_timeout}s (stamp ages {ages}) — "
                        "host dead or partitioned"),
            )

    def _post_teardown(self, gen: int, *, kind: str, reason: str) -> None:
        self._log(f"gen {gen}: teardown ({reason})")
        self.kv.set(
            k_teardown(gen), json.dumps({"kind": kind, "reason": reason})
        )

    def _maybe_resolve(self, gen: int, st: _LeaderState) -> None:
        deadline = st.teardown_deadline.setdefault(
            gen, time.monotonic() + self.cfg.ack_timeout
        )
        acks = [
            a for a in range(self.cfg.num_agents)
            if self.kv.try_get(k_teardown_ack(gen, a)) is not None
        ]
        if len(acks) < self.cfg.num_agents and time.monotonic() < deadline:
            return
        # non-blocking: _maybe_resolve only runs after _post_teardown wrote
        # the record, but a blocking get() here would park the leader past
        # its lease TTL if the store hiccups — re-observe next tick instead
        raw_td = self.kv.try_get(k_teardown(gen))
        if raw_td is None:
            return
        td = json.loads(raw_td)
        reports = self._reports(gen)
        outcomes = {r["outcome"] for r in reports.values()}
        if "failure" in outcomes:
            outcome = "failure"
        elif "preemption" in outcomes:
            outcome = "preemption"
        elif td["kind"] == "wedged":
            outcome = "wedged"
        else:
            outcome = "failure"
        charged = self.kv.add(k_charge_claim(gen), 1) == 1
        if outcome == "preemption":
            preemptions = (self.kv.add(K_PREEMPTIONS, 1) if charged
                           else int(self.kv.try_get(K_PREEMPTIONS) or 0))
            restarts = int(self.kv.try_get(K_RESTARTS) or 0)
            if self._external_preempt:
                self._post_job_done(
                    ok=False, preempted=True,
                    reason="preempted from outside; state saved — exiting "
                           "without relaunch",
                )
                return
            if preemptions > self.cfg.max_preemptions:
                self._post_job_done(
                    ok=False,
                    reason=(f"more than {self.cfg.max_preemptions} "
                            "preemptions; refusing to thrash"),
                )
                return
            delay = self.cfg.backoff  # prompt, no exponential ramp
        else:
            restarts = (self.kv.add(K_RESTARTS, 1) if charged
                        else int(self.kv.try_get(K_RESTARTS) or 0))
            preemptions = int(self.kv.try_get(K_PREEMPTIONS) or 0)
            if restarts > self.cfg.max_restarts:
                self._post_job_done(
                    ok=False,
                    reason=(f"generation {gen} {outcome} ({td['reason']}) "
                            f"and the restart budget "
                            f"({self.cfg.max_restarts}) is spent"),
                )
                return
            delay = min(
                self.cfg.backoff * (2 ** max(restarts - 1, 0)),
                self.cfg.backoff_max,
            )
        st.resolved_gen = gen
        st.next_launch_at = time.monotonic() + delay
        st.gate_deadline[gen] = (
            time.monotonic() + delay + self.cfg.agent_wait
        )
        self._log(
            f"gen {gen} {outcome} ({td['reason']}); acks {acks}; "
            f"relaunching in >={delay:.1f}s "
            f"[{restarts}/{self.cfg.max_restarts} restarts charged"
            + (" +1 this gen" if charged and outcome != "preemption" else "")
            + f", {preemptions} preemption(s)]"
        )

    def _maybe_relaunch(self, gen: int, st: _LeaderState) -> None:
        if time.monotonic() < st.next_launch_at:
            return
        # Relaunch gate: every agent must (a) have acked the teardown — its
        # local ranks are genuinely dead, so a partitioned host's zombies
        # can't beat into the next generation's health plane — and (b) be
        # heartbeating right now, so the new generation has a full world.
        self._ensure_watchdogs(st)
        ahealth = {h.rank: h for h in st.agent_watchdog.check()}
        waiting = [
            a for a in range(self.cfg.num_agents)
            if self.kv.try_get(k_teardown_ack(gen, a)) is None
            or not ahealth[a].alive
        ]
        if waiting:
            if time.monotonic() > st.gate_deadline.get(gen, 0.0):
                self._post_job_done(
                    ok=False,
                    reason=(f"agent(s) {waiting} never returned after the "
                            f"gen-{gen} teardown (waited "
                            f"{self.cfg.agent_wait:.0f}s); a replacement "
                            "host is required"),
                )
            return
        self._advance_generation(gen + 1)

    def _post_job_done(self, *, ok: bool, preempted: bool = False,
                       reason: str = "") -> None:
        gens = self._current_gen()
        restarts = int(self.kv.try_get(K_RESTARTS) or 0)
        preemptions = int(self.kv.try_get(K_PREEMPTIONS) or 0)
        summary = (
            f"{gens} generation(s); {restarts} restart(s) charged, "
            f"{preemptions} preemption(s)"
        )
        self._log(f"done ({'ok' if ok else 'failed'}): {reason} — {summary}")
        self.kv.set(
            K_JOB_DONE,
            json.dumps({
                "ok": ok, "preempted": preempted,
                "reason": reason, "summary": summary,
                "restarts": restarts, "preemptions": preemptions,
                "generations": gens,
            }),
        )


class AgentLauncher:
    """Single-machine, single-job agent runner: owns the KV server, spawns
    one agent process per simulated host, and replaces any agent that dies
    before the job's terminal verdict (a real scheduler rescheduling a
    lost host). The launcher has NO job knowledge — all coordination lives
    in the agents; killing the launcher's children in any order must never
    deadlock the job. The multi-job promotion of this class is
    :class:`tpu_sandbox.runtime.scheduler.ClusterScheduler` (durable
    queue, gang scheduling, priority preemption); this one stays as the
    zero-ceremony path for one job on one machine.

    ``agent_command(agent_id, kv_port) -> argv`` builds one agent process's
    command line.
    """

    def __init__(
        self,
        num_agents: int,
        agent_command: Callable[[int, int], Sequence[str]],
        *,
        kv_server: KVServer | None = None,
        respawn_limit: int = 16,
        poll: float = 0.1,
        drain_timeout: float = 60.0,
        extra_env: Mapping[str, str] | None = None,
        verbose: bool = True,
    ):
        if num_agents < 1:
            raise ValueError(f"num_agents must be >= 1, got {num_agents}")
        self.num_agents = num_agents
        self.agent_command = agent_command
        self._kv_server = kv_server
        self._owns_server = kv_server is None
        self.respawn_limit = respawn_limit
        self.poll = poll
        self.drain_timeout = drain_timeout
        self.extra_env = dict(extra_env or {})
        self.verbose = verbose
        self.respawns = 0

    def _log(self, msg: str) -> None:
        if self.verbose:
            print(f"[launcher] {msg}", flush=True)

    def run(self) -> int:
        import subprocess

        server = self._kv_server or KVServer()
        kv = KVClient(port=server.port)
        env = dict(os.environ)
        env.update(self.extra_env)
        procs: dict[int, subprocess.Popen] = {}

        def spawn(aid: int):
            procs[aid] = subprocess.Popen(
                list(self.agent_command(aid, server.port)), env=env
            )

        def forward(signum, frame):
            for p in procs.values():
                if p.poll() is None:
                    try:
                        p.terminate()
                    except OSError:
                        pass
        try:
            prev = signal.signal(signal.SIGTERM, forward)
        except ValueError:
            prev = None
        try:
            for a in range(self.num_agents):
                spawn(a)
            self._log(f"spawned {self.num_agents} host agent(s), "
                      f"kv port {server.port}")
            while True:
                verdict = kv.try_get(K_JOB_DONE)
                if verdict is not None:
                    return self._drain(json.loads(verdict), procs)
                for a, p in list(procs.items()):
                    code = p.poll()
                    if code is None:
                        continue
                    if kv.try_get(K_JOB_DONE) is not None:
                        break  # verdict just landed; drain on next pass
                    self.respawns += 1
                    if self.respawns > self.respawn_limit:
                        self._log(
                            f"agent {a} died (exit {code}) and the respawn "
                            f"limit ({self.respawn_limit}) is spent; "
                            "aborting the job"
                        )
                        for q in procs.values():
                            if q.poll() is None:
                                q.kill()
                        return 1
                    self._log(
                        f"agent {a} died (exit {code}); respawning "
                        f"[{self.respawns}/{self.respawn_limit}]"
                    )
                    spawn(a)
                time.sleep(self.poll)
        finally:
            if prev is not None:
                try:
                    signal.signal(signal.SIGTERM, prev)
                except ValueError:
                    pass
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
                    p.wait()
            kv.close()
            if self._owns_server:
                server.stop()

    def _drain(self, verdict: dict, procs) -> int:
        """Job verdict posted: let the agents see it and exit on their own
        (they clean their ranks), then report the verdict's exit code."""
        deadline = time.monotonic() + self.drain_timeout
        for p in procs.values():
            while p.poll() is None and time.monotonic() < deadline:
                time.sleep(self.poll)
            if p.poll() is None:
                p.kill()
                p.wait()
        ok = verdict.get("ok", False)
        self._log(
            f"job {'ok' if ok else 'FAILED'}: "
            f"{verdict.get('reason', '')} — {verdict.get('summary', '')}"
        )
        if ok:
            return 0
        return PREEMPTED_EXIT_CODE if verdict.get("preempted") else 1
