from tpu_sandbox.runtime.bootstrap import (  # noqa: F401
    backend_name,
    cleanup,
    coordinator_address,
    find_free_port,
    init,
    is_initialized,
    process_count,
    process_index,
    topology,
    topology_summary,
)
from tpu_sandbox.runtime.election import (  # noqa: F401
    LeaderInfo,
    LeaseElection,
)
from tpu_sandbox.runtime.faults import (  # noqa: F401
    Fault,
    FaultInjector,
    FaultPlan,
)
from tpu_sandbox.runtime.host_agent import (  # noqa: F401
    AgentConfig,
    AgentLauncher,
    HostAgent,
    ranks_for_agent,
)
from tpu_sandbox.runtime.mesh import make_mesh, submesh  # noqa: F401
from tpu_sandbox.runtime.supervisor import (  # noqa: F401
    PREEMPTED_EXIT_CODE,
    ElasticResult,
    GenerationReport,
    RankGroup,
    RestartBudgetExceeded,
    Supervisor,
)
from tpu_sandbox.runtime.watchdog import (  # noqa: F401
    DeadRankError,
    Heartbeat,
    RendezvousTimeout,
    Watchdog,
    wait_for_world,
)
