"""Deterministic fault injection — the test half of the detect→recover loop.

The supervisor (runtime/supervisor.py) can only be trusted if rank death,
preemption, wedged ranks, and corrupt checkpoints are *reproducible*; this
module makes failures first-class inputs instead of flaky sleeps-and-kills
in tests:

- ``FaultPlan``     — a declarative list of :class:`Fault` entries ("rank 1
                      dies at optimizer step 7", "rank 0 receives SIGTERM at
                      step 4", ...), serialized through one env var so it
                      crosses the supervisor→worker process boundary.
- ``FaultInjector`` — lives inside the worker's training loop; at every
                      step boundary ``maybe_fire(step)`` fires any fault
                      scheduled for (this rank, this step). Each fault is
                      claimed through the shared KV store, so it fires
                      exactly once *across elastic restarts* — replaying
                      the same step after recovery must not re-kill the
                      worker, or the job would crash-loop forever.

Actions:

``kill``            SIGKILL self — the hard crash the watchdog + supervisor
                    must turn into a restart, not a hang.
``sigterm``         SIGTERM self — models a TPU/spot preemption notice; the
                    trainer's PreemptionHandler turns it into save+exit(75).
``hang_heartbeat``  stop publishing heartbeats while the process keeps
                    running — the wedged-not-dead case only the watchdog
                    (never exit-code polling) can detect.
``corrupt_ckpt``    scribble garbage over the newest checkpoint step under
                    ``target`` — exercises restore's quarantine-and-fall-
                    back path (checkpoint.py).
``corrupt_shard``   scribble garbage over this rank's shard file in the
                    newest SEALED sharded-checkpoint step under ``target``
                    — the step still *looks* committed (manifest intact),
                    so only the SHA-256 verification can catch it.
``kill_during_commit``  SIGKILL self from INSIDE the checkpoint commit
                    window (after this rank's shard is written and claimed,
                    on rank 0 right before the manifest rename) — the
                    torn-step case the two-phase protocol exists for. Fired
                    via ``maybe_fire_commit`` from the checkpoint layer's
                    commit hook, never at a step boundary.
``kill_agent``      SIGKILL this rank's *host agent* (host_agent.py) — the
                    host-death / leader-death case of the cross-host design.
                    The rank posts a command on the agent's KV mailbox
                    (``agent/cmd/<id>``); the agent executes it, and its
                    PR_SET_PDEATHSIG children die with it, exactly like a
                    machine vanishing.
``partition_host``  the agent stops talking to the KV store for ``target``
                    seconds while its ranks keep running — the network
                    partition only agent-level heartbeat monitoring can see.
                    Routed through the same agent mailbox.
``shed_storm``      post a shed order on a serve replica's fault mailbox
                    (``serve/cmd/<target>``): the replica sheds its whole
                    local waiting queue with explicit SHED verdicts — the
                    burst-overload case the SLO layer must answer without
                    hanging any client.
``stall_replica``   freeze the replica named by ``target`` for longer than
                    its lease TTL (``serve/cmd/<tag>:<seconds>`` target
                    syntax). Its leases lapse, peers scavenge its claims,
                    and the client-side hedging/retry path gets a live
                    straggler to race. Same mailbox, consumed by
                    ``ReplicaWorker._poll_faults``.
"""

from __future__ import annotations

import json
import os
import signal
from dataclasses import asdict, dataclass
from pathlib import Path
from typing import Callable, Mapping, MutableMapping

ENV_PLAN = "TPU_SANDBOX_FAULT_PLAN"

ACTIONS = ("kill", "sigterm", "hang_heartbeat", "corrupt_ckpt",
           "corrupt_shard", "kill_during_commit", "kill_agent",
           "partition_host", "shed_storm", "stall_replica")

#: Actions that fire inside the checkpoint commit window (via
#: ``maybe_fire_commit``) rather than at an optimizer-step boundary.
COMMIT_ACTIONS = ("kill_during_commit",)

#: Actions executed by this rank's HOST AGENT, not by the rank itself:
#: the rank claims the fault at its step boundary, then posts a command on
#: the agent's KV mailbox. Requires agent-mode elastic runs (a KV store and
#: TPU_SANDBOX_AGENT_ID in the rank's env).
AGENT_ACTIONS = ("kill_agent", "partition_host")

#: Actions executed by a serve REPLICA: posted to its fault mailbox
#: (``serve/cmd/<tag>``), consumed once per fault by the replica's poll
#: loop. ``target`` names the replica tag; ``stall_replica`` may append
#: ``:<seconds>`` for the stall duration.
SERVE_ACTIONS = ("shed_storm", "stall_replica")

ENV_AGENT_ID = "TPU_SANDBOX_AGENT_ID"


def agent_cmd_key(agent_id: int | str) -> str:
    """The agent's fault-command mailbox (single-slot: agents consume it
    with delete-after-read)."""
    return f"agent/cmd/{agent_id}"


def serve_cmd_key(tag: str) -> str:
    """A serve replica's fault mailbox (single-slot, delete-after-read —
    mirrors the agent mailbox; key layout owned by serve/replica.py)."""
    return f"serve/cmd/{tag}"


def agent_id_from_env(environ: Mapping[str, str] | None = None) -> int | None:
    raw = (environ or os.environ).get(ENV_AGENT_ID, "")
    return int(raw) if raw else None


@dataclass(frozen=True)
class Fault:
    rank: int
    step: int
    action: str
    target: str | None = None  # corrupt_ckpt/corrupt_shard: checkpoint dir

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(
                f"unknown fault action {self.action!r}; choose from {ACTIONS}"
            )
        if self.action in ("corrupt_ckpt", "corrupt_shard") and not self.target:
            raise ValueError(
                f"{self.action} needs target=<checkpoint dir>"
            )
        if self.action in SERVE_ACTIONS and not self.target:
            raise ValueError(
                f"{self.action} needs target=<replica tag>"
                + (":<seconds>" if self.action == "stall_replica" else "")
            )
        if self.action == "partition_host" and self.target is not None:
            try:
                float(self.target)
            except ValueError:
                raise ValueError(
                    "partition_host target must be a duration in seconds, "
                    f"got {self.target!r}"
                ) from None


class FaultPlan:
    """An ordered set of faults, round-trippable through one env var."""

    def __init__(self, faults: list[Fault] | tuple[Fault, ...] = ()):
        self.faults = list(faults)

    def add(self, rank: int, step: int, action: str,
            target: str | None = None) -> "FaultPlan":
        self.faults.append(Fault(rank, step, action, target))
        return self

    def __len__(self) -> int:
        return len(self.faults)

    def to_json(self) -> str:
        return json.dumps([asdict(f) for f in self.faults])

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls([Fault(**d) for d in json.loads(text)])

    def to_env(self, env: MutableMapping[str, str] | None = None) -> dict:
        """Write the plan into ``env`` (default: a fresh copy of
        ``os.environ``) and return that mapping — hand it to Popen."""
        env = dict(os.environ) if env is None else env
        env[ENV_PLAN] = self.to_json()
        return env

    @classmethod
    def from_env(cls, environ: Mapping[str, str] | None = None) -> "FaultPlan":
        """The plan the launcher serialized, or an empty plan (the normal,
        fault-free run) when the env var is unset."""
        raw = (environ or os.environ).get(ENV_PLAN, "")
        return cls.from_json(raw) if raw else cls()


class FaultInjector:
    """Worker-side trigger. Call ``maybe_fire(step)`` at every optimizer-step
    boundary; faults scheduled for (rank, step) fire at most once globally.

    ``kv``: a KVClient sharing the supervisor's store. When present, each
    fault is claimed with an atomic counter (``fault/<i>/claimed``) that
    survives worker restarts — the claim, not the process, is what makes a
    kill-at-step-7 happen once instead of on every replay of step 7.
    Without a store (single-process tests) claims are process-local.
    In cluster mode hand a job-scoped view (``kvstore.for_job``): claims
    and agent mailboxes then live inside the job's namespace, so job A's
    fault plan can never fire on (or be claimed by) job B.

    ``on_hang_heartbeat``: callback that silences this rank's liveness
    publishing (wire it to ``Heartbeat.stop``); the process itself keeps
    training, which is the point of that fault.
    """

    def __init__(
        self,
        plan: FaultPlan,
        rank: int,
        kv=None,
        *,
        on_hang_heartbeat: Callable[[], None] | None = None,
        agent_id: int | None = None,
    ):
        self.plan = plan
        self.rank = rank
        self.kv = kv
        self.on_hang_heartbeat = on_hang_heartbeat
        # which host agent owns this rank (agent-mode elastic runs set
        # TPU_SANDBOX_AGENT_ID in the worker env); agent-targeted faults
        # are posted to that agent's mailbox
        self.agent_id = agent_id if agent_id is not None else (
            agent_id_from_env()
        )
        self._claimed_local: set[int] = set()

    def _claim(self, index: int) -> bool:
        if index in self._claimed_local:
            return False
        self._claimed_local.add(index)
        if self.kv is not None:
            return self.kv.add(f"fault/{index}/claimed", 1) == 1
        return True

    def maybe_fire(self, step: int) -> list[Fault]:
        """Fire this rank's step-boundary faults scheduled exactly at
        ``step``; returns the faults that fired (kill, of course, never
        returns). Commit-window faults are skipped here — they belong to
        :meth:`maybe_fire_commit`."""
        fired = []
        for i, f in enumerate(self.plan.faults):
            if f.rank != self.rank or f.step != step:
                continue
            if f.action in COMMIT_ACTIONS:
                continue
            if not self._claim(i):
                continue
            self._fire(f)
            fired.append(f)
        return fired

    def maybe_fire_commit(self, step: int) -> list[Fault]:
        """Fire this rank's commit-window faults for ``step``. Called by
        the sharded checkpoint's commit hook, i.e. from INSIDE the
        two-phase save — after this rank's shard claim, and on rank 0
        between claim-gathering and the manifest rename. The KV claim
        still applies: the relaunched generation re-saves the same step
        without being re-killed."""
        fired = []
        for i, f in enumerate(self.plan.faults):
            if f.rank != self.rank or f.step != step:
                continue
            if f.action not in COMMIT_ACTIONS:
                continue
            if not self._claim(i):
                continue
            self._fire(f)
            fired.append(f)
        return fired

    def _fire(self, f: Fault) -> None:
        # announce BEFORE executing: instants flush to disk, so even a
        # self-SIGKILL on the next line leaves its mark on the timeline
        from tpu_sandbox.obs import get_recorder

        get_recorder().instant(f"fault:{f.action}",
                               args={"rank": self.rank, "step": f.step})
        if f.action in ("kill", "kill_during_commit"):
            os.kill(os.getpid(), signal.SIGKILL)
        elif f.action == "sigterm":
            # handler (trainer.PreemptionHandler) runs at the next bytecode
            # boundary; the in-flight step then finishes before save+exit
            os.kill(os.getpid(), signal.SIGTERM)
        elif f.action == "hang_heartbeat":
            if self.on_hang_heartbeat is not None:
                self.on_hang_heartbeat()
        elif f.action == "corrupt_ckpt":
            corrupt_latest_step(f.target)
        elif f.action == "corrupt_shard":
            corrupt_latest_shard(f.target, rank=self.rank)
        elif f.action in AGENT_ACTIONS:
            if self.kv is None or self.agent_id is None:
                raise RuntimeError(
                    f"{f.action} needs a KV store and {ENV_AGENT_ID} in the "
                    "worker env — agent-mode elastic runs only (--agents N)"
                )
            self.kv.set(
                agent_cmd_key(self.agent_id),
                json.dumps({"action": f.action, "arg": f.target}),
            )
        elif f.action in SERVE_ACTIONS:
            if self.kv is None:
                raise RuntimeError(f"{f.action} needs a KV store")
            tag, _, dur = f.target.partition(":")
            body = {"action": f.action}
            if dur:
                body["duration"] = float(dur)
            self.kv.set(serve_cmd_key(tag), json.dumps(body))


# -- checkpoint corruption (also used directly by tests) -------------------

def corrupt_step_dir(step_dir: str | os.PathLike) -> list[Path]:
    """Overwrite every regular file under ``step_dir`` with garbage bytes
    (keeping the layout, so the step still *looks* committed — the nastier
    corruption mode). Returns the files touched."""
    touched = []
    for p in sorted(Path(step_dir).rglob("*")):
        if p.is_file():
            p.write_bytes(b"\xde\xad\xbe\xef garbage " * 4)
            touched.append(p)
    return touched


def _sealed_sharded_steps(root: Path) -> list[Path]:
    """Sealed ShardedCheckpoint step dirs (``step-XXXXXXXX/`` holding a
    MANIFEST.json), sorted by step number."""
    out = []
    for p in root.glob("step-*"):
        tail = p.name.split("-", 1)[1]
        if p.is_dir() and tail.isdigit() and (p / "MANIFEST.json").exists():
            out.append(p)
    return sorted(out, key=lambda p: int(p.name.split("-", 1)[1]))


def corrupt_latest_shard(
    directory: str | os.PathLike, rank: int = 0
) -> Path | None:
    """Scribble over ONE shard file of the newest *sealed* sharded step —
    the manifest stays intact, so the step still looks committed and only
    the restore-time SHA-256 check (or the verifier) can tell. Prefers
    rank ``rank``'s shard, falls back to the first shard present. Returns
    the file corrupted, or None when no sealed sharded step exists."""
    root = Path(directory)
    if not root.is_dir():
        return None
    sealed = _sealed_sharded_steps(root)
    if not sealed:
        return None
    sd = sealed[-1]
    target = sd / f"shard-{rank:05d}.npz"
    if not target.exists():
        shards = sorted(sd.glob("shard-*.npz"))
        if not shards:
            return None
        target = shards[0]
    target.write_bytes(b"\xde\xad\xbe\xef bitrot " * 4)
    return target


def corrupt_latest_step(directory: str | os.PathLike) -> Path | None:
    """Corrupt the newest committed checkpoint step under ``directory``.

    Understands all three on-disk layouts in this repo: orbax step
    directories (numeric child dirs), sealed ShardedCheckpoint step dirs
    (``step-XXXXXXXX/`` with a manifest), and HostCheckpoint step files
    (``step-*.npz``). Returns what was corrupted, or None when the dir
    holds no steps yet.
    """
    root = Path(directory)
    if not root.is_dir():
        return None
    step_dirs = [p for p in root.iterdir() if p.is_dir() and p.name.isdigit()]
    if step_dirs:
        latest = max(step_dirs, key=lambda p: int(p.name))
        corrupt_step_dir(latest)
        return latest
    sealed = _sealed_sharded_steps(root)
    if sealed:
        corrupt_step_dir(sealed[-1])
        return sealed[-1]
    npzs = [
        p for p in root.glob("step-*.npz")
        if p.stem.split("-", 1)[1].isdigit()
    ]
    if npzs:
        # numeric, not lexicographic: step-10 is newer than step-2
        latest = max(npzs, key=lambda p: int(p.stem.split("-", 1)[1]))
        latest.write_bytes(b"\xde\xad\xbe\xef not a zipfile")
        return latest
    return None
