"""Seeded chaos campaigns: compose every fault family the platform
already survives one-at-a-time, fire them against a live multi-gateway
fleet mid-workload, and audit the wreckage.

The existing fault hooks are scattered across planes — replica mailboxes
(``serve/cmd/<tag>``: shed_storm, stall_replica), agent mailboxes
(``agent/cmd/<id>``: kill_agent, partition_host), process kills (gateway
SIGKILL / ``Gateway.kill()``), leader death (autoscaler / deploy
controller resign-or-kill). Each is exercised by a hand-written scenario
somewhere in the test tree. This module composes them: a seed expands
into a deterministic fault schedule over a replayed workload trace
(:mod:`tpu_sandbox.obs.workload`), and the campaign ends with the fleet
invariants that must hold *no matter what fired*:

- **exactly one terminal verdict per request** — every submitted rid
  holds a ``serve/result`` body (zero lost), and the claim-once
  ``serve/done`` marker arbitrated every publication race (zero
  duplicated answers; duplicated *compute* is allowed and counted).
- **alert discipline** — every durable alert record has its claim
  marker: the claim-once ``raise_alert`` ordering held through any
  monitor death the campaign caused.
- **byte-identical audit** — the campaign's claim audit (fault firing
  sequence + per-rid verdict kind and token digest) serializes
  canonically; running the same seed twice against a fresh fleet yields
  the same bytes. This is the determinism receipt: verdict *bodies* are
  bitwise by the serve protocol, the firing *sequence* is pinned by the
  seed, and campaign-level retries scrub timing-dependent sheds so the
  terminal state is timing-free.

Determinism is sequence-level, not wall-clock-level: submits and fault
fires interleave in one thread in seeded order (ties break submit-first),
so "kill gateway gw1 after the 14th arrival" means the same thing on a
loaded laptop and a quiet CI box. What is NOT deterministic — which
replica executed a rid, how many scavenges raced — stays out of the
audit bytes and in the human-facing report instead.

The campaign drives gateways and agents through injected hooks (a test
kills an in-process ``Gateway``; the bench SIGKILLs a real gateway
process) — the orchestrator owns sequencing and auditing, never process
management.
"""

from __future__ import annotations

import hashlib
import json
import random
import time
from dataclasses import dataclass, field
from typing import Callable

from tpu_sandbox.obs import get_recorder, workload
from tpu_sandbox.obs.health import K_ALERT_PREFIX, k_alert_claim
from tpu_sandbox.serve.replica import (enqueue, k_cmd, k_done, k_pin,
                                       k_result)

#: audit schema — bump on any field change, the workload.py discipline
AUDIT_SCHEMA = "tpu-sandbox.chaos-audit/1"

#: every action a schedule may draw; the campaign validates that each
#: scheduled action has an executor (mailbox-backed or injected hook)
CHAOS_ACTIONS = ("kill_gateway", "kill_agent", "partition_host",
                 "kill_leader", "shed_storm", "stall_replica")

#: actions the campaign executes itself through the serve fault mailbox;
#: everything else needs a hook from the embedder
MAILBOX_ACTIONS = ("shed_storm", "stall_replica")


@dataclass(frozen=True)
class ChaosFault:
    """One scheduled fault: fire ``action`` on ``target`` once the
    campaign clock passes ``at_s`` (trace-relative seconds, same axis as
    the workload's arrival times). ``stall_replica`` targets carry the
    stall duration as ``tag:seconds``, the FaultPlan syntax."""

    at_s: float
    action: str
    target: str

    def as_dict(self) -> dict:
        return {"at_s": self.at_s, "action": self.action,
                "target": self.target}


def build_schedule(seed: int, *, duration_s: float,
                   targets: dict[str, list[str]],
                   n_faults: int = 4) -> list[ChaosFault]:
    """Expand a seed into a fault schedule. ``targets`` maps action ->
    candidate target list; only actions with candidates are drawn, so an
    embedder without agents simply omits the agent actions. Same seed +
    same targets dict -> same schedule, element for element (the draws
    consume the Random stream in a fixed order)."""
    rng = random.Random(seed)
    unknown = sorted(set(targets) - set(CHAOS_ACTIONS))
    if unknown:
        raise ValueError(f"unknown chaos actions: {unknown}")
    actions = [a for a in CHAOS_ACTIONS if targets.get(a)]
    if not actions:
        raise ValueError("no action has candidate targets")
    faults = []
    for _ in range(n_faults):
        action = actions[rng.randrange(len(actions))]
        pool = targets[action]
        target = pool[rng.randrange(len(pool))]
        faults.append(ChaosFault(
            at_s=round(rng.uniform(0.0, duration_s), 6),
            action=action, target=target))
    return sorted(faults, key=lambda f: (f.at_s, f.action, f.target))


@dataclass
class CampaignResult:
    """What one campaign did and whether the invariants held."""

    seed: int
    fired: list[dict] = field(default_factory=list)
    submitted: int = 0
    admitted: int = 0
    door_shed: int = 0
    retried: int = 0
    lost: list[str] = field(default_factory=list)
    #: rid -> {"verdict": kind, "tokens": digest} — the deterministic half
    verdicts: dict[str, dict] = field(default_factory=dict)
    failures: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.failures and not self.lost

    def audit_bytes(self) -> str:
        """The canonical claim audit: fault firing sequence + per-rid
        terminal state, sorted keys, compact separators, one trailing
        newline — the workload.py canonicalization discipline, so two
        same-seed campaigns diff byte-for-byte. Deliberately excludes
        everything timing-flavored (which replica executed, scavenge
        counts, retry counts, wall stamps)."""
        return json.dumps(
            {"schema": AUDIT_SCHEMA, "seed": self.seed,
             "faults": self.fired,
             "verdicts": {rid: self.verdicts[rid]
                          for rid in sorted(self.verdicts)},
             "lost": sorted(self.lost)},
            sort_keys=True, separators=(",", ":")) + "\n"


def _token_digest(verdict: dict) -> str:
    """Short stable digest of a verdict's answer bytes. Tokens are
    bitwise-identical across executions of a rid, so this is the
    deterministic fingerprint the audit carries instead of the list."""
    tokens = verdict.get("tokens", [])
    blob = json.dumps(tokens, separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


class ChaosCampaign:
    """One seeded campaign against a live fleet.

    ``kv`` is the fleet-scoped serve-plane view (the same one the
    replicas and gateways use for this fleet). ``submit`` is the
    embedder's door: ``submit(rid, prompt, max_new_tokens) -> bool``
    (admitted?) — typically a failover :class:`GatewayClient` so a
    killed gateway costs latency, not the campaign. ``hooks`` maps the
    non-mailbox actions to executors (``kill_gateway`` -> kill that
    gateway process/object, ``kill_leader`` -> kill/resign the named
    controller, ...).

    Prompts are derived from the trace deterministically: rows sharing a
    ``chain`` label share their leading block of tokens, so prefix
    routing has real structure to find; the rest of the prompt is seeded
    by the rid. ``time_scale`` compresses the trace's arrival axis (and
    the fault schedule with it) so a 1-second trace can run a 100 ms
    campaign in the fast tier."""

    def __init__(self, kv, trace: dict,
                 submit: Callable[[str, list, int], bool], *,
                 seed: int, schedule: list[ChaosFault],
                 hooks: dict[str, Callable[[str], None]] | None = None,
                 time_scale: float = 1.0, vocab: int = 64,
                 block_size: int = 8, max_retries: int = 10,
                 verdict_timeout: float = 30.0):
        self.kv = kv
        self.trace = trace
        self.submit = submit
        self.seed = seed
        self.schedule = list(schedule)
        self.hooks = dict(hooks or {})
        self.time_scale = time_scale
        self.vocab = vocab
        self.block_size = block_size
        self.max_retries = max_retries
        self.verdict_timeout = verdict_timeout
        for f in self.schedule:
            if f.action not in CHAOS_ACTIONS:
                raise ValueError(f"unknown action {f.action!r}")
            if f.action not in MAILBOX_ACTIONS \
                    and f.action not in self.hooks:
                raise ValueError(f"scheduled {f.action!r} has no hook")

    # -- inputs ---------------------------------------------------------------

    def prompt_for(self, row: dict) -> list[int]:
        """Deterministic tokens for a trace row: the first block comes
        from the chain label (shared prefix = shared bytes), the rest
        from the rid."""
        n = max(1, int(row["prompt_tokens"]))
        head = random.Random(f"chain:{row['chain']}")
        tail = random.Random(f"rid:{row['rid']}")
        k = min(self.block_size, n)
        return [head.randrange(self.vocab) for _ in range(k)] + \
               [tail.randrange(self.vocab) for _ in range(n - k)]

    # -- fault execution ------------------------------------------------------

    def _fire(self, f: ChaosFault) -> None:
        get_recorder().instant(f"chaos:{f.action}",
                               args={"target": f.target,
                                     "at_s": f.at_s, "seed": self.seed})
        if f.action in MAILBOX_ACTIONS:
            tag, _, dur = f.target.partition(":")
            body = {"action": f.action}
            if dur:
                body["duration"] = float(dur)
            # the same mailbox FaultInjector posts to; the fleet view
            # prefixes it
            self.kv.set(k_cmd(tag), json.dumps(body))
        else:
            self.hooks[f.action](f.target)

    # -- the campaign ---------------------------------------------------------

    def run(self) -> CampaignResult:
        """Replay the trace and fire the schedule in one seeded
        interleaving, then wait out verdicts (retrying sheds — a shed is
        an answer, but campaigns measure loss, and a retried rid must
        still converge to its one bitwise verdict), then audit."""
        res = CampaignResult(seed=self.seed)
        rows = workload.replay_order(self.trace)
        events: list[tuple[float, int, object]] = \
            [(row["t_s"] * self.time_scale, 0, row) for row in rows] + \
            [(f.at_s * self.time_scale, 1, f) for f in self.schedule]
        # ties submit-first, then rid/action order: the interleaving is a
        # pure function of (trace, schedule), never of the host's clock
        events.sort(key=lambda e: (
            e[0], e[1],
            e[2].action if e[1] else e[2]["rid"]))  # type: ignore[union-attr]
        rids: dict[str, dict] = {}
        with get_recorder().span("campaign", args={"seed": self.seed}):
            t0 = time.monotonic()
            for at, kind, payload in events:
                lag = t0 + at - time.monotonic()
                if lag > 0:
                    time.sleep(lag)
                if kind == 1:
                    self._fire(payload)
                    res.fired.append(payload.as_dict())
                else:
                    rid = payload["rid"]
                    rids[rid] = payload
                    res.submitted += 1
                    if self.submit(rid, self.prompt_for(payload),
                                   int(payload["decode_tokens"])):
                        res.admitted += 1
                    else:
                        res.door_shed += 1
            self._await_verdicts(res, rids)
        return res

    def _await_verdicts(self, res: CampaignResult,
                        rids: dict[str, dict]) -> None:
        """Poll the store (not any gateway — gateways may be dead) until
        every rid holds a terminal verdict. SHED verdicts are cleared and
        re-enqueued up to ``max_retries`` times: the campaign's loss
        metric is about *requests*, and a request the fleet answered
        'not now' under a shed_storm must still converge to its bitwise
        answer once the storm passes."""
        retries: dict[str, int] = {}
        open_rids = set(rids)
        deadline = time.monotonic() + self.verdict_timeout
        while open_rids and time.monotonic() < deadline:
            for rid in sorted(open_rids):
                raw = self.kv.try_get(k_result(rid))
                if raw is None:
                    continue
                verdict = json.loads(raw)
                if verdict.get("verdict", "ok") != "SHED":
                    res.verdicts[rid] = {"verdict": "ok",
                                         "tokens": _token_digest(verdict)}
                    open_rids.discard(rid)
                    continue
                if retries.get(rid, 0) >= self.max_retries:
                    res.verdicts[rid] = {"verdict": "SHED", "tokens": ""}
                    open_rids.discard(rid)
                    continue
                retries[rid] = retries.get(rid, 0) + 1
                res.retried += 1
                # the ServeClient._retry delete-triple, then a fresh
                # shared-queue entry (the request body persists)
                self.kv.delete(k_result(rid))
                self.kv.delete(k_done(rid))
                self.kv.delete(k_pin(rid))
                enqueue(self.kv, rid)
            time.sleep(0.01)
        res.lost = sorted(open_rids)
        for rid in res.lost:
            res.failures.append(f"no terminal verdict for {rid} "
                                f"within {self.verdict_timeout}s")
        # exactly-one-verdict: the claim marker must exist wherever a
        # verdict does (the result write is gated on winning it)
        for rid in sorted(res.verdicts):
            if self.kv.try_get(k_done(rid)) is None:
                res.failures.append(
                    f"verdict without done-claim for {rid}")


def check_alert_claims(kv) -> list[str]:
    """The alert half of the audit: every durable alert record must have
    won (or lost) its claim through the raise_alert ordering — a record
    with NO claim key means some monitor died between the idempotent set
    and the add() gate and no successor completed it, i.e. an alert that
    was recorded but never accounted as fired-exactly-once. ``kv`` is
    the view the monitors wrote through (fleet view for per-fleet
    monitors, root for global)."""
    failures = []
    for key in kv.keys(K_ALERT_PREFIX):
        parts = key[len(K_ALERT_PREFIX):].split("/")
        if len(parts) != 3:
            failures.append(f"malformed alert record key {key!r}")
            continue
        rule, subject, window = parts
        if kv.try_get(k_alert_claim(rule, subject, int(window))) is None:
            failures.append(
                f"alert {rule}/{subject}/{window} recorded but unclaimed")
    return failures


def prefix_probe(client, prompt: list[int], rid: str,
                 max_new_tokens: int = 4) -> bool:
    """Ask a (surviving) gateway to route one request whose prefix is
    known-resident and report whether prefix routing actually engaged —
    the post-campaign check that failover didn't degrade the door to
    blind load balancing. Uses the gateway's own routed_prefix counter
    so the answer reflects the routing decision, not a guess from
    outside. The probe's rid becomes a real request; callers wait out
    its verdict like any other."""
    before = client.gateway_stats()["stats"].get("routed_prefix", 0)
    client.submit(rid, prompt, max_new_tokens)
    after = client.gateway_stats()["stats"].get("routed_prefix", 0)
    return after > before
