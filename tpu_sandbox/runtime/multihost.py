"""Multi-host (multi-process) array utilities.

The reference's multi-node story is rank arithmetic + per-process data
sharding (mnist_distributed.py:49,73-75) — and its launcher was actually
broken for real multi-node (hardcoded localhost master, SURVEY §2.1 C15).
Here multi-host is first-class: one process per host joins via
runtime.bootstrap (jax.distributed), and a global sharded array is
assembled from each process's local shard with
``jax.make_array_from_process_local_data`` — the DistributedSampler
equivalent at the array level.

Verified (tests/test_multiprocess.py) with 2 real processes on the CPU
backend, whose cross-process collectives run over Gloo — the same fabric
the reference's CPU fallback used.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def global_batch_from_local(
    mesh: Mesh,
    local_batch: np.ndarray,
    *,
    spec: P | None = None,
) -> jax.Array:
    """Assemble the global batch from this process's local rows.

    ``local_batch``: this process's shard (global_batch / num_processes
    rows, the rank-strided or contiguous split its sampler produced).
    Returns a global jax.Array sharded over ``spec`` (default: dim 0 over
    the mesh's first axis).
    """
    spec = spec if spec is not None else P(mesh.axis_names[0])
    sharding = NamedSharding(mesh, spec)
    global_shape = (
        local_batch.shape[0] * jax.process_count(),
        *local_batch.shape[1:],
    )
    return jax.make_array_from_process_local_data(sharding, local_batch, global_shape)


def process_local_rows(n: int) -> tuple[int, int]:
    """[start, stop) rows of a length-n global batch owned by this process
    (contiguous split; pair with a per-process DistributedSampler for the
    reference's strided semantics)."""
    per = n // jax.process_count()
    r = jax.process_index()
    return r * per, (r + 1) * per
